"""Windowed time series derived from a :class:`~.metrics.MetricsRegistry`.

The registry answers "what is counter Y *now*"; nothing answered "what
was the rate of Y over the last window" — the view every SLO and every
burn-rate alert is defined over. :class:`SeriesStore` is that layer: a
bounded ring of fixed-width windows, each one a snapshot-delta of an
attached registry taken at the window boundary:

* **counters** — the positive delta since the previous boundary, i.e.
  a per-window rate once divided by the window width;
* **gauges** — last value, sampled at the boundary;
* **histograms** — the bucket-count VECTOR delta between boundaries,
  so windowed p50/p99 come out of the existing fixed-log grid
  (:data:`~.metrics.DEFAULT_BUCKETS`) via the same nearest-bucket
  quantile the registry exports (:func:`~.metrics._bucket_quantile`).

Clock discipline (graftcheck GC008, the TraceBook rule): the store
NEVER reads the OS clock. Rollover is driven either by an injected
``clock=`` (``.now()`` object or 0-arg callable — ``time.monotonic``
live, a :class:`~..sim.clock.VirtualClock` in the sim) or by explicit
``maybe_roll(now)`` calls from whoever owns the timeline
(:func:`~..sim.workload.run_router_day` does exactly this with the day
clock, so an instrumented day stays digest-neutral by construction:
rolls happen only at drive-loop points the dark run already visits,
and the store only READS the registry).

Respawn discipline (the aggregate-plane contract): a worker counter is
cumulative *per incarnation* — a respawned rank restarts at zero.
With ``aggregator=`` bound, ``worker``-labeled series fold the
aggregate plane's per-incarnation boot id
(:meth:`~.aggregate.TelemetryAggregator.boots`) into the delta key, so
an incarnation flip re-baselines the series instead of subtracting a
fresh counter from a dead one; any observed decrease (a reset the boot
map missed) is treated the same way. Either way a respawn can never
produce a negative-rate window.

Window semantics under coarse driving: ``maybe_roll`` attributes the
whole delta since the last boundary to the most recent elapsed window
and emits the intervening windows empty — the driver's call cadence is
the attribution resolution (the sim driver rolls at every step/submit,
so gaps are at most one quiet window wide).
"""

from __future__ import annotations

from collections import deque
from typing import Any

from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    _bucket_quantile,
)

__all__ = ["SeriesStore"]

_EPS = 1e-12
_US = 1e6


def _flat(name: str, labels: tuple) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class SeriesStore:
    """Bounded ring of per-window registry deltas (module docstring).

    >>> store = SeriesStore(registry, clock=clock, window_s=1.0)
    >>> ...  # traffic
    >>> store.maybe_roll(clock.now())
    >>> store.window_rate("router_requests_total")
    >>> store.window_quantile("router_ttft_seconds", 0.99)
    """

    def __init__(
        self, registry: MetricsRegistry, *, clock=None,
        window_s: float = 1.0, max_windows: int = 600,
        aggregator=None, name: str = "series",
    ):
        if registry is None:
            raise ValueError(
                "SeriesStore needs a MetricsRegistry to derive "
                "windows from"
            )
        self.registry = registry
        self.window_s = float(window_s)
        if self.window_s <= 0.0:
            raise ValueError(
                f"window_s must be > 0, got {window_s}"
            )
        self.max_windows = int(max_windows)
        if self.max_windows < 1:
            raise ValueError(
                f"max_windows must be >= 1, got {max_windows}"
            )
        self.aggregator = aggregator
        self.name = str(name)
        self._now = (
            clock.now if hasattr(clock, "now") else clock
        )
        # (name, labels) -> (incarnation, last cumulative value)
        self._last: dict[tuple, tuple[str, float]] = {}
        # (name, labels) -> (incarnation, counts, sum, count)
        self._last_hist: dict[tuple, tuple] = {}
        # id(instrument) -> (instrument, key, labels, kind): the delta
        # key is pure function of an instrument's identity, so build
        # it once per series, not once per window. The strong ref
        # keeps the id from ever being recycled under the cache.
        self._keys: dict[int, tuple] = {}
        # first boundary: lazily pinned at the first maybe_roll (or
        # now, when a clock was injected) so a store built before its
        # day clock exists still aligns its grid to that day's t=0
        self._t0: float | None = (
            None if self._now is None else float(self._now())
        )
        if self._t0 is not None:
            self._baseline()
        self.n_rolled = 0  # total windows ever closed (ring evicts)
        self._ring: deque[dict[str, Any]] = deque(
            maxlen=self.max_windows
        )

    # -- sampling ---------------------------------------------------------

    def _incarnation(self, labels: dict, boots) -> str:
        """The aggregate plane's boot id for this series' rank, or ""
        for series that are not per-worker (coordinator-local series
        have exactly one incarnation: this process)."""
        if boots is None:
            return ""
        w = labels.get("worker")
        if w is None:
            return ""
        try:
            return boots.get(int(w), "")
        except (TypeError, ValueError):
            return ""

    def _boots(self):
        agg = self.aggregator
        if agg is None:
            return None
        boots = getattr(agg, "boots", None)
        return boots() if callable(boots) else None

    _HIST, _CTR, _GAUGE, _OTHER = 0, 1, 2, 3

    def _key(self, inst) -> tuple:
        """(instrument, delta key, labels, kind) — cached per series
        so window close does not rebuild sorted label tuples."""
        ck = self._keys.get(id(inst))
        if ck is None:
            labels = dict(inst.labels)
            kind = (
                self._HIST if isinstance(inst, Histogram)
                else self._CTR if isinstance(inst, Counter)
                else self._GAUGE if isinstance(inst, Gauge)
                else self._OTHER
            )
            ck = (
                inst,
                (inst.name, tuple(sorted(labels.items()))),
                labels,
                kind,
            )
            self._keys[id(inst)] = ck
        return ck

    def _baseline(self) -> None:
        """Prime the delta state so the first window carries only
        in-window activity, not the registry's whole history."""
        boots = self._boots()
        for inst in self.registry:
            _, key, labels, kind = self._key(inst)
            inc = (
                "" if boots is None
                else self._incarnation(labels, boots)
            )
            if kind == self._HIST:
                counts, total, n = inst.read()
                self._last_hist[key] = (inc, counts, total, n)
            elif kind == self._CTR:
                self._last[key] = (inc, inst.value)

    def _sample(self, t0: float, t1: float) -> dict[str, Any]:
        """Close one window: snapshot the registry, delta against the
        previous boundary under the incarnation discipline (module
        docstring), return the window record."""
        boots = self._boots()
        counters: dict[tuple, float] = {}
        gauges: dict[tuple, float] = {}
        hists: dict[tuple, tuple] = {}
        for inst in self.registry:
            _, key, labels, kind = self._key(inst)
            inc = (
                "" if boots is None
                else self._incarnation(labels, boots)
            )
            if kind == self._HIST:
                counts, total, n = inst.read()
                prev = self._last_hist.get(key)
                if prev is None:
                    dc, ds, dn = counts, total, n
                else:
                    pinc, pcounts, ptotal, pn = prev
                    if pinc != inc or n < pn:
                        # respawned incarnation: the fresh histogram
                        # counts from zero — subtracting the dead
                        # incarnation's snapshot would go negative
                        dc, ds, dn = counts, total, n
                    else:
                        dc = [
                            c - p for c, p in zip(counts, pcounts)
                        ]
                        ds, dn = total - ptotal, n - pn
                self._last_hist[key] = (inc, counts, total, n)
                if dn:
                    hists[key] = (inst.bounds, dc, ds, dn)
            elif kind == self._CTR:
                cur = inst.value
                prev = self._last.get(key)
                if prev is None:
                    delta = cur  # series born since the last boundary
                else:
                    pinc, pval = prev
                    if pinc != inc or cur < pval:
                        # incarnation flip (or a reset the boot map
                        # missed): count the fresh incarnation from
                        # zero — never a negative-rate window. A
                        # monotone merged counter under a flip still
                        # subtracts cleanly (cur >= pval).
                        delta = cur - pval if cur >= pval else cur
                    else:
                        delta = cur - pval
                self._last[key] = (inc, cur)
                if delta:
                    counters[key] = delta
            elif kind == self._GAUGE:
                gauges[key] = inst.value
        return {
            "i": self.n_rolled, "t0": t0, "t1": t1,
            "counters": counters, "gauges": gauges, "hists": hists,
        }

    # -- rollover ---------------------------------------------------------

    def maybe_roll(self, now: float | None = None) -> int:
        """Close every window boundary at or before ``now``; returns
        how many windows closed (0 when none are due — idempotent, so
        any number of drive-loop call sites may share one store)."""
        if now is None:
            if self._now is None:
                raise ValueError(
                    "maybe_roll() needs an explicit now= on a store "
                    "built without clock="
                )
            now = self._now()
        now = float(now)
        if self._t0 is None:
            self._t0 = now
            self._baseline()
            return 0
        w = self.window_s
        k = int((now - self._t0 + _EPS) / w)
        if k <= 0:
            return 0
        # one registry snapshot: the whole delta lands in the most
        # recent elapsed window; intervening windows close empty
        # (module docstring — the driver's cadence is the resolution)
        for j in range(k - 1):
            t0 = self._t0 + j * w
            self._ring.append({
                "i": self.n_rolled, "t0": t0, "t1": t0 + w,
                "counters": {}, "gauges": {}, "hists": {},
            })
            self.n_rolled += 1
        t0 = self._t0 + (k - 1) * w
        self._ring.append(self._sample(t0, t0 + w))
        self.n_rolled += 1
        self._t0 += k * w
        return k

    # -- reads ------------------------------------------------------------

    def windows(self, n: int | None = None) -> list[dict[str, Any]]:
        """The most recent ``n`` closed windows (all retained when
        None), oldest first."""
        wins = list(self._ring)
        return wins if n is None else wins[-int(n):]

    def windows_upto(self, i: int, n: int) -> list[dict[str, Any]]:
        """Up to ``n`` windows ending at absolute index ``i`` (the SLO
        plane evaluates each window as it closes, even when several
        close in one roll)."""
        return [
            rec for rec in self._ring
            if i - n < rec["i"] <= i
        ]

    def counter_deltas(
        self, name: str, *, windows: int = 1,
        _wins: list | None = None,
    ) -> list[tuple[dict, float]]:
        """``(labels, delta)`` per labeled series of ``name`` over the
        last ``windows`` windows (deltas summed per series)."""
        acc: dict[tuple, float] = {}
        for rec in (self.windows(windows) if _wins is None else _wins):
            for (n, lt), d in rec["counters"].items():
                if n == name:
                    acc[lt] = acc.get(lt, 0.0) + d
        return [(dict(lt), d) for lt, d in acc.items()]

    def window_delta(
        self, name: str, *, labels: dict | None = None,
        windows: int = 1,
    ) -> float:
        """Summed counter delta of ``name`` over the last ``windows``
        windows, across every label set matching the ``labels``
        subset."""
        want = None if labels is None else set(labels.items())
        total = 0.0
        for lt, d in self.counter_deltas(name, windows=windows):
            if want is None or want <= set(lt.items()):
                total += d
        return total

    def window_rate(
        self, name: str, *, labels: dict | None = None,
        windows: int = 1,
    ) -> float:
        """:meth:`window_delta` divided by the covered span."""
        return self.window_delta(
            name, labels=labels, windows=windows
        ) / (self.window_s * max(int(windows), 1))

    def _merge_hists(
        self, name: str, windows: int, wins: list | None = None,
    ) -> tuple[tuple, list[int], float, int] | None:
        bounds = None
        dc: list[int] | None = None
        ds, dn = 0.0, 0
        for rec in (self.windows(windows) if wins is None else wins):
            for (n, _lt), (b, c, s, cnt) in rec["hists"].items():
                if n != name:
                    continue
                if dc is None:
                    bounds, dc = b, list(c)
                else:
                    dc = [x + y for x, y in zip(dc, c)]
                ds += s
                dn += cnt
        if dc is None:
            return None
        return bounds, dc, ds, dn

    def window_quantile(
        self, name: str, q: float, *, windows: int = 1,
    ) -> float | None:
        """Nearest-bucket quantile of histogram ``name`` over the last
        ``windows`` windows (None when no observation landed); label
        sets of one family merge bucket-wise — the fixed grid is what
        makes them addable."""
        got = self._merge_hists(name, windows)
        if got is None:
            return None
        bounds, dc, _ds, dn = got
        return _bucket_quantile(bounds, dc, dn, q)

    def window_count(self, name: str, *, windows: int = 1) -> int:
        """Observations of histogram ``name`` over the last
        ``windows`` windows."""
        got = self._merge_hists(name, windows)
        return 0 if got is None else got[3]

    def gauge_value(self, name: str, *, labels: dict | None = None):
        """Last sampled value of gauge ``name`` in the newest closed
        window (None before any window closed or when unseen)."""
        if not self._ring:
            return None
        want = None if labels is None else set(labels.items())
        for (n, lt), v in self._ring[-1]["gauges"].items():
            if n == name and (want is None or want <= set(lt)):
                return v
        return None

    # -- exports ----------------------------------------------------------

    def __len__(self) -> int:
        return len(self._ring)

    def to_doc(self) -> dict[str, Any]:
        """JSON-able snapshot: ring of windows with flat
        ``name{label="v"}`` series keys; histogram bucket grids hoisted
        once into ``buckets`` (they repeat per window otherwise)."""
        buckets: dict[str, list[float]] = {}
        wins = []
        for rec in self._ring:
            hists = {}
            for (n, lt), (b, c, s, cnt) in rec["hists"].items():
                buckets.setdefault(n, list(b))
                hists[_flat(n, lt)] = {
                    "counts": list(c), "sum": s, "count": cnt,
                }
            wins.append({
                "i": rec["i"], "t0": rec["t0"], "t1": rec["t1"],
                "counters": {
                    _flat(n, lt): d
                    for (n, lt), d in rec["counters"].items()
                },
                "gauges": {
                    _flat(n, lt): v
                    for (n, lt), v in rec["gauges"].items()
                },
                "hists": hists,
            })
        return {
            "name": self.name, "window_s": self.window_s,
            "max_windows": self.max_windows,
            "n_rolled": self.n_rolled, "buckets": buckets,
            "windows": wins,
        }

    def chrome_events(
        self, pid: int = 0
    ) -> tuple[list[dict], list[dict]]:
        """(metadata, counter events) under ``pid`` — the
        :meth:`~.timeline.SpanRecorder.chrome_events` merge contract,
        so a store rides :func:`~.timeline.merged_chrome_trace` as
        Perfetto counter tracks: one sample per window at the window's
        close, counters as rates, gauges as-is."""
        meta = [
            {"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": f"series {self.name}"}},
        ]
        events: list[dict[str, Any]] = []
        w = self.window_s
        for rec in self._ring:
            ts = rec["t1"] * _US
            for (n, lt), d in rec["counters"].items():
                fn = _flat(n, lt)
                events.append({
                    "name": fn, "ph": "C", "pid": pid, "ts": ts,
                    "args": {fn: d / w},
                })
            for (n, lt), v in rec["gauges"].items():
                fn = _flat(n, lt)
                events.append({
                    "name": fn, "ph": "C", "pid": pid, "ts": ts,
                    "args": {fn: v},
                })
        return meta, events

    def __repr__(self) -> str:
        return (
            f"SeriesStore({self.name!r}, window_s={self.window_s}, "
            f"{len(self._ring)}/{self.max_windows} windows, "
            f"{self.n_rolled} rolled)"
        )
