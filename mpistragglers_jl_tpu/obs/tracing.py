"""Request-scoped causal tracing: one record per request, door to door.

Spans (r7) time *components* and counters (r9) aggregate *planes*; the
:class:`TraceBook` follows ONE request through every plane it crosses —
the router door, the DRR queue, a prefill tier, a KV-page migration, a
hedge race, a retry resubmission — as a flat list of typed events on
the owner's injected clock. That makes the record:

* **engine-agnostic** — the identical code path stamps live runs (wall
  clock) and sim runs (virtual clock); the book itself never reads a
  clock, callers pass ``t`` explicitly (so sim/qos stay GC008-clean);
* **deterministic** — trace ids mint in submission order and events
  append in code order on virtual timestamps, so a seeded sim day
  yields byte-identical books across replays;
* **digest-neutral** — tracing draws no randomness and never perturbs
  virtual timing, so ``WorkloadReport.digest()`` is unchanged whether
  a day ran dark or traced (pinned in tests/test_tracing.py).

Everything is strictly OPT-IN per the GC004 contract: instrumented
layers accept ``trace=`` defaulting to ``None`` and dark paths pay one
``is None`` check — no allocation, no clock reads.

Event taxonomy (the full set stamped by the serving planes):

========================  ============================================
kind                      stamped by / meaning
========================  ============================================
``submitted``             router/scheduler door; attrs: tenant, prompt
``shed``                  admission refusal; attrs: reason
``drr_queued``            DRR admission queue entry; attrs: tenant
``drr_picked``            DRR grant; attrs: tenant, cost
``admitted``              placed into a slot; attrs: replica/tick
``prefill_chunk``         one prompt chunk advanced; attrs: replica
``first_token``           first decode token surfaced
``share_hit``             prefix page shared instead of prefilled
``cow_copy``              copy-on-write fork of a shared page
``migrate_out``           KV pages captured; attrs: replica, nbytes
``adopt``                 pages landed; attrs: replica (``bounced``
                          when the dest died mid-flight)
``hedge_armed``           hedge deadline armed; attrs: fire_at
``hedge_fired``           second leg dispatched; attrs: replica
``hedge_won``             the HEDGE leg's token won the race
``hedge_cancelled``       the hedge leg lost the race and was reaped
``hedge_abandoned``       a hedge leg lost to a kill/partition, not
                          to the race; attrs: replica
``partition_abandoned``   leg unreachable behind a partition
``rerouted``              fresh leg on a surviving replica
``evacuated``             leg lost to a dead replica; attrs: replica
``evacuated_on_resize``   fleet controller drained the replica
``retry_resubmit``        timed-out request resubmitted; stamped on
                          the CHILD trace; attrs: parent, attempt
``retired``               served to completion; attrs: outcome,
                          tokens
``cancelled``             terminal cancel (timeout reap, shutdown)
========================  ============================================

Terminal kinds (``shed`` / ``retired`` / ``cancelled``) are stamped
exactly once per trace, by the request's OWNER (router or scheduler),
never by a replica reaping an individual leg — that is what makes the
conservation audit (:mod:`.audit`) decidable.
"""

from __future__ import annotations

from typing import Any, Iterator

__all__ = ["TraceBook", "TERMINAL_KINDS"]

_US = 1e6  # seconds -> Chrome trace microseconds

#: Kinds that close a trace. Exactly one per submitted request.
TERMINAL_KINDS = ("retired", "shed", "cancelled")

#: Waterfall phases derived from event pairs (start kind, end kinds,
#: phase name) — the queued/prefill/decode decomposition of a request's
#: lifetime, rendered as nested Chrome spans.
_PHASES = (
    ("submitted", ("admitted", "first_token") + TERMINAL_KINDS,
     "queued"),
    ("admitted", ("first_token",) + TERMINAL_KINDS, "prefill"),
    ("first_token", TERMINAL_KINDS, "decode"),
)


class TraceBook:
    """Mint trace ids and append typed lifecycle events.

    The book is a dumb, fast store: ``mint()`` hands out dense integer
    ids in call order, ``event()`` appends ``(kind, t, attrs)`` tuples.
    All derived views (waterfalls, cohorts, the Chrome export) walk the
    raw lists on demand — nothing is indexed at append time, so the
    traced hot path stays one list-append per transition.

    Not thread-safe by design: each book belongs to one serving plane
    on one clock, the same ownership discipline as ``SpanRecorder``.
    """

    __slots__ = ("_events", "_parent", "_children", "name")

    def __init__(self, name: str = "traces"):
        self.name = name
        self._events: list[list[tuple[str, float, dict | None]]] = []
        self._parent: dict[int, int] = {}
        self._children: dict[int, list[int]] = {}

    # -- write path -------------------------------------------------------

    def mint(self, *, parent: int | None = None) -> int:
        """Allocate the next trace id (dense, submission-ordered).

        ``parent`` links a retry resubmission's child trace back to
        the timed-out original; the link is navigable both ways."""
        tid = len(self._events)
        self._events.append([])
        if parent is not None:
            self._parent[tid] = int(parent)
            self._children.setdefault(int(parent), []).append(tid)
        return tid

    def link(self, child: int, parent: int) -> None:
        """Link ``child`` to ``parent`` after the fact — the retry
        driver's hook: the router mints the resubmission's trace as a
        fresh door entry, and the retry client (which alone knows the
        chain) attaches the lineage."""
        child, parent = int(child), int(parent)
        if self._parent.get(child) == parent:
            return
        self._parent[child] = parent
        self._children.setdefault(parent, []).append(child)

    def event(self, tid: int, kind: str, t: float, **attrs: Any) -> None:
        """Append one typed event at caller-provided time ``t``.

        The caller holds the clock (injected wall or virtual) — the
        book never reads one, so the same call site is legal in
        GC008-covered packages (sim/, qos/)."""
        self._events[tid].append((kind, float(t), attrs or None))

    # -- read path --------------------------------------------------------

    def __len__(self) -> int:
        return len(self._events)

    def __contains__(self, tid: int) -> bool:
        return 0 <= int(tid) < len(self._events)

    def ids(self) -> range:
        return range(len(self._events))

    def events(self, tid: int) -> list[tuple[str, float, dict | None]]:
        """The raw ``(kind, t, attrs)`` list for one trace."""
        return self._events[tid]

    def kinds(self, tid: int) -> list[str]:
        return [k for k, _, _ in self._events[tid]]

    def parent(self, tid: int) -> int | None:
        return self._parent.get(tid)

    def children(self, tid: int) -> list[int]:
        return list(self._children.get(tid, ()))

    def find(self, tid: int, kind: str) -> tuple[str, float, dict | None] | None:
        """First event of ``kind`` on trace ``tid``, or None."""
        for ev in self._events[tid]:
            if ev[0] == kind:
                return ev
        return None

    def find_last(self, tid: int, kind: str) -> tuple[str, float, dict | None] | None:
        """LAST event of ``kind`` — the one the scheduler's own
        bookkeeping reflects for stamps a re-route resets and
        re-records (``admitted``, ``first_token``)."""
        for ev in reversed(self._events[tid]):
            if ev[0] == kind:
                return ev
        return None

    def terminal(self, tid: int) -> tuple[str, float, dict | None] | None:
        """The trace's terminal event (retired/shed/cancelled), or
        None while the request is still in flight."""
        for ev in self._events[tid]:
            if ev[0] in TERMINAL_KINDS:
                return ev
        return None

    def iter_events(
        self,
    ) -> Iterator[tuple[int, str, float, dict | None]]:
        """All events across all traces as ``(tid, kind, t, attrs)``."""
        for tid, evs in enumerate(self._events):
            for kind, t, attrs in evs:
                yield tid, kind, t, attrs

    # -- derived views ----------------------------------------------------

    def cohort(self, tid: int) -> str:
        """The request cohort a trace belongs to — the Perfetto track
        grouping: how did this request's day actually go?"""
        kinds = set(self.kinds(tid))
        if "shed" in kinds:
            return "shed"
        if "cancelled" in kinds:
            return "cancelled"
        if "retired" not in kinds:
            return "open"
        if "hedge_fired" in kinds:
            return "hedged"
        if "migrate_out" in kinds:
            return "migrated"
        if "rerouted" in kinds or "retry_resubmit" in kinds:
            return "rescued"
        return "served"

    def waterfall(self, tid: int) -> dict:
        """One request's life as JSON — the ``GET /trace/<id>`` body.

        Timestamps are the owner's clock verbatim; ``ttft`` and
        ``latency`` are derived from the SAME stamps the scheduler's
        own bookkeeping uses, so they reproduce it exactly."""
        tid = int(tid)
        if tid not in self:
            raise KeyError(f"unknown trace id {tid}")
        evs = self._events[tid]
        t0 = evs[0][1] if evs else 0.0
        # LAST first_token: a re-route restarts the stream and the
        # scheduler's TTFT stamp restarts with it
        first_tok = self.find_last(tid, "first_token")
        term = self.terminal(tid)
        return {
            "trace": tid,
            "cohort": self.cohort(tid),
            "parent": self._parent.get(tid),
            "children": self.children(tid),
            "t0": t0,
            "ttft": None if first_tok is None else first_tok[1] - t0,
            "latency": None if term is None else term[1] - t0,
            "outcome": None if term is None else term[0],
            "events": [
                {"kind": k, "t": t, "dt": t - t0, "attrs": a or {}}
                for k, t, a in evs
            ],
        }

    def audit_view(self) -> dict:
        """Aggregate counts the audit and ``GET /audit`` both read."""
        n_open = n_retired = n_shed = n_cancelled = 0
        for tid in self.ids():
            term = self.terminal(tid)
            if term is None:
                n_open += 1
            elif term[0] == "retired":
                n_retired += 1
            elif term[0] == "shed":
                n_shed += 1
            else:
                n_cancelled += 1
        return {
            "traces": len(self),
            "open": n_open,
            "retired": n_retired,
            "shed": n_shed,
            "cancelled": n_cancelled,
            "retry_children": len(self._parent),
        }

    # -- chrome export ----------------------------------------------------

    def chrome_events(
        self, pid: int = 0
    ) -> tuple[list[dict], list[dict]]:
        """(metadata events, span events) under process ``pid`` — the
        merge contract shared with ``SpanRecorder.chrome_events``.

        One Chrome *thread* (track) per request cohort; each trace
        renders as an outer ``req#<id>`` span with nested
        queued/prefill/decode phase spans, so the merged Perfetto doc
        shows the request waterfalls alongside the component spans."""
        cohorts: list[str] = []
        tid_of: dict[str, int] = {}
        meta: list[dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": self.name}},
        ]
        events: list[dict[str, Any]] = []
        for trace_id in self.ids():
            evs = self._events[trace_id]
            if not evs:
                continue
            cohort = self.cohort(trace_id)
            if cohort not in tid_of:
                tid_of[cohort] = len(cohorts)
                cohorts.append(cohort)
                meta.append(
                    {"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid_of[cohort], "args": {"name": cohort}}
                )
            tid = tid_of[cohort]
            t0, t_end = evs[0][1], evs[-1][1]
            events.append({
                "name": f"req#{trace_id}", "ph": "X", "pid": pid,
                "tid": tid, "ts": t0 * _US,
                "dur": max(t_end - t0, 0.0) * _US,
                "args": {"cohort": cohort, "events": len(evs)},
            })
            for start_kind, end_kinds, phase in _PHASES:
                start = self.find(trace_id, start_kind)
                if start is None:
                    continue
                end = None
                for ev in evs:
                    if ev[0] in end_kinds and ev[1] >= start[1]:
                        end = ev
                        break
                if end is None:
                    continue
                events.append({
                    "name": phase, "ph": "X", "pid": pid, "tid": tid,
                    "ts": start[1] * _US,
                    "dur": max(end[1] - start[1], 0.0) * _US,
                    "args": {"trace": trace_id},
                })
        return meta, events

    def __repr__(self) -> str:
        v = self.audit_view()
        return (
            f"TraceBook({self.name!r}, {v['traces']} traces: "
            f"{v['retired']} retired, {v['shed']} shed, "
            f"{v['cancelled']} cancelled, {v['open']} open)"
        )
