"""Zero-dependency metrics registry: counters, gauges, histograms.

The runtime's series store — the quantitative half of the observability
subsystem (the timeline half is :mod:`.timeline`). Stdlib-only by
design: the registry is importable wherever the pool is (the package
root stays jax-free, tests/test_no_compiler.py), and every instrument
is THREAD-SAFE so writers off the coordinator thread — the native
transport's epoll/harvest thread, a HedgedServer draining losers from
a helper thread — can record without corrupting counts (the pool's own
hot loop stays single-threaded; the lock is uncontended there).

Design, mirroring the tracer's opt-in contract (utils/trace.py):
instrumented layers take a ``registry=None`` argument and pay nothing
when none is passed — instruments are resolved ONCE at construction
(a dict lookup + lock), so the steady-state cost of an enabled series
is one locked float add per event.

Exports: :meth:`MetricsRegistry.snapshot` (JSON-able dict, the bench
contract's telemetry attachment), :meth:`MetricsRegistry.to_json`, and
:meth:`MetricsRegistry.to_prometheus` (text exposition format 0.0.4 —
scrapeable, and parseable line-by-line in tests).
"""

from __future__ import annotations

import json
import math
import re
import threading
from bisect import bisect_left
from typing import Any, Iterable

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
]

# Fixed log buckets for latency histograms: half-decade steps from 1 µs
# to 100 s (17 bounds + the implicit +Inf). Fixed — not adaptive — so
# two processes' histograms merge by bucket-wise addition and a series
# is comparable across runs.
DEFAULT_BUCKETS: tuple[float, ...] = tuple(
    round(10.0 ** (k / 2.0), 10) for k in range(-12, 5)
)


# exactly the Prometheus name grammar (ASCII — str.isalnum would admit
# unicode letters a scraper rejects); permitting anything wider (dots,
# say) would need a lossy export mapping under which two distinct
# families ("a.b", "a_b") collide into one exposition name, an invalid
# scrape
_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} must match [a-zA-Z_:][a-zA-Z0-9_:]*"
        )
    return name


def _check_labels(labels: dict, kind: str) -> None:
    # kwargs reach here as any valid PYTHON identifier, which admits
    # unicode the exposition grammar rejects; "__" is Prometheus-
    # reserved, and "le" on a histogram would collide with the bucket
    # label (overwritten on _bucket lines, kept on _sum/_count — two
    # disjoint label sets in one family)
    for k in labels:
        if not _NAME_RE.match(k) or k.startswith("__") or ":" in k:
            raise ValueError(
                f"label name {k!r} must match [a-zA-Z_][a-zA-Z0-9_]* "
                "and not start with __"
            )
        if k == "le" and kind == "histogram":
            raise ValueError(
                'label "le" is reserved for histogram buckets'
            )


class _Instrument:
    """Shared identity: name + frozen label set + help text."""

    kind = "untyped"

    def __init__(self, name: str, labels: dict[str, str], help: str = ""):
        self.name = _check_name(name)
        self.labels = dict(labels)
        self.help = help
        self._lock = threading.Lock()

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}"
            f"({self.name}{_labels_str(self.labels)})"
        )


class Counter(_Instrument):
    """Monotonically increasing count (events, tokens, decodes)."""

    kind = "counter"

    def __init__(self, name, labels, help=""):
        super().__init__(name, labels, help)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0: {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Instrument):
    """Point-in-time level (queue depth, slot occupancy, a fitted rate)."""

    kind = "gauge"

    def __init__(self, name, labels, help=""):
        super().__init__(name, labels, help)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Instrument):
    """Distribution over fixed log buckets (:data:`DEFAULT_BUCKETS`).

    ``observe(v)`` is one bisect + two adds under the lock; quantiles
    come from the cumulative bucket counts (:meth:`quantile` returns
    the upper bound of the covering bucket — resolution is the bucket
    grid, which is the deal fixed buckets buy).
    """

    kind = "histogram"

    def __init__(self, name, labels, help="", buckets=None):
        super().__init__(name, labels, help)
        bounds = tuple(
            float(b) for b in (DEFAULT_BUCKETS if buckets is None
                               else buckets)
        )
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError(
                f"histogram buckets must be strictly increasing: {bounds}"
            )
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return
        i = bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float | None:
        """Upper bucket bound covering quantile ``q`` (None when empty;
        ``inf`` when it lands in the overflow bucket)."""
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"q must be in [0, 1], got {q}")
        counts, _, total = self.read()
        return _bucket_quantile(self.bounds, counts, total, q)

    def bucket_counts(self) -> list[int]:
        with self._lock:
            return list(self._counts)

    def read(self) -> tuple[list[int], float, int]:
        """(bucket counts, sum, count) under ONE lock acquisition —
        the export path must use this, not the individual properties:
        a concurrent ``observe`` between separate reads would emit an
        exposition where ``_bucket{le="+Inf"}`` != ``_count``, breaking
        the Prometheus histogram invariant."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def merge_deltas(
        self, counts: Iterable[int], sum_delta: float, count_delta: int
    ) -> None:
        """Fold another process's bucket-count DELTAS in (the fixed
        grid is what makes histograms addable — module docstring); the
        cross-process aggregation path (:mod:`.aggregate`). ``counts``
        must cover the full grid including the +Inf overflow bucket;
        negative deltas are rejected (a shrinking histogram is a
        protocol bug upstream, never mergeable)."""
        dc = [int(c) for c in counts]
        if len(dc) != len(self._counts):
            raise ValueError(
                f"bucket delta length {len(dc)} != grid size "
                f"{len(self._counts)} (bounds + overflow)"
            )
        if any(c < 0 for c in dc) or count_delta < 0:
            raise ValueError("histogram deltas must be >= 0")
        with self._lock:
            for i, c in enumerate(dc):
                self._counts[i] += c
            self._sum += float(sum_delta)
            self._count += int(count_delta)


def _bucket_quantile(bounds, counts, total, q) -> float | None:
    """Quantile over an already-read (counts, total) snapshot."""
    if total == 0:
        return None
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target and c:
            return bounds[i] if i < len(bounds) else math.inf
    return math.inf


def _escape_label(v: str) -> str:
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(v: str) -> str:
    # exposition 0.0.4 HELP escaping: backslash and newline only (no
    # quote escaping — HELP text is not quoted). Round-trips exactly,
    # unlike the old newline->space flattening.
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _prom_name(name: str) -> str:
    # _check_name already enforces the exposition grammar; kept as the
    # single seam if the registry grammar ever widens again
    return name


def _prom_num(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class MetricsRegistry:
    """Get-or-create instrument store with JSON / Prometheus exports.

    >>> reg = MetricsRegistry()
    >>> reg.counter("serving_tokens_total").inc(8)
    >>> reg.gauge("serving_queue_depth").set(3)
    >>> reg.histogram("serving_ttft_seconds").observe(0.12)
    >>> print(reg.to_prometheus())

    ``counter/gauge/histogram`` return the SAME object for the same
    (name, labels) pair — callers resolve instruments once and hold
    them; labeled series of one name share one TYPE/HELP family (a
    name registered as two different kinds raises). All methods are
    thread-safe.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[tuple, _Instrument] = {}
        self._kinds: dict[str, str] = {}
        self._buckets: dict[str, tuple] = {}  # histogram family grids

    # -- get-or-create ---------------------------------------------------
    def _get(self, cls, name, help, labels, **kw):
        _check_labels(labels, cls.kind)
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            # one bucket grid per histogram FAMILY, not per labeled
            # series: series of one name with different grids would
            # export disjoint le sets that sum-by-le quantile queries
            # silently misaggregate. First registration fixes the
            # grid; later series inherit it (buckets=None) or must
            # match it; a mismatch is a conflict exactly like a kind
            # mismatch (silently handing back another grid would route
            # out-of-range observes into +Inf with no error).
            if cls is Histogram:
                fam = self._buckets.get(name)
                want = kw.get("buckets")
                if want is not None:
                    want = tuple(float(b) for b in want)
                if fam is not None and want is not None and want != fam:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam}, conflicting request {want}"
                    )
                if fam is not None:
                    kw = {**kw, "buckets": fam}
            inst = self._metrics.get(key)
            if inst is None:
                seen = self._kinds.get(name)
                if seen is not None and seen != cls.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {seen}, "
                        f"cannot re-register as {cls.kind}"
                    )
                inst = cls(name, labels, help=help, **kw)
                self._metrics[key] = inst
                self._kinds[name] = cls.kind
                if cls is Histogram:
                    self._buckets[name] = inst.bounds
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r}{labels} is a {inst.kind}, "
                    f"not a {cls.kind}"
                )
            return inst

    def counter(self, name: str, *, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, *, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(
        self, name: str, *, help: str = "",
        buckets: Iterable[float] | None = None, **labels,
    ) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def __len__(self) -> int:
        with self._lock:
            return len(self._metrics)

    def __iter__(self):
        with self._lock:
            return iter(list(self._metrics.values()))

    # -- exports ---------------------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        """JSON-able dict: ``{name: {type, help, series: [...]}}``.
        Histogram series carry count/sum/mean/p50/p95 plus the raw
        bucket counts — the form the bench contract attaches."""
        out: dict[str, Any] = {}
        for inst in self:
            fam = out.setdefault(
                inst.name,
                {"type": inst.kind, "help": inst.help, "series": []},
            )
            if isinstance(inst, Histogram):
                counts, total, n = inst.read()
                val: Any = {
                    "count": n,
                    "sum": round(total, 9),
                    "mean": round(total / n, 9) if n else 0.0,
                    "p50": _json_num(_bucket_quantile(
                        inst.bounds, counts, n, 0.5)),
                    "p95": _json_num(_bucket_quantile(
                        inst.bounds, counts, n, 0.95)),
                    "buckets": dict(
                        zip(
                            [_prom_num(b) for b in inst.bounds]
                            + ["+Inf"],
                            counts,
                        )
                    ),
                }
            else:
                val = inst.value
            fam["series"].append({"labels": inst.labels, "value": val})
        return out

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus(self) -> str:
        """Text exposition format 0.0.4: ``# HELP`` / ``# TYPE`` per
        family, one sample line per series (histograms expand to
        cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``)."""
        by_name: dict[str, list[_Instrument]] = {}
        for inst in self:
            by_name.setdefault(inst.name, []).append(inst)
        lines: list[str] = []
        for name in sorted(by_name):
            insts = by_name[name]
            pname = _prom_name(name)
            if insts[0].help:
                lines.append(
                    f"# HELP {pname} "
                    + _escape_help(insts[0].help)
                )
            lines.append(f"# TYPE {pname} {insts[0].kind}")
            for inst in insts:
                if isinstance(inst, Histogram):
                    base = dict(inst.labels)
                    cum = 0
                    counts, total, n_obs = inst.read()
                    for bound, c in zip(
                        list(inst.bounds) + [math.inf], counts
                    ):
                        cum += c
                        lbl = _labels_str(
                            {**base, "le": _prom_num(bound)}
                        )
                        lines.append(f"{pname}_bucket{lbl} {cum}")
                    lbl = _labels_str(base)
                    lines.append(
                        f"{pname}_sum{lbl} {_prom_num(total)}"
                    )
                    lines.append(f"{pname}_count{lbl} {n_obs}")
                else:
                    lines.append(
                        f"{pname}{_labels_str(inst.labels)} "
                        f"{_prom_num(inst.value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def dump_prometheus(self, path) -> None:
        with open(path, "w") as f:
            f.write(self.to_prometheus())

    def dump_json(self, path) -> None:
        with open(path, "w") as f:
            f.write(self.to_json(indent=2))

    def __repr__(self) -> str:
        return f"MetricsRegistry({len(self)} series)"


def _labels_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _json_num(v):
    if v is None:
        return None
    return "+Inf" if v == math.inf else v
