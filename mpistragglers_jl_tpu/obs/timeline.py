"""Unified runtime timeline: one Chrome/Perfetto trace for every layer.

:class:`~..utils.trace.EpochTracer` draws the pool hot path (worker
spans + coordinator calls); this module adds the host-side spans the
pool never sees — scheduler ticks, admission prefill chunks, training
steps — and merges all of them into ONE trace-event JSON that loads in
ui.perfetto.dev, each source as its own Chrome "process" track group
on the shared ``time.perf_counter`` clock (the tracer's clock, so pool
spans and scheduler ticks line up without translation).

Stdlib-only at import (the jax-free package-root contract);
:func:`annotate` reaches for ``jax.profiler`` lazily and degrades to a
no-op wherever jax (or its profiler) is unavailable, so CPU CI runs
the instrumented code paths unchanged.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Any

__all__ = [
    "SpanRecorder",
    "dump_merged_chrome_trace",
    "merged_chrome_trace",
    "annotate",
]

_US = 1e6


class SpanRecorder:
    """Append-only host-side span/counter store for one subsystem.

    One recorder = one Chrome process in the merged trace, named
    ``process``; spans land on named tracks (Chrome threads) within it.
    Timestamps are absolute ``time.perf_counter`` seconds — the same
    clock :class:`~..utils.trace.EpochTracer` stamps, so a pool tracer
    and a scheduler recorder merge aligned.

    >>> rec = SpanRecorder("serving")
    >>> with rec.span("tick 3", track="scheduler", queue=2):
    ...     ...
    >>> rec.add("decode", t0, dur, track="scheduler")   # retro span
    >>> rec.count("queue_depth", 4)                     # counter series

    Recording is plain list appends (no locks): each recorder belongs
    to one writer thread, mirroring the tracer's single-threaded
    contract. Cross-thread aggregation belongs in the registry.

    ``max_events`` (default 200k, ~tens of MB of tuples) bounds a
    long-lived writer — an instrumented scheduler appends a handful of
    events per tick forever, and an uncapped recorder would grow until
    OOM. At the cap new events are DROPPED and counted (``dropped``;
    surfaced as a marker event in the exported trace, never silently):
    the timeline keeps its beginning, the aggregate series live in the
    registry which is O(1) regardless. ``max_events=None`` removes the
    bound for short captures.
    """

    def __init__(
        self, process: str = "host", *,
        max_events: int | None = 200_000,
    ) -> None:
        self.process = str(process)
        self.max_events = None if max_events is None else int(max_events)
        self.dropped = 0
        # (track, name, t0_s, dur_s, args)
        self.spans: list[tuple[str, str, float, float, dict]] = []
        # (name, t_s, value)
        self.counters: list[tuple[str, float, float]] = []

    def _room(self) -> bool:
        if (
            self.max_events is not None
            and len(self.spans) + len(self.counters) >= self.max_events
        ):
            self.dropped += 1
            return False
        return True

    def add(
        self, name: str, t0: float, dur: float, *,
        track: str = "main", **args,
    ) -> None:
        """Record a completed span: ``t0`` absolute perf_counter
        seconds, ``dur`` seconds (clamped at 0 — a clock hiccup must
        not produce a negative-width span that Perfetto rejects)."""
        if self._room():
            self.spans.append(
                (track, str(name), float(t0), max(float(dur), 0.0),
                 args)
            )

    @contextmanager
    def span(self, name: str, *, track: str = "main", **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(
                name, t0, time.perf_counter() - t0, track=track, **args
            )

    def count(
        self, name: str, value: float, *, t: float | None = None
    ) -> None:
        """One sample of a counter series (Perfetto renders these as a
        filled step chart above the spans)."""
        if self._room():
            self.counters.append(
                (str(name),
                 time.perf_counter() if t is None else float(t),
                 float(value))
            )

    def __len__(self) -> int:
        return len(self.spans) + len(self.counters)

    def __repr__(self) -> str:
        drop = f", {self.dropped} dropped" if self.dropped else ""
        return (
            f"SpanRecorder({self.process!r}, {len(self.spans)} spans, "
            f"{len(self.counters)} counter samples{drop})"
        )

    # -- chrome export ----------------------------------------------------
    def chrome_events(
        self, pid: int = 0
    ) -> tuple[list[dict], list[dict]]:
        """(metadata events, span/counter events) under process ``pid``
        — the merge contract shared with ``EpochTracer.chrome_events``.

        Snapshots the span/counter lists ONCE up front: the live
        ``/trace`` endpoint calls this on recorders other threads are
        still appending to, and a two-pass read (build the track map,
        then the events) would KeyError on a span whose track landed
        between the passes. ``list()`` of an append-only list is
        GIL-atomic, so the snapshot is consistent."""
        spans = list(self.spans)
        counters = list(self.counters)
        tracks = []
        for track, *_ in spans:
            if track not in tracks:
                tracks.append(track)
        tid_of = {t: i for i, t in enumerate(tracks)}
        meta: list[dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": self.process}},
        ] + [
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": i,
             "args": {"name": t}}
            for t, i in tid_of.items()
        ]
        events: list[dict[str, Any]] = [
            {"name": name, "ph": "X", "pid": pid, "tid": tid_of[track],
             "ts": t0 * _US, "dur": dur * _US, "args": args}
            for track, name, t0, dur, args in spans
        ]
        events += [
            {"name": name, "ph": "C", "pid": pid,
             "ts": t * _US, "args": {name: value}}
            for name, t, value in counters
        ]
        if self.dropped:
            # the cap must read as a visible truncation marker in the
            # UI, never as "the run ended here"
            last = max((s[2] + s[3] for s in spans), default=0.0)
            events.append({
                "name": f"[recorder cap: {self.dropped} events dropped]",
                "ph": "I", "pid": pid, "tid": 0, "ts": last * _US,
                "s": "p",
            })
        return meta, events

    def dump_chrome_trace(self, path) -> int:
        """Standalone export (one-process trace); the merged form is
        :func:`dump_merged_chrome_trace`."""
        return dump_merged_chrome_trace(path, recorders=[self])


def merged_chrome_trace(
    *, tracers=(), recorders=()
) -> tuple[dict, int]:
    """Merge pool tracers and span recorders into one trace document.

    Returns ``(trace_doc, n_events)`` — the Chrome trace-event dict and
    the number of non-metadata events in it. This is the in-memory half
    of :func:`dump_merged_chrome_trace`, split out so a live exporter
    (``obs/export.py``'s ``/trace`` endpoint) can serve the merged
    timeline over HTTP without touching the filesystem.
    """
    meta: list[dict] = []
    events: list[dict] = []
    pid = 0
    for tracer in tracers:
        m, e = tracer.chrome_events(pid=pid)
        meta += m
        events += e
        pid += 1
    for rec in recorders:
        m, e = rec.chrome_events(pid=pid)
        meta += m
        events += e
        pid += 1
    return (
        {"traceEvents": meta + events, "displayTimeUnit": "ms"},
        len(events),
    )


def dump_merged_chrome_trace(
    path, *, tracers=(), recorders=()
) -> int:
    """Merge pool tracers and span recorders into ONE Chrome trace.

    ``tracers``: :class:`~..utils.trace.EpochTracer` instances (each
    becomes a "pool" process with its worker/coordinator tracks);
    ``recorders``: :class:`SpanRecorder` instances (scheduler ticks,
    training steps, ...). Every source gets its own Chrome pid, all on
    the shared perf_counter clock. Returns the number of non-metadata
    events written. Open the file in ui.perfetto.dev (or
    chrome://tracing).
    """
    doc, n = merged_chrome_trace(tracers=tracers, recorders=recorders)
    with open(path, "w") as f:
        # span args are arbitrary user objects; degrade to repr rather
        # than refuse the whole trace over one value
        json.dump(doc, f, default=repr)
    return n


@contextmanager
def annotate(name: str):
    """``jax.profiler.TraceAnnotation`` when jax's profiler is
    importable, a no-op otherwise — instrumented device code (the
    serving decode scan, a coded train step) shows up inside
    ``jax.profiler.trace`` captures on real chips while CPU CI and
    numpy-only installs run the identical path."""
    try:
        from jax.profiler import TraceAnnotation
    except Exception:  # jax absent or profiler unavailable
        yield
        return
    with TraceAnnotation(name):
        yield
