"""Runtime observability: registry, timeline, and the live telemetry plane.

The subsystem the reference never had (its only telemetry is the
per-worker ``pool.latency`` field — SURVEY §5 "Metrics / logging:
absent"), in two halves:

**In-process** (PR 2): :mod:`.metrics` is a zero-dependency,
thread-safe series store (counters, gauges, fixed log-bucket
histograms) with JSON and Prometheus text exports; :mod:`.timeline`
records host-side spans (scheduler ticks, training steps) and merges
them with :class:`~..utils.trace.EpochTracer` pool timelines into one
Chrome/Perfetto trace.

**Distributed + live** (this PR): :mod:`.export` serves the registry,
health checks, the merged timeline, and the flight ring over HTTP
(:class:`ObsServer` — ``/metrics``, ``/healthz``, ``/trace``,
``/flight``); :mod:`.aggregate` merges worker-process telemetry into
the coordinator registry under ``worker="<rank>"`` labels with
counter-delta semantics across respawns and clock-aligned spans;
:mod:`.flight` keeps a bounded ring of recent spans/events/counter
deltas and dumps it automatically on watchdog stalls, pool deadline
expiries, and interpreter exit — the postmortem artifact for hangs.

**Request-scoped** (round 22): :mod:`.tracing` follows ONE request
through every serving plane it crosses as a flat list of typed
lifecycle events on the owner's clock (:class:`TraceBook` —
deterministic under sim replay, digest-neutral, one ``is None`` on
dark paths); :mod:`.audit` closes the loop with a conservation audit
(:func:`audit`) proving every submitted id resolved exactly once and
the books' arithmetic — tokens, pages, hedge legs, migration bytes —
matches the report and the metrics registry. :class:`ObsServer`
serves both: ``/trace/<id>`` waterfalls and ``/audit``.

**Windowed SLO plane** (round 24): :mod:`.series` derives bounded
ring-buffer time series from an attached registry on a caller-injected
clock (:class:`SeriesStore` — counter deltas as per-window rates,
gauge last-values, histogram bucket-delta windows so windowed p50/p99
come out of the fixed log grid; respawn-safe via the aggregate plane's
boot ids); :mod:`.slo` evaluates named objectives over those windows
with error-budget accounting, multi-window fast/slow burn-rate alerts,
and a per-tenant cost ledger (:class:`SloPolicy`, :class:`SloObjective`
— flight-stamped fire/clear, bit-identical under sim replay).
:class:`ObsServer` serves both: ``/series`` and ``/slo`` (503 while a
fast-burn alert fires).

Everything here is strictly OPT-IN, mirroring the tracer contract:
instrumented layers (``ServingScheduler``, ``CodedGradTrainer``,
``CodedGemm``, ``HedgedServer``, ``ProcessBackend``) accept
``registry=`` / ``spans=`` / ``exporter=`` / ``flight=`` and pay
nothing — no allocation, no clock reads — when none is passed (GC004
checks it statically). Stdlib-only at import: the package root's
jax-free import contract holds.
"""

from .aggregate import OBS_TAG, TelemetryAggregator, WorkerTelemetry
from .audit import AuditFailure, AuditResult, audit
from .export import HealthCheck, ObsServer
from .flight import FlightRecorder, FlightWatchdog
from .metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from .series import SeriesStore
from .slo import SloObjective, SloPolicy
from .timeline import (
    SpanRecorder,
    annotate,
    dump_merged_chrome_trace,
    merged_chrome_trace,
)
from .tracing import TERMINAL_KINDS, TraceBook

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "SpanRecorder",
    "annotate",
    "dump_merged_chrome_trace",
    "merged_chrome_trace",
    "ObsServer",
    "HealthCheck",
    "TelemetryAggregator",
    "WorkerTelemetry",
    "OBS_TAG",
    "FlightRecorder",
    "FlightWatchdog",
    "SeriesStore",
    "SloObjective",
    "SloPolicy",
    "TraceBook",
    "TERMINAL_KINDS",
    "audit",
    "AuditResult",
    "AuditFailure",
]
