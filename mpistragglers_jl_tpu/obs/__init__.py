"""Runtime observability: metrics registry + unified Perfetto timeline.

The subsystem the reference never had (its only telemetry is the
per-worker ``pool.latency`` field — SURVEY §5 "Metrics / logging:
absent") and the tracer alone does not cover: :mod:`.metrics` is a
zero-dependency, thread-safe series store (counters, gauges, fixed
log-bucket histograms) with JSON and Prometheus text exports;
:mod:`.timeline` records host-side spans (scheduler ticks, training
steps) and merges them with :class:`~..utils.trace.EpochTracer` pool
timelines into one Chrome/Perfetto trace.

Everything here is strictly OPT-IN, mirroring the tracer contract:
instrumented layers (``ServingScheduler``, ``CodedGradTrainer``,
``CodedGemm``, ``HedgedServer``) accept ``registry=``/``spans=`` and
pay nothing — no allocation, no clock reads — when neither is passed.
Stdlib-only at import: the package root's jax-free import contract
holds.
"""

from .metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from .timeline import SpanRecorder, annotate, dump_merged_chrome_trace

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "SpanRecorder",
    "annotate",
    "dump_merged_chrome_trace",
]
