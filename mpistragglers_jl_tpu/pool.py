"""Core pool state machine and async scatter/gather with fastest-k return.

This module is the TPU-native re-design of the reference library's entire L2
layer (reference: src/MPIAsyncPools.jl:24-224). The behavioral contract is
identical — per-worker epoch bookkeeping, fastest-k or predicate-driven
return, stale-result harvesting with immediate re-tasking, and a quiescence
barrier — but the transport is abstracted behind a :class:`Backend` (the
analog of the ``comm: MPI.Comm`` argument, reference src/MPIAsyncPools.jl:68)
so the same pool drives thread workers, single-host XLA devices, or a
multi-chip TPU mesh.

Key design departures from the reference, all TPU-motivated:

* **No caller-managed ``isendbuf``/``irecvbuf``.** The reference needs a
  private per-worker snapshot of ``sendbuf`` so in-flight MPI sends survive
  caller mutation (src/MPIAsyncPools.jl:63-66, :130). JAX arrays are
  immutable and ``jax.device_put`` snapshots by construction, so the
  snapshot discipline is owned by the backend, not the caller.
* **Results may stay on device.** ``recvbuf`` is optional; when omitted,
  per-worker results are retained as (possibly device-resident) arrays in
  ``pool.results`` so a decode/combine step can consume them without a
  host round-trip. When provided, ``recvbuf`` is byte-partitioned into
  ``n_workers`` equal chunks exactly like ``MPI.Gather!`` over the
  reference's ``reinterpret(UInt8, ...)`` views (src/MPIAsyncPools.jl:58-61,
  :80-84) and arrivals are *bit-copied* into their chunk — payload-
  agnostic and never value-cast, so mixed-dtype and structured payloads
  round-trip exactly.
* **The hot wait loop** (reference ``MPI.Waitany!``, src/MPIAsyncPools.jl:161)
  becomes host-side polling of per-dispatch completion events / JAX array
  readiness — see backends.

State-machine invariants preserved exactly (reference §2.1 semantics):

* each ``asyncmap`` call *is* an epoch; default ``epoch = pool.epoch + 1``
  but any value may be passed (src/MPIAsyncPools.jl:68, :87);
* ``repochs[i] == epoch0`` means "never heard from worker i"
  (src/MPIAsyncPools.jl:39; test/kmap2.jl:42-44);
* with integer ``nwait``, only phase-3 arrivals stamped with the *current*
  epoch count toward completion (src/MPIAsyncPools.jl:173-176); stale
  arrivals are written to ``recvbuf``, stamped in ``repochs``, and the
  worker is immediately re-tasked with the current payload and stays
  active (src/MPIAsyncPools.jl:177-184);
* a functional ``nwait`` is evaluated as ``nwait(epoch, repochs)`` before
  the first wait and after every arrival, over the live ``repochs``
  (src/MPIAsyncPools.jl:148-158);
* after ``waitall``, no worker is active (src/MPIAsyncPools.jl:193).
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Callable, Sequence, Union

import numpy as np

from .backends.base import (  # noqa: F401  (DeadWorkerError re-export)
    Backend,
    Deadline,
    DeadWorkerError,
    WorkerError,
)

if TYPE_CHECKING:  # runtime import would be circular (utils -> pool)
    from .obs.flight import FlightRecorder
    from .utils.trace import EpochTracer

NwaitArg = Union[int, Callable[[int, np.ndarray], bool]]

__all__ = ["AsyncPool", "asyncmap", "asyncmap_fused", "waitall"]


class AsyncPool:
    """Bookkeeping for a pool of potentially-straggling workers.

    Mirrors the reference ``MPIAsyncPool`` struct field-for-field
    (src/MPIAsyncPools.jl:24-46) minus the MPI request handles, which live
    in the backend:

    ``ranks``        worker ids managed by the pool; pool index ``i`` maps
                     to ``ranks[i]`` and ``recvbuf`` chunk order is *pool*
                     order, not rank order.
    ``sepochs[i]``   epoch at which the in-flight dispatch to worker ``i``
                     was initiated.
    ``stags[i]``     tag the in-flight dispatch was posted with — the
                     analog of an MPI request remembering its tag, so
                     harvests probe the right backend channel even when
                     pools multiplex one backend on distinct tags
                     (reference convention: test/kmap2.jl:11-12).
    ``repochs[i]``   epoch of the most recently received result — the
                     freshness oracle returned to callers.
    ``active[i]``    True iff worker ``i`` has an outstanding task.
    ``stimestamps``  perf-counter ns at dispatch.
    ``latency[i]``   last measured round-trip seconds per worker.
    ``nwait``        default wait-count (ctor kwarg, default ``n``).
    ``epoch``        current epoch, starts at ``epoch0``.

    Construction performs no communication; the pool is lazy like the
    reference (first backend activity happens inside the first
    ``asyncmap`` — reference §3.4).
    """

    def __init__(
        self,
        ranks: Union[int, Sequence[int]],
        *,
        epoch0: int = 0,
        nwait: int | None = None,
    ):
        if isinstance(ranks, (int, np.integer)):
            # convenience form, reference src/MPIAsyncPools.jl:46 (ranks 1:n
            # there; 0-based 0:n here, idiomatic for device indices)
            ranks = list(range(int(ranks)))
        self.ranks: list[int] = [int(r) for r in ranks]
        if len(set(self.ranks)) != len(self.ranks):
            raise ValueError(f"ranks must be unique, got {self.ranks}")
        if self.ranks and min(self.ranks) < 0:
            raise ValueError(f"ranks must be >= 0, got {self.ranks}")
        # pool index <-> backend rank: every backend call below routes
        # through ranks[i], so a pool over a rank SUBSET of a shared
        # backend addresses exactly those workers (reference
        # src/MPIAsyncPools.jl:21 `MPIAsyncPool([1,4,5])`, routed at
        # :137-138 — the pool sends to ranks[i], not to i)
        self._idx_of_rank = {r: j for j, r in enumerate(self.ranks)}
        n = len(self.ranks)
        if nwait is None:
            nwait = n
        if not (0 <= int(nwait) <= n):
            raise ValueError(f"default nwait must be in [0, {n}], got {nwait}")
        self.sepochs = np.full(n, epoch0, dtype=np.int64)
        self.stags = np.zeros(n, dtype=np.int64)
        self.repochs = np.full(n, epoch0, dtype=np.int64)
        self.active = np.zeros(n, dtype=bool)
        self.stimestamps = np.zeros(n, dtype=np.int64)
        self.latency = np.zeros(n, dtype=np.float64)
        self.nwait = int(nwait)
        self.epoch = int(epoch0)
        self.epoch0 = int(epoch0)
        # most recently received result object per worker (device array or
        # ndarray); None = never received. TPU-native addition: lets decode
        # steps consume device-resident shards without a recvbuf copy.
        self.results: list = [None] * n

    @property
    def n_workers(self) -> int:
        return len(self.ranks)

    def fresh_indices(self, epoch: int | None = None) -> np.ndarray:
        """Workers whose latest *stored* result is from ``epoch``
        (default: the current one) — the decode-selection mask.

        ``repochs[i] == epoch`` alone is not sufficient: at
        ``epoch == epoch0`` it also matches workers never heard from
        (``repochs`` initializes to ``epoch0``, reference
        src/MPIAsyncPools.jl:39), whose ``results[i]`` is still None.
        Every coded workload selects shards through this method so that
        invariant lives in one place.
        """
        if epoch is None:
            epoch = self.epoch
        heard = np.array(
            [r is not None for r in self.results], dtype=bool
        )
        return np.flatnonzero((self.repochs == epoch) & heard)

    def reset_worker(self, i: int) -> None:
        """Elastic-recovery hook: forget worker ``i``'s in-flight task.

        Use after a dead rank rejoins (``backend.reaccept``/``respawn``
        under ``on_dead="straggle"``): the old incarnation's dispatch
        can never complete, so the worker must be marked idle to become
        dispatchable next epoch. ``repochs`` keeps its last truthful
        value — the rank is simply stale until it answers again.
        """
        self.active[int(i)] = False

    def carry(self, ranks, *, nwait: int | None = None) -> "AsyncPool":
        """Elastic-resize hook: a NEW pool over ``ranks`` carrying this
        pool's epoch bookkeeping onto the resized rank set (the
        fleet-controller pair of :meth:`reset_worker`).

        Surviving ranks keep their ``sepochs``/``stags``/``repochs``/
        ``active``/``latency``/``results`` — an in-flight dispatch to a
        kept rank stays harvestable by the same backend on the same tag
        channel. Joining ranks initialize never-heard-from (``repochs
        == epoch0``): stale until they answer, exactly like a respawned
        rank under ``reset_worker``. Dropped ranks' state leaves with
        them (their worker processes are being reaped). ``nwait``
        defaults to the old value clamped into the new pool's range —
        pass it explicitly when the resize changes the decodability
        floor (the fleet controller re-derives it via
        ``sweep_hierarchical``).
        """
        if isinstance(ranks, (int, np.integer)):
            ranks = list(range(int(ranks)))
        ranks = [int(r) for r in ranks]
        new = AsyncPool(
            ranks,
            epoch0=self.epoch0,
            nwait=(
                min(self.nwait, len(ranks)) if nwait is None else nwait
            ),
        )
        new.epoch = self.epoch
        for j, r in enumerate(new.ranks):
            i = self._idx_of_rank.get(r)
            if i is None:
                continue
            new.sepochs[j] = self.sepochs[i]
            new.stags[j] = self.stags[i]
            new.repochs[j] = self.repochs[i]
            new.active[j] = self.active[i]
            new.stimestamps[j] = self.stimestamps[i]
            new.latency[j] = self.latency[i]
            new.results[j] = self.results[i]
        return new

    def __repr__(self) -> str:
        return (
            f"AsyncPool(n={self.n_workers}, epoch={self.epoch}, "
            f"nwait={self.nwait}, active={int(self.active.sum())})"
        )


def _recv_chunks(recvbuf: np.ndarray | None, n: int) -> list[np.ndarray] | None:
    """Partition ``recvbuf`` into n equal *byte* chunks, ``MPI.Gather!``
    layout.

    Reference parity: the reference type-erases every caller buffer via
    ``reinterpret(UInt8, ...)`` and slices bytes
    (src/MPIAsyncPools.jl:80-84, :206-209), which makes a pool
    payload-agnostic — mixed dtypes, structured records, anything with a
    fixed byte layout round-trips bit-exactly. Arrivals are **bit-copied**
    into their chunk (never value-cast): a worker result whose byte size
    doesn't fill the chunk is an error, not a silent ``astype``.
    """
    if recvbuf is None:
        return None
    if not isinstance(recvbuf, np.ndarray):
        raise TypeError("recvbuf must be a numpy ndarray (host gather arena)")
    if recvbuf.dtype.hasobject:
        raise TypeError("recvbuf eltype must be a fixed-size dtype")
    if not recvbuf.flags.c_contiguous:
        # a non-contiguous buffer cannot be byte-viewed; silently
        # reshaping would write into a copy the caller never sees
        raise ValueError("recvbuf must be C-contiguous")
    if recvbuf.nbytes % n != 0:
        # reference src/MPIAsyncPools.jl:77 (length % n there; bytes
        # here, since chunks are byte spans)
        raise ValueError(
            f"recvbuf ({recvbuf.nbytes} bytes) must partition evenly "
            f"into {n} worker chunks"
        )
    flat = recvbuf.reshape(-1).view(np.uint8)
    rl = recvbuf.nbytes // n
    return [flat[i * rl : (i + 1) * rl] for i in range(n)]


def _store(
    pool: AsyncPool, i: int, result, recvbufs: list[np.ndarray] | None
) -> None:
    """Harvest one arrival: latency, result store, epoch stamp.

    Reference: the arrival block repeated at src/MPIAsyncPools.jl:104-110,
    :163-168 and :215-218.
    """
    pool.latency[i] = (time.perf_counter_ns() - pool.stimestamps[i]) / 1e9
    if isinstance(result, WorkerError):
        # keep the pool recoverable: the backend slot is already consumed,
        # so mark the worker idle (re-dispatchable next epoch) and leave
        # repochs unstamped (nothing useful arrived) before raising
        pool.active[i] = False
        result.raise_()
    pool.results[i] = result
    pool.repochs[i] = pool.sepochs[i]
    if recvbufs is not None:
        chunk = recvbufs[i]
        arr = np.ascontiguousarray(result)
        if arr.nbytes != chunk.nbytes:
            # the arrival is real (results/repochs above reflect it) and
            # the backend slot is consumed — mark the worker idle like the
            # WorkerError path, or a later waitall blocks forever on a
            # completion that was already taken
            pool.active[i] = False
            raise ValueError(
                f"worker {i} returned {arr.nbytes} bytes "
                f"({arr.size} x {arr.dtype}) but recvbuf chunks hold "
                f"{chunk.nbytes} bytes; the pool bit-copies (reference "
                "src/MPIAsyncPools.jl:80-84) — match the recvbuf dtype "
                "width to the worker result, it is never value-cast"
            )
        chunk[:] = arr.reshape(-1).view(np.uint8)


def _dispatch(pool: AsyncPool, backend: Backend, i: int, sendbuf, tag: int) -> None:
    """Dispatch current payload to worker i and mark it active.

    Reference: dispatch block at src/MPIAsyncPools.jl:126-138 (and the
    re-task copy at :178-183). The payload snapshot the reference does via
    ``isendbufs[i] .= sendbuf`` (:130) is the backend's responsibility here.
    """
    pool.sepochs[i] = pool.epoch
    pool.stags[i] = int(tag)
    pool.stimestamps[i] = time.perf_counter_ns()
    backend.dispatch(pool.ranks[i], sendbuf, pool.epoch, tag=tag)
    # only after the backend accepted the task: a failed dispatch must not
    # leave pool.active[i] pointing at a slot the backend never opened
    # (waitall would then block on a completion that can never come)
    pool.active[i] = True


def asyncmap(
    pool: AsyncPool,
    sendbuf,
    backend: Backend,
    recvbuf: np.ndarray | None = None,
    *,
    nwait: NwaitArg | None = None,
    epoch: int | None = None,
    tag: int = 0,
    timeout: float | None = None,
    tracer: "EpochTracer | None" = None,
    flight: "FlightRecorder | None" = None,
) -> np.ndarray:
    """Broadcast ``sendbuf`` to all idle workers; wait for the fastest few.

    Dispatches the payload asynchronously to every idle worker, then blocks
    until either ``nwait`` workers have responded with results *from this
    epoch* (integer ``nwait``) or ``nwait(epoch, repochs)`` evaluates True
    (callable ``nwait``). Late results from earlier epochs are harvested
    opportunistically; workers that return stale results are immediately
    re-tasked with the current payload. Returns ``pool.repochs`` — the
    per-worker freshness mask (``repochs[i] == epoch`` iff chunk ``i`` is
    from this epoch), which is exactly the arrival mask an erasure decoder
    needs to select any-k-of-n shards.

    Reference semantics: ``Base.asyncmap!`` at src/MPIAsyncPools.jl:68-188;
    docstring contract :48-67; the returned array aliases ``pool.repochs``
    like the reference (:187) — callers must copy if they retain it across
    epochs (test/kmap2.jl relies on reading it before the next call).

    ``timeout`` (seconds, new capability — the reference's phase-3
    ``Waitany!`` blocks forever when ``nwait`` is unsatisfiable): bounds
    the whole call; on expiry a :class:`DeadWorkerError` names the
    workers still outstanding. The pool stays usable — tardy workers
    remain active and their late results are drained by later calls.

    ``flight`` (an :class:`~.obs.FlightRecorder`, opt-in like
    ``tracer``): the call records one epoch span + fresh/stale arrival
    counter deltas into the bounded postmortem ring, and a wait that
    blows its deadline TRIPS an automatic flight dump before the
    :class:`DeadWorkerError` raises — the artifact for the hang exists
    even though nothing after the raise runs cleanly.
    """
    n = pool.n_workers
    if nwait is None:
        nwait = pool.nwait
    if epoch is None:
        epoch = pool.epoch + 1
    if isinstance(nwait, (int, np.integer)):
        if not (0 <= nwait <= n):
            # reference src/MPIAsyncPools.jl:71
            raise ValueError(f"nwait must be in [0, {n}], got {nwait}")
    elif not callable(nwait):
        # reference src/MPIAsyncPools.jl:157
        raise TypeError(f"nwait must be an int or callable, got {type(nwait)}")
    recvbufs = _recv_chunks(recvbuf, n)
    # ranks must be addressable backend slots — checked up front so a
    # subset pool misconfigured against a narrower backend fails with
    # the mapping spelled out, not an IndexError inside the transport
    bn = getattr(backend, "n_workers", None)
    if bn is not None and n and max(pool.ranks) >= bn:
        raise ValueError(
            f"pool.ranks {pool.ranks} address workers beyond the "
            f"backend's {bn} slots; the pool routes pool index i to "
            "backend worker ranks[i] (reference src/MPIAsyncPools.jl:21)"
        )
    # fail BEFORE any dispatch, like the reference's cross-buffer sizeof
    # checks (src/MPIAsyncPools.jl:72-76): an active worker's in-flight
    # result will be harvested into this recvbuf (stale arrivals are
    # written too, reference :167), so a chunk that can't hold what that
    # worker last produced is caught here, not mid-epoch at harvest.
    if recvbufs is not None:
        for i in np.flatnonzero(pool.active):
            nb = getattr(pool.results[i], "nbytes", None)
            if nb is not None and nb != recvbufs[i].nbytes:
                raise ValueError(
                    f"recvbuf chunks hold {recvbufs[i].nbytes} bytes but "
                    f"in-flight worker {int(i)} last produced {nb} bytes; "
                    "size the recvbuf before dispatching"
                )

    # each call to asyncmap is the start of a new epoch
    # (reference src/MPIAsyncPools.jl:87)
    pool.epoch = int(epoch)
    backend.begin_epoch(pool.epoch)
    if tracer is not None:
        tracer.begin("asyncmap", pool.epoch, nwait)
    _t_fl = time.perf_counter() if flight is not None else 0.0
    _n_fresh = _n_stale = 0

    # the finally clause flushes the open trace record even when a
    # WorkerFailure or buffer-size error aborts the call — failure traces
    # are the ones worth keeping
    try:
        # PHASE 1 — opportunistic, non-blocking drain of results that
        # arrived since the last call, to keep iterations independent
        # (reference src/MPIAsyncPools.jl:91-114).
        for i in range(n):
            if not pool.active[i]:
                continue
            result = backend.test(pool.ranks[i], tag=int(pool.stags[i]))
            if result is None:
                continue
            _store(pool, i, result, recvbufs)
            pool.active[i] = False
            if tracer is not None:
                tracer.arrival(
                    i, pool.repochs[i],
                    fresh=pool.repochs[i] == pool.epoch, drain=True,
                )

        # PHASE 2 — dispatch to every idle worker; all workers are active
        # after this loop (reference src/MPIAsyncPools.jl:118-139).
        for i in range(n):
            if pool.active[i]:
                continue
            _dispatch(pool, backend, i, sendbuf, tag)
            if tracer is not None:
                tracer.dispatch(i, pool.epoch)

        # coalescing backends submit buffered dispatches now, in one
        # program per device (no-op elsewhere)
        backend.flush()

        # PHASE 3 — collect until satisfied: the hot loop
        # (reference src/MPIAsyncPools.jl:145-185). Only arrivals stamped
        # with the current epoch count toward integer-nwait completion;
        # stale arrivals trigger an immediate re-task and the worker
        # stays active.
        deadline = Deadline(timeout)
        nrecv = 0
        while True:
            if callable(nwait):
                if bool(nwait(pool.epoch, pool.repochs)):
                    break
            else:
                if nrecv >= nwait:
                    break
            # block until any active worker responds
            # (reference MPI.Waitany! at src/MPIAsyncPools.jl:161)
            act = np.flatnonzero(pool.active)
            got = backend.wait_any(
                [pool.ranks[j] for j in act],
                timeout=deadline.remaining(),
                tags=pool.stags[act],
            )
            if got is None:
                # Report backend ranks, not pool indices: a subset pool
                # over ranks [1,4,5] must name the dead worker as 4, not
                # the misleading pool-local 1 (advisor r3 finding).
                dead = [
                    int(pool.ranks[j]) for j in np.flatnonzero(pool.active)
                ]
                if flight is not None:
                    # the hang postmortem: dump the ring NOW — nothing
                    # after this raise is guaranteed to run
                    flight.trip(
                        f"asyncmap epoch {pool.epoch}: wait past "
                        f"deadline ({timeout}s), workers {dead} "
                        "outstanding"
                    )
                raise DeadWorkerError(dead, timeout)
            rank, result = got
            i = pool._idx_of_rank[rank]
            _store(pool, i, result, recvbufs)
            fresh = pool.repochs[i] == pool.epoch
            if tracer is not None:
                tracer.arrival(i, pool.repochs[i], fresh=fresh)
            if fresh:
                nrecv += 1
                _n_fresh += 1
                pool.active[i] = False
            else:
                _n_stale += 1
                _dispatch(pool, backend, i, sendbuf, tag)
                if tracer is not None:
                    tracer.dispatch(i, pool.epoch, retask=True)
    finally:
        backend.end_epoch()
        if tracer is not None:
            tracer.end(pool)
        if flight is not None:
            flight.span(
                f"asyncmap {pool.epoch}", _t_fl,
                time.perf_counter() - _t_fl,
                track="pool", fresh=_n_fresh, stale=_n_stale,
            )
            # cumulative across the pool's life -> the ring stores the
            # per-record delta (how much moved since the last record)
            flight.counter("pool_epochs_total", pool.epoch - pool.epoch0)
    return pool.repochs


def asyncmap_fused(
    pool: AsyncPool,
    sendbuf,
    coordinator,
    *,
    epochs: int,
) -> np.ndarray:
    """K epochs of :func:`asyncmap` as ONE compiled device program —
    the host stages inputs and harvests every ``epochs`` epochs
    instead of re-entering the interpreter per epoch (ROADMAP item 4;
    the numba-mpi frame: no interpreter on the critical path).

    ``coordinator`` is a :class:`~.parallel.device_coord.
    DeviceCoordinator` (duck-typed here — this module stays jax-free,
    GC001): it owns the fused program, the per-worker coded blocks,
    the ``nwait`` policy, and the injected-delay schedule. ``repochs``
    semantics are preserved exactly — the returned ``(epochs, n)``
    HISTORY's row ``j`` is bit-for-bit what the host loop's epoch
    ``pool.epoch + 1 + j`` call would have returned on the same
    schedule (under x64; see parallel/device_coord.py's fidelity
    caveats), stale workers' shards masked by the on-device arrival
    mask exactly as this file's loop masks them, and the pool leaves
    the window in the host loop's end state (``epoch``, ``repochs``,
    ``sepochs``, ``active``; in-flight workers carry into the next
    window). Unlike :func:`asyncmap` the return value does NOT alias
    ``pool.repochs`` — the history is the caller's to keep.

    Host-loop-only capabilities a compiled window cannot express —
    ``timeout=``/``DeadWorkerError``, ``tracer=``, callable ``nwait``
    beyond the built-in hierarchical rule, a ``recvbuf`` bit-copy per
    epoch — stay with :func:`asyncmap`; decoded products are harvested
    from ``coordinator.last_decoded`` instead.
    """
    return coordinator.run_window(pool, sendbuf, epochs=epochs)


def waitall(
    pool: AsyncPool,
    backend: Backend,
    recvbuf: np.ndarray | None = None,
    *,
    timeout: float | None = None,
    tracer: "EpochTracer | None" = None,
    flight: "FlightRecorder | None" = None,
) -> np.ndarray:
    """Drain the pool: block until every active worker has responded.

    All workers are inactive on return (reference ``waitall!`` at
    src/MPIAsyncPools.jl:190-224; quiescence asserted in test/kmap2.jl:60).

    ``timeout`` (seconds) is a new capability the reference lacks (its
    ``MPI.Waitall!`` would hang forever on a dead worker — SURVEY §5):
    if the pool does not quiesce in time, a :class:`DeadWorkerError` is
    raised naming the workers that never responded.
    """
    n = pool.n_workers
    recvbufs = _recv_chunks(recvbuf, n)
    backend.flush()  # direct-dispatch users may drain without asyncmap
    if not pool.active.any():
        return pool.repochs
    if tracer is not None:
        # nwait field = number of workers actually being drained
        tracer.begin("waitall", pool.epoch, int(pool.active.sum()))
    _t_fl = time.perf_counter() if flight is not None else 0.0
    try:
        deadline = Deadline(timeout)
        while pool.active.any():
            # harvest in ARRIVAL order, not index order: waiting on worker
            # 0 first would charge its wait time to workers 1..n-1's
            # ``latency`` stamps (the reference shares this flaw — its
            # ``Waitall!`` at src/MPIAsyncPools.jl:212 completes all
            # requests before any timestamping; utils/straggle.py fits
            # latency models to these numbers, so they must be true
            # per-worker round-trip times)
            act = np.flatnonzero(pool.active)
            got = backend.wait_any(
                [pool.ranks[j] for j in act],
                timeout=deadline.remaining(),
                tags=pool.stags[act],
            )
            if got is None:
                # Translated backend ranks, as in asyncmap above.
                dead = [
                    int(pool.ranks[j]) for j in np.flatnonzero(pool.active)
                ]
                if flight is not None:
                    flight.trip(
                        f"waitall at epoch {pool.epoch}: drain past "
                        f"deadline ({timeout}s), workers {dead} "
                        "outstanding"
                    )
                raise DeadWorkerError(dead, timeout)
            rank, result = got
            i = pool._idx_of_rank[rank]
            _store(pool, i, result, recvbufs)
            pool.active[i] = False
            if tracer is not None:
                tracer.arrival(
                    i, pool.repochs[i], fresh=pool.repochs[i] == pool.epoch
                )
    finally:
        if tracer is not None:
            tracer.end(pool)
        if flight is not None:
            flight.span(
                f"waitall {pool.epoch}", _t_fl,
                time.perf_counter() - _t_fl, track="pool",
            )
    return pool.repochs


# DeadWorkerError lives beside the Backend contract (backends/base.py) —
# straggle-mode backends raise it too, and backends must not import the
# orchestration layer above them. Re-exported here (imported at the top)
# because asyncmap/waitall are its primary raisers and callers import it
# from the pool.
