"""Core pool state machine and async scatter/gather with fastest-k return.

This module is the TPU-native re-design of the reference library's entire L2
layer (reference: src/MPIAsyncPools.jl:24-224). The behavioral contract is
identical — per-worker epoch bookkeeping, fastest-k or predicate-driven
return, stale-result harvesting with immediate re-tasking, and a quiescence
barrier — but the transport is abstracted behind a :class:`Backend` (the
analog of the ``comm: MPI.Comm`` argument, reference src/MPIAsyncPools.jl:68)
so the same pool drives thread workers, single-host XLA devices, or a
multi-chip TPU mesh.

Key design departures from the reference, all TPU-motivated:

* **No caller-managed ``isendbuf``/``irecvbuf``.** The reference needs a
  private per-worker snapshot of ``sendbuf`` so in-flight MPI sends survive
  caller mutation (src/MPIAsyncPools.jl:63-66, :130). JAX arrays are
  immutable and ``jax.device_put`` snapshots by construction, so the
  snapshot discipline is owned by the backend, not the caller.
* **Results may stay on device.** ``recvbuf`` is optional; when omitted,
  per-worker results are retained as (possibly device-resident) arrays in
  ``pool.results`` so a decode/combine step can consume them without a
  host round-trip. When provided, ``recvbuf`` is partitioned into
  ``n_workers`` equal chunks exactly like ``MPI.Gather!``
  (src/MPIAsyncPools.jl:58-61) and arrivals are copied into their chunk.
* **The hot wait loop** (reference ``MPI.Waitany!``, src/MPIAsyncPools.jl:161)
  becomes host-side polling of per-dispatch completion events / JAX array
  readiness — see backends.

State-machine invariants preserved exactly (reference §2.1 semantics):

* each ``asyncmap`` call *is* an epoch; default ``epoch = pool.epoch + 1``
  but any value may be passed (src/MPIAsyncPools.jl:68, :87);
* ``repochs[i] == epoch0`` means "never heard from worker i"
  (src/MPIAsyncPools.jl:39; test/kmap2.jl:42-44);
* with integer ``nwait``, only phase-3 arrivals stamped with the *current*
  epoch count toward completion (src/MPIAsyncPools.jl:173-176); stale
  arrivals are written to ``recvbuf``, stamped in ``repochs``, and the
  worker is immediately re-tasked with the current payload and stays
  active (src/MPIAsyncPools.jl:177-184);
* a functional ``nwait`` is evaluated as ``nwait(epoch, repochs)`` before
  the first wait and after every arrival, over the live ``repochs``
  (src/MPIAsyncPools.jl:148-158);
* after ``waitall``, no worker is active (src/MPIAsyncPools.jl:193).
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Callable, Sequence, Union

import numpy as np

from .backends.base import Backend, Deadline, WorkerError

if TYPE_CHECKING:  # runtime import would be circular (utils -> pool)
    from .utils.trace import EpochTracer

NwaitArg = Union[int, Callable[[int, np.ndarray], bool]]

__all__ = ["AsyncPool", "asyncmap", "waitall"]


class AsyncPool:
    """Bookkeeping for a pool of potentially-straggling workers.

    Mirrors the reference ``MPIAsyncPool`` struct field-for-field
    (src/MPIAsyncPools.jl:24-46) minus the MPI request handles, which live
    in the backend:

    ``ranks``        worker ids managed by the pool; pool index ``i`` maps
                     to ``ranks[i]`` and ``recvbuf`` chunk order is *pool*
                     order, not rank order.
    ``sepochs[i]``   epoch at which the in-flight dispatch to worker ``i``
                     was initiated.
    ``repochs[i]``   epoch of the most recently received result — the
                     freshness oracle returned to callers.
    ``active[i]``    True iff worker ``i`` has an outstanding task.
    ``stimestamps``  perf-counter ns at dispatch.
    ``latency[i]``   last measured round-trip seconds per worker.
    ``nwait``        default wait-count (ctor kwarg, default ``n``).
    ``epoch``        current epoch, starts at ``epoch0``.

    Construction performs no communication; the pool is lazy like the
    reference (first backend activity happens inside the first
    ``asyncmap`` — reference §3.4).
    """

    def __init__(
        self,
        ranks: Union[int, Sequence[int]],
        *,
        epoch0: int = 0,
        nwait: int | None = None,
    ):
        if isinstance(ranks, (int, np.integer)):
            # convenience form, reference src/MPIAsyncPools.jl:46 (ranks 1:n
            # there; 0-based 0:n here, idiomatic for device indices)
            ranks = list(range(int(ranks)))
        self.ranks: list[int] = [int(r) for r in ranks]
        if len(set(self.ranks)) != len(self.ranks):
            raise ValueError(f"ranks must be unique, got {self.ranks}")
        n = len(self.ranks)
        if nwait is None:
            nwait = n
        if not (0 <= int(nwait) <= n):
            raise ValueError(f"default nwait must be in [0, {n}], got {nwait}")
        self.sepochs = np.full(n, epoch0, dtype=np.int64)
        self.repochs = np.full(n, epoch0, dtype=np.int64)
        self.active = np.zeros(n, dtype=bool)
        self.stimestamps = np.zeros(n, dtype=np.int64)
        self.latency = np.zeros(n, dtype=np.float64)
        self.nwait = int(nwait)
        self.epoch = int(epoch0)
        self.epoch0 = int(epoch0)
        # most recently received result object per worker (device array or
        # ndarray); None = never received. TPU-native addition: lets decode
        # steps consume device-resident shards without a recvbuf copy.
        self.results: list = [None] * n

    @property
    def n_workers(self) -> int:
        return len(self.ranks)

    def fresh_indices(self, epoch: int | None = None) -> np.ndarray:
        """Workers whose latest *stored* result is from ``epoch``
        (default: the current one) — the decode-selection mask.

        ``repochs[i] == epoch`` alone is not sufficient: at
        ``epoch == epoch0`` it also matches workers never heard from
        (``repochs`` initializes to ``epoch0``, reference
        src/MPIAsyncPools.jl:39), whose ``results[i]`` is still None.
        Every coded workload selects shards through this method so that
        invariant lives in one place.
        """
        if epoch is None:
            epoch = self.epoch
        heard = np.array(
            [r is not None for r in self.results], dtype=bool
        )
        return np.flatnonzero((self.repochs == epoch) & heard)

    def __repr__(self) -> str:
        return (
            f"AsyncPool(n={self.n_workers}, epoch={self.epoch}, "
            f"nwait={self.nwait}, active={int(self.active.sum())})"
        )


def _recv_chunks(recvbuf: np.ndarray | None, n: int) -> list[np.ndarray] | None:
    """Partition ``recvbuf`` into n equal chunks, ``MPI.Gather!`` layout.

    Reference: byte-view partitioning at src/MPIAsyncPools.jl:80-84. We
    slice the flat element view rather than a byte reinterpretation; the
    chunk-j <- worker-j correspondence is the same.
    """
    if recvbuf is None:
        return None
    if not isinstance(recvbuf, np.ndarray):
        raise TypeError("recvbuf must be a numpy ndarray (host gather arena)")
    if recvbuf.dtype == object:
        raise TypeError("recvbuf eltype must be a fixed-size dtype")
    if recvbuf.size % n != 0:
        # reference src/MPIAsyncPools.jl:77
        raise ValueError(
            f"recvbuf length {recvbuf.size} must be a multiple of the "
            f"number of workers {n}"
        )
    flat = recvbuf.reshape(-1)
    rl = recvbuf.size // n
    return [flat[i * rl : (i + 1) * rl] for i in range(n)]


def _store(
    pool: AsyncPool, i: int, result, recvbufs: list[np.ndarray] | None
) -> None:
    """Harvest one arrival: latency, result store, epoch stamp.

    Reference: the arrival block repeated at src/MPIAsyncPools.jl:104-110,
    :163-168 and :215-218.
    """
    pool.latency[i] = (time.perf_counter_ns() - pool.stimestamps[i]) / 1e9
    if isinstance(result, WorkerError):
        # keep the pool recoverable: the backend slot is already consumed,
        # so mark the worker idle (re-dispatchable next epoch) and leave
        # repochs unstamped (nothing useful arrived) before raising
        pool.active[i] = False
        result.raise_()
    pool.results[i] = result
    if recvbufs is not None:
        chunk = recvbufs[i]
        arr = np.asarray(result).reshape(-1)
        if arr.size != chunk.size:
            raise ValueError(
                f"worker {i} returned {arr.size} elements but recvbuf "
                f"chunks hold {chunk.size}"
            )
        chunk[:] = arr.astype(chunk.dtype, copy=False)
    pool.repochs[i] = pool.sepochs[i]


def _dispatch(pool: AsyncPool, backend: Backend, i: int, sendbuf, tag: int) -> None:
    """Dispatch current payload to worker i and mark it active.

    Reference: dispatch block at src/MPIAsyncPools.jl:126-138 (and the
    re-task copy at :178-183). The payload snapshot the reference does via
    ``isendbufs[i] .= sendbuf`` (:130) is the backend's responsibility here.
    """
    pool.sepochs[i] = pool.epoch
    pool.stimestamps[i] = time.perf_counter_ns()
    backend.dispatch(i, sendbuf, pool.epoch, tag=tag)
    # only after the backend accepted the task: a failed dispatch must not
    # leave pool.active[i] pointing at a slot the backend never opened
    # (waitall would then block on a completion that can never come)
    pool.active[i] = True


def asyncmap(
    pool: AsyncPool,
    sendbuf,
    backend: Backend,
    recvbuf: np.ndarray | None = None,
    *,
    nwait: NwaitArg | None = None,
    epoch: int | None = None,
    tag: int = 0,
    timeout: float | None = None,
    tracer: "EpochTracer | None" = None,
) -> np.ndarray:
    """Broadcast ``sendbuf`` to all idle workers; wait for the fastest few.

    Dispatches the payload asynchronously to every idle worker, then blocks
    until either ``nwait`` workers have responded with results *from this
    epoch* (integer ``nwait``) or ``nwait(epoch, repochs)`` evaluates True
    (callable ``nwait``). Late results from earlier epochs are harvested
    opportunistically; workers that return stale results are immediately
    re-tasked with the current payload. Returns ``pool.repochs`` — the
    per-worker freshness mask (``repochs[i] == epoch`` iff chunk ``i`` is
    from this epoch), which is exactly the arrival mask an erasure decoder
    needs to select any-k-of-n shards.

    Reference semantics: ``Base.asyncmap!`` at src/MPIAsyncPools.jl:68-188;
    docstring contract :48-67; the returned array aliases ``pool.repochs``
    like the reference (:187) — callers must copy if they retain it across
    epochs (test/kmap2.jl relies on reading it before the next call).

    ``timeout`` (seconds, new capability — the reference's phase-3
    ``Waitany!`` blocks forever when ``nwait`` is unsatisfiable): bounds
    the whole call; on expiry a :class:`DeadWorkerError` names the
    workers still outstanding. The pool stays usable — tardy workers
    remain active and their late results are drained by later calls.
    """
    n = pool.n_workers
    if nwait is None:
        nwait = pool.nwait
    if epoch is None:
        epoch = pool.epoch + 1
    if isinstance(nwait, (int, np.integer)):
        if not (0 <= nwait <= n):
            # reference src/MPIAsyncPools.jl:71
            raise ValueError(f"nwait must be in [0, {n}], got {nwait}")
    elif not callable(nwait):
        # reference src/MPIAsyncPools.jl:157
        raise TypeError(f"nwait must be an int or callable, got {type(nwait)}")
    recvbufs = _recv_chunks(recvbuf, n)

    # each call to asyncmap is the start of a new epoch
    # (reference src/MPIAsyncPools.jl:87)
    pool.epoch = int(epoch)
    backend.begin_epoch(pool.epoch)
    if tracer is not None:
        tracer.begin("asyncmap", pool.epoch, nwait)

    # the finally clause flushes the open trace record even when a
    # WorkerFailure or buffer-size error aborts the call — failure traces
    # are the ones worth keeping
    try:
        # PHASE 1 — opportunistic, non-blocking drain of results that
        # arrived since the last call, to keep iterations independent
        # (reference src/MPIAsyncPools.jl:91-114).
        for i in range(n):
            if not pool.active[i]:
                continue
            result = backend.test(i)
            if result is None:
                continue
            _store(pool, i, result, recvbufs)
            pool.active[i] = False
            if tracer is not None:
                tracer.arrival(
                    i, pool.repochs[i],
                    fresh=pool.repochs[i] == pool.epoch, drain=True,
                )

        # PHASE 2 — dispatch to every idle worker; all workers are active
        # after this loop (reference src/MPIAsyncPools.jl:118-139).
        for i in range(n):
            if pool.active[i]:
                continue
            _dispatch(pool, backend, i, sendbuf, tag)
            if tracer is not None:
                tracer.dispatch(i, pool.epoch)

        # PHASE 3 — collect until satisfied: the hot loop
        # (reference src/MPIAsyncPools.jl:145-185). Only arrivals stamped
        # with the current epoch count toward integer-nwait completion;
        # stale arrivals trigger an immediate re-task and the worker
        # stays active.
        deadline = Deadline(timeout)
        nrecv = 0
        while True:
            if callable(nwait):
                if bool(nwait(pool.epoch, pool.repochs)):
                    break
            else:
                if nrecv >= nwait:
                    break
            # block until any active worker responds
            # (reference MPI.Waitany! at src/MPIAsyncPools.jl:161)
            got = backend.wait_any(
                np.flatnonzero(pool.active), timeout=deadline.remaining()
            )
            if got is None:
                raise DeadWorkerError(
                    [int(j) for j in np.flatnonzero(pool.active)], timeout
                )
            i, result = got
            _store(pool, i, result, recvbufs)
            fresh = pool.repochs[i] == pool.epoch
            if tracer is not None:
                tracer.arrival(i, pool.repochs[i], fresh=fresh)
            if fresh:
                nrecv += 1
                pool.active[i] = False
            else:
                _dispatch(pool, backend, i, sendbuf, tag)
                if tracer is not None:
                    tracer.dispatch(i, pool.epoch, retask=True)
    finally:
        backend.end_epoch()
        if tracer is not None:
            tracer.end(pool)
    return pool.repochs


def waitall(
    pool: AsyncPool,
    backend: Backend,
    recvbuf: np.ndarray | None = None,
    *,
    timeout: float | None = None,
    tracer: "EpochTracer | None" = None,
) -> np.ndarray:
    """Drain the pool: block until every active worker has responded.

    All workers are inactive on return (reference ``waitall!`` at
    src/MPIAsyncPools.jl:190-224; quiescence asserted in test/kmap2.jl:60).

    ``timeout`` (seconds) is a new capability the reference lacks (its
    ``MPI.Waitall!`` would hang forever on a dead worker — SURVEY §5):
    if the pool does not quiesce in time, a :class:`DeadWorkerError` is
    raised naming the workers that never responded.
    """
    n = pool.n_workers
    recvbufs = _recv_chunks(recvbuf, n)
    if not pool.active.any():
        return pool.repochs
    if tracer is not None:
        # nwait field = number of workers actually being drained
        tracer.begin("waitall", pool.epoch, int(pool.active.sum()))
    try:
        deadline = Deadline(timeout)
        while pool.active.any():
            # harvest in ARRIVAL order, not index order: waiting on worker
            # 0 first would charge its wait time to workers 1..n-1's
            # ``latency`` stamps (the reference shares this flaw — its
            # ``Waitall!`` at src/MPIAsyncPools.jl:212 completes all
            # requests before any timestamping; utils/straggle.py fits
            # latency models to these numbers, so they must be true
            # per-worker round-trip times)
            got = backend.wait_any(
                np.flatnonzero(pool.active), timeout=deadline.remaining()
            )
            if got is None:
                dead = [int(j) for j in np.flatnonzero(pool.active)]
                raise DeadWorkerError(dead, timeout)
            i, result = got
            _store(pool, i, result, recvbufs)
            pool.active[i] = False
            if tracer is not None:
                tracer.arrival(
                    i, pool.repochs[i], fresh=pool.repochs[i] == pool.epoch
                )
    finally:
        if tracer is not None:
            tracer.end(pool)
    return pool.repochs


class DeadWorkerError(TimeoutError):
    """Raised by :func:`asyncmap` (with ``timeout=``) and
    :func:`waitall` when workers fail to respond in time.

    The reference has no failure detection: a dead worker is
    indistinguishable from an infinite straggler and ``waitall!`` hangs
    (SURVEY §5 'Failure detection'). ``dead`` lists the pool indices that
    were still active at the deadline.
    """

    def __init__(self, dead: list[int], timeout: float | None):
        self.dead = dead
        self.timeout = timeout
        super().__init__(
            f"workers {dead} did not respond within {timeout} s"
        )
