"""Virtual time: an event-heap clock for discrete-event simulation.

Everything in this package that "waits" waits on a :class:`VirtualClock`
instead of the OS clock: time is a number that jumps straight to the
next interesting event, so a 10k-epoch straggling fleet simulates in
milliseconds of wall clock and two runs of the same scenario read the
exact same timestamps (bit-reproducible — there is no scheduler jitter
to race against, the failure mode that forced PRs 3 and 4 to widen
injected-straggler margins from 0.25 s to 1.5 s in the wall-clock
tests).

Two usage modes:

* **single-threaded discrete-event** (what :class:`~.backend.SimBackend`
  uses): the driver schedules events with :meth:`call_at` /
  :meth:`call_later` and advances with :meth:`run_until` /
  :meth:`advance`; ``now()`` is the only clock anybody reads.
* **thread rendezvous** (opt-in): real threads :meth:`register` with
  the clock and block in :meth:`sleep`; the driver's ``run_until``
  stops at every wake-up and refuses to move on until the woken
  thread has run its turn and parked in ``sleep`` again (or
  unregistered) — thread interleavings are replayed deterministically
  instead of raced. Declare the fleet size with :meth:`expect` BEFORE
  starting the threads so the driver cannot advance past a worker's
  first wake-up while the OS is still scheduling the thread.
  Registered threads must only block via :meth:`sleep` (a thread
  parked on a bare ``queue.get`` is invisible to the rendezvous and
  would stall it — the stall surfaces as a :class:`RuntimeError`
  after ``stall_timeout`` real seconds, never as a silent hang).
"""

from __future__ import annotations

import heapq
import threading
from typing import Callable

__all__ = ["VirtualClock"]


class VirtualClock:
    """Event-heap virtual time. ``now()`` starts at ``start`` and only
    moves when the driver advances it; ties fire in schedule order
    (the heap is keyed ``(time, seq)``), so arrival order is a pure
    function of the scenario."""

    def __init__(self, start: float = 0.0, *, stall_timeout: float = 30.0):
        self._now = float(start)
        self._seq = 0
        # scheduled callbacks: (fire_t, seq, callback | None) — None is
        # a bare timestamp the advance loop stops at and discards
        self._heap: list[tuple[float, int, Callable[[], None] | None]] = []
        self._cond = threading.Condition()
        # sleeping threads: seq -> wake time. Deliberately NOT heap
        # entries: a sleeper removes its own entry when it wakes (under
        # the lock), which is the acknowledgment the driver's advance
        # loop waits on — without it the driver could race past a
        # wake-up while the woken thread is still between sleeps.
        self._sleepers: dict[int, float] = {}
        self._threads: set[int] = set()  # registered thread idents
        self._blocked = 0  # registered threads currently in sleep()
        self._pending = 0  # expected registrations not yet arrived
        # real-seconds bound on rendezvous waits: a mis-parked thread
        # becomes a diagnosable error instead of a hung test run
        self.stall_timeout = float(stall_timeout)

    # -- reading ----------------------------------------------------------
    def now(self) -> float:
        """Current virtual time, seconds. Lock-free: attribute reads
        are GIL-atomic, and every ``_now`` write happens under
        ``self._cond``, whose release publishes it — ``now()`` sits on
        the simulator's hottest path (one read per dispatch/wait)."""
        return self._now

    def next_event(self) -> float | None:
        """Virtual time of the earliest pending event or sleeper
        wake-up (or None)."""
        with self._cond:
            return self._next_locked()

    def _next_locked(self) -> float | None:
        candidates = []
        if self._heap:
            candidates.append(self._heap[0][0])
        if self._sleepers:
            candidates.append(min(self._sleepers.values()))
        return min(candidates) if candidates else None

    # -- scheduling -------------------------------------------------------
    def call_at(self, t: float, fn: Callable[[], None] | None = None) -> None:
        """Schedule ``fn`` (may be None: a bare timestamp the advance
        loop will stop at) to fire when virtual time reaches ``t``.
        Times in the past fire at the current time, never backwards."""
        with self._cond:
            self._seq += 1
            heapq.heappush(
                self._heap, (max(float(t), self._now), self._seq, fn)
            )
            self._cond.notify_all()

    def call_later(
        self, delay: float, fn: Callable[[], None] | None = None
    ) -> None:
        with self._cond:
            self._seq += 1
            heapq.heappush(
                self._heap,
                (self._now + max(float(delay), 0.0), self._seq, fn),
            )
            self._cond.notify_all()

    # -- thread rendezvous ------------------------------------------------
    def expect(self, n: int) -> None:
        """Reserve ``n`` registrations: the driver will not advance
        time until that many threads have :meth:`register`-ed (and are
        sleeping). Closes the startup race where the driver advances
        past a worker's first wake-up before the worker thread has
        even been scheduled by the OS."""
        with self._cond:
            self._pending += int(n)
            self._cond.notify_all()

    def register(self) -> None:
        """Join the rendezvous: the calling thread promises to block
        only via :meth:`sleep`; the driver will not advance time while
        it is running between sleeps."""
        with self._cond:
            self._threads.add(threading.get_ident())
            self._pending = max(self._pending - 1, 0)
            self._cond.notify_all()

    def unregister(self) -> None:
        """Leave the rendezvous (call before the thread exits, or the
        driver waits ``stall_timeout`` for a sleep that never comes)."""
        with self._cond:
            self._threads.discard(threading.get_ident())
            self._cond.notify_all()

    def sleep(self, delay: float) -> None:
        """Block the calling thread until virtual time advances by
        ``delay``. From a registered thread this is the rendezvous
        point; the driver's advance loop supplies the wake-up and
        waits for this thread to park again before time moves on."""
        with self._cond:
            if float(delay) <= 0.0:
                return
            wake = self._now + float(delay)
            self._seq += 1
            seq = self._seq
            self._sleepers[seq] = wake
            registered = threading.get_ident() in self._threads
            if registered:
                self._blocked += 1
            self._cond.notify_all()
            try:
                ok = self._cond.wait_for(
                    lambda: self._now >= wake,
                    timeout=self.stall_timeout,
                )
                if not ok:
                    raise RuntimeError(
                        f"virtual sleep until t={wake:.6f} was never "
                        f"advanced past (now={self._now:.6f}); the "
                        "driver must run_until/advance the clock"
                    )
            finally:
                # removal under the SAME lock acquisition the wake-up
                # observed: this is the ack _wait_quiescent requires
                del self._sleepers[seq]
                if registered:
                    self._blocked -= 1
                self._cond.notify_all()

    def _wait_quiescent(self) -> None:
        """Driver-side: wait (real time) until every expected thread
        has registered, every registered thread is parked in
        :meth:`sleep`, and no sleeper's wake time has already passed
        without the sleeper acknowledging. Caller holds ``self._cond``."""

        def quiet() -> bool:
            return (
                self._pending == 0
                and self._blocked >= len(self._threads)
                and not any(w <= self._now for w in self._sleepers.values())
            )

        ok = self._cond.wait_for(quiet, timeout=self.stall_timeout)
        if not ok:
            raise RuntimeError(
                f"rendezvous stalled after {self.stall_timeout}s real "
                f"time: {self._pending} expected registration(s) "
                f"missing, {len(self._threads) - self._blocked} "
                "registered thread(s) neither sleeping nor unregistered"
            )

    # -- advancing --------------------------------------------------------
    def run_until(self, t: float) -> float:
        """Advance virtual time to ``t``, firing every event scheduled
        in between (in time order, schedule order on ties) and waking
        sleepers as their wake times pass. The loop stops at every
        wake-up until the woken thread has run and re-parked, so woken
        threads may schedule new, earlier events before time moves
        again. Returns the new ``now`` (== ``t``)."""
        t = float(t)
        # fast path for the dominant single-threaded discrete-event
        # case (SimBackend advancing to the next arrival): no
        # rendezvous participants and nothing scheduled before t means
        # one lock hold and a float write — the quiescence machinery
        # below exists for woken threads, of which there are none
        with self._cond:
            if (
                not self._threads
                and not self._sleepers
                and not self._pending
                and (not self._heap or self._heap[0][0] > t)
            ):
                self._now = max(self._now, t)
                return self._now
        while True:
            fn = None
            fired = False
            with self._cond:
                self._wait_quiescent()
                nxt = self._next_locked()
                if nxt is None or nxt > t:
                    self._now = max(self._now, t)
                    self._cond.notify_all()
                    return self._now
                if self._heap and self._heap[0][0] <= nxt:
                    when, _, fn = heapq.heappop(self._heap)
                    self._now = max(self._now, when)
                    fired = fn is not None
                else:
                    # a sleeper wake-up: advance to it and notify; the
                    # sleeper's own removal is the ack the next
                    # _wait_quiescent blocks on
                    self._now = max(self._now, nxt)
                self._cond.notify_all()
            if fired:
                fn()  # outside the lock: callbacks may re-schedule

    def advance(self, delay: float) -> float:
        """``run_until(now + delay)``."""
        return self.run_until(self.now() + max(float(delay), 0.0))

    def advance_next(self) -> float | None:
        """Advance to (and fire) the single earliest pending event;
        returns the new ``now``, or None when nothing is pending."""
        nxt = self.next_event()
        if nxt is None:
            return None
        return self.run_until(nxt)

    def run_all(self, *, max_events: int = 1_000_000) -> float:
        """Drain the event heap completely (bounded — a callback that
        perpetually re-schedules itself is a bug, not a simulation)."""
        for _ in range(max_events):
            if self.advance_next() is None:
                return self.now()
        raise RuntimeError(
            f"run_all exceeded {max_events} events; a callback is "
            "re-scheduling itself forever"
        )

    def __repr__(self) -> str:
        with self._cond:
            pending = len(self._heap) + len(self._sleepers)
            return (
                f"VirtualClock(now={self._now:.6f}, "
                f"{pending} pending, "
                f"{len(self._threads)} registered)"
            )
