"""SimBackend: the full Backend protocol on virtual time.

The real ``asyncmap``/``waitall`` (pool.py), ``HedgedServer``, and any
other Backend consumer run UNMODIFIED on top of this: ``dispatch``
computes the worker result immediately (numpy on the coordinator
thread) but schedules its *arrival* at ``clock.now() + delay + service``
on the :class:`~.clock.VirtualClock`; ``wait_any``/``wait`` advance
virtual time straight to the next arrival instead of blocking an OS
thread. A 10k-epoch straggling fleet completes in milliseconds of wall
clock with bit-reproducible arrival orders (the event heap breaks ties
by dispatch order — there is no thread scheduler to race).

Latency sources, in the order a study usually reaches for them:

* ``delay_fn`` — any :data:`~..backends.base.DelayFn` from
  :mod:`..utils.faults` (seeded lognormal fleets, designated
  stragglers, dead-from schedules, recorded-trace replays);
* :func:`model_delay_fn` — deterministic per-(worker, epoch) draws
  from fitted :class:`~..utils.straggle.WorkerStats` /
  :class:`~..utils.straggle.PoolLatencyModel` shifted-exponentials,
  so a latency model fitted on production samples becomes a
  counterfactual testbed.

Protocol-fidelity caveats (also in docs/API.md):

* **Timeouts are virtual seconds.** The pool's ``Deadline`` arithmetic
  runs on the real clock, but a sim coordinator consumes ~no real
  time, so the ``timeout=`` each ``wait_any`` receives is ~the full
  caller budget, which this backend then spends as virtual time. A
  multi-arrival epoch can therefore span more *virtual* time than the
  caller's single budget — per-wait timeout semantics are exact,
  whole-call semantics are conservative.
* **``pool.latency`` stamps are real-clock** (≈0 in sim). Virtual
  round-trips live here instead: ``last_latency`` mirrors the pool
  field on the virtual axis, and :meth:`observe_into` feeds them to a
  :class:`~..utils.straggle.PoolLatencyModel`.
* **Phase-1 drains see only elapsed virtual time.** Between epochs no
  virtual time passes unless the driver advances the clock, so a
  cross-epoch straggler is harvested stale in phase 3 rather than
  drained in phase 1 — same outcome, different phase.
"""

from __future__ import annotations

import copy
from typing import Callable, Sequence

import numpy as np

from ..backends.base import Backend, DelayFn, WorkerError
from .clock import VirtualClock

WorkFn = Callable[[int, object, int], object]

__all__ = ["SimBackend", "SimEvent", "model_delay_fn"]


class SimEvent:
    """One completed simulated task (the backend's own flight log)."""

    __slots__ = ("worker", "epoch", "tag", "t_dispatch", "t_done")

    def __init__(self, worker, epoch, tag, t_dispatch, t_done):
        self.worker = int(worker)
        self.epoch = int(epoch)
        self.tag = int(tag)
        self.t_dispatch = float(t_dispatch)
        self.t_done = float(t_done)

    @property
    def latency(self) -> float:
        return self.t_done - self.t_dispatch

    def __repr__(self) -> str:
        return (
            f"SimEvent(w{self.worker} e{self.epoch} "
            f"{self.t_dispatch:.6f}->{self.t_done:.6f})"
        )


def model_delay_fn(model, *, seed: int = 0) -> DelayFn:
    """A :data:`~..backends.base.DelayFn` sampling each (worker, epoch)
    round-trip from fitted shifted-exponential latency models —
    deterministically (the draw is keyed on ``(seed, worker, epoch)``,
    the same discipline as :mod:`..utils.faults`), so a simulated fleet
    driven by a production fit reproduces bit-for-bit.

    ``model`` is a :class:`~..utils.straggle.PoolLatencyModel` or a
    sequence of :class:`~..utils.straggle.WorkerStats`. Workers with no
    samples draw from the pooled prior of the observed workers (mean
    floor/mean of the fleet — a silent worker must not simulate as
    infinitely fast, mirroring ``PoolLatencyModel.sample_latencies``).
    """
    workers = list(getattr(model, "workers", model))
    fitted = [
        (w.shift, w.rate) for w in workers if w.count > 0
    ]
    if fitted:
        prior_shift = min(s for s, _ in fitted)
        means = [
            s + (0.0 if not np.isfinite(r) else 1.0 / r)
            for s, r in fitted
        ]
        prior_mean = float(np.mean(means))
        tail = prior_mean - prior_shift
        prior = (
            prior_shift, np.inf if tail <= 0 else 1.0 / tail
        )
    else:
        prior = (0.0, np.inf)
    params = [
        (w.shift, w.rate) if w.count > 0 else prior for w in workers
    ]

    def fn(worker: int, epoch: int) -> float:
        shift, rate = params[worker]
        if not np.isfinite(rate):
            return float(shift)
        rng = np.random.default_rng(
            (int(seed) & 0x7FFFFFFF, int(worker), int(epoch) & 0x7FFFFFFF)
        )
        return float(shift + rng.exponential(1.0 / rate))

    return fn


class _SimSlot:
    """One in-flight simulated task per (worker, tag) channel."""

    __slots__ = (
        "seq", "outstanding", "done_at", "t_dispatch", "result", "epoch",
    )

    def __init__(self):
        self.seq = 0
        self.outstanding = False
        self.done_at = 0.0
        self.t_dispatch = 0.0
        self.result = None
        self.epoch = 0


class SimBackend(Backend):
    """n simulated workers computing ``work_fn(worker, payload, epoch)``
    with virtual-time arrivals.

    >>> clock = VirtualClock()
    >>> backend = SimBackend(work, 8, delay_fn=sched, clock=clock)
    >>> repochs = asyncmap(pool, payload, backend, nwait=6)  # real pool
    >>> clock.now()                      # virtual epoch wall, seconds

    ``delay_fn(worker, epoch)`` is the injected round-trip latency;
    ``service_fn`` adds a second, separately-specified term (e.g. a
    compute-time model on top of a network-delay model). The result is
    computed eagerly at dispatch on the calling thread — numerically
    identical to a thread backend, but scheduled to *arrive* at
    ``now + delay + service``.

    ``registry=`` / ``spans=`` follow the package-wide opt-in contract
    (GC004): a dark backend pays only ``is None`` checks. With
    ``spans=`` every delivered task becomes one span on the virtual
    axis (track ``worker <i>`` in a ``sim`` Perfetto process), so
    simulated fleets merge into the same
    :func:`~..obs.timeline.dump_merged_chrome_trace` documents as live
    ones.
    """

    def __init__(
        self,
        work_fn: WorkFn,
        n_workers: int,
        *,
        delay_fn: DelayFn | None = None,
        service_fn: DelayFn | None = None,
        clock: VirtualClock | None = None,
        registry=None,
        spans=None,
    ):
        self.work_fn = work_fn
        self.n_workers = int(n_workers)
        self.delay_fn = delay_fn
        self.service_fn = service_fn
        self.clock = clock if clock is not None else VirtualClock()
        self._channels: dict[int, list[_SimSlot]] = {
            0: [_SimSlot() for _ in range(self.n_workers)]
        }
        self._gseq = 0
        self._closed = False
        self.events: list[SimEvent] = []  # delivered tasks, arrival order
        self.n_dispatched = 0
        self.n_delivered = 0
        # virtual round-trip of each worker's most recent delivery —
        # the sim-axis mirror of pool.latency (which stamps ~0 real
        # seconds here); feed a latency model via observe_into()
        self.last_latency = np.zeros(self.n_workers, dtype=np.float64)
        self._spans = spans
        self._m = None
        if registry is not None:
            self._m = {
                "dispatched": registry.counter(
                    "sim_tasks_dispatched_total",
                    help="simulated dispatches",
                ),
                "delivered": registry.counter(
                    "sim_tasks_delivered_total",
                    help="simulated arrivals handed to the pool",
                ),
                "vtime": registry.gauge(
                    "sim_virtual_time_seconds",
                    help="virtual clock at the latest delivery",
                ),
                "latency": registry.histogram(
                    "sim_task_virtual_seconds",
                    help="virtual round-trip per delivered task",
                ),
            }

    @classmethod
    def from_latency_model(
        cls, work_fn: WorkFn, model, *, seed: int = 0, **kw
    ) -> "SimBackend":
        """A backend whose fleet straggles like ``model`` says it does
        (:func:`model_delay_fn` over fitted per-worker distributions)."""
        n = getattr(model, "n_workers", None)
        if n is None:
            n = len(list(model))
        return cls(work_fn, n, delay_fn=model_delay_fn(model, seed=seed),
                   **kw)

    # -- internals --------------------------------------------------------
    def _chan(self, tag: int) -> list[_SimSlot]:
        slots = self._channels.get(tag)
        if slots is None:
            slots = [_SimSlot() for _ in range(self.n_workers)]
            self._channels[tag] = slots
        return slots

    def _deliver(self, i: int, slot: _SimSlot):
        result = slot.result
        slot.result = None
        slot.outstanding = False
        lat = slot.done_at - slot.t_dispatch
        self.last_latency[i] = lat
        self.n_delivered += 1
        self.events.append(
            SimEvent(i, slot.epoch, 0, slot.t_dispatch, slot.done_at)
        )
        if self._spans is not None:
            self._spans.add(
                f"task e{slot.epoch}", slot.t_dispatch, lat,
                track=f"worker {i}", worker=i, epoch=slot.epoch,
            )
        if self._m is not None:
            self._m["delivered"].inc()
            self._m["vtime"].set(slot.done_at)
            self._m["latency"].observe(lat)
        return result

    # -- Backend interface ------------------------------------------------
    def dispatch(self, i: int, sendbuf, epoch: int, *, tag: int = 0) -> None:
        if self._closed:
            raise RuntimeError("backend has been shut down")
        i, tag = int(i), int(tag)
        slot = self._chan(tag)[i]
        if slot.outstanding:
            raise RuntimeError(
                f"worker {i} already has an outstanding task on tag "
                f"{tag}; the pool must only dispatch to inactive workers"
            )
        # private payload snapshot (the reference isendbuf discipline):
        # in-flight simulated sends survive caller mutation too
        try:
            payload = np.array(sendbuf, copy=True)
        except Exception:
            payload = copy.deepcopy(sendbuf)
        # Exception, not BaseException: unlike the thread/process
        # backends, work_fn runs eagerly on the CALLING thread here, so
        # KeyboardInterrupt/SystemExit must abort the simulation, not
        # masquerade as a simulated worker fault at harvest
        try:
            result = self.work_fn(i, payload, epoch)
        except Exception as e:  # surfaced at harvest, never lost
            result = WorkerError(i, epoch, e)
        now = self.clock.now()
        delay = 0.0
        if self.delay_fn is not None:
            delay += max(float(self.delay_fn(i, epoch)), 0.0)
        if self.service_fn is not None:
            delay += max(float(self.service_fn(i, epoch)), 0.0)
        self._gseq += 1
        slot.seq = self._gseq
        slot.outstanding = True
        slot.t_dispatch = now
        slot.done_at = now + delay
        slot.result = result
        slot.epoch = int(epoch)
        self.n_dispatched += 1
        if self._m is not None:
            self._m["dispatched"].inc()

    def test(self, i: int, *, tag: int = 0):
        slots = self._channels.get(int(tag))
        if slots is None:  # channel never dispatched on
            return None
        slot = slots[int(i)]
        if slot.outstanding and slot.done_at <= self.clock.now():
            return self._deliver(int(i), slot)
        return None

    def wait_any(
        self,
        indices: Sequence[int],
        timeout: float | None = None,
        *,
        tags: Sequence[int] | None = None,
    ):
        idx = [int(i) for i in indices]
        if not idx:
            raise ValueError("wait_any over an empty index set would hang")
        tgs = [0] * len(idx) if tags is None else [int(t) for t in tags]
        if len(tgs) != len(idx):
            raise ValueError("tags must align one-to-one with indices")
        channels = self._channels  # hot path: one dict, no lazy create
        best = None  # (done_at, seq, i, slot)
        for i, t in zip(idx, tgs):
            slots = channels.get(t)
            if slots is None:  # channel never dispatched on
                continue
            slot = slots[i]
            if not slot.outstanding:
                continue
            key = (slot.done_at, slot.seq)
            if best is None or key < (best[0], best[1]):
                best = (slot.done_at, slot.seq, i, slot)
        now = self.clock.now()
        if best is None:
            # nothing in flight on the requested channels: an unbounded
            # wait would hang a real backend forever — make that a
            # diagnosable error here; a bounded one times out honestly
            if timeout is None:
                raise RuntimeError(
                    "wait_any on workers with no outstanding task "
                    "would block forever"
                )
            self.clock.advance(timeout)
            return None
        done_at, _, i, slot = best
        if done_at > now:
            if timeout is not None and done_at > now + float(timeout):
                self.clock.run_until(now + float(timeout))
                return None
            self.clock.run_until(done_at)
        return i, self._deliver(i, slot)

    def wait(self, i: int, timeout: float | None = None, *, tag: int = 0):
        i = int(i)
        slot = self._chan(int(tag))[i]
        if not slot.outstanding:
            raise RuntimeError(
                f"worker {i} has no outstanding task on tag {int(tag)}"
            )
        now = self.clock.now()
        if slot.done_at > now:
            if timeout is not None and slot.done_at > now + float(timeout):
                self.clock.run_until(now + float(timeout))
                return None
            self.clock.run_until(slot.done_at)
        return self._deliver(i, slot)

    def shutdown(self) -> None:
        self._closed = True

    # -- sim conveniences -------------------------------------------------
    def quiesce(self) -> float:
        """Advance virtual time past every outstanding arrival (so a
        following non-blocking harvest — ``test`` / a HedgedServer
        ``_harvest`` — finds them all). Returns the new ``now``."""
        latest = self.clock.now()
        for slots in self._channels.values():
            for slot in slots:
                if slot.outstanding:
                    latest = max(latest, slot.done_at)
        return self.clock.run_until(latest)

    def observe_into(self, model, *, workers: Sequence[int] | None = None):
        """Feed each worker's most recent *virtual* round-trip into a
        :class:`~..utils.straggle.PoolLatencyModel` — the sim-side
        replacement for ``model.observe_pool`` (whose real-clock
        ``pool.latency`` samples are ≈0 here)."""
        ws = range(self.n_workers) if workers is None else workers
        for w in ws:
            model.observe(int(w), float(self.last_latency[int(w)]))

    def __repr__(self) -> str:
        return (
            f"SimBackend(n={self.n_workers}, vnow={self.clock.now():.6f}, "
            f"{self.n_delivered}/{self.n_dispatched} delivered)"
        )
