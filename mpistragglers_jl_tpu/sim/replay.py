"""Trace replay: re-run a recorded fleet under a different policy.

The record -> replay loop, closed on virtual time: a run traced with an
:class:`~..utils.trace.EpochTracer` (live object, ``dump_jsonl`` file,
or the Chrome/Perfetto documents the obs/ plane exports) becomes a
:class:`ReplayTrace` — per-(worker, epoch) round-trips plus per-epoch
metadata — and :func:`replay` re-executes it through the REAL
``asyncmap``/``waitall`` on a :class:`~.backend.SimBackend`, possibly
under a *different* ``nwait``, reporting counterfactual epoch latency,
fresh-worker sets, and staleness. "What would last night's straggler
incident have cost at nwait=5?" is one function call, in milliseconds.

Replay label contract (what :meth:`ReplayTrace.from_chrome` parses —
the format :meth:`~..utils.trace.EpochTracer.chrome_events` emits and
``dump_merged_chrome_trace``/``/trace`` embed): per-worker task spans
named ``epoch <N>`` (stale ones suffixed `` (stale)``) with ``tid`` =
worker index, and coordinator spans named
``asyncmap(epoch=<N>, nwait=<k>)`` on ``tid`` -1, all within one
"pool" process. Chrome docs without pool worker spans (e.g. a bare
flight ring of coordinator spans) cannot seed per-worker replay and
are rejected with a pointer to the JSONL path.

Fidelity: recorded round-trips are injected as sim delays, so replay
reproduces arrival *order* up to the true compute time of the original
workload (microseconds under millisecond-scale delays) and epoch walls
up to coordinator overhead — the drift :func:`compare` quantifies and
the bench `sim` rung tracks.
"""

from __future__ import annotations

import json
from typing import Any, Sequence

import numpy as np

from ..pool import AsyncPool, asyncmap, waitall
from ..utils.faults import from_trace
from .backend import SimBackend
from .clock import VirtualClock

__all__ = [
    "ReplayTrace", "ReplayResult", "replay", "compare",
    "replay_router_day",
]


def replay_router_day(
    router, path, *, events=(), retry=None, fast: str = "auto",
    timer=None,
):
    """Replay a recorded arrival stream (a
    :func:`~.workload.dump_arrivals_jsonl` file) through ``router`` —
    the router-plane sibling of :func:`replay`. ``fast="auto"``
    (default) runs the day on the vectorized
    :func:`~.fastpath.run_router_day_fast` engine where the day shape
    supports it (bit-identical ``digest()``, ``report.fastpath`` names
    the path taken); ``fast="never"`` pins the scalar loop, the parity
    reference. Counterfactuals — "what would yesterday's traffic have
    cost under prefix_affinity?" — are one router construction plus
    this call, in milliseconds."""
    from .tune import _resolve_fast
    from .workload import arrivals_from_jsonl, run_router_day

    arrivals = arrivals_from_jsonl(path)
    if _resolve_fast(fast):
        from .fastpath import run_router_day_fast

        return run_router_day_fast(
            router, arrivals, events=events, retry=retry, timer=timer,
        )
    return run_router_day(
        router, arrivals, events=events, retry=retry, timer=timer,
    )


class _EpochSnap:
    """Per-``asyncmap`` metadata from the recorded run."""

    __slots__ = ("epoch", "nwait", "wall", "fresh", "n_workers")

    def __init__(self, epoch, nwait, wall, fresh, n_workers):
        self.epoch = int(epoch)
        self.nwait = nwait  # int or "<callable>"
        self.wall = float(wall)
        self.fresh = frozenset(int(w) for w in fresh)
        self.n_workers = int(n_workers)

    def __repr__(self) -> str:
        return (
            f"_EpochSnap(e{self.epoch}, nwait={self.nwait}, "
            f"wall={self.wall:.4f}, fresh={sorted(self.fresh)})"
        )


class ReplayTrace:
    """A recorded run in replayable form.

    ``records`` is the list of :meth:`~..utils.trace.EpochRecord.to_dict`
    dicts (the JSONL line format); construction derives the per-epoch
    snapshots and the (worker, epoch) latency table.
    """

    def __init__(self, records: Sequence[dict]):
        self.records = [dict(r) for r in records]
        if not self.records:
            raise ValueError("empty trace: nothing to replay")
        self.epochs: list[_EpochSnap] = []
        n_workers = 0
        for rec in self.records:
            rep = rec.get("repochs") or []
            n_workers = max(n_workers, len(rep))
            if rec.get("call") != "asyncmap":
                continue
            epoch = int(rec["epoch"])
            fresh = [i for i, r in enumerate(rep) if int(r) == epoch]
            self.epochs.append(
                _EpochSnap(
                    epoch, rec.get("nwait"), rec.get("wall_s", 0.0),
                    fresh, len(rep),
                )
            )
        if not self.epochs:
            raise ValueError(
                "trace holds no asyncmap records (a bare waitall drain "
                "has no epoch policy to replay)"
            )
        self.n_workers = n_workers

    # -- constructors -----------------------------------------------------
    @classmethod
    def from_tracer(cls, tracer) -> "ReplayTrace":
        """From a live :class:`~..utils.trace.EpochTracer` (no file)."""
        return cls([r.to_dict() for r in tracer.records])

    @classmethod
    def from_jsonl(cls, path) -> "ReplayTrace":
        """From an ``EpochTracer.dump_jsonl`` file."""
        with open(path) as f:
            return cls([json.loads(line) for line in f if line.strip()])

    @classmethod
    def from_chrome(cls, doc, *, n_workers: int | None = None) -> "ReplayTrace":
        """From a Chrome trace-event document (dict, or a path to one):
        the ``/trace`` endpoint's merged output, a
        ``dump_merged_chrome_trace`` file, or a bare
        ``EpochTracer.dump_chrome_trace``. Reconstructs epoch records
        from the pool process's spans per the replay label contract
        (module docstring).

        Format caveat: the Chrome doc only draws spans for tasks that
        ARRIVED, so a worker dead/stalled for the entire recording has
        no track at all and the fleet size is inferred one short —
        pass ``n_workers=`` explicitly to replay such an incident (the
        missing rank then replays as the ``missing``-stall fallback),
        or prefer ``from_jsonl``/``from_tracer``, whose records carry
        the true width in ``repochs``."""
        if not isinstance(doc, dict):
            with open(doc) as f:
                doc = json.load(f)
        events = doc.get("traceEvents", [])
        # pool processes are the pids whose process_name metadata says
        # "pool" (EpochTracer.chrome_events contract); a single-tracer
        # dump has exactly one, a merged doc may interleave several —
        # replay the first
        pool_pids = sorted(
            e["pid"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
            and e.get("args", {}).get("name") == "pool"
        )
        if not pool_pids:
            raise ValueError(
                "no 'pool' process in the Chrome doc: per-worker replay "
                "needs EpochTracer spans (record with tracer= and use "
                "dump_jsonl/dump_chrome_trace, or merge the tracer into "
                "the /trace document)"
            )
        pid = pool_pids[0]
        us = 1e6
        coord: list[dict] = []   # asyncmap/waitall call spans
        tasks: list[dict] = []   # per-worker task spans
        for e in events:
            if e.get("pid") != pid or e.get("ph") != "X":
                continue
            if e.get("tid") == -1:
                coord.append(e)
            elif isinstance(e.get("tid"), int) and e["tid"] >= 0:
                tasks.append(e)
        if not tasks:
            raise ValueError(
                "pool process has no worker task spans: the doc cannot "
                "seed per-worker replay (see the replay label contract)"
            )
        records: list[dict] = []
        import re

        if n_workers is None:
            n_workers = max(t["tid"] for t in tasks) + 1
        n_workers = int(n_workers)
        for c in sorted(coord, key=lambda e: e["ts"]):
            m = re.match(
                r"(asyncmap|waitall)\(epoch=(-?\d+), nwait=(.+)\)",
                c.get("name", ""),
            )
            if not m:
                continue
            call, epoch = m.group(1), int(m.group(2))
            nwait = (
                int(m.group(3)) if m.group(3).lstrip("-").isdigit()
                else m.group(3)
            )
            t0, t1 = c["ts"], c["ts"] + c.get("dur", 0.0)
            events_out, repochs = [], [0] * n_workers
            latency = [0.0] * n_workers
            for t in tasks:
                te = t["ts"] + t.get("dur", 0.0)
                if not (t0 <= te <= t1 + 1e-3):
                    continue  # arrival outside this call span
                em = re.match(r"epoch (-?\d+)", t.get("name", ""))
                if not em:
                    continue
                sepoch, w = int(em.group(1)), int(t["tid"])
                lat = t.get("dur", 0.0) / us
                events_out.append({
                    "t": (t["ts"] - t0) / us, "kind": "dispatch",
                    "worker": w, "epoch": sepoch,
                })
                events_out.append({
                    "t": (te - t0) / us, "kind": "arrival", "worker": w,
                    "epoch": sepoch,
                    "fresh": bool(t.get("args", {}).get("fresh", True)),
                })
                repochs[w] = max(repochs[w], sepoch)
                latency[w] = lat
            records.append({
                "epoch": epoch, "call": call, "nwait": nwait,
                "wall_s": c.get("dur", 0.0) / us, "repochs": repochs,
                "latency_s": latency,
                "events": sorted(events_out, key=lambda e: e["t"]),
            })
        return cls(records)

    # -- derived ----------------------------------------------------------
    def delay_fn(self, *, missing: float | None = None):
        """The recorded latencies as a deterministic
        :data:`~..backends.base.DelayFn` (``utils.faults.from_trace``
        fallback semantics: absent epochs replay at that worker's
        median, never-heard workers as long stalls)."""
        return from_trace.from_records(self.records, missing=missing)

    def recorded_nwaits(self) -> list[int]:
        out = []
        for e in self.epochs:
            if not isinstance(e.nwait, int):
                raise ValueError(
                    f"epoch {e.epoch} was recorded with a callable "
                    "nwait; pass an explicit nwait= to replay()"
                )
            out.append(e.nwait)
        return out

    def __repr__(self) -> str:
        return (
            f"ReplayTrace({len(self.epochs)} epochs, "
            f"{self.n_workers} workers)"
        )


class ReplayResult:
    """Counterfactual outcome of one replay.

    ``epochs`` rows: ``epoch``, ``nwait`` (the policy replayed),
    ``wall`` (virtual seconds), ``fresh`` (frozenset of fresh workers),
    ``n_stale`` harvested that epoch.
    """

    def __init__(self, nwait_label, rows: list[dict], backend: SimBackend):
        self.nwait = nwait_label
        self.epochs = rows
        self.backend = backend

    @property
    def walls(self) -> np.ndarray:
        return np.array([r["wall"] for r in self.epochs])

    def summary(self) -> dict[str, Any]:
        walls = self.walls
        fresh = [len(r["fresh"]) for r in self.epochs]
        return {
            "nwait": self.nwait,
            "epochs": len(self.epochs),
            "wall_total_s": float(walls.sum()),
            "wall_mean_s": float(walls.mean()),
            "wall_p95_s": float(np.percentile(walls, 95)),
            "fresh_mean": float(np.mean(fresh)),
            "n_stale": int(sum(r["n_stale"] for r in self.epochs)),
            "staleness_rate": float(
                sum(r["n_stale"] for r in self.epochs)
                / max(self.backend.n_delivered, 1)
            ),
        }

    def __repr__(self) -> str:
        s = self.summary()
        return (
            f"ReplayResult(nwait={s['nwait']}, {s['epochs']} epochs, "
            f"mean {s['wall_mean_s']*1e3:.2f} ms)"
        )


def replay(
    trace: ReplayTrace,
    *,
    nwait: int | None = None,
    work_fn=None,
    payload=None,
    missing: float | None = None,
    drain: bool = True,
    clock: VirtualClock | None = None,
    registry=None,
    spans=None,
) -> ReplayResult:
    """Re-run ``trace`` through the real pool on virtual time.

    ``nwait=None`` replays each epoch under its RECORDED nwait (the
    faithfulness baseline — :func:`compare` against the trace validates
    the simulator); an int replays the counterfactual policy. The
    same epoch numbers are reused so the trace's (worker, epoch) delay
    table lines up. ``registry=`` / ``spans=`` thread through to the
    :class:`~.backend.SimBackend` (opt-in, GC004 contract).
    """
    if work_fn is None:
        work_fn = _echo
    if payload is None:
        payload = np.zeros(1, dtype=np.float64)
    backend = SimBackend(
        work_fn, trace.n_workers,
        delay_fn=trace.delay_fn(missing=missing),
        clock=clock if clock is not None else VirtualClock(),
        registry=registry, spans=spans,
    )
    pool = AsyncPool(trace.n_workers)
    nwaits = (
        trace.recorded_nwaits() if nwait is None
        else [int(nwait)] * len(trace.epochs)
    )
    rows: list[dict] = []
    for snap, k in zip(trace.epochs, nwaits):
        t0 = backend.clock.now()
        # count stale harvests over only THIS call's deliveries (a
        # full-list rescan per epoch would make replay quadratic)
        ev0 = len(backend.events)
        asyncmap(pool, payload, backend, nwait=k, epoch=snap.epoch)
        rows.append({
            "epoch": snap.epoch,
            "nwait": k,
            "wall": backend.clock.now() - t0,
            "fresh": frozenset(int(i) for i in pool.fresh_indices()),
            "n_stale": sum(
                1 for e in backend.events[ev0:] if e.epoch < snap.epoch
            ),
        })
    if drain and pool.active.any():
        waitall(pool, backend)
    return ReplayResult(
        "recorded" if nwait is None else int(nwait), rows, backend
    )


def _echo(i, payload, epoch):
    """Default replay workload: the payload itself (the recorded run's
    numerics are gone; only its timing is being replayed)."""
    return payload


def compare(trace: ReplayTrace, result: ReplayResult) -> dict[str, Any]:
    """Drift between a recorded run and its (same-policy) replay.

    ``fresh_exact_rate`` is the headline fidelity claim — the fraction
    of epochs whose fresh-worker SET reproduced exactly; wall drift
    quantifies how much coordinator/compute overhead the recorded
    walls carried that injected delays cannot (``sim`` bench rung).
    """
    by_epoch = {r["epoch"]: r for r in result.epochs}
    matched, jaccard, drift_abs, drift_rel = [], [], [], []
    for snap in trace.epochs:
        row = by_epoch.get(snap.epoch)
        if row is None:
            continue
        matched.append(row["fresh"] == snap.fresh)
        union = row["fresh"] | snap.fresh
        jaccard.append(
            len(row["fresh"] & snap.fresh) / len(union) if union else 1.0
        )
        drift_abs.append(abs(row["wall"] - snap.wall))
        if snap.wall > 0:
            drift_rel.append(abs(row["wall"] - snap.wall) / snap.wall)
    n = len(matched)
    return {
        "epochs": n,
        "fresh_exact_rate": float(np.mean(matched)) if n else 0.0,
        "fresh_jaccard_mean": float(np.mean(jaccard)) if n else 0.0,
        "wall_drift_mean_s": float(np.mean(drift_abs)) if n else 0.0,
        "wall_drift_max_s": float(np.max(drift_abs)) if n else 0.0,
        "wall_drift_rel_mean": (
            float(np.mean(drift_rel)) if drift_rel else 0.0
        ),
    }
