"""Vectorized day driver: ``run_router_day`` semantics off the
interpreted event loop, bit-identical digests (round 16).

The scalar driver (:func:`~.workload.run_router_day`) advances the
clock to every replica tick and re-runs the router's per-slot python
loop each time — ~30 µs per request on a million-request day, which
the fleet controller's *online* sweeps (``fleet/controller.py``, a
decision budget of candidate-days) cannot afford. This module replays
the SAME day as a batched discrete-event program over struct-of-arrays
state:

* **arrival cohorts** — :class:`ArrivalBatch` carries a seeded day as
  numpy columns (:func:`poisson_arrival_batch` /
  :func:`diurnal_arrival_batch` twin the generators draw-for-draw:
  same rng streams, same chunking, same one-coin class/tenant fold),
  so a million arrivals never materialize a million objects;
* **tick streams** — a busy :class:`~.workload.SimReplica` fires a
  *chain* of ticks whose times are a prefix-sum of per-index ``tick_s``
  draws; the engine materializes whole chains with ``np.cumsum``
  (sequential accumulation — bit-equal to the scalar ``t += dt`` walk)
  and touches only the *eventful* ticks: admissions, retirements, and
  chain boundaries. Prefill/decode progress is analytic: a request
  admitted at tick ``k`` with ``c`` chunks emits its first token at
  ``k + c - 1`` and retires ``ceil((max_new - 1)/n_inner)`` ticks
  later — the intermediate ticks change nothing and are never
  visited;
* **DRR rotation windows** — qos days drive the REAL
  :class:`~..qos.DeficitScheduler` instances on the replicas (integer
  work handles instead of request objects), so admission order is the
  deficit scheduler's own arithmetic, not a reimplementation;
* **retry coins** — resubmission dues come from the REAL
  :class:`~.workload.RetryPolicy` (same seeded jitter coin), scheduled
  on the engine's event heap.

**The digest witness is the spec.** The fast path must reproduce the
scalar loop's :meth:`~.workload.WorkloadReport.digest` bit-identically
on every seeded day it accepts — any divergence is a fast-path bug by
definition (tests/test_sim_fastpath.py pins plain, prefix, QoS, hedge,
and retry-storm days). The witness arrays themselves are assembled by
:meth:`~.workload.WorkloadReport.from_arrays` inside ``workload.py``
— one writer for both paths (graftcheck GC011).

**Scalar fallback boundaries.** Genuinely event-driven days fall back
to the scalar loop (the report says so in ``report.fastpath``):
fleet controllers (FleetResize / CoordinatorKill — the topology
mutates mid-day), control-plane event streams and chaos episodes
(partitions, kill/recover ``clock.call_at`` injections), two-tier
routing and ``chunk_s`` prefill pricing (tick *durations* become
state-dependent), custom health probes, observability hooks, and
non-``lognormal_ticks`` tick callables (an arbitrary stateful callable
is only correct on the scalar call sequence). The controller's sweep
entry points (``sim/tune.py``) route here with ``fast="auto"`` —
supported days vectorize, the rest keep their recorded digests via
the scalar path.

Known accepted divergence (shared with the scalar path's own docs):
the scalar loop fires events within ``1e-12`` of each other in one
step; the engine uses exact times. Seeded random days never produce
such collisions across distinct event sources — the parity suite is
the empirical witness.

sim purity (graftcheck GC008): this module never reads the OS clock —
wall measurement comes from an injected ``timer=``.
"""

from __future__ import annotations

import heapq
import math
from bisect import bisect_right
from collections import deque
from typing import Callable, Iterable

import numpy as np

from .clock import VirtualClock
from .workload import (
    _CHUNK,
    _TENANT_STRIDE,
    Arrival,
    RetryPolicy,
    SimPrompt,
    SimReplica,
    WorkloadReport,
    lognormal_ticks,
    run_router_day,
)

__all__ = [
    "ArrivalBatch",
    "poisson_arrival_batch",
    "diurnal_arrival_batch",
    "fastpath_supported",
    "run_router_day_fast",
]

_INF = math.inf
_BIG = 1 << 60

# outcome codes for the struct-of-arrays request table
_INFLIGHT, _OK, _HEDGED, _HEDGE_WON, _SHED = 0, 1, 2, 3, 4
_OUT_NAMES = {_OK: "ok", _HEDGED: "hedged", _HEDGE_WON: "hedge_won",
              _SHED: "shed"}
_SHED_NAMES = {1: "budget", 2: "overload", 3: "overload_hard"}
_SHED_CODES = {v: k for k, v in _SHED_NAMES.items()}


# -- arrival cohorts ------------------------------------------------------


class ArrivalBatch:
    """A whole arrival day as numpy columns: times, prompt lengths,
    prefix group/length (``-1``/``0`` = unique prompt), ``max_new``,
    and tenant codes into ``tenant_names`` (``-1`` = untenanted).
    Iterating yields :class:`~.workload.Arrival` objects equal
    field-for-field to the generator stream it twins, so one batch
    can drive BOTH execution paths of the same day (the parity
    suite's harness, and the scalar fallback's input)."""

    __slots__ = ("t", "plen", "prefix", "prefix_len", "max_new",
                 "tenant", "tenant_names")

    def __init__(self, t, plen, prefix, prefix_len, max_new, tenant,
                 tenant_names):
        self.t = np.asarray(t, np.float64)
        self.plen = np.asarray(plen, np.int64)
        self.prefix = np.asarray(prefix, np.int64)
        self.prefix_len = np.asarray(prefix_len, np.int64)
        self.max_new = np.asarray(max_new, np.int64)
        self.tenant = np.asarray(tenant, np.int64)
        self.tenant_names = list(tenant_names)
        n = self.t.size
        for col in (self.plen, self.prefix, self.prefix_len,
                    self.max_new, self.tenant):
            if col.size != n:
                raise ValueError("ArrivalBatch columns must be equal "
                                 f"length (got {col.size} vs {n})")

    def __len__(self) -> int:
        return int(self.t.size)

    def __iter__(self):
        names = self.tenant_names
        for t, pl, g, gl, mn, tc in zip(
            self.t.tolist(), self.plen.tolist(), self.prefix.tolist(),
            self.prefix_len.tolist(), self.max_new.tolist(),
            self.tenant.tolist(),
        ):
            p = (SimPrompt(pl) if g < 0
                 else SimPrompt(pl, prefix=g, prefix_len=gl))
            yield Arrival(t, p, mn,
                          tenant=None if tc < 0 else names[tc])

    @classmethod
    def from_arrivals(cls, arrivals: Iterable[Arrival]) -> "ArrivalBatch":
        """Ingest any :class:`~.workload.Arrival` iterable (a recorded
        trace, a hand-built list) into columns. Prefix groups must be
        ints (the sim convention); int prompts are bare lengths."""
        ts, pls, gs, gls, mns, tcs = [], [], [], [], [], []
        names: list = []
        codes: dict = {}
        for a in arrivals:
            p = a.prompt
            if isinstance(p, (int, np.integer)):
                pl, g, gl = int(p), -1, 0
            else:
                pl = int(p.length)
                g = p.prefix
                if g is None:
                    g, gl = -1, 0
                else:
                    g, gl = int(g), int(p.prefix_len)
            ts.append(a.t)
            pls.append(pl)
            gs.append(g)
            gls.append(gl)
            mns.append(int(a.max_new))
            tn = a.tenant
            if tn is None:
                tcs.append(-1)
            else:
                c = codes.get(tn)
                if c is None:
                    c = codes[tn] = len(names)
                    names.append(tn)
                tcs.append(c)
        return cls(ts, pls, gs, gls, mns, tcs, names)


def _classify(coins: np.ndarray, prompt_len: int, prefix_share: float,
              prefix_len: int, n_prefix_groups: int, max_new: int,
              long_share: float, long_prompt_len, long_max_new,
              tenants):
    """The one-coin class/tenant fold of ``_default_prompt_fn`` /
    ``_tenant_fn``, vectorized with the exact scalar float ops (same
    division/compare order, truncating casts, ``% 1.0`` as fmod) —
    bit-identical class and tenant per coin."""
    n = coins.size
    share = float(prefix_share)
    lshare = float(long_share)
    if not (0.0 <= share <= 1.0):
        raise ValueError(f"prefix_share must be in [0, 1], got {share}")
    if not (0.0 <= lshare <= 1.0) or share + lshare > 1.0:
        raise ValueError(
            f"long_share must be in [0, 1] with prefix_share + "
            f"long_share <= 1, got {long_share} (+{share})"
        )
    if share > 0.0 and not (0 < prefix_len <= prompt_len):
        raise ValueError(
            "prefix_share > 0 needs 0 < prefix_len <= prompt_len"
        )
    if lshare > 0.0 and not (long_prompt_len or 0) > 0:
        raise ValueError("long_share > 0 needs long_prompt_len > 0")
    long_mn = int(long_max_new if long_max_new is not None else max_new)
    plen = np.full(n, int(prompt_len), np.int64)
    prefix = np.full(n, -1, np.int64)
    pfxlen = np.zeros(n, np.int64)
    mn = np.full(n, int(max_new), np.int64)
    if share > 0.0:
        is_pfx = coins < share
        g = np.minimum(
            (coins / share * n_prefix_groups).astype(np.int64),
            n_prefix_groups - 1,
        )
        prefix[is_pfx] = g[is_pfx]
        pfxlen[is_pfx] = int(prefix_len)
    else:
        is_pfx = np.zeros(n, bool)
    if lshare > 0.0:
        is_long = (~is_pfx) & (coins >= 1.0 - lshare)
        plen[is_long] = int(long_prompt_len)
        mn[is_long] = long_mn
    if tenants is None:
        tcode = np.full(n, -1, np.int64)
        names: list = []
    else:
        names = list(tenants)
        shares = [float(tenants[nm]) for nm in names]
        if not names or any(s <= 0 for s in shares) or abs(
                sum(shares) - 1.0) > 1e-9:
            raise ValueError(
                f"tenant shares must be > 0 and sum to 1, got "
                f"{dict(tenants)}"
            )
        cum, acc = [], 0.0
        for s in shares:
            acc += s
            cum.append(acc)
        v = np.remainder(coins * _TENANT_STRIDE, 1.0)
        tcode = np.minimum(
            np.searchsorted(np.asarray(cum), v, side="right"),
            len(names) - 1,
        ).astype(np.int64)
    return plen, prefix, pfxlen, mn, tcode, names


def poisson_arrival_batch(
    rate: float, *, n: int, seed: int = 0, start: float = 0.0,
    prompt_len: int = 128, max_new: int = 32,
    prefix_share: float = 0.0, prefix_len: int = 0,
    n_prefix_groups: int = 1, long_share: float = 0.0,
    long_prompt_len: int | None = None,
    long_max_new: int | None = None, tenants: dict | None = None,
) -> ArrivalBatch:
    """:func:`~.workload.poisson_arrivals` as columns: same generator
    seed, same ``_CHUNK``-sized draw order, same carried chunk tail —
    the stream is bit-identical arrival for arrival."""
    if rate <= 0 or n < 1:
        raise ValueError("need rate > 0 and n >= 1")
    rng = np.random.default_rng((0x9E3779B9, int(seed)))
    t = float(start)
    left = int(n)
    ts_parts, coin_parts = [], []
    while left:
        m = min(_CHUNK, left)
        ts = t + np.cumsum(rng.exponential(1.0 / rate, size=m))
        coins = rng.random(size=m)
        t = float(ts[-1])
        ts_parts.append(ts)
        coin_parts.append(coins)
        left -= m
    ts = np.concatenate(ts_parts)
    coins = np.concatenate(coin_parts)
    plen, prefix, pfxlen, mn, tcode, names = _classify(
        coins, prompt_len, prefix_share, prefix_len, n_prefix_groups,
        max_new, long_share, long_prompt_len, long_max_new, tenants)
    return ArrivalBatch(ts, plen, prefix, pfxlen, mn, tcode, names)


def diurnal_arrival_batch(
    mean_rate: float, *, n: int, period: float = 86_400.0,
    amplitude: float = 0.8, seed: int = 0, start: float = 0.0,
    prompt_len: int = 128, max_new: int = 32,
    prefix_share: float = 0.0, prefix_len: int = 0,
    n_prefix_groups: int = 1, long_share: float = 0.0,
    long_prompt_len: int | None = None,
    long_max_new: int | None = None, tenants: dict | None = None,
) -> ArrivalBatch:
    """:func:`~.workload.diurnal_arrivals` as columns — the same Lewis
    thinning, chunk for chunk (full-``_CHUNK`` candidate draws, the
    carry taken BEFORE truncating to ``n`` survivors)."""
    if mean_rate <= 0 or n < 1:
        raise ValueError("need mean_rate > 0 and n >= 1")
    if not (0.0 <= amplitude < 1.0):
        raise ValueError(f"amplitude must be in [0, 1), got {amplitude}")
    rng = np.random.default_rng((0x51ED2701, int(seed)))
    peak = mean_rate * (1.0 + amplitude)
    w = 2.0 * math.pi / period
    t = float(start)
    out = 0
    n = int(n)
    ts_parts, coin_parts = [], []
    while out < n:
        ts = t + np.cumsum(rng.exponential(1.0 / peak, size=_CHUNK))
        accept = rng.random(size=_CHUNK)
        coins = rng.random(size=_CHUNK)
        t = float(ts[-1])
        rates = mean_rate * (
            1.0 + amplitude * np.sin(w * ts - math.pi / 2.0)
        )
        keep = accept * peak < rates
        kts, kcoins = ts[keep], coins[keep]
        take = min(kts.size, n - out)
        ts_parts.append(kts[:take])
        coin_parts.append(kcoins[:take])
        out += take
    ts = np.concatenate(ts_parts)
    coins = np.concatenate(coin_parts)
    plen, prefix, pfxlen, mn, tcode, names = _classify(
        coins, prompt_len, prefix_share, prefix_len, n_prefix_groups,
        max_new, long_share, long_prompt_len, long_max_new, tenants)
    return ArrivalBatch(ts, plen, prefix, pfxlen, mn, tcode, names)


# -- the support gate -----------------------------------------------------

_FAST_POLICIES = ("round_robin", "least_loaded", "prefix_affinity",
                  "hedge_p99")


def fastpath_supported(router, *, controller=None, events=(),
                       retry=None, series=None,
                       slo=None) -> tuple[bool, str]:
    """Can this day run on the vectorized engine? Returns
    ``(ok, reason)`` — the reason names the scalar-fallback boundary
    (module docstring) and lands in ``report.fastpath``."""
    if controller is not None:
        return False, "controller attached (elastic day)"
    if events:
        return False, "control-plane events in stream"
    if series is not None or slo is not None:
        # window rollover needs the scalar driver's per-step clock
        # walk; the vectorized engine never visits intermediate times
        return False, "series/slo attached"
    clock = router.clock
    if clock is None:
        return False, "no VirtualClock (live router)"
    if not isinstance(clock, VirtualClock):
        return False, "custom clock"
    if (clock._heap or clock._sleepers or clock._threads
            or clock._pending):
        return False, "clock has scheduled injections (chaos day)"
    if getattr(router, "_obs", None) is not None:
        return False, "router observability attached"
    if getattr(router, "_trace", None) is not None:
        return False, "tracing attached"
    policy = getattr(router, "policy", None)
    if policy not in _FAST_POLICIES:
        return False, f"policy {policy!r} (two_tier is event-driven)"
    if router._health_fn is not None:
        return False, "custom health probe"
    if (router._migrating or router._partitioned or router._orphans
            or router._down_manual):
        return False, "router mid-episode (partition/migration)"
    if router.n_submitted or router.n_completed:
        return False, "router already carries traffic"
    if len(router._hedge):
        return False, "hedges already armed"
    if not router._routable:
        return False, "no routable replicas"
    for i, r in enumerate(router.replicas):
        if type(r) is not SimReplica:
            return False, "non-SimReplica replica"
        if r.chunk_s != 0.0:
            return False, "chunk_s prefill pricing (two-tier timing)"
        if getattr(r, "cache", None) is not None:
            return False, "fleet cache attached (spill/fetch pricing)"
        if (r.tick_count or r.next_tick_at is not None or r.pending
                or r.active or r._resident or r.busy_s):
            return False, "replica already carries state"
        if router._up[i] != r.alive:
            return False, "router health view out of date"
        spec = getattr(r, "_tick_spec", None)
        if callable(spec) and not isinstance(spec, lognormal_ticks):
            return False, "custom tick_s callable"
        drr = r._drr
        if drr is not None and (drr._order or drr._n
                                or drr._max_cost != 1.0):
            return False, "deficit scheduler already carries state"
    for b in router._buckets.values():
        if b is not None and b._last is not None:
            return False, "token bucket already charged"
    return True, "vectorized"


# -- per-replica engine state ---------------------------------------------


class _H(int):
    """Deficit-scheduler work handle: an int with object identity, so
    the REAL ``DeficitScheduler.remove`` (identity scan, like the
    scalar path's request objects) works on encoded work items."""

    __slots__ = ()


class _Rep:
    """Struct-of-state twin of one SimReplica: FIFO/DRR backlog of
    work items (``ridx*2 + leg``), slot generations for O(log n)
    cancel invalidation, a retirement heap keyed by tick index, and
    the current tick *chain* — times materialized by block cumsum."""

    __slots__ = (
        "i", "S", "n_inner", "C", "max_queue", "drr", "tenant_of",
        "handles", "fifo", "q_len", "resident", "slot_gen", "free",
        "retire", "load", "active", "idle", "cur", "base", "times",
        "dts", "wake", "busy_parts", "last_tick_t", "tick_fn",
        "tick_const", "next_ev", "next_k", "n_retired", "n_cancelled",
        "n_shared_admits",
    )

    def __init__(self, i: int, r: SimReplica):
        self.i = i
        self.S = r.S
        self.n_inner = r.n_inner
        self.C = r.C
        self.max_queue = r.max_queue
        self.drr = r._drr  # the REAL deficit scheduler (fresh, gated)
        self.handles: dict[int, _H] = {}
        self.fifo: deque[int] = deque()
        self.q_len = 0
        self.resident: dict = {}
        self.slot_gen = [0] * r.S
        self.free = list(range(r.S))  # already a heap (ascending)
        self.retire: list = []  # (tick, slot, gen, item)
        self.load = 0
        self.active = 0
        self.idle = True
        self.cur = 0  # fired-tick count == scalar tick_count
        self.base = 0
        self.times: list[float] = []
        self.dts: list[float] = []
        self.wake: int | None = None
        self.busy_parts: list[list] = []
        self.last_tick_t: float | None = None
        spec = r._tick_spec
        if callable(spec):
            if spec.sigma == 0.0:
                self.tick_fn, self.tick_const = None, spec.base
            else:
                self.tick_fn, self.tick_const = spec, 0.0
        else:
            self.tick_fn, self.tick_const = None, float(spec)
        self.next_ev = _INF
        self.next_k = 0
        self.n_retired = 0
        self.n_cancelled = 0
        self.n_shared_admits = 0

    # time materialization: times[j] is the time of absolute tick
    # base+j; dts[j] = tick_s(base+j), so times[j+1] = times[j] +
    # dts[j] — the block cumsum threads the exact running value
    # through, bit-equal to the scalar t += dt walk
    def ensure(self, j: int) -> None:
        times, dts = self.times, self.dts
        need = j - (len(times) - 1)
        while need > 0:
            m = need if need > 512 else 512
            b = self.base + len(dts)
            fn = self.tick_fn
            if fn is None:
                blk = [self.tick_const] * m
            else:
                fn(b + m - 1)  # extend the shared seeded cache
                blk = fn._cache[b:b + m]
            arr = np.empty(m + 1)
            arr[0] = times[-1]
            arr[1:] = blk
            np.cumsum(arr, out=arr)
            times.extend(arr[1:].tolist())
            dts.extend(blk)
            need = j - (len(times) - 1)

    def tick_after(self, t: float) -> int:
        """First chain tick strictly after time ``t`` (a tick exactly
        at ``t`` fired before this moment — driver ordering)."""
        times = self.times
        while times[-1] <= t:
            self.ensure(len(times) + 511)
        j = bisect_right(times, t)
        return self.base + (j if j > 0 else 1)

    def refresh(self) -> None:
        rh = self.retire
        sg = self.slot_gen
        while rh and sg[rh[0][1]] != rh[0][2]:
            heapq.heappop(rh)
        k = self.wake
        if rh and (k is None or rh[0][0] < k):
            k = rh[0][0]
        if k is None:
            self.next_ev = _INF
        else:
            self.next_k = k
            j = k - self.base
            self.ensure(j)
            self.next_ev = self.times[j]


# -- the engine -----------------------------------------------------------


class _Engine:
    def __init__(self, router, retry: RetryPolicy | None):
        self.router = router
        self.retry = retry
        self.clock = router.clock
        self.reps = [_Rep(i, r) for i, r in enumerate(router.replicas)]
        self.routable = list(router._routable)
        self.n_all = len(router.replicas)
        self.policy = router.policy
        self.rrc = router._rr
        self.hedging = router.policy == "hedge_p99"
        self.slo = getattr(router, "ttft_slo", None)
        self.shed_depth = router.shed_depth
        self.shed_depth_hard = router.shed_depth_hard
        self.depth = 0  # queued over the routable fleet (= queue_depth)
        # qos door state: per-tenant-code contract facts, the REAL
        # token buckets (router._buckets — left exactly as a scalar
        # day would leave them), and hedge entitlement outstanding
        self.qos = router._qos
        self.buckets = router._buckets
        self.hedges_out: dict[str, int] = {}
        self.c_name: list = []
        self.c_shed: list = []
        self.c_hedges: list = []
        self.c_bucket: list = []
        # struct-of-arrays request table (python lists; np at the end)
        self.r_sub: list[float] = []
        self.r_adm: list[float] = []
        self.r_ft: list[float] = []
        self.r_done: list[float] = []
        self.r_out: list[int] = []
        self.r_shedc: list[int] = []
        self.r_tcode: list[int] = []
        self.r_plen: list[int] = []
        self.r_prefix: list[int] = []
        self.r_pfxlen: list[int] = []
        self.r_maxnew: list[int] = []
        self.r_rep0: list[int] = []
        self.r_hedged: list[bool] = []
        self.r_repfin: list[int] = []
        # hedge-leg books (hedge_p99 only)
        self.leg_admit: dict[int, float] = {}   # item -> admit time
        self.leg_ft: dict[int, float] = {}      # item -> scheduled ft
        self.leg_fin: set[int] = set()
        self.leg_slot: dict[int, tuple] = {}    # item -> (_Rep, slot)
        self.hedge_rep: dict[int, int] = {}     # ridx -> hedge replica
        self.winner: dict[int, int] = {}        # ridx -> winning item
        self.res_heap: list = []                # (ft_t, seq, ridx)
        self.res_seq = 0
        self.hheap: list = []                   # (deadline, seq, ridx)
        self.armed: set[int] = set()
        self.hseq = 0
        self.charged: set[int] = set()
        self.rheap: list = []                   # (due, idx, ridx, att)
        self.n_submitted = 0
        self.n_completed = 0
        self.n_shed = 0
        self.n_hedges = 0
        self.n_hedges_refused = 0
        self.n_over_budget = 0
        self.n_resubmits = 0
        self.last_t = self.clock.now()

    # -- tenant facts ---------------------------------------------------

    def bind_tenants(self, names: list) -> str | None:
        """Resolve per-code contract facts; a name outside the
        registry (or tenantless traffic on a qos router) is a scalar
        matter — return the fallback reason instead of guessing."""
        if self.qos is None:
            self.c_name = list(names)
            return None
        for nm in names:
            try:
                c = self.qos.get(nm)
            except KeyError:
                return f"unknown tenant {nm!r} (scalar raises by name)"
            self.c_name.append(nm)
            self.c_shed.append(c.sheddable)
            self.c_hedges.append(c.hedges)
            self.c_bucket.append(self.buckets.get(nm))
        return None

    # -- placement (the router's pick, replicated) ----------------------

    def _least_loaded(self, cands: list[int]) -> int:
        reps = self.reps
        best, bl = cands[0], None
        for i in cands:
            load = reps[i].load
            if bl is None or load < bl:
                best, bl = i, load
        return best

    def _pick(self, g: int, pl: int) -> int:
        if self.policy == "round_robin":
            n = self.n_all
            routable = self.routable
            for d in range(n):
                i = (self.rrc + d) % n
                if i in routable:
                    self.rrc = (i + 1) % n
                    return i
        if self.policy == "prefix_affinity":
            return self._bounded_affinity(g, pl, self.routable)
        return self._least_loaded(self.routable)

    def _bounded_affinity(self, g: int, pl: int,
                          cands: list[int]) -> int:
        reps = self.reps
        aff, aff_sc = None, 0
        for i in cands:
            r = reps[i]
            if g == -1 or r.resident.get(g, 0) < 1:
                sc = 0
            else:
                sc = -(-pl // r.C)
            if sc > aff_sc or (
                sc == aff_sc and sc > 0
                and reps[i].load < reps[aff].load
            ):
                aff, aff_sc = i, sc
        ll = self._least_loaded(cands)
        if aff is None or aff_sc == 0:
            return ll
        if reps[aff].load <= reps[ll].load + reps[aff].S:
            return aff
        return ll

    # -- replica work ---------------------------------------------------

    def _enqueue(self, rep: _Rep, it: int, ridx: int, t: float) -> None:
        if rep.max_queue is not None and rep.q_len >= rep.max_queue:
            raise RuntimeError(
                f"queue ceiling: {rep.q_len} requests already queued "
                f"at max_queue={rep.max_queue} — shed at the router "
                "(shed_depth=) instead of queueing unboundedly"
            )
        if rep.drr is not None:
            tc = self.r_tcode[ridx]
            if tc < 0:
                raise ValueError(
                    "qos SimReplica needs tenant= at submit: "
                    "admission order is per-contract (register a "
                    "catch-all TenantContract for untagged traffic)"
                )
            h = _H(it)
            rep.handles[it] = h
            rep.drr.enqueue(
                self.c_name[tc], h,
                float(self.r_plen[ridx] + self.r_maxnew[ridx]),
            )
        else:
            rep.fifo.append(it)
        rep.q_len += 1
        rep.load += 1
        self.depth += 1
        if rep.idle:
            # chain start: the scalar submit schedules the first tick
            # off the PRE-increment tick index
            rep.idle = False
            rep.base = rep.cur
            rep.times = [t]
            rep.dts = []
            rep.wake = rep.cur + 1
        elif rep.free:
            k = rep.tick_after(t)
            if rep.wake is None or k < rep.wake:
                rep.wake = k
        rep.refresh()

    def _release_residency(self, rep: _Rep, g: int) -> None:
        left = rep.resident.get(g, 0) - 1
        if left > 0:
            rep.resident[g] = left
        else:
            rep.resident.pop(g, None)

    def _complete(self, ridx: int, it: int, t: float) -> None:
        """Winning leg finished (hedge mode): stamp the completion the
        way ``_resolve_completions`` would at this step."""
        self.r_done[ridx] = t
        if self.r_hedged[ridx]:
            self.r_out[ridx] = (
                _HEDGE_WON if (it & 1) else _HEDGED
            )
        else:
            self.r_out[ridx] = _OK
        self.n_completed += 1

    def _tick(self, rep: _Rep, t: float) -> None:
        """Process one *eventful* tick at time ``t``: retirements and
        admissions interleaved in ascending slot order — the scalar
        step()'s single fused pass."""
        k = rep.next_k
        rep.cur = k
        rep.last_tick_t = t
        if rep.wake is not None and rep.wake <= k:
            rep.wake = None
        rh = rep.retire
        sg = rep.slot_gen
        ret: list = []
        while rh and rh[0][0] <= k:
            e = heapq.heappop(rh)
            if sg[e[1]] == e[2]:
                ret.append((e[1], e[3]))
        ret.sort()
        free = rep.free
        fifo = rep.fifo
        drr = rep.drr
        hedging = self.hedging
        newly: list[int] = []
        ri, nret = 0, len(ret)
        can_admit = True
        while True:
            rslot = ret[ri][0] if ri < nret else _BIG
            fslot = free[0] if (can_admit and free) else _BIG
            if rslot >= _BIG and fslot >= _BIG:
                break
            if rslot < fslot:
                s, it = ret[ri]
                ri += 1
                sg[s] += 1
                rep.active -= 1
                rep.load -= 1
                ridx = it >> 1
                g = self.r_prefix[ridx]
                if g != -1:
                    self._release_residency(rep, g)
                rep.n_retired += 1
                newly.append(s)
                if hedging:
                    self.leg_fin.add(it)
                    self.leg_slot.pop(it, None)
                    if self.winner.get(ridx) == it:
                        self._complete(ridx, it, t)
                else:
                    self.n_completed += 1
                continue
            # admission attempt at slot fslot
            if drr is not None:
                picked = drr.pick()
                if picked is None:
                    can_admit = False
                    continue
                it = int(picked[1])
                rep.handles.pop(it, None)
            else:
                if not fifo:
                    can_admit = False
                    continue
                it = fifo.popleft()
            s = heapq.heappop(free)
            rep.q_len -= 1
            self.depth -= 1
            ridx = it >> 1
            g = self.r_prefix[ridx]
            skip = 0
            if g != -1:
                if rep.resident.get(g, 0):
                    skip = self.r_pfxlen[ridx]
                    rep.n_shared_admits += 1
            chunks = -(-(self.r_plen[ridx] - skip) // rep.C)
            if chunks < 1:
                chunks = 1
            mn = self.r_maxnew[ridx]
            ftk = k + chunks - 1
            dk = (ftk if mn == 1
                  else ftk + -(-(mn - 1) // rep.n_inner))
            if dk == k:
                # chunks == 1 and max_new == 1: admitted, first token,
                # and retired in this very tick — residency is a net
                # no-op (scalar: +1 then _free's -1/pop), the slot
                # frees back for the NEXT tick, load drops by the
                # departed queue entry
                rep.n_retired += 1
                rep.load -= 1
                newly.append(s)
                if hedging:
                    self.leg_admit[it] = t
                    self.leg_ft[it] = t
                    self.leg_fin.add(it)
                    heapq.heappush(self.res_heap,
                                   (t, self.res_seq, ridx))
                    self.res_seq += 1
                    if self.winner.get(ridx) == it:
                        self._complete(ridx, it, t)
                else:
                    self.r_adm[ridx] = t
                    self.r_ft[ridx] = t
                    self.r_done[ridx] = t
                    self.r_out[ridx] = _OK
                    self.r_repfin[ridx] = rep.i
                    self.n_completed += 1
                continue
            if g != -1:
                rep.resident[g] = rep.resident.get(g, 0) + 1
            heapq.heappush(rh, (dk, s, sg[s], it))
            rep.active += 1
            rep.ensure(dk - rep.base)
            ft_t = rep.times[ftk - rep.base]
            dn_t = rep.times[dk - rep.base]
            if hedging:
                self.leg_admit[it] = t
                self.leg_ft[it] = ft_t
                self.leg_slot[it] = (rep, s)
                heapq.heappush(self.res_heap,
                               (ft_t, self.res_seq, ridx))
                self.res_seq += 1
            else:
                self.r_adm[ridx] = t
                self.r_ft[ridx] = ft_t
                self.r_done[ridx] = dn_t
                self.r_out[ridx] = _OK
                self.r_repfin[ridx] = rep.i
        for s in newly:
            heapq.heappush(free, s)
        # chain boundary: empty after the scan means THIS tick was the
        # terminating one (scalar: next_tick_at = None, no busy add)
        if rep.active == 0 and rep.q_len == 0:
            rep.wake = None
            rep.busy_parts.append(rep.dts[1:k - rep.base])
            rep.idle = True
            rep.times = []
            rep.dts = []
            rep.next_ev = _INF
            return
        if rep.q_len and free:
            rep.wake = k + 1
        rep.refresh()

    # -- hedge resolution (hedge_p99 only) ------------------------------

    def _resolve(self, ridx: int, t: float) -> None:
        if ridx in self.winner or self.r_out[ridx] == _SHED:
            return
        it0 = ridx * 2
        f0 = self.leg_ft.get(it0)
        hrep = self.hedge_rep.get(ridx)
        if f0 is not None and f0 <= t:
            win = it0
        else:
            win = it0 + 1
        self.winner[ridx] = win
        adm = self.leg_admit.get(it0)
        a1 = self.leg_admit.get(it0 + 1)
        if a1 is not None and (adm is None or a1 < adm):
            adm = a1
        self.r_adm[ridx] = adm
        self.r_ft[ridx] = t
        self.r_repfin[ridx] = (hrep if (win & 1) else
                               self.r_rep0[ridx])
        self.armed.discard(ridx)
        if ridx in self.charged:
            self.charged.discard(ridx)
            nm = self.c_name[self.r_tcode[ridx]]
            left = self.hedges_out.get(nm, 0) - 1
            if left > 0:
                self.hedges_out[nm] = left
            else:
                self.hedges_out.pop(nm, None)
        # cancel the losing leg (scalar: replicas[jj].cancel(loser) —
        # a no-op on a finished leg)
        lose = it0 + 1 if win == it0 else it0
        if (lose == it0 or hrep is not None) and lose not in self.leg_fin:
            lrep = self.reps[self.r_rep0[ridx] if lose == it0 else hrep]
            slot = self.leg_slot.pop(lose, None)
            if slot is not None:
                _, s = slot
                lrep.slot_gen[s] += 1
                lrep.active -= 1
                lrep.load -= 1
                g = self.r_prefix[ridx]
                if g != -1:
                    self._release_residency(lrep, g)
                lrep.n_cancelled += 1
                heapq.heappush(lrep.free, s)
                if lrep.q_len:
                    k = lrep.tick_after(t)
                    if lrep.wake is None or k < lrep.wake:
                        lrep.wake = k
                elif lrep.active == 0:
                    lrep.wake = lrep.tick_after(t)  # ghost/ending tick
                lrep.refresh()
            else:
                # still queued: withdraw it
                if lrep.drr is not None:
                    h = lrep.handles.pop(lose, None)
                    if h is not None and lrep.drr.remove(h):
                        lrep.q_len -= 1
                        lrep.load -= 1
                        self.depth -= 1
                        lrep.n_cancelled += 1
                else:
                    try:
                        lrep.fifo.remove(lose)
                    except ValueError:
                        pass
                    else:
                        lrep.q_len -= 1
                        lrep.load -= 1
                        self.depth -= 1
                        lrep.n_cancelled += 1
        if win in self.leg_fin:
            self._complete(ridx, win, t)

    def _fire_hedge(self, ridx: int, t: float) -> None:
        primary = self.r_rep0[ridx]
        cands = [i for i in self.routable if i != primary]
        if not cands:
            return  # nowhere to hedge to; the primary stands
        tc = self.r_tcode[ridx]
        if self.qos is not None and tc >= 0:
            ent = self.c_hedges[tc]
            if ent is not None:
                nm = self.c_name[tc]
                out = self.hedges_out.get(nm, 0)
                if out >= ent:
                    self.n_hedges_refused += 1
                    return
                self.hedges_out[nm] = out + 1
                self.charged.add(ridx)
        j = self._least_loaded(cands)
        self.hedge_rep[ridx] = j
        self.r_hedged[ridx] = True
        self._enqueue(self.reps[j], ridx * 2 + 1, ridx, t)
        self.n_hedges += 1

    # -- the entry door -------------------------------------------------

    def _shed(self, t: float, reason_code: int) -> None:
        self.r_adm.append(_INF)
        self.r_ft.append(_INF)
        self.r_done.append(t)
        self.r_out.append(_SHED)
        self.r_shedc[-1] = reason_code
        self.r_rep0.append(-1)
        self.r_hedged.append(False)
        self.r_repfin.append(-1)
        self.n_submitted += 1
        self.n_completed += 1
        self.n_shed += 1

    def _submit(self, t: float, plen: int, g: int, pl: int, mn: int,
                tc: int) -> int:
        """The router submit door, array-native. Returns the new ridx;
        the request is shed iff its outcome code says so."""
        ridx = len(self.r_sub)
        self.r_sub.append(t)
        self.r_tcode.append(tc)
        self.r_plen.append(plen)
        self.r_prefix.append(g)
        self.r_pfxlen.append(pl)
        self.r_maxnew.append(mn)
        self.r_shedc.append(0)
        if self.shed_depth is not None:
            depth = self.depth
            if depth >= self.shed_depth_hard:
                reason_code = _SHED_CODES["overload_hard"]
                self._shed(t, reason_code)
                return ridx
            if depth >= self.shed_depth and (
                self.qos is None or self.c_shed[tc]
            ):
                reason_code = _SHED_CODES["overload"]
                self._shed(t, reason_code)
                return ridx
        if self.qos is not None:
            b = self.c_bucket[tc]
            if b is not None and not b.take(plen + mn, t):
                if self.c_shed[tc]:
                    reason_code = _SHED_CODES["budget"]
                    self._shed(t, reason_code)
                    return ridx
                self.n_over_budget += 1
        i = self._pick(g, pl)
        self.r_adm.append(_INF)
        self.r_ft.append(_INF)
        self.r_done.append(_INF)
        self.r_out.append(_INFLIGHT)
        self.r_rep0.append(i)
        self.r_hedged.append(False)
        self.r_repfin.append(-1)
        self._enqueue(self.reps[i], ridx * 2, ridx, t)
        if self.hedging:
            heapq.heappush(self.hheap,
                           (t + self.slo, self.hseq, ridx))
            self.hseq += 1
            self.armed.add(ridx)
        self.n_submitted += 1
        return ridx

    # -- the drive loop -------------------------------------------------

    def run(self, batch: ArrivalBatch) -> None:
        arr_t = batch.t.tolist()
        arr_pl = batch.plen.tolist()
        arr_g = batch.prefix.tolist()
        arr_gl = batch.prefix_len.tolist()
        arr_mn = batch.max_new.tolist()
        arr_tc = batch.tenant.tolist()
        n_arr = len(arr_t)
        ai = 0
        reps = self.reps
        order = self.routable  # phase-1 order == scalar _routable scan
        retry = self.retry
        rheap = self.rheap
        res_heap = self.res_heap
        hheap = self.hheap
        winner = self.winner
        armed = self.armed
        while True:
            if ai >= n_arr and self.n_completed == self.n_submitted:
                break
            # next boundary over all live event sources
            t = arr_t[ai] if ai < n_arr else _INF
            for rep in reps:
                ne = rep.next_ev
                if ne < t:
                    t = ne
            while res_heap and res_heap[0][2] in winner:
                heapq.heappop(res_heap)
            if res_heap and res_heap[0][0] < t:
                t = res_heap[0][0]
            while hheap and hheap[0][2] not in armed:
                heapq.heappop(hheap)
            if hheap and hheap[0][0] < t:
                t = hheap[0][0]
            if rheap and rheap[0][0] < t:
                t = rheap[0][0]
            if t == _INF:
                raise RuntimeError(
                    "workload stalled with "
                    f"{self.n_submitted - self.n_completed} requests "
                    "in flight: no replica tick, hedge deadline, or "
                    "clock event pending"
                )
            self.last_t = t
            # phase 1: replica ticks (routable order, like step())
            for i in order:
                rep = reps[i]
                if rep.next_ev == t:
                    self._tick(rep, t)
            # phase 2: first-token resolutions due now
            while res_heap and res_heap[0][0] == t:
                e = heapq.heappop(res_heap)
                self._resolve(e[2], t)
            # phase 3: hedge deadlines due now
            while hheap:
                while hheap and hheap[0][2] not in armed:
                    heapq.heappop(hheap)
                if not hheap or hheap[0][0] != t:
                    break
                _d, _s, ridx = heapq.heappop(hheap)
                armed.discard(ridx)
                self._fire_hedge(ridx, t)
            # phase 4: retry dues (the scalar fire_retries, pre-arrival)
            while rheap and rheap[0][0] == t:
                _due, _idx, r0, attempt = heapq.heappop(rheap)
                if self.r_ft[r0] <= t:
                    continue  # first token landed; the chain expires
                if attempt + 1 > retry.max_retries:
                    continue
                r2 = self._submit(
                    t, self.r_plen[r0], self.r_prefix[r0],
                    self.r_pfxlen[r0], self.r_maxnew[r0],
                    self.r_tcode[r0],
                )
                self.n_resubmits += 1
                if self.r_out[r2] != _SHED:
                    due2 = retry.resubmit_at(t, self.n_submitted,
                                             attempt + 1)
                    heapq.heappush(
                        rheap, (due2, self.n_submitted, r2, attempt + 1)
                    )
            # phase 5: arrivals stamped exactly now
            while ai < n_arr and arr_t[ai] == t:
                r1 = self._submit(t, arr_pl[ai], arr_g[ai],
                                  arr_gl[ai], arr_mn[ai], arr_tc[ai])
                ai += 1
                if retry is not None and self.r_out[r1] != _SHED:
                    due = retry.resubmit_at(t, self.n_submitted, 0)
                    heapq.heappush(
                        rheap, (due, self.n_submitted, r1, 0)
                    )

    # -- write-back and report ------------------------------------------

    def finish(self) -> int:
        """Land the day's end state on the REAL router/replicas — the
        sweeps read replica counters off the objects, and a fast day
        must leave the fleet exactly as the scalar drain would.
        Returns the fleet's total fired ticks."""
        router = self.router
        total_ticks = 0
        for rep in self.reps:
            r = router.replicas[rep.i]
            r.tick_count = rep.cur
            total_ticks += rep.cur
            r.last_tick_at = rep.last_tick_t
            if rep.idle:
                r.next_tick_at = None
            else:
                # an open chain at day end: the scalar drain stopped
                # at in-flight zero with this replica's (ghost) tick
                # still scheduled — schedule it, fire it never
                j = rep.cur + 1 - rep.base
                rep.ensure(j)
                r.next_tick_at = rep.times[j]
                rep.busy_parts.append(
                    rep.dts[1:rep.cur - rep.base + 1]
                )
            parts = [p for p in rep.busy_parts if p]
            if parts:
                flat = np.concatenate(
                    [np.asarray(p) for p in parts]
                )
                r.busy_s = float(np.cumsum(flat)[-1])
            else:
                r.busy_s = 0.0
            r.n_retired = rep.n_retired
            r.n_cancelled = rep.n_cancelled
            r.n_shared_admits = rep.n_shared_admits
        router.n_submitted = self.n_submitted
        router.n_completed = self.n_completed
        router.n_shed = self.n_shed
        router.n_hedges = self.n_hedges
        router.n_hedges_refused = self.n_hedges_refused
        router.n_over_budget = self.n_over_budget
        router._rr = self.rrc
        if self.last_t > self.clock.now():
            self.clock.run_until(self.last_t)
        return total_ticks

    def report(self, n_events: int | None,
               wall_s: float | None) -> WorkloadReport:
        sub = np.asarray(self.r_sub)
        ft = np.asarray(self.r_ft)
        done = np.asarray(self.r_done)
        out = np.asarray(self.r_out, np.int64)
        mn = np.asarray(self.r_maxnew, np.int64)
        served = out != _SHED
        outcomes: dict[str, int] = {}
        counts = np.bincount(out, minlength=5)
        for code in (_OK, _HEDGED, _HEDGE_WON, _SHED):
            c = int(counts[code])
            if c:
                outcomes[_OUT_NAMES[code]] = c
        shed_reasons: dict[str, int] = {}
        if self.n_shed:
            sc = np.bincount(np.asarray(self.r_shedc, np.int64),
                             minlength=4)
            for code, nm in _SHED_NAMES.items():
                if sc[code]:
                    shed_reasons[nm] = int(sc[code])
        decode = served & (mn > 1)
        itl = (done[decode] - ft[decode]) / (mn[decode] - 1)
        requests = _FastRequests(self)
        return WorkloadReport.from_arrays(
            requests, self.last_t, self.router,
            ttft=ft[served] - sub[served],
            latency=done[served] - sub[served],
            outcomes=outcomes, shed_reasons=shed_reasons,
            dropped=int(np.count_nonzero(out == _INFLIGHT)),
            decode_itl=itl, n_resubmits=self.n_resubmits,
            n_events=n_events, wall_s=wall_s,
        )


# -- lazy request views ---------------------------------------------------


class _ReqView:
    """One request's report-facing record: the attributes the sweeps
    and per-tenant books read off scalar ``RoutedRequest``s, served
    from the engine's arrays."""

    __slots__ = ("t_submit", "t_admitted", "t_first_token", "t_done",
                 "tenant", "outcome", "shed_reason", "finished",
                 "hedged", "replica", "max_new", "key")

    @property
    def ttft(self):
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def latency(self):
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit

    @property
    def tokens(self):
        return range(self.max_new if self.outcome != "shed" else 0)


class _FastRequests:
    """Sequence facade over the engine's struct-of-arrays request
    table: ``report.requests[i]`` / iteration materialize lightweight
    views on demand — a million-request day never builds a million
    records unless someone actually walks them."""

    def __init__(self, eng: _Engine):
        self._e = eng

    def __len__(self) -> int:
        return len(self._e.r_sub)

    def _view(self, i: int) -> _ReqView:
        e = self._e
        v = _ReqView()
        out = e.r_out[i]
        v.t_submit = e.r_sub[i]
        shed = out == _SHED
        v.t_admitted = None if shed else e.r_adm[i]
        v.t_first_token = None if shed else e.r_ft[i]
        v.t_done = e.r_done[i]
        tc = e.r_tcode[i]
        v.tenant = None if tc < 0 else e.c_name[tc]
        v.outcome = _OUT_NAMES.get(out)
        v.shed_reason = (_SHED_NAMES.get(e.r_shedc[i])
                         if shed else None)
        v.finished = out != _INFLIGHT
        v.hedged = e.r_hedged[i]
        v.replica = None if shed else e.r_repfin[i]
        v.max_new = e.r_maxnew[i]
        v.key = None
        return v

    def __getitem__(self, i: int) -> _ReqView:
        n = len(self._e.r_sub)
        if i < 0:
            i += n
        if not (0 <= i < n):
            raise IndexError(i)
        return self._view(i)

    def __iter__(self):
        for i in range(len(self._e.r_sub)):
            yield self._view(i)


# -- the public driver ----------------------------------------------------


def run_router_day_fast(
    router, arrivals, *, controller=None, events: Iterable = (),
    retry: RetryPolicy | None = None,
    timer: Callable[[], float] | None = None,
    series=None, slo=None,
) -> WorkloadReport:
    """:func:`~.workload.run_router_day` with the vectorized engine on
    supported days and a transparent scalar fallback on the rest —
    same signature, same report, bit-identical
    :meth:`~.workload.WorkloadReport.digest` either way.
    ``report.fastpath`` says which path ran (``"vectorized"`` or
    ``"scalar-fallback: <reason>"``); ``timer=`` opts into events/s
    self-measurement exactly as on the scalar driver."""
    evs = list(events)
    ok, reason = fastpath_supported(
        router, controller=controller, events=evs, retry=retry,
        series=series, slo=slo,
    )
    batch = None
    if ok:
        batch = (arrivals if isinstance(arrivals, ArrivalBatch)
                 else ArrivalBatch.from_arrivals(arrivals))
        if router._qos is not None and bool((batch.tenant < 0).any()):
            ok, reason = False, "untenanted traffic on a qos router"
        arrivals = batch  # the columns ARE the stream, for either path
    if ok:
        eng = _Engine(router, retry)
        bad = eng.bind_tenants(batch.tenant_names)
        if bad is not None:
            ok, reason = False, bad
    if not ok:
        rep = run_router_day(router, arrivals, controller=controller,
                             events=evs, retry=retry, timer=timer,
                             series=series, slo=slo)
        rep.fastpath = f"scalar-fallback: {reason}"
        return rep
    wall_t0 = timer() if timer is not None else None
    eng.run(batch)
    total_ticks = eng.finish()
    n_events = eng.n_submitted + total_ticks
    wall = None if wall_t0 is None else timer() - wall_t0
    rep = eng.report(n_events, wall)
    rep.fastpath = "vectorized"
    return rep
