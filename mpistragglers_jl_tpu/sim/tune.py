"""Policy autotuning: sweep (nwait, hedge width, code rate) on virtual time.

The paper's entire value proposition is one knob — return after the
``nwait`` fastest workers — and until now the only ways to price a
setting were live runs with injected sleeps (wall-clock, flaky) or
:meth:`~..utils.straggle.PoolLatencyModel.optimal_nwait`'s closed-form
Monte Carlo (fast, but it models an epoch as one order statistic and
never exercises the real pool's stale-harvest/re-task machinery).
This module is the third estimator: run the REAL ``asyncmap`` loop on a
:class:`~.backend.SimBackend` for every candidate policy and measure
virtual wall clock — the full pool semantics at simulator speed,
against either a recorded trace (:class:`~.replay.ReplayTrace`), a
fitted latency model (:func:`~.backend.model_delay_fn`), or any
:mod:`..utils.faults` schedule.

Every sweep respects the decodability floor: for an (n, k) code, fewer
than k fresh shards cannot decode, so candidates below ``floor`` are
never evaluated (the same ``kmin`` contract as
``PoolLatencyModel.optimal_nwait`` and ``AdaptiveNwait``), and
:func:`recommend_nwait` cross-checks the sim sweep against the model's
analytic pick so the two estimators keep each other honest.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from ..backends.base import DelayFn
from ..pool import AsyncPool, asyncmap, waitall
from ..utils.hedge import HedgedServer
from ..utils.trace import EpochTracer
from .backend import SimBackend, model_delay_fn
from .clock import VirtualClock
from .replay import ReplayTrace

__all__ = [
    "NwaitSweep",
    "sweep_nwait",
    "sweep_hedge",
    "sweep_code_rate",
    "sweep_harvest_k",
    "sweep_hierarchical",
    "sweep_router_policy",
    "sweep_spill_capacity",
    "sweep_tenant_weights",
    "sweep_tier_split",
    "recommend_nwait",
    "recovered_work_per_s",
]


def _echo(i, payload, epoch):
    return payload


def recovered_work_per_s(
    k: float, mean_epoch_s: float,
    *, utility: Callable[[int], float] | None = None,
) -> float:
    """The recovered-work-per-virtual-second objective every
    code-rate-style sweep shares (``sweep_nwait``, ``sweep_code_rate``,
    ``sweep_hierarchical`` — ONE implementation, not three):
    ``utility(k) / mean_epoch_s`` with the default utility ``k`` —
    source blocks recovered per epoch, so the default objective is
    maximum decoded work per second. ``k`` is whatever the sweep's
    recovery unit is (fresh shards for a flat code, ``L * inner_nwait``
    source blocks for the hierarchical pair)."""
    u = float(k) if utility is None else float(utility(k))
    return u / mean_epoch_s if mean_epoch_s > 0 else float(np.inf)


def _resolve_fast(fast: str) -> bool:
    """Shared ``fast=`` knob of the router-day sweeps: ``"auto"`` runs
    each candidate day through :func:`~.fastpath.run_router_day_fast`
    (bit-identical digests by contract, so the sweep's decision is
    unchanged — only its cost), ``"never"`` pins the scalar loop.
    Unsupported day shapes (e.g. ``chunk_s`` tiers) fall back to the
    scalar path inside ``run_router_day_fast`` itself, so ``"auto"``
    is always safe to leave on."""
    if fast not in ("auto", "never"):
        raise ValueError(
            f'fast must be "auto" or "never", got {fast!r}'
        )
    return fast == "auto"


def _resolve_delay(source, *, seed: int) -> tuple[DelayFn, int | None]:
    """(delay_fn, n_workers hint) from a trace / model / DelayFn."""
    if isinstance(source, ReplayTrace):
        return source.delay_fn(), source.n_workers
    if hasattr(source, "workers") and hasattr(source, "observe_pool"):
        return model_delay_fn(source, seed=seed), source.n_workers
    if callable(source):
        return source, None
    raise TypeError(
        "latency source must be a ReplayTrace, a PoolLatencyModel, or "
        f"a DelayFn callable, got {type(source)}"
    )


class NwaitSweep:
    """Result table of one policy sweep.

    ``entries`` rows: ``nwait``, ``mean_epoch_s`` / ``p95_epoch_s``
    (virtual), ``utility_per_s`` (``utility(k) / mean_epoch_s`` — the
    ``optimal_nwait`` objective, default utility ``k`` = fresh results
    per epoch), ``n_stale`` harvested over the run. ``best`` is the
    recommended nwait (argmax utility-per-second, never below the
    floor by construction).
    """

    def __init__(self, entries: list[dict], floor: int):
        if not entries:
            raise ValueError("empty sweep: no candidate policies ran")
        self.entries = entries
        self.floor = int(floor)
        self.best = int(
            max(entries, key=lambda r: r["utility_per_s"])["nwait"]
        )

    def entry(self, nwait: int) -> dict:
        for r in self.entries:
            if r["nwait"] == nwait:
                return r
        raise KeyError(f"nwait={nwait} was not swept")

    def table(self) -> str:
        """Human-readable sweep table (examples/policy_tuning.py)."""
        lines = [
            f"{'nwait':>6} {'mean epoch':>12} {'p95 epoch':>12} "
            f"{'util/s':>10} {'stale':>6}"
        ]
        for r in self.entries:
            mark = " <- best" if r["nwait"] == self.best else ""
            lines.append(
                f"{r['nwait']:>6} {r['mean_epoch_s']*1e3:>9.3f} ms "
                f"{r['p95_epoch_s']*1e3:>9.3f} ms "
                f"{r['utility_per_s']:>10.1f} {r['n_stale']:>6}{mark}"
            )
        return "\n".join(lines)

    def __repr__(self) -> str:
        return (
            f"NwaitSweep(best={self.best}, floor={self.floor}, "
            f"{len(self.entries)} candidates)"
        )


def sweep_nwait(
    source,
    *,
    n_workers: int | None = None,
    epochs: int = 100,
    floor: int = 1,
    nwait_values: Sequence[int] | None = None,
    utility: Callable[[int], float] | None = None,
    work_fn=None,
    payload=None,
    seed: int = 0,
    registry=None,
    spans=None,
) -> NwaitSweep:
    """Price every candidate ``nwait`` by running the real pool loop on
    virtual time.

    ``source`` supplies the fleet's latency behavior: a
    :class:`~.replay.ReplayTrace` (recorded incident), a
    :class:`~..utils.straggle.PoolLatencyModel` (fitted fleet), or a
    raw :data:`~..backends.base.DelayFn` (synthetic scenario —
    ``n_workers`` required then). Candidates default to
    ``floor..n_workers``; anything below ``floor`` (the code's
    decodability k) is refused rather than silently clamped.
    """
    delay_fn, n_hint = _resolve_delay(source, seed=seed)
    n = int(n_workers if n_workers is not None else (n_hint or 0))
    if n <= 0:
        raise ValueError(
            "n_workers is required when the latency source does not "
            "carry a pool size"
        )
    floor = int(floor)
    if not (1 <= floor <= n):
        raise ValueError(f"floor must be in [1, {n}], got {floor}")
    ks = (
        list(range(floor, n + 1)) if nwait_values is None
        else sorted({int(k) for k in nwait_values})
    )
    if any(k < floor for k in ks):
        raise ValueError(
            f"nwait candidates {sorted(k for k in ks if k < floor)} sit "
            f"below the decodability floor {floor}: fewer than "
            f"{floor} fresh shards cannot decode"
        )
    if any(k > n for k in ks):
        raise ValueError(f"nwait candidates must be <= n_workers={n}")
    if work_fn is None:
        work_fn = _echo
    if payload is None:
        payload = np.zeros(1, dtype=np.float64)
    entries: list[dict] = []
    for k in ks:
        backend = SimBackend(
            work_fn, n, delay_fn=delay_fn, clock=VirtualClock(),
            registry=registry, spans=spans,
        )
        pool = AsyncPool(n)
        tracer = EpochTracer()  # sim runs feed the same tracer plane
        walls = np.empty(epochs)
        for e in range(epochs):
            t0 = backend.clock.now()
            asyncmap(pool, payload, backend, nwait=k, tracer=tracer)
            walls[e] = backend.clock.now() - t0
        if pool.active.any():
            waitall(pool, backend, tracer=tracer)
        mean = float(walls.mean())
        entries.append({
            "nwait": k,
            "mean_epoch_s": mean,
            "p95_epoch_s": float(np.percentile(walls, 95)),
            "utility_per_s": recovered_work_per_s(k, mean, utility=utility),
            "n_stale": int(sum(r.n_stale for r in tracer.records)),
        })
    return NwaitSweep(entries, floor)


def sweep_code_rate(
    source,
    *,
    n_workers: int | None = None,
    k_values: Sequence[int],
    epochs: int = 100,
    utility: Callable[[int], float] | None = None,
    seed: int = 0,
) -> NwaitSweep:
    """Price (n, k) code rates: each candidate k runs at ``nwait=k``
    (the decodability floor IS the policy — an (n, k) code returns the
    moment k shards are fresh), utility defaulting to recovered work
    per second (``k / E[epoch]``). Lower k dodges deeper order
    statistics but discards more redundant compute; the sweep prices
    that trade on the actual pool semantics."""
    ks = sorted({int(k) for k in k_values})
    return sweep_nwait(
        source, n_workers=n_workers, epochs=epochs, floor=min(ks),
        nwait_values=ks, utility=utility, seed=seed,
    )


def sweep_hierarchical(
    source,
    *,
    groups: int,
    n_inner: int,
    candidates: Sequence[tuple[float, int]],
    inner_floor: int = 1,
    epochs: int = 60,
    failures=None,
    outer_kind: str = "auto",
    utility: Callable[[int], float] | None = None,
    seed: int = 0,
    model=None,
    registry=None,
    spans=None,
) -> dict[str, Any]:
    """Price ``(outer_rate, inner_nwait)`` pairs for the two-level
    hierarchical code (:class:`~..ops.hierarchical.
    HierarchicalCodedGemm`) by running the REAL pool loop — the real
    ``asyncmap`` under the real :func:`~..ops.outer_code.
    hierarchical_nwait` two-level predicate — on a :class:`~.backend.
    SimBackend` fleet of ``groups * n_inner`` workers, per candidate.
    This is the (outer rate, inner nwait) latency–communication
    trade-off of arxiv 1808.06583 priced on the actual pool semantics
    instead of a closed form.

    ``source`` supplies fleet latency like every sweep here (a
    :class:`~.replay.ReplayTrace`, a fitted
    :class:`~..utils.straggle.PoolLatencyModel`, or a raw DelayFn);
    ``failures`` maps group id -> kill epoch and injects whole-host
    failures via :class:`~..utils.faults.kill_group` on top of it —
    the scenario the outer code exists for, testable deterministically.

    Candidates below EITHER decodability floor are REFUSED, never
    clamped (the ``sweep_nwait`` contract): an ``inner_nwait`` below
    ``inner_floor`` cannot inner-decode, an ``outer_rate`` rounding to
    ``L < 1`` source groups cannot outer-decode, and an ``outer_rate``
    whose ``L`` exceeds the groups surviving the scheduled failures
    can never complete an epoch after the kill.

    Utility is the shared :func:`recovered_work_per_s` objective with
    recovery unit ``L * inner_nwait`` (source blocks decoded per
    epoch) — sweep_code_rate's recovered-work/s, not a third copy.

    The returned dict carries the ``recommend_nwait``-style inner
    cross-check: ``inner_model`` is the analytic
    ``PoolLatencyModel.optimal_nwait`` over ONE surviving group's
    fitted per-worker distributions (``check_group``), and ``agree``
    flags whether the sim's chosen inner_nwait matches it — divergence
    means the two-level pool dynamics (which only the sim exercises)
    moved the inner optimum.
    """
    # sim/ is a GC001 hermetic root: the outer-code machinery is numpy
    # + ops/lt.py (jax-free), but ops/ is the accelerator package —
    # keep the import lazy so the sim closure stays provably clean
    from ..ops.outer_code import (
        hierarchical_nwait,
        make_outer,
        partition_groups,
    )
    from ..utils import faults
    from ..utils.straggle import PoolLatencyModel

    H, ni = int(groups), int(n_inner)
    if H < 1 or ni < 1:
        raise ValueError(f"need groups >= 1 and n_inner >= 1, got {groups}, {n_inner}")
    n = H * ni
    inner_floor = int(inner_floor)
    if not (1 <= inner_floor <= ni):
        raise ValueError(
            f"inner_floor must be in [1, {ni}], got {inner_floor}"
        )
    cands = [(float(r), int(k)) for r, k in candidates]
    if not cands:
        raise ValueError("empty sweep: no candidate policies given")
    kills = {} if failures is None else {
        int(g): int(e) for g, e in dict(failures).items()
    }
    # groups whose kill never fires inside the run count as survivors
    surviving_ids = [
        g for g in range(H) if kills.get(g, epochs + 1) > epochs
    ]
    # validate EVERY candidate before any runs: a refusal names the
    # floor it sits under, it never silently clamps. The check is on
    # the surviving group-ID SET, not its size: an LT outer whose
    # survivors are all non-systematic shards can have |survivors| >=
    # L and still never peel (review finding — the count check let
    # such a candidate run and priced the 3600 s dead-stall as data).
    outers = []
    for rate, k in cands:
        if k < inner_floor:
            raise ValueError(
                f"inner_nwait={k} sits below the inner decodability "
                f"floor {inner_floor}: fewer than {inner_floor} fresh "
                "shards cannot inner-decode a group"
            )
        if k > ni:
            raise ValueError(
                f"inner_nwait={k} exceeds the {ni} workers of a group"
            )
        outer = make_outer(H, rate=rate, kind=outer_kind, seed=seed)
        if not outer.decodable(surviving_ids):
            raise ValueError(
                f"outer_rate={rate} needs L={outer.L} decodable groups "
                f"but only groups {surviving_ids} of {H} survive the "
                f"scheduled host failures {kills}, and that set cannot "
                "clear the outer decodability floor after the kill"
            )
        outers.append(outer)
    delay_fn, n_hint = _resolve_delay(source, seed=seed)
    if n_hint is not None and int(n_hint) != n:
        raise ValueError(
            f"latency source describes {n_hint} workers but the fleet "
            f"is groups*n_inner = {H}*{ni} = {n}"
        )
    part = partition_groups(n, H)
    if kills:
        delay_fn = faults.compose(
            delay_fn, faults.kill_group(part, kills)
        )
    entries: list[dict] = []
    for (rate, k), outer in zip(cands, outers):
        def inner_arrived(g, fresh, _k=k):
            return int(fresh[part[g]].sum()) >= _k

        pred = hierarchical_nwait(part, inner_arrived, outer)
        backend = SimBackend(
            _echo, n, delay_fn=delay_fn, clock=VirtualClock(),
            registry=registry, spans=spans,
        )
        pool = AsyncPool(n)
        tracer = EpochTracer()
        walls = np.empty(epochs)
        for e in range(epochs):
            t0 = backend.clock.now()
            asyncmap(pool, np.zeros(1), backend, nwait=pred,
                     tracer=tracer)
            walls[e] = backend.clock.now() - t0
        mean = float(walls.mean())
        entries.append({
            "outer_rate": rate,
            "L": outer.L,
            "inner_nwait": k,
            "mean_epoch_s": mean,
            "p95_epoch_s": float(np.percentile(walls, 95)),
            "utility_per_s": recovered_work_per_s(
                outer.L * k, mean, utility=utility
            ),
            "n_stale": int(sum(r.n_stale for r in tracer.records)),
        })
    best = max(entries, key=lambda r: r["utility_per_s"])
    # -- recommend_nwait-style inner cross-check --------------------------
    # the analytic side sees one SURVIVING group's fitted per-worker
    # distributions; the sim's inner pick should match it whenever the
    # candidate grid covers the inner optimum
    # surviving_ids is non-empty here: every candidate proved it can
    # clear the outer floor from the survivors (a scheduled kill whose
    # epoch lies beyond the run leaves its group a survivor — the
    # membership-in-kills test crashed on exactly that, review finding)
    check_group = surviving_ids[0]
    sub = PoolLatencyModel(ni, seed=seed)
    if model is not None or (
        hasattr(source, "workers") and hasattr(source, "observe_pool")
    ):
        src_model = model if model is not None else source
        sub.workers = [
            src_model.workers[int(w)] for w in part[check_group]
        ]
    else:
        base_delay, _ = _resolve_delay(source, seed=seed)
        for e in range(150):
            for j, w in enumerate(part[check_group]):
                sub.observe(j, base_delay(int(w), e))
    inner_model = int(sub.optimal_nwait(
        kmin=inner_floor, kmax=ni, utility=utility
    ))
    return {
        "entries": entries,
        "best": (best["outer_rate"], best["inner_nwait"]),
        "best_entry": best,
        "inner_sim": int(best["inner_nwait"]),
        "inner_model": inner_model,
        "agree": int(best["inner_nwait"]) == inner_model,
        "check_group": int(check_group),
        "surviving_groups": len(surviving_ids),
    }


def sweep_router_policy(
    *,
    n_replicas: int = 4,
    slots: int = 4,
    n_inner: int = 8,
    tick_s: float = 0.02,
    tick_sigma: float = 0.3,
    straggler: dict | None = None,
    policies: Sequence[str] | None = None,
    load: float = 0.8,
    prefix_share: float = 0.0,
    requests: int = 2000,
    prompt_len: int = 96,
    prefix_len: int = 64,
    n_prefix_groups: int = 4,
    max_new: int = 32,
    prompt_chunk: int = 64,
    ttft_slo: float | None = None,
    admission_slo_s: float | None = None,
    dead: Sequence[int] = (),
    seed: int = 0,
    fast: str = "auto",
) -> dict[str, Any]:
    """Recommend a request-routing policy for ONE (``load``,
    ``prefix_share``) operating point by running the REAL
    :class:`~..models.router.RequestRouter` — the identical routing
    code a live fleet runs — over :class:`~.workload.SimReplica`
    scheduler models on virtual time, one seeded Poisson stream per
    candidate policy (same seed, so every policy faces the identical
    arrivals). Call it per point to map a (load, prefix-share) grid.

    The fleet straggles realistically: per-tick service jitter
    (``tick_sigma`` lognormal, seeded per replica) plus optional
    designated stragglers (``straggler={replica: tick_multiplier}``) —
    the imbalance ``least_loaded`` routes around, ``prefix_affinity``
    trades against locality, and ``hedge_p99`` papers over at the
    cost of duplicate dispatches. ``load`` is offered load as a
    fraction of the admittable fleet's mean service capacity; ``dead``
    replicas are killed before the run (the router must route around
    them from the first request).

    Refusals, never clamps (the ``sweep_nwait`` contract — each names
    its floor, pinned by tests/test_sim_workload.py):

    * **zero admittable replicas** — every replica dead: no admission
      SLO is meetable by any policy;
    * **offered load >= 1** — open-loop saturation: queues grow
      without bound, so no routing policy can meet an admission SLO;
    * **hedge_p99 without ttft_slo** — the deadline IS the policy;
    * **no policy meets the admission SLO** (post-run, when
      ``admission_slo_s`` is given and every candidate's p99 queue
      wait exceeds it).

    Returns entries per policy (p50/p99/mean TTFT, p99 queue wait,
    hedges, re-routes, shared admissions, ``admissible``), ``best``
    (lowest p99 TTFT among admissible policies), and
    ``p99_vs_round_robin`` — the headline ratio the bench rung pins.

    ``fast="auto"`` (default) prices each candidate day on the
    vectorized :mod:`~.fastpath` engine — same digest, so the same
    decision, at a fraction of the cost; the identical seeded arrival
    stream is materialized ONCE as an :class:`~.fastpath.ArrivalBatch`
    and shared across candidates. ``fast="never"`` pins the scalar
    loop (the parity suite's reference).
    """
    # lazy, like sweep_hierarchical's ops import: models/ is the
    # accelerator package namespace (the router itself is jax-free) —
    # keep the sim/ GC001 hermetic closure provably clean
    from ..models.router import ROUTER_POLICIES, RequestRouter
    from .workload import (
        SimReplica,
        lognormal_ticks,
        poisson_arrivals,
        run_router_day,
    )

    n_replicas = int(n_replicas)
    dead_set = {int(d) for d in dead}
    if not (dead_set <= set(range(n_replicas))):
        raise ValueError(
            f"dead replicas {sorted(dead_set)} outside the fleet "
            f"[0, {n_replicas})"
        )
    admittable = n_replicas - len(dead_set)
    if admittable < 1:
        raise ValueError(
            f"sweep refused: zero admittable replicas "
            f"({len(dead_set)} of {n_replicas} dead) — no routing "
            "policy can admit anything"
        )
    load = float(load)
    if not (0.0 < load < 1.0):
        raise ValueError(
            f"sweep refused: offered load {load:.2f} must sit in "
            "(0, 1) — at or beyond 1 the open-loop queue grows "
            "without bound and no routing policy can meet an "
            "admission SLO"
        )
    if policies is None:
        # two_tier is NOT a candidate here: it needs a two-tier fleet
        # shape (and a migration byte model), which is exactly what
        # sweep_tier_split builds and prices
        policies = [
            p for p in ROUTER_POLICIES
            if (p != "hedge_p99" or ttft_slo is not None)
            and p != "two_tier"
        ]
    policies = list(policies)
    if "two_tier" in policies:
        raise ValueError(
            "sweep refused: two_tier is priced by sweep_tier_split "
            "(it sweeps the (n_prefill, n_decode) fleet shape and "
            "migration threshold, not just a policy flag)"
        )
    unknown = [p for p in policies if p not in ROUTER_POLICIES]
    if unknown:
        raise ValueError(
            f"unknown router policies {unknown}; choose from "
            f"{ROUTER_POLICIES}"
        )
    if "hedge_p99" in policies and ttft_slo is None:
        raise ValueError(
            "sweep refused: hedge_p99 without ttft_slo — the TTFT "
            "deadline IS the policy; pass ttft_slo=<seconds>"
        )
    mult = {int(k): float(v) for k, v in (straggler or {}).items()}
    # offered rate = load x the admittable fleet's mean service
    # capacity (slot-holding ticks per request at the mean tick —
    # the ONE formula, shared with fleet.signals.replica_capacity_rps)
    from .workload import service_ticks_per_request

    ticks_per_req = service_ticks_per_request(
        prompt_len=prompt_len, prompt_chunk=prompt_chunk,
        max_new=max_new, n_inner=n_inner,
    )
    per_slot_rate = 1.0 / (ticks_per_req * float(tick_s))
    fleet_rate = sum(
        int(slots) * per_slot_rate / mult.get(i, 1.0)
        for i in range(n_replicas) if i not in dead_set
    )
    rate = load * fleet_rate
    arrival_kw = dict(
        prompt_len=prompt_len, max_new=max_new,
        prefix_share=prefix_share, prefix_len=prefix_len,
        n_prefix_groups=n_prefix_groups,
    )
    batch = None
    if _resolve_fast(fast):
        from .fastpath import poisson_arrival_batch, run_router_day_fast

        # every candidate faces the identical seeded stream, so the
        # cohort batch is generated once and shared across policies
        batch = poisson_arrival_batch(
            rate, n=requests, seed=seed, **arrival_kw
        )
    entries: list[dict] = []
    for policy in policies:
        clock = VirtualClock()
        replicas = []
        for i in range(n_replicas):
            rep = SimReplica(
                clock, slots=slots, n_inner=n_inner,
                prompt_chunk=prompt_chunk,
                tick_s=lognormal_ticks(
                    float(tick_s) * mult.get(i, 1.0),
                    float(tick_sigma), seed=int(seed) * 1009 + i,
                ),
            )
            if i in dead_set:
                rep.kill()
            replicas.append(rep)
        router = RequestRouter(
            replicas, policy=policy, clock=clock,
            ttft_slo=ttft_slo if policy == "hedge_p99" else None,
        )
        if batch is not None:
            report = run_router_day_fast(router, batch)
        else:
            report = run_router_day(
                router,
                poisson_arrivals(
                    rate, n=requests, seed=seed, **arrival_kw
                ),
            )
        waits = np.asarray([
            (r.t_admitted - r.t_submit) for r in report.requests
            if r.t_admitted is not None
        ])
        p99_wait = (
            float(np.percentile(waits, 99)) if waits.size else 0.0
        )
        entries.append({
            "policy": policy,
            "p50_ttft_s": report.p50_ttft(),
            "p99_ttft_s": report.p99_ttft(),
            "mean_ttft_s": float(report.ttft.mean()),
            "p99_queue_wait_s": p99_wait,
            "completed": report.n - report.dropped,
            "dropped": report.dropped,
            "hedges": report.n_hedges,
            "rerouted": report.n_rerouted,
            "shared_admits": sum(
                r.n_shared_admits for r in replicas
            ),
            "admissible": (
                admission_slo_s is None
                or p99_wait <= float(admission_slo_s)
            ),
        })
    ok = [e for e in entries if e["admissible"]]
    if not ok:
        raise ValueError(
            f"no policy meets the admission SLO: every candidate's "
            f"p99 queue wait exceeds {admission_slo_s}s at load "
            f"{load:.2f} (swept {[e['policy'] for e in entries]}) — "
            "add replicas or shed load; the sweep refuses rather "
            "than recommend a policy that cannot admit"
        )
    best = min(ok, key=lambda e: e["p99_ttft_s"])
    rr = next(
        (e for e in entries if e["policy"] == "round_robin"), None
    )
    return {
        "entries": entries,
        "best": best["policy"],
        "best_entry": best,
        "p99_vs_round_robin": (
            None if rr is None
            else rr["p99_ttft_s"] / best["p99_ttft_s"]
        ),
        "load": load,
        "prefix_share": float(prefix_share),
        "rate_req_s": rate,
        "requests": int(requests),
    }


def sweep_tenant_weights(
    *,
    contracts: Sequence,
    candidates: Sequence[dict],
    n_replicas: int = 4,
    slots: int = 4,
    n_inner: int = 8,
    tick_s: float = 0.02,
    tick_sigma: float = 0.3,
    load: float = 0.8,
    requests: int = 2000,
    prompt_len: int = 96,
    max_new: int = 32,
    prompt_chunk: int = 64,
    seed: int = 0,
    fast: str = "auto",
    budget_s: float | None = None,
    timer: Callable[[], float] | None = None,
) -> dict[str, Any]:
    """Recommend DRR weights for a set of tenant contracts by running
    the REAL QoS plane — :class:`~..models.router.RequestRouter` +
    :class:`~..qos.DeficitScheduler` admission inside
    :class:`~.workload.SimReplica` fleets — over one seeded
    tenant-mixed day per candidate weight vector (same seed, so every
    candidate faces the identical arrivals: times, prompts, AND
    tenant labels). ``contracts`` is the fleet's
    :class:`~..qos.TenantContract` list; each candidate in
    ``candidates`` maps every tenant name to a weight.

    Each tenant offers ``load`` of ITS OWN token budget (arrival
    shares proportional to budgets), so the swept day measures what
    the weights do to compliant traffic — shed/pacing behavior is the
    bucket's job at the door, not the sweep's.

    Refusals, never clamps (the ``sweep_nwait`` contract — each names
    its floor, pinned by tests/test_qos.py):

    * **infeasible contracts: aggregate budget >= capacity** — the
      tenants' token-rate budgets sum to at least the fleet's token
      capacity (or a tenant has NO budget, making the aggregate
      unbounded): the contracts cannot be jointly honored by any
      weight assignment;
    * **latency-class tenant without a ttft_slo** — the sweep scores
      latency tenants against their advertised deadline; a
      latency-class contract that never states one is an error, not
      a default;
    * **candidate weights not covering the tenant set** — every
      candidate must name exactly the contract tenants, weights > 0;
    * **no candidate meets every latency-class SLO** (post-run): the
      sweep refuses rather than recommend weights that break a
      contract.

    Returns entries per candidate (per-tenant p50/p99 TTFT via
    :meth:`~.workload.WorkloadReport.per_tenant`, the worst
    normalized latency-tenant p99 as ``score``), ``best`` (lowest
    score), and the capacity numbers the feasibility check used.

    ``fast="auto"`` prices each candidate day on the vectorized
    :mod:`~.fastpath` engine (bit-identical digest, same decision,
    lower cost); the seeded tenant-mixed stream is materialized once
    and shared across candidates. ``budget_s`` bounds the sweep's
    decision cost: candidates are evaluated in order until the budget
    is spent (at least one always runs), and the result records
    ``candidates_evaluated`` / ``budget_exhausted`` — the point of the
    fast path is that the SAME budget covers a strictly larger grid.
    Wall time is never read silently (the GC008 contract): ``budget_s``
    requires an injected ``timer``."""
    # lazy, the sweep_router_policy pattern: models/ is the
    # accelerator package namespace; qos/ is stdlib-only but stays a
    # lazy import for the same explicit-closure discipline
    from ..models.router import RequestRouter
    from ..qos import TenantContract, TenantRegistry
    from .workload import (
        SimReplica,
        lognormal_ticks,
        poisson_arrivals,
        run_router_day,
        service_ticks_per_request,
    )

    contracts = list(contracts)
    if not contracts:
        raise ValueError("sweep refused: no tenant contracts given")
    names = [c.name for c in contracts]
    for c in contracts:
        if c.cls == "latency" and c.ttft_slo is None:
            raise ValueError(
                f"sweep refused: latency-class tenant {c.name!r} has "
                "no ttft_slo — the sweep scores latency tenants "
                "against their advertised deadline; state one in the "
                "contract"
            )
        if c.rate is None:
            raise ValueError(
                f"sweep refused: tenant {c.name!r} has no token "
                "budget (rate=None) — the aggregate budget is then "
                "unbounded and can never fit capacity; give every "
                "tenant a rate"
            )
    tok_per_req = int(prompt_len) + int(max_new)
    ticks_per_req = service_ticks_per_request(
        prompt_len=prompt_len, prompt_chunk=prompt_chunk,
        max_new=max_new, n_inner=n_inner,
    )
    fleet_req_rate = (
        int(n_replicas) * int(slots)
        / (ticks_per_req * float(tick_s))
    )
    capacity_tok_s = fleet_req_rate * tok_per_req
    aggregate = sum(c.rate for c in contracts)
    if aggregate >= capacity_tok_s:
        raise ValueError(
            f"sweep refused: infeasible contracts — aggregate token "
            f"budget {aggregate:.0f} tok/s >= fleet capacity "
            f"{capacity_tok_s:.0f} tok/s ({n_replicas} replicas x "
            f"{slots} slots): no weight assignment can honor them; "
            "shrink budgets or grow the fleet"
        )
    candidates = [dict(cand) for cand in candidates]
    if not candidates:
        raise ValueError("sweep refused: no candidate weight vectors")
    for cand in candidates:
        if sorted(cand) != sorted(names):
            raise ValueError(
                f"sweep refused: candidate weights {sorted(cand)} "
                f"must name exactly the contract tenants "
                f"{sorted(names)}"
            )
        for t, w in cand.items():
            if not w > 0:
                raise ValueError(
                    f"sweep refused: candidate weight {w} for tenant "
                    f"{t!r} must be > 0"
                )
    if budget_s is not None and timer is None:
        raise ValueError(
            "budget_s requires an injected timer= (wall time is never "
            "read silently — the GC008 contract); pass "
            "time.perf_counter or a virtual clock"
        )
    # each tenant offers `load` of its own budget; shares follow
    tenant_tok_rate = {c.name: load * c.rate for c in contracts}
    offered_tok = sum(tenant_tok_rate.values())
    rate = offered_tok / tok_per_req
    shares = {t: r / offered_tok for t, r in tenant_tok_rate.items()}
    latency_slo = {
        c.name: c.ttft_slo for c in contracts if c.cls == "latency"
    }
    batch = None
    if _resolve_fast(fast):
        from .fastpath import poisson_arrival_batch, run_router_day_fast

        batch = poisson_arrival_batch(
            rate, n=int(requests), seed=seed, prompt_len=prompt_len,
            max_new=max_new, tenants=shares,
        )
    t0 = timer() if timer is not None else 0.0
    entries: list[dict] = []
    n_evaluated = 0
    for cand in candidates:
        if (
            budget_s is not None and n_evaluated > 0
            and timer() - t0 > float(budget_s)
        ):
            break
        n_evaluated += 1
        reg = TenantRegistry([
            TenantContract(
                c.name, cls=c.cls, weight=cand[c.name], rate=c.rate,
                burst=c.burst, pages=c.pages, hedges=c.hedges,
                ttft_slo=c.ttft_slo,
            )
            for c in contracts
        ])
        clock = VirtualClock()
        replicas = [
            SimReplica(
                clock, slots=slots, n_inner=n_inner,
                prompt_chunk=prompt_chunk, qos=reg,
                tick_s=lognormal_ticks(
                    float(tick_s), float(tick_sigma),
                    seed=int(seed) * 1009 + i,
                ),
            )
            for i in range(int(n_replicas))
        ]
        router = RequestRouter(
            replicas, policy="least_loaded", clock=clock, qos=reg,
        )
        if batch is not None:
            report = run_router_day_fast(router, batch)
        else:
            report = run_router_day(
                router,
                poisson_arrivals(
                    rate, n=int(requests), seed=seed,
                    prompt_len=prompt_len, max_new=max_new,
                    tenants=shares,
                ),
            )
        per = report.per_tenant()
        # score: the worst latency-class p99 normalized by its SLO
        # (<= 1 means every latency contract held)
        score = 0.0
        for t, slo in latency_slo.items():
            if t in per:
                score = max(score, per[t]["p99_ttft_s"] / slo)
        entries.append({
            "weights": dict(cand),
            "per_tenant": per,
            "score": score,
            "shed": report.n_shed,
            "admissible": all(
                per.get(t, {"p99_ttft_s": 0.0})["p99_ttft_s"] <= slo
                for t, slo in latency_slo.items()
            ),
        })
    ok = [e for e in entries if e["admissible"]]
    if latency_slo and not ok:
        raise ValueError(
            f"no candidate meets every latency-class SLO "
            f"({latency_slo}): worst normalized p99 per candidate "
            f"{[round(e['score'], 3) for e in entries]} — the sweep "
            "refuses rather than recommend weights that break a "
            "contract; grow the fleet or loosen the SLOs"
        )
    pool = ok if ok else entries
    best = min(pool, key=lambda e: e["score"])
    return {
        "entries": entries,
        "best": best["weights"],
        "best_entry": best,
        "capacity_tok_s": capacity_tok_s,
        "aggregate_budget_tok_s": aggregate,
        "rate_req_s": rate,
        "tenant_shares": shares,
        "requests": int(requests),
        "candidates_evaluated": n_evaluated,
        "budget_s": budget_s,
        "budget_exhausted": n_evaluated < len(candidates),
    }


def sweep_tier_split(
    *,
    splits: Sequence[tuple[int, int]],
    migration_thresholds: Sequence[int | None] = (None,),
    slots: int = 4,
    n_inner: int = 8,
    tick_s: float = 0.02,
    chunk_s: float = 0.01,
    tick_sigma: float = 0.0,
    load: float = 0.8,
    requests: int = 2000,
    prompt_len: int = 64,
    max_new: int = 32,
    long_share: float = 0.1,
    long_prompt_len: int = 1024,
    long_max_new: int | None = None,
    prompt_chunk: int = 64,
    kv_bytes_per_token: float = 4096.0,
    migrate_gbs: float = 5.2,
    decode_p99_slo_s: float | None = None,
    seed: int = 0,
    fast: str = "auto",
) -> dict[str, Any]:
    """Price ``(n_prefill, n_decode)`` tier splits and migration-size
    thresholds for the disaggregated serving tier by running the REAL
    :class:`~..models.router.RequestRouter` ``two_tier`` policy — the
    identical placement/migration code a live fleet runs — over
    two-tier :class:`~.workload.SimReplica` fleets on virtual time,
    one seeded mixed long-prompt/short-chat Poisson stream per
    candidate (same seed: every candidate faces identical arrivals).

    Each candidate is one ``(split, threshold)`` pair from the cross
    product; ``chunk_s`` prices prefill work into tick time (the
    contention disaggregation removes — at ``chunk_s=0`` every split
    ties and the sweep is meaningless), ``migrate_gbs`` prices each
    migration's payload transfer at the measured ring rate, and the
    headline per candidate is **decode p99** — the p99 per-request
    mean inter-token gap (:meth:`~.workload.WorkloadReport.
    p99_decode_itl`), the tail a long-prompt burst wrecks.

    Refusals, never clamps (the ``sweep_nwait`` contract — each names
    its floor, pinned by tests/test_disagg.py):

    * **zero replicas in either tier** — a split with no prefill or no
      decode replicas is not a two-tier fleet;
    * **offered load >= 1** — open-loop saturation: queues grow
      without bound and no split can meet a decode SLO;
    * **no split meets the decode-p99 SLO** (post-run, when
      ``decode_p99_slo_s`` is given and every candidate's decode p99
      exceeds it).

    Returns entries per candidate (decode p99, TTFT percentiles,
    migrations landed/kept local, bytes moved), ``best`` — the
    ``(split, threshold)`` with the lowest decode p99 among admissible
    candidates — and ``decode_p99_vs_worst`` for quick reading.

    ``fast="auto"`` accepts the shared sweep knob for uniformity, but
    two-tier days price prefill contention through ``chunk_s`` — a
    carried-state tick stretch the vectorized engine does not model —
    so ``run_router_day_fast`` detects the shape and runs the scalar
    loop (``report.fastpath`` names the reason); the arrival batch is
    still materialized once per split and shared across thresholds."""
    from ..models.router import RequestRouter
    from .workload import (
        SimReplica,
        lognormal_ticks,
        poisson_arrivals,
        run_router_day,
    )

    cands = [(int(p), int(d)) for p, d in splits]
    if not cands:
        raise ValueError("empty sweep: no candidate splits given")
    for p, d in cands:
        if p < 1 or d < 1:
            raise ValueError(
                f"sweep refused: split ({p}, {d}) leaves a tier empty "
                "— a two-tier fleet needs at least one prefill AND "
                "one decode replica"
            )
    load = float(load)
    if not (0.0 < load < 1.0):
        raise ValueError(
            f"sweep refused: offered load {load:.2f} must sit in "
            "(0, 1) — at or beyond 1 the open-loop queue grows "
            "without bound and no tier split can meet a decode SLO"
        )
    thresholds = list(migration_thresholds)
    lmn = int(long_max_new if long_max_new is not None else max_new)
    # offered rate: load x the fleet's bottleneck-tier capacity under
    # the EXPECTED per-request work (the long mix in expectation).
    # Prefill-tier work per request: its chunk count; decode-tier
    # work: its decode ticks. Tick time approximated at the base
    # tick_s (chunk_s stretches are what the sweep prices).
    ls = float(long_share)
    e_chunks = (
        (1.0 - ls) * -(-int(prompt_len) // int(prompt_chunk))
        + ls * -(-int(long_prompt_len) // int(prompt_chunk))
    )
    e_decode_ticks = (
        (1.0 - ls) * -(-max(int(max_new) - 1, 0) // int(n_inner))
        + ls * -(-max(lmn - 1, 0) // int(n_inner))
    )
    use_fast = _resolve_fast(fast)
    if use_fast:
        from .fastpath import poisson_arrival_batch, run_router_day_fast
    entries: list[dict] = []
    for (n_p, n_d) in cands:
        # a saturated prefill replica's tick stretches by one chunk_s
        # per admitting slot (the very contention being priced), so
        # its capacity is chunks over the STRETCHED tick; decode-tier
        # ticks run chunk-free (adoption admits without prefill)
        prefill_tick = tick_s + slots * chunk_s
        cap_prefill = n_p * slots / (e_chunks * prefill_tick)
        cap_decode = n_d * slots / (e_decode_ticks * tick_s)
        rate = load * min(cap_prefill, cap_decode)
        batch = poisson_arrival_batch(
            rate, n=requests, seed=seed, prompt_len=prompt_len,
            max_new=max_new, long_share=long_share,
            long_prompt_len=long_prompt_len,
            long_max_new=long_max_new,
        ) if use_fast else None
        for thr in thresholds:
            clock = VirtualClock()
            fleet = []
            for i in range(n_p + n_d):
                fleet.append(SimReplica(
                    clock, slots=slots, n_inner=n_inner,
                    prompt_chunk=prompt_chunk,
                    tier="prefill" if i < n_p else "decode",
                    chunk_s=chunk_s,
                    kv_bytes_per_token=kv_bytes_per_token,
                    tick_s=lognormal_ticks(
                        float(tick_s), float(tick_sigma),
                        seed=int(seed) * 1013 + i,
                    ),
                ))
            router = RequestRouter(
                fleet, policy="two_tier", clock=clock,
                migrate_threshold_bytes=thr,
                migrate_gbs=migrate_gbs,
            )
            if batch is not None:
                report = run_router_day_fast(router, batch)
            else:
                report = run_router_day(
                    router,
                    poisson_arrivals(
                        rate, n=requests, seed=seed,
                        prompt_len=prompt_len, max_new=max_new,
                        long_share=long_share,
                        long_prompt_len=long_prompt_len,
                        long_max_new=long_max_new,
                    ),
                )
            p99d = report.p99_decode_itl()
            entries.append({
                "split": (n_p, n_d),
                "threshold_bytes": thr,
                "decode_p99_s": p99d,
                "p50_ttft_s": report.p50_ttft(),
                "p99_ttft_s": report.p99_ttft(),
                "migrated": report.n_migrated,
                "kept_local": report.n_kept_local,
                "migrated_bytes": router.migrated_bytes,
                "completed": report.n - report.dropped,
                "dropped": report.dropped,
                "rate_req_s": rate,
                "admissible": (
                    decode_p99_slo_s is None
                    or p99d <= float(decode_p99_slo_s)
                ),
            })
    ok = [e for e in entries if e["admissible"]]
    if not ok:
        raise ValueError(
            f"no split meets the decode-p99 SLO: every candidate's "
            f"p99 inter-token gap exceeds {decode_p99_slo_s}s at load "
            f"{load:.2f} (swept "
            f"{[(e['split'], e['threshold_bytes']) for e in entries]})"
            " — add decode replicas or shed load; the sweep refuses "
            "rather than recommend a split that cannot hold decode"
        )
    # decode p99 is the objective; among candidates within 5% of the
    # best (the tiers hold decode equally well), the lowest p99 TTFT
    # wins — a tie on the headline must not discard the prefill
    # tier's sizing signal
    best_d = min(e["decode_p99_s"] for e in ok)
    near = [e for e in ok if e["decode_p99_s"] <= best_d * 1.05]
    best = min(near, key=lambda e: e["p99_ttft_s"])
    worst = max(entries, key=lambda e: e["decode_p99_s"])
    return {
        "entries": entries,
        "best": (best["split"], best["threshold_bytes"]),
        "best_entry": best,
        "decode_p99_vs_worst": (
            worst["decode_p99_s"] / best["decode_p99_s"]
            if best["decode_p99_s"] > 0 else float(np.inf)
        ),
        "load": load,
        "long_share": ls,
        "requests": int(requests),
    }


def sweep_spill_capacity(
    *,
    store_groups_candidates: Sequence[int],
    replicas: int = 3,
    slots: int = 4,
    n_inner: int = 8,
    tick_s: float = 0.02,
    tick_sigma: float = 0.0,
    chunk_s: float = 0.004,
    load: float = 0.8,
    requests: int = 2000,
    prompt_len: int = 512,
    max_new: int = 32,
    prefix_share: float = 0.7,
    prefix_len: int = 256,
    n_prefix_groups: int = 16,
    prompt_chunk: int = 64,
    kv_bytes_per_token: float = 4096.0,
    spill_gbs: float = 8.0,
    fetch_gbs: float = 8.0,
    seed: int = 0,
    fast: str = "auto",
) -> dict[str, Any]:
    """Price the host-DRAM spill tier's capacity
    (:class:`~.workload.SimFleetCache` ``store_groups``) by running
    the real router over fleets sharing one fleet cache per candidate,
    one seeded prefix-heavy Poisson stream for ALL candidates (same
    seed: identical arrivals, so the ONLY variable is how many prefix
    groups the DRAM tier can hold).

    The trade being swept: a fleet fetch skips a request's shared
    prefill chunks but charges the planner-priced transfer seconds to
    the admitting tick (``spill_gbs``/``fetch_gbs`` — the PERF byte
    model), while a capacity-0 tier falls back to peer-HBM hits only
    and a too-small tier churns (``evictions`` in the entry says so).
    The headline per candidate is **p99 TTFT** with the prefill
    chip-seconds saved (``chunks_saved * chunk_s``) as the efficiency
    axis.

    Refusals, never clamps (the ``sweep_nwait`` contract):

    * **empty candidate list** — nothing to sweep;
    * **negative capacity** — ``store_groups`` is a page-count floor
      at 0 (0 = peer-only fleet, a legal baseline candidate);
    * **shareless stream** (``prefix_share <= 0`` or
      ``prefix_len < 1``) — without shared prefixes every fetch path
      is dead and the sweep would recommend noise;
    * **offered load >= 1** — open-loop saturation.

    Returns entries per candidate (TTFT percentiles, fleet hits by
    tier, spills/evictions/fallbacks, bytes moved, chip seconds
    saved), ``best`` — the capacity with the lowest p99 TTFT — and
    ``p99_ttft_vs_no_dram`` against the 0-capacity baseline when one
    was swept. ``fast=`` is accepted for knob uniformity; fleet-cache
    days price tick stretches the vectorized engine does not model, so
    ``run_router_day_fast`` falls back to the scalar loop by shape."""
    from ..cache import SpillFetchPlanner
    from ..models.router import RequestRouter
    from .workload import (
        SimFleetCache,
        SimReplica,
        lognormal_ticks,
        poisson_arrivals,
        run_router_day,
    )

    cands = [int(g) for g in store_groups_candidates]
    if not cands:
        raise ValueError(
            "empty sweep: no store_groups candidates given"
        )
    for g in cands:
        if g < 0:
            raise ValueError(
                f"sweep refused: store_groups {g} is negative — the "
                "DRAM tier holds 0 or more groups (0 = peer-only "
                "baseline)"
            )
    if not (0.0 < float(prefix_share) <= 1.0) or int(prefix_len) < 1:
        raise ValueError(
            f"sweep refused: prefix_share {prefix_share} / prefix_len "
            f"{prefix_len} leaves nothing shareable — a spill-capacity "
            "sweep over a shareless stream prices a dead code path"
        )
    load = float(load)
    if not (0.0 < load < 1.0):
        raise ValueError(
            f"sweep refused: offered load {load:.2f} must sit in "
            "(0, 1) — at or beyond 1 the open-loop queue grows "
            "without bound and no cache capacity can hold TTFT"
        )
    if int(replicas) < 2:
        raise ValueError(
            "sweep refused: a fleet cache needs >= 2 replicas — with "
            "one there is no peer tier and DRAM only re-serves the "
            "spiller itself"
        )
    # offered rate: load x fleet tick capacity under expected
    # per-request work WITHOUT sharing (the pessimistic floor — cache
    # hits only relieve it, so every candidate faces feasible load)
    e_chunks = -(-int(prompt_len) // int(prompt_chunk))
    e_ticks = e_chunks + -(-max(int(max_new) - 1, 0) // int(n_inner))
    rate = load * int(replicas) * int(slots) / (
        e_ticks * (float(tick_s) + float(chunk_s))
    )
    use_fast = _resolve_fast(fast)
    if use_fast:
        from .fastpath import poisson_arrival_batch, run_router_day_fast

        batch = poisson_arrival_batch(
            rate, n=requests, seed=seed, prompt_len=prompt_len,
            max_new=max_new, prefix_share=prefix_share,
            prefix_len=prefix_len, n_prefix_groups=n_prefix_groups,
        )
    chunks_per_hit = -(-int(prefix_len) // int(prompt_chunk))
    entries: list[dict] = []
    for g in cands:
        clock = VirtualClock()
        cache = SimFleetCache(
            store_groups=g,
            kv_bytes_per_token=kv_bytes_per_token,
            planner=SpillFetchPlanner(
                spill_gbs=spill_gbs, fetch_gbs=fetch_gbs,
            ),
        )
        fleet = [
            SimReplica(
                clock, slots=slots, n_inner=n_inner,
                prompt_chunk=prompt_chunk, chunk_s=chunk_s,
                kv_bytes_per_token=kv_bytes_per_token,
                tick_s=lognormal_ticks(
                    float(tick_s), float(tick_sigma),
                    seed=int(seed) * 1013 + i,
                ),
                cache=cache,
            )
            for i in range(int(replicas))
        ]
        router = RequestRouter(
            fleet, policy="least_loaded", clock=clock,
        )
        if use_fast:
            report = run_router_day_fast(router, batch)
        else:
            report = run_router_day(
                router,
                poisson_arrivals(
                    rate, n=requests, seed=seed,
                    prompt_len=prompt_len, max_new=max_new,
                    prefix_share=prefix_share, prefix_len=prefix_len,
                    n_prefix_groups=n_prefix_groups,
                ),
            )
        hits = sum(r.n_fleet_hits for r in fleet)
        st = cache.stats()
        entries.append({
            "store_groups": g,
            "p50_ttft_s": report.p50_ttft(),
            "p99_ttft_s": report.p99_ttft(),
            "fleet_hits": hits,
            "fetches": st["fetches"],
            "fallbacks": st["fallbacks"],
            "spills": st["spills"],
            "evictions": st["evictions"],
            "spill_bytes": st["spill_bytes"],
            "fetch_bytes": st["fetch_bytes"],
            "local_shared_admits": sum(
                r.n_shared_admits for r in fleet
            ),
            "prefill_chip_s_saved": (
                hits * chunks_per_hit * float(chunk_s)
            ),
            "completed": report.n - report.dropped,
            "dropped": report.dropped,
            "rate_req_s": rate,
        })
    best = min(entries, key=lambda e: e["p99_ttft_s"])
    base = next(
        (e for e in entries if e["store_groups"] == 0), None
    )
    return {
        "entries": entries,
        "best": best["store_groups"],
        "best_entry": best,
        "p99_ttft_vs_no_dram": (
            base["p99_ttft_s"] / best["p99_ttft_s"]
            if base is not None and best["p99_ttft_s"] > 0 else None
        ),
        "load": load,
        "requests": int(requests),
    }


def sweep_harvest_k(
    source,
    *,
    n_workers: int | None = None,
    nwait: int,
    epochs: int = 200,
    k_values: Sequence[int] = (1, 2, 4, 8, 16, 32, 64),
    host_epoch_s: float = 2e-3,
    host_harvest_s: float = 4e-3,
    staleness_bound_s: float | None = None,
    seed: int = 0,
    registry=None,
    spans=None,
) -> dict[str, Any]:
    """Price the K-epoch harvest cadence of device-resident
    coordination (:class:`~..parallel.device_coord.DeviceCoordinator`)
    on virtual time — the sim twin of the fused window.

    The fused window's arrival recurrence is arithmetically identical
    to the host loop over a :class:`~.backend.SimBackend` (that is the
    ``repochs``-parity contract tests/test_device_coord.py pins), so
    ONE real ``asyncmap`` run on virtual time yields the exact
    per-epoch completion times every candidate K would produce; each K
    then re-slices that timeline into ceil(epochs / K) windows. Two
    terms trade against each other (the arxiv 1808.06583
    latency/communication trade):

    * **amortized host cost** — the host loop pays ``host_epoch_s``
      interpreter time per epoch (2 + 3W host touches); a fused window
      pays ``host_harvest_s`` per harvest (stage + harvest, 2/K per
      epoch amortized). ``utility`` per K is effective epochs/second:
      ``epochs / (virtual_s + n_harvests * host_harvest_s)``. Pass the
      bench-measured costs for this box
      (benchmarks/device_coord_bench.py measures both).
    * **staleness** — a result decoded at the window's first epoch is
      only visible to the host at the window's end; ``staleness_s``
      per K is the maximum such age (≈ the longest window's virtual
      span).

    Refusals, never clamps (the ``sweep_nwait`` contract, each naming
    its floor — pinned by tests/test_device_coord.py):

    * **K < 1** — not a window;
    * **K > epochs** — the run cannot fill one window;
    * **staleness bound violated** — any candidate K whose worst
      window holds results longer than ``staleness_bound_s`` virtual
      seconds before the host sees them.

    Returns entries per K (``window_s`` max/mean, ``staleness_s``,
    ``epochs_per_s``, ``overhead_x`` vs the host loop), ``best`` (the
    K maximizing effective epochs/second), and the host-loop baseline
    rate.
    """
    delay_fn, n_hint = _resolve_delay(source, seed=seed)
    n = int(n_workers if n_workers is not None else (n_hint or 0))
    if n <= 0:
        raise ValueError(
            "n_workers is required when the latency source does not "
            "carry a pool size"
        )
    nwait = int(nwait)
    if not (1 <= nwait <= n):
        raise ValueError(f"nwait must be in [1, {n}], got {nwait}")
    epochs = int(epochs)
    ks = sorted({int(k) for k in k_values})
    bad = [k for k in ks if k < 1]
    if bad:
        raise ValueError(
            f"sweep refused: harvest window K={bad} — a window must "
            "cover at least 1 epoch"
        )
    bad = [k for k in ks if k > epochs]
    if bad:
        raise ValueError(
            f"sweep refused: harvest window K={bad} exceeds the "
            f"{epochs}-epoch run — the host would never harvest"
        )
    backend = SimBackend(
        _echo, n, delay_fn=delay_fn, clock=VirtualClock(),
        registry=registry, spans=spans,
    )
    pool = AsyncPool(n)
    walls = np.empty(epochs)
    for e in range(epochs):
        t0 = backend.clock.now()
        asyncmap(pool, np.zeros(1), backend, nwait=nwait)
        walls[e] = backend.clock.now() - t0
    virtual_s = float(walls.sum())
    host_rate = epochs / (virtual_s + epochs * float(host_epoch_s))
    entries: list[dict] = []
    violations: list[tuple[int, float]] = []
    for k in ks:
        spans_k = [
            float(walls[i : i + k].sum())
            for i in range(0, epochs, k)
        ]
        n_harvests = len(spans_k)
        stale = max(spans_k)
        if (
            staleness_bound_s is not None
            and stale > float(staleness_bound_s)
        ):
            violations.append((k, stale))
        rate = epochs / (
            virtual_s + n_harvests * float(host_harvest_s)
        )
        entries.append({
            "K": k,
            "n_harvests": n_harvests,
            "window_mean_s": float(np.mean(spans_k)),
            "window_max_s": stale,
            "staleness_s": stale,
            "epochs_per_s": rate,
            "overhead_x": rate / host_rate,
        })
    if violations:
        worst_k, worst_s = max(violations, key=lambda v: v[1])
        raise ValueError(
            f"sweep refused: harvest window K="
            f"{[k for k, _ in violations]} violates the staleness "
            f"bound {float(staleness_bound_s):.6g}s — K={worst_k} "
            f"holds results up to {worst_s:.6g} virtual seconds "
            "before the host sees them; shrink K or relax the bound"
        )
    best = max(entries, key=lambda r: r["epochs_per_s"])
    return {
        "entries": entries,
        "best": int(best["K"]),
        "best_entry": best,
        "virtual_s": virtual_s,
        "host_loop_epochs_per_s": host_rate,
        "host_epoch_s": float(host_epoch_s),
        "host_harvest_s": float(host_harvest_s),
        "nwait": nwait,
        "epochs": epochs,
    }


def sweep_hedge(
    source,
    *,
    n_workers: int | None = None,
    widths: Sequence[int] | None = None,
    requests: int = 40,
    tolerance: float = 0.05,
    seed: int = 0,
) -> dict[str, Any]:
    """Price hedge widths by running the REAL :class:`HedgedServer` on
    virtual time: per width, ``requests`` sequential requests (the
    fleet quiesced between requests so every width sees identical
    conditions), reporting virtual first-arrival latency stats and the
    replica-seconds each width burns. Recommended width: the narrowest
    whose p95 is within ``tolerance`` of the best p95 — wider hedges
    that buy no tail are pure dispatch cost."""
    delay_fn, n_hint = _resolve_delay(source, seed=seed)
    n = int(n_workers if n_workers is not None else (n_hint or 0))
    if n <= 0:
        raise ValueError(
            "n_workers is required when the latency source does not "
            "carry a pool size"
        )
    ws = list(range(1, n + 1)) if widths is None else sorted(
        {int(w) for w in widths}
    )
    if any(w < 1 or w > n for w in ws):
        raise ValueError(f"hedge widths must be in [1, {n}], got {ws}")
    entries = []
    for w in ws:
        backend = SimBackend(
            _echo, n, delay_fn=delay_fn, clock=VirtualClock()
        )
        srv = HedgedServer(backend)
        lats = np.empty(requests)
        for q in range(requests):
            t0 = backend.clock.now()
            srv.request(np.asarray([q], dtype=np.int64), hedge=w)
            lats[q] = backend.clock.now() - t0
            backend.quiesce()   # losers land before the next request
            srv._harvest()
        entries.append({
            "width": w,
            "mean_latency_s": float(lats.mean()),
            "p95_latency_s": float(np.percentile(lats, 95)),
            "max_latency_s": float(lats.max()),
            "dispatches": int(backend.n_dispatched),
        })
    best_p95 = min(r["p95_latency_s"] for r in entries)
    rec = next(
        r["width"] for r in entries
        if r["p95_latency_s"] <= best_p95 * (1.0 + tolerance)
    )
    return {
        "entries": entries,
        "recommended_width": int(rec),
        "best_p95_s": float(best_p95),
    }


def recommend_nwait(
    model,
    *,
    floor: int = 1,
    kmax: int | None = None,
    epochs: int = 300,
    seed: int = 0,
    utility: Callable[[int], float] | None = None,
) -> dict[str, Any]:
    """Cross-checked nwait recommendation from a fitted
    :class:`~..utils.straggle.PoolLatencyModel`: the sim sweep (real
    pool loop, virtual time, :func:`~.backend.model_delay_fn` fleet)
    and the model's analytic ``optimal_nwait`` side by side. Agreement
    is the expected state — both estimate argmax utility(k)/E[T_(k)]
    over the same distributions; divergence means the pool's
    stale-harvest dynamics (which only the sim sees) are moving the
    optimum, and the sim's answer is the one that priced them."""
    sweep = sweep_nwait(
        model, epochs=epochs, floor=floor,
        nwait_values=(
            None if kmax is None else range(floor, int(kmax) + 1)
        ),
        utility=utility, seed=seed,
    )
    analytic = model.optimal_nwait(
        kmin=floor, kmax=kmax, utility=utility
    )
    return {
        "sim_nwait": sweep.best,
        "model_nwait": int(analytic),
        "agree": sweep.best == int(analytic),
        "sweep": sweep,
    }
