"""Policy autotuning: sweep (nwait, hedge width, code rate) on virtual time.

The paper's entire value proposition is one knob — return after the
``nwait`` fastest workers — and until now the only ways to price a
setting were live runs with injected sleeps (wall-clock, flaky) or
:meth:`~..utils.straggle.PoolLatencyModel.optimal_nwait`'s closed-form
Monte Carlo (fast, but it models an epoch as one order statistic and
never exercises the real pool's stale-harvest/re-task machinery).
This module is the third estimator: run the REAL ``asyncmap`` loop on a
:class:`~.backend.SimBackend` for every candidate policy and measure
virtual wall clock — the full pool semantics at simulator speed,
against either a recorded trace (:class:`~.replay.ReplayTrace`), a
fitted latency model (:func:`~.backend.model_delay_fn`), or any
:mod:`..utils.faults` schedule.

Every sweep respects the decodability floor: for an (n, k) code, fewer
than k fresh shards cannot decode, so candidates below ``floor`` are
never evaluated (the same ``kmin`` contract as
``PoolLatencyModel.optimal_nwait`` and ``AdaptiveNwait``), and
:func:`recommend_nwait` cross-checks the sim sweep against the model's
analytic pick so the two estimators keep each other honest.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from ..backends.base import DelayFn
from ..pool import AsyncPool, asyncmap, waitall
from ..utils.hedge import HedgedServer
from ..utils.trace import EpochTracer
from .backend import SimBackend, model_delay_fn
from .clock import VirtualClock
from .replay import ReplayTrace

__all__ = [
    "NwaitSweep",
    "sweep_nwait",
    "sweep_hedge",
    "sweep_code_rate",
    "recommend_nwait",
]


def _echo(i, payload, epoch):
    return payload


def _resolve_delay(source, *, seed: int) -> tuple[DelayFn, int | None]:
    """(delay_fn, n_workers hint) from a trace / model / DelayFn."""
    if isinstance(source, ReplayTrace):
        return source.delay_fn(), source.n_workers
    if hasattr(source, "workers") and hasattr(source, "observe_pool"):
        return model_delay_fn(source, seed=seed), source.n_workers
    if callable(source):
        return source, None
    raise TypeError(
        "latency source must be a ReplayTrace, a PoolLatencyModel, or "
        f"a DelayFn callable, got {type(source)}"
    )


class NwaitSweep:
    """Result table of one policy sweep.

    ``entries`` rows: ``nwait``, ``mean_epoch_s`` / ``p95_epoch_s``
    (virtual), ``utility_per_s`` (``utility(k) / mean_epoch_s`` — the
    ``optimal_nwait`` objective, default utility ``k`` = fresh results
    per epoch), ``n_stale`` harvested over the run. ``best`` is the
    recommended nwait (argmax utility-per-second, never below the
    floor by construction).
    """

    def __init__(self, entries: list[dict], floor: int):
        if not entries:
            raise ValueError("empty sweep: no candidate policies ran")
        self.entries = entries
        self.floor = int(floor)
        self.best = int(
            max(entries, key=lambda r: r["utility_per_s"])["nwait"]
        )

    def entry(self, nwait: int) -> dict:
        for r in self.entries:
            if r["nwait"] == nwait:
                return r
        raise KeyError(f"nwait={nwait} was not swept")

    def table(self) -> str:
        """Human-readable sweep table (examples/policy_tuning.py)."""
        lines = [
            f"{'nwait':>6} {'mean epoch':>12} {'p95 epoch':>12} "
            f"{'util/s':>10} {'stale':>6}"
        ]
        for r in self.entries:
            mark = " <- best" if r["nwait"] == self.best else ""
            lines.append(
                f"{r['nwait']:>6} {r['mean_epoch_s']*1e3:>9.3f} ms "
                f"{r['p95_epoch_s']*1e3:>9.3f} ms "
                f"{r['utility_per_s']:>10.1f} {r['n_stale']:>6}{mark}"
            )
        return "\n".join(lines)

    def __repr__(self) -> str:
        return (
            f"NwaitSweep(best={self.best}, floor={self.floor}, "
            f"{len(self.entries)} candidates)"
        )


def sweep_nwait(
    source,
    *,
    n_workers: int | None = None,
    epochs: int = 100,
    floor: int = 1,
    nwait_values: Sequence[int] | None = None,
    utility: Callable[[int], float] | None = None,
    work_fn=None,
    payload=None,
    seed: int = 0,
    registry=None,
    spans=None,
) -> NwaitSweep:
    """Price every candidate ``nwait`` by running the real pool loop on
    virtual time.

    ``source`` supplies the fleet's latency behavior: a
    :class:`~.replay.ReplayTrace` (recorded incident), a
    :class:`~..utils.straggle.PoolLatencyModel` (fitted fleet), or a
    raw :data:`~..backends.base.DelayFn` (synthetic scenario —
    ``n_workers`` required then). Candidates default to
    ``floor..n_workers``; anything below ``floor`` (the code's
    decodability k) is refused rather than silently clamped.
    """
    delay_fn, n_hint = _resolve_delay(source, seed=seed)
    n = int(n_workers if n_workers is not None else (n_hint or 0))
    if n <= 0:
        raise ValueError(
            "n_workers is required when the latency source does not "
            "carry a pool size"
        )
    floor = int(floor)
    if not (1 <= floor <= n):
        raise ValueError(f"floor must be in [1, {n}], got {floor}")
    ks = (
        list(range(floor, n + 1)) if nwait_values is None
        else sorted({int(k) for k in nwait_values})
    )
    if any(k < floor for k in ks):
        raise ValueError(
            f"nwait candidates {sorted(k for k in ks if k < floor)} sit "
            f"below the decodability floor {floor}: fewer than "
            f"{floor} fresh shards cannot decode"
        )
    if any(k > n for k in ks):
        raise ValueError(f"nwait candidates must be <= n_workers={n}")
    u = (lambda k: float(k)) if utility is None else utility
    if work_fn is None:
        work_fn = _echo
    if payload is None:
        payload = np.zeros(1, dtype=np.float64)
    entries: list[dict] = []
    for k in ks:
        backend = SimBackend(
            work_fn, n, delay_fn=delay_fn, clock=VirtualClock(),
            registry=registry, spans=spans,
        )
        pool = AsyncPool(n)
        tracer = EpochTracer()  # sim runs feed the same tracer plane
        walls = np.empty(epochs)
        for e in range(epochs):
            t0 = backend.clock.now()
            asyncmap(pool, payload, backend, nwait=k, tracer=tracer)
            walls[e] = backend.clock.now() - t0
        if pool.active.any():
            waitall(pool, backend, tracer=tracer)
        mean = float(walls.mean())
        entries.append({
            "nwait": k,
            "mean_epoch_s": mean,
            "p95_epoch_s": float(np.percentile(walls, 95)),
            "utility_per_s": float(u(k)) / mean if mean > 0 else np.inf,
            "n_stale": int(sum(r.n_stale for r in tracer.records)),
        })
    return NwaitSweep(entries, floor)


def sweep_code_rate(
    source,
    *,
    n_workers: int | None = None,
    k_values: Sequence[int],
    epochs: int = 100,
    utility: Callable[[int], float] | None = None,
    seed: int = 0,
) -> NwaitSweep:
    """Price (n, k) code rates: each candidate k runs at ``nwait=k``
    (the decodability floor IS the policy — an (n, k) code returns the
    moment k shards are fresh), utility defaulting to recovered work
    per second (``k / E[epoch]``). Lower k dodges deeper order
    statistics but discards more redundant compute; the sweep prices
    that trade on the actual pool semantics."""
    ks = sorted({int(k) for k in k_values})
    return sweep_nwait(
        source, n_workers=n_workers, epochs=epochs, floor=min(ks),
        nwait_values=ks, utility=utility, seed=seed,
    )


def sweep_hedge(
    source,
    *,
    n_workers: int | None = None,
    widths: Sequence[int] | None = None,
    requests: int = 40,
    tolerance: float = 0.05,
    seed: int = 0,
) -> dict[str, Any]:
    """Price hedge widths by running the REAL :class:`HedgedServer` on
    virtual time: per width, ``requests`` sequential requests (the
    fleet quiesced between requests so every width sees identical
    conditions), reporting virtual first-arrival latency stats and the
    replica-seconds each width burns. Recommended width: the narrowest
    whose p95 is within ``tolerance`` of the best p95 — wider hedges
    that buy no tail are pure dispatch cost."""
    delay_fn, n_hint = _resolve_delay(source, seed=seed)
    n = int(n_workers if n_workers is not None else (n_hint or 0))
    if n <= 0:
        raise ValueError(
            "n_workers is required when the latency source does not "
            "carry a pool size"
        )
    ws = list(range(1, n + 1)) if widths is None else sorted(
        {int(w) for w in widths}
    )
    if any(w < 1 or w > n for w in ws):
        raise ValueError(f"hedge widths must be in [1, {n}], got {ws}")
    entries = []
    for w in ws:
        backend = SimBackend(
            _echo, n, delay_fn=delay_fn, clock=VirtualClock()
        )
        srv = HedgedServer(backend)
        lats = np.empty(requests)
        for q in range(requests):
            t0 = backend.clock.now()
            srv.request(np.asarray([q], dtype=np.int64), hedge=w)
            lats[q] = backend.clock.now() - t0
            backend.quiesce()   # losers land before the next request
            srv._harvest()
        entries.append({
            "width": w,
            "mean_latency_s": float(lats.mean()),
            "p95_latency_s": float(np.percentile(lats, 95)),
            "max_latency_s": float(lats.max()),
            "dispatches": int(backend.n_dispatched),
        })
    best_p95 = min(r["p95_latency_s"] for r in entries)
    rec = next(
        r["width"] for r in entries
        if r["p95_latency_s"] <= best_p95 * (1.0 + tolerance)
    )
    return {
        "entries": entries,
        "recommended_width": int(rec),
        "best_p95_s": float(best_p95),
    }


def recommend_nwait(
    model,
    *,
    floor: int = 1,
    kmax: int | None = None,
    epochs: int = 300,
    seed: int = 0,
    utility: Callable[[int], float] | None = None,
) -> dict[str, Any]:
    """Cross-checked nwait recommendation from a fitted
    :class:`~..utils.straggle.PoolLatencyModel`: the sim sweep (real
    pool loop, virtual time, :func:`~.backend.model_delay_fn` fleet)
    and the model's analytic ``optimal_nwait`` side by side. Agreement
    is the expected state — both estimate argmax utility(k)/E[T_(k)]
    over the same distributions; divergence means the pool's
    stale-harvest dynamics (which only the sim sees) are moving the
    optimum, and the sim's answer is the one that priced them."""
    sweep = sweep_nwait(
        model, epochs=epochs, floor=floor,
        nwait_values=(
            None if kmax is None else range(floor, int(kmax) + 1)
        ),
        utility=utility, seed=seed,
    )
    analytic = model.optimal_nwait(
        kmin=floor, kmax=kmax, utility=utility
    )
    return {
        "sim_nwait": sweep.best,
        "model_nwait": int(analytic),
        "agree": sweep.best == int(analytic),
        "sweep": sweep,
    }
