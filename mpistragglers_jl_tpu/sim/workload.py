"""Open-loop arrival workloads: traffic for the router plane, on virtual time.

The tuner prices *pool* policies by running the real ``asyncmap`` on a
:class:`~.backend.SimBackend`; this module does the same for *serving*
policies: an open-loop arrival process (seeded Poisson, a diurnal rate
schedule, or a recorded JSONL trace) drives the REAL
:class:`~..models.router.RequestRouter` — the identical routing code a
live fleet runs — over a fleet of :class:`SimReplica` scheduler models
on a :class:`~.clock.VirtualClock`. A simulated 1M-request diurnal day
replays in seconds of wall clock, bit-identically across runs (every
draw is seeded, every book is insertion-ordered), so
``sim/tune.py::sweep_router_policy`` can recommend a routing policy per
(load, prefix-share) operating point before a live run — exactly as
``sweep_nwait`` already prices nwait.

What is real and what is modeled:

* **real** — the router: policy choice, health ejection/re-route,
  TTFT-deadline hedging (:class:`~..utils.hedge.RequestHedge`),
  first-token-wins, loser cancellation, all metrics;
* **modeled** — the scheduler replica: :class:`SimReplica` reproduces
  :class:`~..models.serving.ServingScheduler`'s *timing skeleton*
  (S slots, one C-token prefill chunk per tick per admitting slot with
  the first chunk running on the admission tick, ``n_inner`` tokens
  per decode tick, FIFO admission, EOS-free length retirement, and
  residency-scoped prefix sharing that skips shared prefill chunks)
  without the jax math — a tick is a ``tick_s`` virtual-second event,
  not a compiled program. Token VALUES do not exist here; TTFT and
  completion dynamics do.

Arrival records carry a :class:`SimPrompt` (length + optional shared
prefix group) rather than token arrays — a million requests must not
materialize a million prompts. Live fleets route real token arrays
through the same router; the arrival MODELS are reusable for both via
``prompt_fn``.
"""

# sim purity (graftcheck GC008): this module never reads the OS clock —
# virtual time is the only time here.

from __future__ import annotations

import heapq
import json
import math
from collections import deque
from typing import Callable, Iterable, Iterator

import numpy as np

from ..utils.faults import _unit
from .clock import VirtualClock

__all__ = [
    "Arrival",
    "ReplicaPartition",
    "RetryPolicy",
    "SimFleetCache",
    "SimPrompt",
    "SimRequest",
    "SimReplica",
    "SimTicket",
    "WorkloadReport",
    "poisson_arrivals",
    "diurnal_arrivals",
    "arrivals_from_jsonl",
    "dump_arrivals_jsonl",
    "lognormal_ticks",
    "run_router_day",
]

_CHUNK = 4096  # rng draws are batched; part of the determinism contract


class SimPrompt:
    """A prompt descriptor: ``length`` tokens, of which the leading
    ``prefix_len`` belong to shared-prefix group ``prefix`` (None =
    unique prompt, nothing shareable). Interned per distinct triple —
    replicas never mutate prompts, so a million arrivals can share a
    handful of these."""

    __slots__ = ("length", "prefix", "prefix_len")
    _interned: dict[tuple, "SimPrompt"] = {}

    def __new__(cls, length: int, prefix=None, prefix_len: int = 0):
        key = (int(length), prefix, int(prefix_len))
        got = cls._interned.get(key)
        if got is not None:
            return got
        self = super().__new__(cls)
        self.length, self.prefix, self.prefix_len = key
        if self.length < 1:
            raise ValueError("empty prompt")
        if not (0 <= self.prefix_len <= self.length):
            raise ValueError("prefix_len must be within the prompt")
        cls._interned[key] = self
        return self

    def __repr__(self) -> str:
        return (
            f"SimPrompt({self.length}, prefix={self.prefix}, "
            f"prefix_len={self.prefix_len})"
        )


class Arrival:
    """One open-loop arrival: at virtual time ``t``, a request for
    ``max_new`` tokens from ``prompt`` (a :class:`SimPrompt` here; a
    token array when an arrival model feeds a live fleet).
    ``tenant`` names the contract the request bills to (the QoS
    plane; None = untenanted traffic)."""

    __slots__ = ("t", "prompt", "max_new", "tenant")

    def __init__(self, t: float, prompt, max_new: int,
                 tenant: str | None = None):
        self.t = float(t)
        self.prompt = prompt
        self.max_new = int(max_new)
        self.tenant = tenant

    def __repr__(self) -> str:
        return f"Arrival(t={self.t:.6f}, max_new={self.max_new})"


# decorrelation stride for the tenant coin: the tenant label derives
# from the SAME per-arrival uniform draw as the prompt class (no extra
# rng draw — arrival times and prompt mixes stay bit-identical at
# every tenant mix, the r16 long_share pattern), but through a fixed
# multiplicative fold so tenant intervals do not align with the
# prefix/long-class intervals of u itself
_TENANT_STRIDE = 9973.0


def _tenant_fn(tenants) -> Callable[[float], str | None]:
    """(u,) -> tenant name (or None): ``tenants`` is an ordered
    ``{name: share}`` mapping with positive shares summing to 1 —
    refused otherwise by name, never renormalized silently. The label
    is a pure function of the arrival's existing coin ``u`` (module
    comment on ``_TENANT_STRIDE``)."""
    if tenants is None:
        return lambda u: None
    names = list(tenants)
    if not names:
        raise ValueError("tenants= needs at least one (name, share)")
    shares = [float(tenants[n]) for n in names]
    if any(s <= 0 for s in shares):
        raise ValueError(
            f"tenant shares must all be > 0, got {dict(tenants)}"
        )
    if abs(sum(shares) - 1.0) > 1e-9:
        raise ValueError(
            f"tenant shares must sum to 1 (got {sum(shares):.6f}); "
            "shares are the arrival mix, not weights — normalize "
            "explicitly"
        )
    cum = []
    acc = 0.0
    for s in shares:
        acc += s
        cum.append(acc)
    last = len(names) - 1

    def fn(u: float) -> str:
        v = (u * _TENANT_STRIDE) % 1.0
        for i, c in enumerate(cum):
            if v < c:
                return names[i]
        return names[last]

    return fn


def _default_prompt_fn(
    prompt_len: int, prefix_share: float, prefix_len: int,
    n_prefix_groups: int, max_new: int,
    long_share: float = 0.0, long_prompt_len: int | None = None,
    long_max_new: int | None = None,
) -> Callable:
    """(u,) -> (prompt, max_new): with probability ``prefix_share``
    the prompt opens with one of ``n_prefix_groups`` shared system
    prompts of ``prefix_len`` tokens (the prefix-affinity / COW
    scenario); with probability ``long_share`` it is a LONG prompt of
    ``long_prompt_len`` tokens decoding ``long_max_new`` (default: the
    short class's budget) — the mixed long-prompt/short-chat day the
    disaggregation bench replays; else a unique short prompt. ONE rng
    draw decides all of it (the two classes live in disjoint intervals
    of ``u``), so the arrival TIMES are identical at every share and
    mix rate — and streams with the defaults are bit-identical to
    every pre-mix recording."""
    share = float(prefix_share)
    lshare = float(long_share)
    if not (0.0 <= share <= 1.0):
        raise ValueError(f"prefix_share must be in [0, 1], got {share}")
    if not (0.0 <= lshare <= 1.0) or share + lshare > 1.0:
        raise ValueError(
            f"long_share must be in [0, 1] with prefix_share + "
            f"long_share <= 1, got {long_share} (+{share})"
        )
    if share > 0.0 and not (0 < prefix_len <= prompt_len):
        raise ValueError(
            "prefix_share > 0 needs 0 < prefix_len <= prompt_len"
        )
    if lshare > 0.0 and not (long_prompt_len or 0) > 0:
        raise ValueError("long_share > 0 needs long_prompt_len > 0")
    long_mn = int(long_max_new if long_max_new is not None else max_new)

    def fn(u: float):
        if share > 0.0 and u < share:
            g = int(u / share * n_prefix_groups)  # deterministic in u
            g = min(g, n_prefix_groups - 1)
            return SimPrompt(prompt_len, prefix=g,
                             prefix_len=prefix_len), max_new
        if lshare > 0.0 and u >= 1.0 - lshare:
            return SimPrompt(long_prompt_len), long_mn
        return SimPrompt(prompt_len), max_new

    return fn




def poisson_arrivals(
    rate: float,
    *,
    n: int,
    seed: int = 0,
    start: float = 0.0,
    prompt_len: int = 128,
    max_new: int = 32,
    prefix_share: float = 0.0,
    prefix_len: int = 0,
    n_prefix_groups: int = 1,
    long_share: float = 0.0,
    long_prompt_len: int | None = None,
    long_max_new: int | None = None,
    tenants: dict | None = None,
) -> Iterator[Arrival]:
    """Seeded homogeneous Poisson arrivals: ``n`` requests at mean
    ``rate``/s from virtual ``start``. Every draw comes from one
    generator seeded on ``seed`` in a fixed chunked order, so two calls
    with the same arguments yield bit-identical streams (pinned by
    tests/test_sim_workload.py). ``long_share``/``long_prompt_len``/
    ``long_max_new`` mix in a long-prompt class on the same coin (see
    :func:`_default_prompt_fn` — arrival times never move).
    ``tenants`` (``{name: share}``, shares summing to 1) labels each
    arrival with a tenant off the SAME coin — no extra draw, so
    arrival times and prompt classes are bit-identical at every
    tenant mix (:func:`_tenant_fn`)."""
    if rate <= 0 or n < 1:
        raise ValueError("need rate > 0 and n >= 1")
    rng = np.random.default_rng((0x9E3779B9, int(seed)))
    fn = _default_prompt_fn(prompt_len, prefix_share, prefix_len,
                            n_prefix_groups, max_new, long_share,
                            long_prompt_len, long_max_new)
    tfn = _tenant_fn(tenants)
    t = float(start)
    left = int(n)
    while left:
        m = min(_CHUNK, left)
        ts = t + np.cumsum(rng.exponential(1.0 / rate, size=m))
        coins = rng.random(size=m)
        t = float(ts[-1])
        for tt, u in zip(ts.tolist(), coins.tolist()):
            p, mn = fn(u)
            yield Arrival(tt, p, mn, tenant=tfn(u))
        left -= m


def diurnal_arrivals(
    mean_rate: float,
    *,
    n: int,
    period: float = 86_400.0,
    amplitude: float = 0.8,
    seed: int = 0,
    start: float = 0.0,
    prompt_len: int = 128,
    max_new: int = 32,
    prefix_share: float = 0.0,
    prefix_len: int = 0,
    n_prefix_groups: int = 1,
    long_share: float = 0.0,
    long_prompt_len: int | None = None,
    long_max_new: int | None = None,
    tenants: dict | None = None,
) -> Iterator[Arrival]:
    """Seeded non-homogeneous Poisson arrivals on a diurnal rate
    schedule: ``rate(t) = mean_rate * (1 + amplitude * sin(2*pi*t/
    period - pi/2))`` — trough at ``t = 0``, peak at mid-period (the
    classic traffic day compressed to ``period`` virtual seconds).
    Sampled by Lewis thinning against the peak rate with every
    candidate and acceptance coin drawn from one seeded generator in
    chunked order — bit-identical across runs, like
    :func:`poisson_arrivals` (whose long-prompt mix kwargs apply here
    too: the disaggregation bench's burst day is this function with
    ``long_share > 0``; ``tenants=`` labels arrivals off the same
    coin without moving a single arrival time)."""
    if mean_rate <= 0 or n < 1:
        raise ValueError("need mean_rate > 0 and n >= 1")
    if not (0.0 <= amplitude < 1.0):
        raise ValueError(
            f"amplitude must be in [0, 1), got {amplitude}"
        )
    rng = np.random.default_rng((0x51ED2701, int(seed)))
    fn = _default_prompt_fn(prompt_len, prefix_share, prefix_len,
                            n_prefix_groups, max_new, long_share,
                            long_prompt_len, long_max_new)
    tfn = _tenant_fn(tenants)
    peak = mean_rate * (1.0 + amplitude)
    w = 2.0 * math.pi / period
    t = float(start)
    out = 0
    n = int(n)
    while out < n:
        # Lewis thinning, one chunk of candidates at a time, fully
        # vectorized: candidate times by cumsum, the instantaneous rate
        # at each, and the acceptance mask in numpy — the python loop
        # touches only the survivors
        ts = t + np.cumsum(rng.exponential(1.0 / peak, size=_CHUNK))
        accept = rng.random(size=_CHUNK)
        coins = rng.random(size=_CHUNK)
        t = float(ts[-1])
        rates = mean_rate * (
            1.0 + amplitude * np.sin(w * ts - math.pi / 2.0)
        )
        keep = accept * peak < rates
        for tt, u in zip(ts[keep].tolist(), coins[keep].tolist()):
            p, mn = fn(u)
            yield Arrival(tt, p, mn, tenant=tfn(u))
            out += 1
            if out == n:
                break


def arrivals_from_jsonl(path) -> list[Arrival]:
    """Trace-driven arrivals from a JSONL file (the ``ReplayTrace``
    style: one record per line) — each line
    ``{"t": s, "prompt_len": n, "max_new": m}`` plus optional
    ``"prefix"``/``"prefix_len"`` for shared-prefix requests. Replays
    exactly: the returned list IS the recorded stream."""
    out = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            rec = json.loads(line)
            out.append(Arrival(
                rec["t"],
                SimPrompt(
                    rec["prompt_len"],
                    prefix=rec.get("prefix"),
                    prefix_len=rec.get("prefix_len", 0),
                ),
                rec["max_new"],
                tenant=rec.get("tenant"),
            ))
    if not out:
        raise ValueError(f"empty arrival trace: {path}")
    return out


def dump_arrivals_jsonl(arrivals: Iterable[Arrival], path) -> int:
    """Record an arrival stream for trace-driven replay; returns the
    record count."""
    n = 0
    with open(path, "w") as f:
        for a in arrivals:
            rec = {
                "t": a.t, "prompt_len": a.prompt.length,
                "max_new": a.max_new,
            }
            if a.prompt.prefix is not None:
                rec["prefix"] = a.prompt.prefix
                rec["prefix_len"] = a.prompt.prefix_len
            if a.tenant is not None:
                rec["tenant"] = a.tenant
            f.write(json.dumps(rec) + "\n")
            n += 1
    return n


def service_ticks_per_request(
    *, prompt_len: int, prompt_chunk: int, max_new: int, n_inner: int,
) -> int:
    """Slot-holding ticks one request costs a :class:`SimReplica` (and
    the real scheduler whose tick skeleton it models): its prefill
    chunks plus its decode ticks. THE capacity arithmetic —
    ``sweep_router_policy`` sizes offered load with it and the fleet
    controller's ``replica_capacity_rps`` prices utilization with it
    (one formula, so the controller's signal can never drift from the
    sweep it cross-checks)."""
    if min(prompt_len, prompt_chunk, max_new, n_inner) < 1:
        raise ValueError(
            "prompt_len/prompt_chunk/max_new/n_inner must be >= 1"
        )
    return (
        -(-int(prompt_len) // int(prompt_chunk))
        + -(-max(int(max_new) - 1, 0) // int(n_inner))
    )


class FleetResize:
    """Control-plane event in the simulated day's event stream: at
    virtual time ``t``, an operator forces the fleet to ``target``
    replicas through the attached controller (``run_router_day``'s
    ``controller=``). The controller's range contract still applies —
    a target outside its elastic band is refused by name, never
    clamped — and the resize re-derives (code pair, policy) exactly
    like a hysteresis-triggered one."""

    __slots__ = ("t", "target", "reason")

    def __init__(self, t: float, target: int, reason: str = "operator"):
        self.t = float(t)
        self.target = int(target)
        self.reason = str(reason)

    def fire(self, router, controller) -> None:
        if controller is None:
            raise ValueError(
                "FleetResize event with no controller attached: pass "
                "controller= to run_router_day — there is nothing to "
                "resize"
            )
        controller.resize_to(self.target, reason=self.reason)

    def __repr__(self) -> str:
        return (
            f"FleetResize(t={self.t:.3f}, target={self.target}, "
            f"{self.reason!r})"
        )


class CoordinatorKill:
    """Control-plane event: at virtual time ``t`` the active
    coordinator dies. The data plane (router, replicas) keeps serving;
    decisions stop until the standby adopts the last coded checkpoint
    (:class:`~..fleet.failover.ControllerSupervisor` semantics) — the
    zero-drop failover scenario, replayed bit-identically."""

    __slots__ = ("t",)

    def __init__(self, t: float):
        self.t = float(t)

    def fire(self, router, controller) -> None:
        kill = getattr(controller, "kill", None)
        if kill is None:
            raise ValueError(
                "CoordinatorKill event needs a supervised controller "
                "(fleet.ControllerSupervisor as run_router_day's "
                "controller=): killing an unsupervised coordinator "
                "would end the day, not fail it over"
            )
        kill()

    def __repr__(self) -> str:
        return f"CoordinatorKill(t={self.t:.3f})"


def _retry_coin(seed: int, index: int, attempt: int) -> float:
    """Deterministic uniform [0, 1) from (seed, submit index, attempt)
    — the retry client's seeded coin, delegated to THE fault-plane
    coin (:func:`~..utils.faults._unit`, one implementation) but keyed
    on the DAY-LOCAL submit index rather than a process-global request
    id, so two replays of the same day draw identical jitter."""
    return _unit(int(seed), int(index), int(attempt))


class RetryPolicy:
    """Timeout-and-resubmit client model — the classic metastable-
    failure generator (chaos plane). A client whose request shows no
    first token within ``timeout_s`` resubmits it as a FRESH request
    (the original is NOT cancelled: the client cannot reach into the
    fleet, so both copies consume capacity — that feedback is the
    amplification), up to ``max_retries`` resubmissions per original,
    with per-attempt timeouts stretched by ``backoff`` and resubmit
    jitter drawn on a seeded coin keyed by (day-local submit index,
    attempt) — the storm itself replays bit-identically. A request
    shed at the door is NOT retried (shed is a fast, named refusal the
    client backs off from — retrying sheds would defeat overload
    shedding).

    Consumed by :func:`run_router_day` (``retry=``); resubmissions
    feed back into the day's arrival stream as first-class submits, so
    every attempt appears in the :class:`WorkloadReport` (and its
    digest) and ``n_resubmits`` counts the amplification."""

    __slots__ = ("timeout_s", "max_retries", "backoff", "jitter_s",
                 "seed")

    def __init__(self, timeout_s: float, *, max_retries: int = 3,
                 backoff: float = 1.0, jitter_s: float = 0.0,
                 seed: int = 0):
        if timeout_s <= 0:
            raise ValueError(
                f"timeout_s must be > 0, got {timeout_s}"
            )
        if max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {max_retries}"
            )
        if backoff < 1.0:
            raise ValueError(
                f"backoff must be >= 1 (timeouts never shrink), got "
                f"{backoff}"
            )
        if jitter_s < 0:
            raise ValueError(
                f"jitter_s must be >= 0, got {jitter_s}"
            )
        self.timeout_s = float(timeout_s)
        self.max_retries = int(max_retries)
        self.backoff = float(backoff)
        self.jitter_s = float(jitter_s)
        self.seed = int(seed)

    def resubmit_at(self, t_submit: float, index: int,
                    attempt: int) -> float:
        """When attempt ``attempt`` (0 = the original) submitted at
        ``t_submit`` would be resubmitted: its timeout plus the seeded
        jitter coin."""
        due = t_submit + self.timeout_s * self.backoff ** attempt
        if self.jitter_s:
            due += self.jitter_s * _retry_coin(
                self.seed, index, attempt
            )
        return due

    def __repr__(self) -> str:
        return (
            f"RetryPolicy(timeout_s={self.timeout_s}, "
            f"max_retries={self.max_retries}, "
            f"backoff={self.backoff}, jitter_s={self.jitter_s})"
        )


class ReplicaPartition:
    """Control-plane event in the simulated day's event stream: at
    virtual time ``t`` the router loses network reachability to the
    named ``replicas`` (a partition is distinct from death — the
    replicas keep ticking, their results are simply unreachable:
    :meth:`~..models.router.RequestRouter.partition`), and at
    ``until`` the partition heals — the replicas rejoin through
    :meth:`~..models.router.RequestRouter.heal`, which withdraws
    every stale leg so no request is double-retired. The heal is
    scheduled on the router's clock at fire time, so it lands exactly
    on time in the same event-driven drive loop as kill/recover
    injections."""

    __slots__ = ("t", "replicas", "until")

    def __init__(self, t: float, replicas, until: float):
        self.t = float(t)
        self.replicas = (
            [int(replicas)]
            if isinstance(replicas, (int, np.integer))
            else [int(i) for i in replicas]
        )
        if not self.replicas:
            raise ValueError("ReplicaPartition with no replicas")
        self.until = float(until)
        if self.until <= self.t:
            raise ValueError(
                f"partition must heal after it begins: t={self.t}, "
                f"until={self.until}"
            )

    def fire(self, router, controller) -> None:
        clock = router.clock
        if clock is None:
            raise ValueError(
                "ReplicaPartition event needs a VirtualClock router: "
                "a live fleet's partitions come from the network, not "
                "the event stream"
            )
        for i in self.replicas:
            router.partition(i)

        def _heal():
            for i in self.replicas:
                router.heal(i)

        clock.call_at(self.until, _heal)

    def __repr__(self) -> str:
        return (
            f"ReplicaPartition(t={self.t:.3f}, "
            f"replicas={self.replicas}, until={self.until:.3f})"
        )


class lognormal_ticks:
    """Deterministic per-tick service-time jitter:
    ``tick_s(tick) = base * exp(sigma * N(0,1))`` with the normals
    drawn from one generator seeded on ``seed`` and cached by tick
    index — the same tick always costs the same, whatever order ticks
    are priced in. The knob that makes scheduler replicas heterogeneous
    (a straggling replica is ``lognormal_ticks(base * 1.5, ...)`` or a
    bigger sigma), which is exactly the imbalance ``least_loaded``
    routes around and ``round_robin`` cannot."""

    def __init__(self, base: float, sigma: float = 0.0, *,
                 seed: int = 0):
        self.base = float(base)
        self.sigma = float(sigma)
        self._rng = np.random.default_rng((0x7F4A7C15, int(seed)))
        self._cache: list[float] = []

    def __call__(self, tick: int) -> float:
        if self.sigma == 0.0:
            return self.base
        while len(self._cache) <= tick:
            draws = self._rng.standard_normal(_CHUNK)
            self._cache.extend(
                self.base * math.exp(self.sigma * float(z))
                for z in draws
            )
        return self._cache[tick]


class SimRequest:
    """The scheduler-request face of one simulated request: ``tokens``
    (length-only — token values do not exist in the model),
    ``finished`` / ``reason`` / ``admitted_tick``, exactly the members
    the router's replica protocol reads."""

    __slots__ = ("prompt", "max_new", "tenant", "n_emitted",
                 "finished", "reason", "admitted_tick", "migrated",
                 "trace", "_holds_prefix")

    def __init__(self, prompt: SimPrompt, max_new: int,
                 tenant: str | None = None):
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        self.prompt = prompt
        self.max_new = int(max_new)
        self.tenant = tenant
        self.n_emitted = 0
        self.finished = False
        self.reason = None
        self.admitted_tick = None
        self.trace = None  # TraceBook id (None = dark)
        # True once adopted by another replica: admission then skips
        # prefill entirely (the pages arrived with the request)
        self.migrated = False
        self._holds_prefix = None

    @property
    def tokens(self):
        # range: len() and truthiness in O(1) — the only reads the
        # router protocol makes
        return range(self.n_emitted)


class SimTicket:
    """The sim face of a KV-page migration ticket: the frozen request,
    the byte/page accounting the router's threshold and transfer
    pricing read, and the reason label the obs counters use. The
    request object itself crosses (in-process sim), so adoption is
    stream-continuous exactly like the live in-process fast path."""

    __slots__ = ("request", "nbytes", "pages", "reason", "trace")

    def __init__(self, request: SimRequest, nbytes: int, pages: int,
                 reason: str = "prefill_done"):
        self.request = request
        self.nbytes = int(nbytes)
        self.pages = int(pages)
        self.reason = reason
        self.trace = None  # trace id riding inside the ticket


class SimFleetCache:
    """The sim twin of :class:`~..cache.FleetPrefixCache`: a
    fleet-level prefix-group namespace with the same three tiers and
    the same byte-priced movement model, on virtual time.

    Replicas :meth:`register` and then report residency transitions:
    0→1 holders of a prefix group publishes it as tier-``hbm`` here
    (:meth:`publish_hbm`); the LAST holder leaving withdraws it and —
    when no sibling still advertises the group — spills it into a
    bounded host-DRAM FIFO of ``store_groups`` groups
    (:meth:`residency_lost`, which returns the planner-priced spill
    seconds the replica charges to its tick). An admission whose
    prefix group is not locally resident asks :meth:`fetch`: DRAM
    first, then a reachable peer's HBM — a hit skips the shared
    prefill chunks at a priced transfer cost instead of for free,
    which is exactly the live scheduler's fetch-instead-of-prefill
    trade and what ``sweep_spill_capacity`` sweeps.

    Failure model mirrors the live hub: :meth:`partition` makes a
    replica unreachable (its HBM advertisements invisible, its own
    fetches fail → fall back to prefill) until :meth:`heal`;
    :meth:`drop_replica` (kill) purges its HBM entries while DRAM
    spills SURVIVE. Everything is insertion-ordered dicts and pure
    arithmetic — no OS clock, no unordered iteration — so a day
    replays bit-identically (GC008), and every counter lives OUTSIDE
    :meth:`WorkloadReport.digest`.

    ``registry=`` (opt-in, GC004) publishes the same counter names as
    the live plane: ``cache_spill_bytes_total``,
    ``cache_fetch_bytes_total{src=}``, ``cache_directory_size``.
    """

    def __init__(self, *, store_groups: int = 64,
                 kv_bytes_per_token: float = 4096.0,
                 planner=None, registry=None):
        if store_groups < 0:
            raise ValueError(
                f"store_groups must be >= 0 (0 disables the DRAM "
                f"tier), got {store_groups}"
            )
        if kv_bytes_per_token < 0.0:
            raise ValueError("kv_bytes_per_token must be >= 0")
        # lazy import: cache/ is stdlib-only; sim/ keeps its closure
        # explicit the way tune.py's models import does
        if planner is None:
            from ..cache import SpillFetchPlanner

            planner = SpillFetchPlanner()
        self.planner = planner
        self.store_groups = int(store_groups)
        self.kv_bytes_per_token = float(kv_bytes_per_token)
        self._hbm: dict[str, set] = {}  # replica -> advertised groups
        self._dram: dict = {}  # group -> nbytes, FIFO eviction order
        self._unreachable: set[str] = set()
        self._n_auto = 0
        self.n_fetches = {"dram": 0, "peer": 0}
        self.n_fallbacks = 0  # group known but unreachable -> prefill
        self.n_spills = 0
        self.n_evictions = 0
        self.n_replica_drops = 0
        self.spill_bytes = 0
        self.fetch_bytes = 0
        self._registry = registry
        self._m_fetch: dict = {}
        if registry is not None:
            self._m_spill = registry.counter(
                "cache_spill_bytes_total",
                help="bytes of prefix pages absorbed by the host-DRAM "
                "spill tier",
            )
            self._m_size = registry.gauge(
                "cache_directory_size",
                help="advertised prefix locations fleet-wide "
                "(hbm + dram)",
            )
        else:
            self._m_spill = None
            self._m_size = None

    # -- membership ------------------------------------------------------

    def register(self, replica) -> str:
        """A SimReplica joins; returns its fleet name (``"s<n>"``)."""
        name = f"s{self._n_auto}"
        self._n_auto += 1
        self._hbm[name] = set()
        return name

    def drop_replica(self, name: str) -> None:
        """Replica death: its HBM advertisements vanish with the
        device memory; its DRAM spills survive (host-side state — the
        whole point of the spill tier)."""
        if self._hbm.pop(name, None) is not None:
            self.n_replica_drops += 1
        self._unreachable.discard(name)
        self._set_size()

    def partition(self, name: str) -> None:
        self._unreachable.add(name)

    def heal(self, name: str) -> None:
        self._unreachable.discard(name)

    # -- residency mirror ------------------------------------------------

    def publish_hbm(self, name: str, group) -> None:
        """First holder of ``group`` landed on ``name``: advertise its
        HBM residency fleet-wide."""
        self._hbm.setdefault(name, set()).add(group)
        self._set_size()

    def residency_lost(self, name: str, group, prefix_len: int) -> float:
        """Last holder of ``group`` left ``name``: withdraw the HBM
        advertisement and, when no sibling still holds the group and
        the DRAM tier has room policy for it, spill it there. Returns
        the priced spill seconds (0.0 when nothing moved) — the
        replica charges them to its next busy tick, the sim's
        device→host DMA."""
        groups = self._hbm.get(name)
        if groups is not None:
            groups.discard(group)
        self._set_size()
        if self.store_groups == 0 or group in self._dram:
            return 0.0
        for held in self._hbm.values():
            if group in held:  # a sibling still serves it from HBM
                return 0.0
        nbytes = int(prefix_len * self.kv_bytes_per_token)
        if nbytes < 1:
            return 0.0
        while len(self._dram) >= self.store_groups:
            oldest = next(iter(self._dram))
            del self._dram[oldest]
            self.n_evictions += 1
        self._dram[group] = nbytes
        self.n_spills += 1
        self.spill_bytes += nbytes
        if self._m_spill is not None:
            self._m_spill.inc(nbytes)
        self._set_size()
        return self.planner.price(nbytes, "spill")

    # -- lookup ----------------------------------------------------------

    def fetch(self, group, prefix_len: int, *,
              exclude: str | None = None):
        """``("dram" | "peer", priced_seconds)`` for a reachable copy
        of ``group``, or None (prefill the chunks). DRAM wins over
        peer like the live hub; a partitioned asker (``exclude``) sees
        nothing at all — it cannot reach the store host either."""
        nbytes = int(prefix_len * self.kv_bytes_per_token)
        if nbytes < 1:
            return None
        if exclude is not None and exclude in self._unreachable:
            if self._known(group, exclude):
                self.n_fallbacks += 1
            return None
        if group in self._dram:
            return self._hit("dram", "fetch_dram", nbytes)
        for name, held in self._hbm.items():
            if name == exclude or name in self._unreachable:
                continue
            if group in held:
                return self._hit("peer", "fetch_peer", nbytes)
        if self._known(group, exclude):
            self.n_fallbacks += 1
        return None

    def _hit(self, src: str, kind: str, nbytes: int):
        self.n_fetches[src] += 1
        self.fetch_bytes += nbytes
        if self._registry is not None:
            m = self._m_fetch.get(src)
            if m is None:
                m = self._registry.counter(
                    "cache_fetch_bytes_total",
                    help="bytes of prefix pages served by the fleet "
                    "cache instead of re-prefill",
                    src=src,
                )
                self._m_fetch[src] = m
            m.inc(nbytes)
        return (src, self.planner.price(nbytes, kind))

    def _known(self, group, exclude: str | None = None) -> bool:
        """Is ``group`` advertised anywhere OTHER than ``exclude``?
        A miss on a group only the asker itself ever held is a cold
        miss, not a fallback — fallbacks name copies that existed and
        could not be reached."""
        if group in self._dram:
            return True
        for name, held in self._hbm.items():
            if name == exclude:
                continue
            if group in held:
                return True
        return False

    def _set_size(self) -> None:
        if self._m_size is not None:
            self._m_size.set(
                len(self._dram)
                + sum(len(h) for h in self._hbm.values())
            )

    # -- bookkeeping -----------------------------------------------------

    def check(self) -> None:
        if len(self._dram) > self.store_groups:
            raise AssertionError(
                f"DRAM tier over capacity: {len(self._dram)} > "
                f"{self.store_groups}"
            )
        for name in self._unreachable:
            if name not in self._hbm:
                raise AssertionError(
                    f"unreachable set holds unknown replica {name!r}"
                )

    def stats(self) -> dict:
        return {
            "replicas": list(self._hbm),
            "unreachable": sorted(self._unreachable),
            "hbm_groups": sum(len(h) for h in self._hbm.values()),
            "dram_groups": len(self._dram),
            "fetches": dict(self.n_fetches),
            "fallbacks": self.n_fallbacks,
            "spills": self.n_spills,
            "evictions": self.n_evictions,
            "replica_drops": self.n_replica_drops,
            "spill_bytes": self.spill_bytes,
            "fetch_bytes": self.fetch_bytes,
        }

    def __repr__(self) -> str:
        return (
            f"SimFleetCache({len(self._hbm)} replicas, "
            f"dram={len(self._dram)}/{self.store_groups})"
        )


class SimReplica:
    """A :class:`~..models.serving.ServingScheduler` timing model on
    virtual time — the router's replica protocol (submit / step /
    cancel / pending / active / prefix_hits / alive / next_tick_at),
    with the scheduler's tick skeleton and none of its math (module
    docstring).

    A tick costs ``tick_s`` virtual seconds (float, or a
    ``f(tick_index) -> s`` callable like :class:`lognormal_ticks`) and
    fires only when due (``next_tick_at``): the workload driver
    advances the clock to the earliest due tick fleet-wide, so
    replicas tick concurrently on the virtual axis exactly as N real
    scheduler processes would on the wall. Per tick, mirroring the
    real ``step()``: admitting slots advance one prefill chunk (the
    first chunk on the admission tick itself), free slots admit FIFO
    from the queue, decoding slots emit ``n_inner`` tokens, rows at
    their ``max_new`` budget retire and free their slot.

    Prefix sharing is residency-scoped like the paged pool: while any
    resident slot holds prefix group g, a newly admitted g-request
    skips its shared prefill chunks (``prefix_len`` tokens) — the
    timing effect of PR 6's page sharing, which is what
    ``prefix_affinity`` routing compounds.

    ``kill()`` models a replica death: state is wiped, in-flight
    requests stop progressing (the router re-routes them on its next
    health probe), ``alive`` flips for the default health probe;
    ``revive()`` brings the replica back empty.

    **Two-tier mode** (the disaggregation model, models/disagg.py's
    sim twin): ``tier`` tags the replica for the router's ``two_tier``
    placement; ``chunk_s`` prices PREFILL work into the tick — each
    prefill chunk advanced in a tick adds ``chunk_s`` virtual seconds
    to it, so a long-prompt burst inflates every tick it shares a
    replica with and the in-flight decodes' inter-token gaps blow out
    (the real scheduler's ``_advance_admissions`` loop runs one
    ``_extend`` program per admitting slot per tick — this is that
    cost, modeled; ``chunk_s=0`` keeps the pre-round-16 timing
    bit-identical). ``migrate_out`` freezes a decoding request into a
    :class:`SimTicket` sized by the ``kv_bytes_per_token`` byte model;
    ``adopt`` re-queues it with ``migrated=True`` — admission then
    takes the slot WITHOUT prefill chunks (the pages came along) and
    carries its shared-prefix residency to this replica, which is what
    the router's residency-affine adoption compounds."""

    def __init__(self, clock: VirtualClock, *, slots: int = 8,
                 n_inner: int = 8, tick_s=0.02,
                 prompt_chunk: int = 256, tier: str = "unified",
                 chunk_s: float = 0.0,
                 kv_bytes_per_token: float = 4096.0,
                 page_tokens: int = 16, qos=None,
                 max_queue: int | None = None, trace=None,
                 cache: "SimFleetCache | None" = None):
        if slots < 1 or n_inner < 1 or prompt_chunk < 1:
            raise ValueError(
                "slots, n_inner and prompt_chunk must be >= 1"
            )
        if max_queue is not None and max_queue < 1:
            raise ValueError(
                f"max_queue must be >= 1 or None, got {max_queue}"
            )
        if tier not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"tier must be unified/prefill/decode, got {tier!r}"
            )
        if chunk_s < 0.0 or kv_bytes_per_token < 0.0 or page_tokens < 1:
            raise ValueError(
                "chunk_s and kv_bytes_per_token must be >= 0, "
                "page_tokens >= 1"
            )
        # multi-tenant QoS (opt-in): the FIFO queue becomes the SAME
        # weighted deficit-round-robin the real scheduler runs under
        # qos= — the timing twin of its admission order, so the
        # isolation claims are measured on virtual time (lazy import:
        # the qos package is stdlib-only, but sim/ keeps its closure
        # explicit the way tune.py's models import does)
        self.qos = qos
        if qos is not None:
            from ..qos import DeficitScheduler

            self._drr = DeficitScheduler(qos)
        else:
            self._drr = None
        self.clock = clock
        self.S = int(slots)
        self.n_inner = int(n_inner)
        self.C = int(prompt_chunk)
        self.tier = tier
        # the scheduler-side bounded-queue backstop (chaos plane):
        # mirrors ServingScheduler(max_queue=) — the router sheds by
        # name first; this is the hard assertion behind it
        self.max_queue = None if max_queue is None else int(max_queue)
        self.chunk_s = float(chunk_s)
        self.kv_bytes_per_token = float(kv_bytes_per_token)
        self.page_tokens = int(page_tokens)
        self._tick_s = (
            tick_s if callable(tick_s)
            else (lambda _t, _d=float(tick_s): _d)
        )
        # the raw tick_s spec, kept for sim/fastpath.py's support
        # gate: a float constant or a recognized index-pure seeded
        # callable (lognormal_ticks) can be replayed off the loop
        self._tick_spec = tick_s
        self._queue: deque[SimRequest] = deque()
        self._slots: list[SimRequest | None] = [None] * self.S
        self._prefill = [0] * self.S
        self._n_active = 0  # occupied slots, O(1) for the router's load reads
        self._resident: dict = {}  # prefix group -> holder count
        self.alive = True
        self.tick_count = 0
        self.next_tick_at: float | None = None
        self.last_tick_at: float | None = None
        self.n_retired = 0
        self.n_cancelled = 0
        self.n_shared_admits = 0
        self.n_adopted = 0
        self.n_migrated_out = 0
        # virtual seconds this replica spent with work on board (tick
        # intervals scheduled while busy) — the numerator of the QoS
        # plane's work-conservation floor; NOT in any digest
        self.busy_s = 0.0
        # fleet prefix cache (opt-in): residency transitions mirror
        # into the shared SimFleetCache; a fleet fetch skips shared
        # prefill chunks at a priced cost accumulated here and charged
        # to the next busy tick (like chunk_s, a tick stretch)
        self.cache = cache
        self.cache_name: str | None = None
        self.n_fleet_hits = 0
        self._xfer_s = 0.0
        if cache is not None:
            self.cache_name = cache.register(self)
        # causal tracing (round 22, opt-in per GC004): replica-side
        # events — DRR queue transitions, prefill chunks — stamped on
        # the VIRTUAL clock against trace ids the router minted
        self._trace = None
        if trace is not None:
            self.attach_trace(trace)

    def attach_trace(self, book) -> None:
        """Arm causal tracing (the router propagates its book here).
        DRR transitions route through the scheduler's own trace hook
        so qos/ stays clock-free — this callback owns the clock."""
        self._trace = book
        if self._drr is not None:
            self._drr.set_trace(self._drr_trace_event)

    def _drr_trace_event(self, kind, tenant, item, cost) -> None:
        tid = item.trace
        if tid is not None:
            self._trace.event(
                tid, kind, self.clock.now(), tenant=tenant, cost=cost
            )

    # -- replica protocol -------------------------------------------------

    @property
    def pending(self) -> int:
        return (self._drr.total if self._drr is not None
                else len(self._queue))

    @property
    def active(self) -> int:
        return self._n_active

    def submit(self, prompt, max_new: int, key=None,
               tenant: str | None = None, trace=None) -> SimRequest:
        if not self.alive:
            raise RuntimeError(
                "submit to a killed SimReplica: the router must not "
                "route to an unroutable replica"
            )
        if self.max_queue is not None and self.pending >= self.max_queue:
            raise RuntimeError(
                f"queue ceiling: {self.pending} requests already "
                f"queued at max_queue={self.max_queue} — shed at the "
                "router (shed_depth=) instead of queueing unboundedly"
            )
        if isinstance(prompt, int):
            prompt = SimPrompt(prompt)
        req = SimRequest(prompt, max_new, tenant=tenant)
        if trace is not None:
            # stamped BEFORE the enqueue so the DRR trace hook sees
            # the id on its drr_queued event
            req.trace = trace
        self._enqueue(req)
        if self.next_tick_at is None:
            self.next_tick_at = (
                self.clock.now() + self._tick_s(self.tick_count)
            )
        return req

    def _enqueue(self, req: SimRequest) -> None:
        if self._drr is not None:
            if req.tenant is None:
                raise ValueError(
                    "qos SimReplica needs tenant= at submit: "
                    "admission order is per-contract (register a "
                    "catch-all TenantContract for untagged traffic)"
                )
            # DRR cost in tokens, the real scheduler's unit
            self._drr.enqueue(
                req.tenant, req,
                float(req.prompt.length + req.max_new),
            )
        else:
            self._queue.append(req)

    def prefix_hits(self, prompt) -> int:
        """Affinity score: shared-prefill chunks this replica would
        skip for ``prompt`` right now (0 when its prefix group is not
        resident here)."""
        if getattr(prompt, "prefix", None) is None:
            return 0
        if self._resident.get(prompt.prefix, 0) < 1:
            return 0
        return -(-prompt.prefix_len // self.C)

    def cancel(self, req: SimRequest) -> bool:
        if req.finished:
            return False
        if self._drr is not None:
            removed = self._drr.remove(req)
        else:
            try:
                self._queue.remove(req)
                removed = True
            except ValueError:
                removed = False
        if removed:
            req.finished, req.reason = True, "cancelled"
            self.n_cancelled += 1
            return True
        for s, r in enumerate(self._slots):
            if r is req:
                self._free(s)
                req.finished, req.reason = True, "cancelled"
                self.n_cancelled += 1
                return True
        return False

    # -- KV-page migration (the two-tier router protocol) ---------------

    def migration_nbytes(self, req: SimRequest) -> int:
        """The byte model the live scheduler measures: resident KV
        bytes for the tokens this stream has landed so far."""
        return int(
            (req.prompt.length + req.n_emitted)
            * self.kv_bytes_per_token
        )

    def migrate_out(self, req: SimRequest,
                    reason: str = "prefill_done") -> SimTicket:
        """Freeze a decoding request into a ticket and free its slot
        (residency drops with it — the pages leave). The request must
        be past its first token and unfinished, the same migratability
        contract as ``ServingScheduler.export_page_state``."""
        if req.finished or req.n_emitted < 1:
            raise ValueError(
                "migrate_out: request must be decoding (first token "
                "emitted, not finished)"
            )
        for s, r in enumerate(self._slots):
            if r is req and not self._prefill[s]:
                self._free(s)
                self.n_migrated_out += 1
                toks = req.prompt.length + req.n_emitted
                return SimTicket(
                    req, self.migration_nbytes(req),
                    -(-toks // self.page_tokens), reason,
                )
        raise ValueError(
            "migrate_out: request is not decoding in a slot here"
        )

    def can_adopt(self, ticket: SimTicket) -> bool:
        return self.alive

    def adopt(self, ticket: SimTicket) -> SimRequest:
        """Land a migrated request: re-queued with ``migrated=True``
        so admission takes a slot without any prefill chunks and
        decode continues from ``n_emitted`` — the page adoption's
        timing skeleton. Returns the SAME request object (in-process
        stream continuity, like the live fast path)."""
        if not self.alive:
            raise RuntimeError(
                "adopt on a killed SimReplica: the router must not "
                "land migrations on an unroutable replica"
            )
        req = ticket.request
        req.migrated = True
        req._holds_prefix = None  # residency re-established at admit
        self._enqueue(req)
        self.n_adopted += 1
        if self.next_tick_at is None:
            self.next_tick_at = (
                self.clock.now() + self._tick_s(self.tick_count)
            )
        return req

    def step(self) -> list[SimRequest]:
        """One scheduler tick, fired only when due (the router steps
        every busy replica; a not-yet-due sim replica must be a no-op
        or fleet timing would serialize). Returns the requests retired
        in the tick."""
        now = self.clock.now()
        if self.next_tick_at is None or self.next_tick_at > now + 1e-12:
            return []
        self.tick_count += 1
        self.last_tick_at = now
        retired: list[SimRequest] = []
        # ONE pass over the slots (this loop is the hot half of a
        # million-request day; three separate admit/prefill/decode
        # passes measured ~2x): slots are independent, so the fused
        # per-slot dispatch preserves the real scheduler's tick
        # semantics — an admitting slot advances exactly one chunk, a
        # newly admitted slot runs its first chunk this very tick, and
        # neither decodes until a later tick.
        queue = self._queue
        drr = self._drr
        slots = self._slots
        prefill = self._prefill
        n_inner = self.n_inner
        trace = self._trace  # hoisted: dark ticks pay one local read
        n_chunks = 0  # prefill chunks advanced this tick (chunk_s)
        for s in range(self.S):
            req = slots[s]
            if req is None:
                # admit (first chunk runs this very tick): FIFO, or
                # the deficit-round-robin pick under qos= — the same
                # admission-order hook the real scheduler carries
                if drr is not None:
                    picked = drr.pick()
                    if picked is None:
                        continue
                    req = picked[1]
                elif queue:
                    req = queue.popleft()
                else:
                    continue
                p = req.prompt
                if req.migrated:
                    # page adoption: NO prefill — the KV pages arrived
                    # with the request; residency (if any) transfers
                    # here and decode continues from n_emitted on the
                    # next tick
                    if p.prefix is not None:
                        held = self._resident.get(p.prefix, 0)
                        if held == 0 and self.cache is not None:
                            self.cache.publish_hbm(
                                self.cache_name, p.prefix
                            )
                        self._resident[p.prefix] = held + 1
                        req._holds_prefix = p.prefix
                    slots[s] = req
                    self._n_active += 1
                    req.admitted_tick = self.tick_count
                    prefill[s] = 0
                    continue
                skip = 0
                if p.prefix is not None:
                    held = self._resident.get(p.prefix, 0)
                    if held:
                        skip = p.prefix_len
                        self.n_shared_admits += 1
                    elif self.cache is not None:
                        # local miss: probe the fleet — a DRAM or peer
                        # hit skips the shared chunks at a priced
                        # transfer cost instead of re-prefilling them
                        got = self.cache.fetch(
                            p.prefix, p.prefix_len,
                            exclude=self.cache_name,
                        )
                        if got is not None:
                            skip = p.prefix_len
                            self.n_fleet_hits += 1
                            self._xfer_s += got[1]
                    if held == 0 and self.cache is not None:
                        self.cache.publish_hbm(
                            self.cache_name, p.prefix
                        )
                    self._resident[p.prefix] = held + 1
                    req._holds_prefix = p.prefix
                chunks = max(-(-(p.length - skip) // self.C), 1)
                slots[s] = req
                self._n_active += 1
                # admission stamp at PLACEMENT (the real scheduler's
                # semantics: queue wait ends when the slot is taken,
                # not when prefill lands) — the router's queue-wait
                # histogram reads this
                req.admitted_tick = self.tick_count
                prefill[s] = chunks - 1
                n_chunks += 1  # the first chunk's work
                if trace is not None and req.trace is not None:
                    trace.event(
                        req.trace, "prefill_chunk", now,
                        tick=self.tick_count,
                    )
                if chunks == 1:
                    req.n_emitted = 1
                    if req.max_new == 1:
                        self._retire(s, req, retired)
                continue
            pf = prefill[s]
            if pf:
                # advance the admission one chunk
                prefill[s] = pf - 1
                n_chunks += 1
                if trace is not None and req.trace is not None:
                    trace.event(
                        req.trace, "prefill_chunk", now,
                        tick=self.tick_count,
                    )
                if pf == 1:
                    req.n_emitted = 1  # first token, last chunk
                    if req.max_new == 1:
                        self._retire(s, req, retired)
                continue
            # decode n_inner tokens
            ne = req.n_emitted + n_inner
            if ne >= req.max_new:
                req.n_emitted = req.max_new
                self._retire(s, req, retired)
            else:
                req.n_emitted = ne
        if queue or self._n_active or (drr is not None and drr.total):
            dt = self._tick_s(self.tick_count)
            if n_chunks and self.chunk_s:
                # prefill work stretches THIS tick: the real
                # scheduler's per-admitting-slot _extend cost, the
                # contention disaggregation removes
                dt += self.chunk_s * n_chunks
            if self._xfer_s:
                # fleet-cache page movement (fetches this tick, spills
                # from the last retires): the modeled DMA/ring seconds
                # stretch this tick the same way prefill work does
                dt += self._xfer_s
                self._xfer_s = 0.0
            self.next_tick_at = now + dt
            self.busy_s += dt
        else:
            self.next_tick_at = None
        return retired

    # -- internals --------------------------------------------------------

    def _retire(self, s: int, req: SimRequest, out: list) -> None:
        req.finished = True
        req.reason = "length"
        self.n_retired += 1
        out.append(req)
        self._free(s)

    def _free(self, s: int) -> None:
        req = self._slots[s]
        self._slots[s] = None
        self._prefill[s] = 0
        self._n_active -= 1
        if req is not None and req._holds_prefix is not None:
            g = req._holds_prefix
            left = self._resident.get(g, 0) - 1
            if left > 0:
                self._resident[g] = left
            else:
                self._resident.pop(g, None)
                if self.cache is not None:
                    # last holder gone: the fleet withdraws the HBM
                    # advertisement and may spill the group to DRAM —
                    # the priced cost lands on the next busy tick
                    self._xfer_s += self.cache.residency_lost(
                        self.cache_name, g, req.prompt.prefix_len
                    )

    # -- fault injection --------------------------------------------------

    def kill(self) -> None:
        """Replica death: wipe all state; in-flight requests freeze
        (never ``finished`` — the router's health probe re-routes
        them, which is the zero-drop contract under test)."""
        self.alive = False
        self._queue.clear()
        if self._drr is not None:
            self._drr.clear()
        self._slots = [None] * self.S
        self._prefill = [0] * self.S
        self._n_active = 0
        self._resident.clear()
        self._xfer_s = 0.0
        if self.cache is not None:
            # device memory died with the process: HBM advertisements
            # purge; DRAM spills survive for the fleet
            self.cache.drop_replica(self.cache_name)
        self.next_tick_at = None

    def revive(self) -> None:
        self.alive = True
        if self.cache is not None:
            # a respawn is a NEW fleet identity (the live directory's
            # generation bump): stale advertisements can never revive
            self.cache_name = self.cache.register(self)

    def __repr__(self) -> str:
        return (
            f"SimReplica(S={self.S}, pending={self.pending}, "
            f"active={self.active}, "
            f"{'alive' if self.alive else 'dead'})"
        )


class WorkloadReport:
    """Per-request outcome of one simulated day: TTFT / completion
    latency arrays (virtual seconds, in submission order), outcome
    counts, hedge/re-route totals, and :meth:`digest` — a content hash
    of the latency arrays, the one-line bit-identity witness two runs
    of the same scenario must agree on."""

    def __init__(self, requests: list, virtual_s: float, router,
                 controller=None, n_resubmits: int = 0,
                 n_events: int | None = None,
                 wall_s: float | None = None):
        self.requests = requests
        self.n = len(requests)
        self.virtual_s = float(virtual_s)
        # sim-plane throughput self-measurement (round 16): events =
        # submits + fleet ticks, wall from an INJECTED timer (GC008:
        # sim/ never reads the OS clock itself). All OUTSIDE digest().
        self.n_events = None if n_events is None else int(n_events)
        self.wall_s = None if wall_s is None else float(wall_s)
        self.events_per_s = (
            None
            if (self.n_events is None or self.wall_s is None
                or self.wall_s <= 0.0)
            else self.n_events / self.wall_s
        )
        # which execution mode produced this report ("scalar" here;
        # sim/fastpath.py overwrites with "vectorized" or a
        # "scalar-fallback: <reason>" tag) — observability only
        self.fastpath = "scalar"
        # chaos-plane counters, all OUTSIDE digest() (the bit-identity
        # witness keeps its latency-array definition): retry-client
        # resubmissions, partition begins/heals, and stale legs the
        # heals withdrew
        self.n_resubmits = int(n_resubmits)
        self.n_partitions = getattr(router, "n_partitions", 0)
        self.n_stale_cancelled = getattr(
            router, "n_stale_cancelled", 0
        )
        # control-plane counters (0 without a controller): how often
        # the fleet resized and how many coordinator takeovers the day
        # survived. NOT part of digest() — the bit-identity witness
        # keeps its latency-array definition, so a no-event day hashes
        # exactly as it did before the control plane existed.
        self.n_resizes = (
            0 if controller is None else int(controller.n_resizes)
        )
        self.n_failovers = (
            0 if controller is None else int(controller.n_failovers)
        )
        # the latency arrays cover SERVED requests: a shed request
        # (refused at the door, QoS plane) has no TTFT to measure and
        # must not poison the percentile/digest arrays. A tenant-less
        # day sheds nothing, so every pre-QoS digest is byte-for-byte
        # unchanged.
        served = [r for r in requests if r.outcome != "shed"]
        self.ttft = np.asarray([r.ttft for r in served], np.float64)
        self.latency = np.asarray(
            [r.latency for r in served], np.float64
        )
        self.outcomes: dict[str, int] = {}
        self.shed_reasons: dict[str, int] = {}
        for r in requests:
            self.outcomes[r.outcome] = self.outcomes.get(r.outcome, 0) + 1
            sr = getattr(r, "shed_reason", None)
            if sr is not None:
                self.shed_reasons[sr] = self.shed_reasons.get(sr, 0) + 1
        self.n_hedges = router.n_hedges
        self.n_rerouted = router.n_rerouted
        self.n_migrated = getattr(router, "n_migrated", 0)
        self.n_kept_local = getattr(router, "n_kept_local", 0)
        self.n_shed = getattr(router, "n_shed", 0)
        self.n_hedges_refused = getattr(router, "n_hedges_refused", 0)
        self.dropped = sum(not r.finished for r in requests)
        # per-request mean inter-token gap (first token -> done over
        # the decode tokens): the decode-steadiness distribution the
        # disaggregation claim is about. NOT part of digest() — the
        # bit-identity witness keeps its pre-round-16 definition.
        itl = []
        for r in requests:
            n = len(r.tokens)
            if (r.t_first_token is not None and r.t_done is not None
                    and n > 1):
                itl.append(
                    (r.t_done - r.t_first_token) / (n - 1)
                )
        self.decode_itl = np.asarray(itl, np.float64)

    @classmethod
    def from_arrays(cls, requests, virtual_s: float, router, *,
                    ttft, latency, outcomes: dict, shed_reasons: dict,
                    dropped: int, decode_itl, n_resubmits: int = 0,
                    n_events: int | None = None,
                    wall_s: float | None = None) -> "WorkloadReport":
        """Array-native constructor for the vectorized day driver
        (sim/fastpath.py): the witness arrays (``ttft`` / ``latency``,
        float64, served requests in submission order) and the outcome
        books arrive precomputed instead of being re-derived from a
        million per-request records. The witness fields are assigned
        HERE — in this module — for both execution paths, so the
        digest definition has a single source of truth (graftcheck
        GC011). ``requests`` may be any sequence of request views
        exposing the per-request attributes the sweeps read."""
        rep = cls.__new__(cls)
        rep.requests = requests
        rep.n = len(requests)
        rep.virtual_s = float(virtual_s)
        rep.n_resubmits = int(n_resubmits)
        rep.n_partitions = getattr(router, "n_partitions", 0)
        rep.n_stale_cancelled = getattr(router, "n_stale_cancelled", 0)
        rep.n_resizes = 0
        rep.n_failovers = 0
        rep.n_events = None if n_events is None else int(n_events)
        rep.wall_s = None if wall_s is None else float(wall_s)
        rep.events_per_s = (
            None
            if (rep.n_events is None or rep.wall_s is None
                or rep.wall_s <= 0.0)
            else rep.n_events / rep.wall_s
        )
        rep.fastpath = "scalar"
        rep.ttft = np.asarray(ttft, np.float64)
        rep.latency = np.asarray(latency, np.float64)
        rep.outcomes = dict(outcomes)
        rep.shed_reasons = dict(shed_reasons)
        rep.n_hedges = router.n_hedges
        rep.n_rerouted = router.n_rerouted
        rep.n_migrated = getattr(router, "n_migrated", 0)
        rep.n_kept_local = getattr(router, "n_kept_local", 0)
        rep.n_shed = getattr(router, "n_shed", 0)
        rep.n_hedges_refused = getattr(router, "n_hedges_refused", 0)
        rep.dropped = int(dropped)
        rep.decode_itl = np.asarray(decode_itl, np.float64)
        return rep

    def p50_ttft(self) -> float:
        return float(np.percentile(self.ttft, 50))

    def p99_ttft(self) -> float:
        return float(np.percentile(self.ttft, 99))

    def p99_decode_itl(self) -> float:
        """p99 of the per-request mean inter-token gap — decode p99,
        the tail a long-prompt burst wrecks on a unified fleet."""
        if self.decode_itl.size == 0:
            return 0.0
        return float(np.percentile(self.decode_itl, 99))

    def per_tenant(self) -> dict[str, dict]:
        """Per-tenant breakdown (QoS plane): request/shed counts and
        TTFT p50/p99 over the tenant's SERVED requests. OUTSIDE
        :meth:`digest` — the bit-identity witness keeps its
        latency-array definition; a tenant-free day returns ``{}``."""
        acc: dict[str, dict] = {}
        for r in self.requests:
            t = getattr(r, "tenant", None)
            if t is None:
                continue
            d = acc.setdefault(t, {"n": 0, "shed": 0, "_ttft": []})
            d["n"] += 1
            if r.outcome == "shed":
                d["shed"] += 1
            elif r.ttft is not None:
                d["_ttft"].append(r.ttft)
        out: dict[str, dict] = {}
        for t, d in acc.items():
            a = np.asarray(d.pop("_ttft"), np.float64)
            out[t] = {
                "n": d["n"],
                "shed": d["shed"],
                "served": int(a.size),
                "p50_ttft_s": (
                    float(np.percentile(a, 50)) if a.size else 0.0
                ),
                "p99_ttft_s": (
                    float(np.percentile(a, 99)) if a.size else 0.0
                ),
                "mean_ttft_s": float(a.mean()) if a.size else 0.0,
            }
        return out

    def digest(self) -> str:
        import hashlib

        h = hashlib.sha256()
        h.update(self.ttft.tobytes())
        h.update(self.latency.tobytes())
        return h.hexdigest()[:16]

    def __repr__(self) -> str:
        return (
            f"WorkloadReport(n={self.n}, "
            f"p99_ttft={self.p99_ttft() * 1e3:.1f}ms, "
            f"virtual={self.virtual_s:.1f}s, "
            f"outcomes={self.outcomes})"
        )


def run_router_day(
    router, arrivals: Iterable[Arrival], *,
    controller=None, events: Iterable = (), retry: RetryPolicy | None = None,
    timer: Callable[[], float] | None = None,
    series=None, slo=None,
) -> WorkloadReport:
    """Drive a virtual-time :class:`~..models.router.RequestRouter`
    through an arrival stream to completion: advance the clock to each
    arrival (stepping the router at every replica tick, hedge
    deadline, and scheduled clock event in between — ``clock.call_at``
    kill/recover injections fire exactly on time), submit, then drain.
    Every submitted request completes (the router's zero-drop
    contract); the report's :meth:`~WorkloadReport.digest` is
    bit-identical across runs of the same scenario.

    ``controller=`` attaches the round-18 control plane (a
    :class:`~..fleet.FleetController`, or its
    :class:`~..fleet.ControllerSupervisor` active/standby wrapper —
    anything with ``observe_arrival`` / ``step`` / ``next_event_at``):
    every arrival feeds its rate estimator, and the driver advances
    the clock to the controller's decision/checkpoint/takeover cadence
    exactly like replica ticks — a whole autoscaling day stays
    bit-identical. ``events=`` interleaves control-plane events
    (:class:`FleetResize`, :class:`CoordinatorKill`) into the stream;
    an event due at ``t`` fires before an arrival stamped ``t``. With
    neither, the drive loop is byte-for-byte the pre-round-18 one, so
    recorded digests still hold.

    ``retry=`` attaches a :class:`RetryPolicy` client model (chaos
    plane): a submitted request showing no first token by its timeout
    is resubmitted as a fresh arrival feeding back into THIS day's
    stream on the policy's seeded coin — the retry storm replays
    bit-identically, every attempt lands in the report (and its
    digest), and ``WorkloadReport.n_resubmits`` counts the
    amplification. Shed requests are never retried. ``retry=None``
    keeps the drive loop event-for-event the pre-round-20 one.

    ``timer=`` (e.g. ``time.perf_counter``) opts into events/s
    self-measurement: the report's ``n_events`` (submits + fleet
    ticks), ``wall_s``, and ``events_per_s`` fill in, all OUTSIDE
    :meth:`~WorkloadReport.digest`. The timer is injected because
    sim/ never reads the OS clock itself (graftcheck GC008).

    ``series=`` / ``slo=`` attach the windowed SLO plane (round 24: a
    :class:`~..obs.SeriesStore` and/or :class:`~..obs.SloPolicy`):
    the driver calls their ``maybe_roll(now)`` with the day clock at
    every drive-loop point it already visits — after each fleet step
    and each submit — so window rollover is digest-neutral by
    construction: no clock event is ever scheduled and no router or
    replica state is touched; the stores only READ the registry.
    Dark (both None), the loop is event-for-event the pre-round-24
    one."""
    wall_t0 = timer() if timer is not None else None
    clock = router.clock
    if clock is None:
        raise ValueError(
            "run_router_day needs a VirtualClock router (clock=...); "
            "live fleets run router.step() in their own serving loop"
        )

    # the clock's event heap is peeked directly (package-internal by
    # design): this driver is the clock's single thread, and the locked
    # clock.next_event() measured ~8% of a million-request day
    heap = clock._heap
    ctl = controller
    # round-24 windowed SLO plane: one bound rollover callable (or
    # None, keeping the dark drive loop branch-cheap); rolls happen
    # only at points the dark loop already visits, so the day's
    # digest is untouched by construction
    obs_roll = None
    if series is not None or slo is not None:
        if slo is not None and (series is None or slo.series is series):
            _store, _roll = slo.series, slo.maybe_roll
        elif series is not None and slo is None:
            _store, _roll = series, series.maybe_roll
        else:
            # distinct stores bound at once (unusual): roll both; no
            # shared boundary to fast-path on
            _store = None

            def _roll(now_v):
                if series is not None:
                    series.maybe_roll(now_v)
                if slo is not None:
                    slo.maybe_roll(now_v)

        if _store is not None:
            from ..obs.series import _EPS as _w_eps

            _w_s = _store.window_s

            def obs_roll(now_v):
                # called at every step/submit with the loop's current
                # virtual time; crossing a boundary is rare, so the
                # common case is one compare against the open window's
                # start (package-internal peek, same license as
                # clock._heap above)
                t0 = _store._t0
                if t0 is None or now_v - t0 + _w_eps >= _w_s:
                    _roll(now_v)
        else:
            obs_roll = _roll
    # retry-client state (chaos plane): a heap of (due, submit-index,
    # request, attempt) timeout checks; empty and untouched when
    # retry=None, keeping the drive loop event-for-event pre-round-20
    rheap: list = []
    n_resubmits = 0

    def next_at():
        nt = router.next_event_at()
        if heap:
            ce = heap[0][0]
            if nt is None or ce < nt:
                nt = ce
        if ctl is not None:
            ct = ctl.next_event_at()
            if ct is not None and (nt is None or ct < nt):
                nt = ct
        if rheap:
            rt = rheap[0][0]
            if nt is None or rt < nt:
                nt = rt
        return nt

    submitted = []
    append = submitted.append
    run_until, step = clock.run_until, router.step
    submit, replicas = router.submit, router.replicas
    ttft_slo = router.ttft_slo
    evs = sorted(events, key=lambda e: e.t)
    ei = 0
    n_evs = len(evs)
    # `nt` (the next event time) is maintained INCREMENTALLY across
    # arrivals: a full next_at() per arrival measured ~25% of a
    # million-request day, and a submit can only add two event kinds —
    # its replica's (possibly fresh) tick and its own hedge deadline
    # (the controller's cadence is monotone and re-read at every full
    # next_at(), so the incremental path never skips past it)
    nt = next_at()

    def arm_retry(rr, attempt):
        # park the client's timeout check; the due time (timeout +
        # seeded jitter) is an event the driver advances to exactly
        nonlocal nt
        idx = router.n_submitted  # day-local, deterministic
        due = retry.resubmit_at(rr.t_submit, idx, attempt)
        heapq.heappush(rheap, (due, idx, rr, attempt))
        if nt is None or due < nt:
            nt = due

    def fire_retries():
        # due timeout checks: a request still showing no first token
        # is resubmitted as a fresh arrival (feedback — the storm);
        # resolved or exhausted chains just expire
        nonlocal n_resubmits
        now_v = clock.now()
        while rheap and rheap[0][0] <= now_v + 1e-12:
            _due, _idx, rr0, attempt = heapq.heappop(rheap)
            if rr0.finished or rr0.t_first_token is not None:
                continue
            if attempt + 1 > retry.max_retries:
                continue
            rr = submit(rr0.prompt, rr0.max_new, key=rr0.key,
                        tenant=rr0.tenant)
            append(rr)
            n_resubmits += 1
            tb = router._trace
            if (tb is not None and rr.trace is not None
                    and rr0.trace is not None):
                # the child trace links back to the timed-out parent:
                # the retry CLIENT alone knows the chain
                tb.link(rr.trace, rr0.trace)
                tb.event(
                    rr.trace, "retry_resubmit", now_v,
                    parent=rr0.trace, attempt=attempt + 1,
                )
            if ctl is not None:
                ctl.observe_arrival(now_v)
            if rr.finished:
                continue  # shed at the door: the client backs off
            arm_retry(rr, attempt + 1)

    def advance_to(t):
        # step the fleet (and the controller, when attached) at every
        # due tick up to virtual time t, then land exactly on t
        nonlocal nt
        while nt is not None and nt <= t:
            run_until(nt)
            step()
            if ctl is not None:
                ctl.step()
            if rheap:
                fire_retries()
            if obs_roll is not None:
                obs_roll(nt)
            nt = next_at()
        run_until(t)
        if obs_roll is not None:
            obs_roll(t)

    def fire_events_through(t):
        # control-plane events due at or before t, in stream order
        nonlocal ei, nt
        while ei < n_evs and evs[ei].t <= t:
            e = evs[ei]
            advance_to(e.t)
            e.fire(router, ctl)
            ei += 1
            nt = next_at()

    for a in arrivals:
        at = a.t
        if ei < n_evs:
            fire_events_through(at)
        while nt is not None and nt <= at:
            run_until(nt)
            step()
            if ctl is not None:
                ctl.step()
            if rheap:
                fire_retries()
            if obs_roll is not None:
                obs_roll(nt)
            nt = next_at()
        run_until(at)
        rr = submit(a.prompt, a.max_new, tenant=a.tenant)
        append(rr)
        if ctl is not None:
            ctl.observe_arrival(at)
        if obs_roll is not None:
            obs_roll(at)
        if rr.finished:
            continue  # shed at the door: no leg, no events to add
        t = getattr(replicas[rr.replica], "next_tick_at", None)
        if t is not None and (nt is None or t < nt):
            nt = t
        if ttft_slo is not None:
            d = rr.t_submit + ttft_slo
            if nt is None or d < nt:
                nt = d
        if retry is not None:
            arm_retry(rr, 0)
    if ei < n_evs:
        # events past the last arrival (an end-of-day kill, a scale-in
        # order): fire them at their times, stepping normally between
        fire_events_through(evs[-1].t)
    # a controller's decision cadence is ALWAYS pending, so with one
    # attached next_at() never returns None and the no-event stall
    # check below can't fire — count barren drain rounds instead
    # (controller stepped, router stepped, yet no replica tick / hedge
    # deadline / clock event appeared and nothing completed) and fail
    # by name after a few, the same contract as the bare stall
    barren = 0
    while router.in_flight:
        nt = next_at()
        if nt is None:
            raise RuntimeError(
                f"workload stalled with {router.in_flight} requests "
                "in flight: no replica tick, hedge deadline, or clock "
                "event pending (every replica down with nothing "
                "scheduled to revive one?)"
            )
        inflight_before = router.in_flight
        clock.run_until(nt)
        router.step()
        if rheap:
            fire_retries()
        if obs_roll is not None:
            obs_roll(nt)
        if ctl is not None:
            ctl.step()
            if (
                router.next_event_at() is None and not heap
                and router.in_flight == inflight_before
            ):
                barren += 1
                if barren >= 3:
                    raise RuntimeError(
                        f"workload stalled with {router.in_flight} "
                        "requests in flight: 3 controller decision "
                        "intervals passed with no replica tick, hedge "
                        "deadline, or clock event and no completion — "
                        "the controller cannot restore a replica it "
                        "never drained (every replica down?)"
                    )
            else:
                barren = 0
    n_events = router.n_submitted + sum(
        getattr(r, "tick_count", 0) for r in router.replicas
    )
    wall = None if wall_t0 is None else timer() - wall_t0
    return WorkloadReport(submitted, clock.now(), router, ctl,
                          n_resubmits=n_resubmits, n_events=n_events,
                          wall_s=wall)
