# graftcheck: hermetic-root  (GC001 walks this subpackage's closure as
# its own root: everything sim/ reaches must stay jax-/accelerator-free
# even if a future refactor detaches it from the package root's walk)
"""Virtual-time straggler simulation: the *decide* plane.

obs/ observes a fleet, graftcheck verifies the code that runs it; this
package closes the loop by making policy decisions cheap to evaluate:
a :class:`VirtualClock` (event-heap time) under a :class:`SimBackend`
(the full :class:`~..backends.base.Backend` protocol) lets the REAL
``asyncmap``/``waitall``, ``HedgedServer``, and anything else written
against the Backend contract run on virtual time — a 10k-epoch
straggling run completes in milliseconds, bit-reproducibly. On top:

* :mod:`.replay` — recorded :class:`~..utils.trace.EpochTracer` /
  obs-plane traces become counterfactual testbeds ("what would that
  incident have cost under nwait=5?");
* :mod:`.tune` — sweep (nwait, hedge width, code rate) against a
  trace, a fitted :class:`~..utils.straggle.PoolLatencyModel`, or any
  :mod:`..utils.faults` schedule, honoring the decodability floor and
  cross-checking ``PoolLatencyModel.optimal_nwait``;
* :mod:`.fastpath` — the vectorized router-day engine:
  :func:`~.fastpath.run_router_day_fast` reproduces the scalar
  :func:`~.workload.run_router_day` ``digest()`` bit for bit on
  supported day shapes at ~10-60x the events/s (falling back to the
  scalar loop at genuinely event-driven boundaries), which is what
  lets the :mod:`.tune` sweeps search larger candidate grids inside
  the same online decision budget.

stdlib + numpy only, like the package root: simulating a TPU fleet
must never require a TPU (or jax) — tests/test_no_compiler.py and
graftcheck GC001 both pin it.
"""

from .backend import SimBackend, SimEvent, model_delay_fn
from .clock import VirtualClock
from .fastpath import (
    ArrivalBatch,
    diurnal_arrival_batch,
    fastpath_supported,
    poisson_arrival_batch,
    run_router_day_fast,
)
from .replay import (
    ReplayResult,
    ReplayTrace,
    compare,
    replay,
    replay_router_day,
)
from .tune import (
    NwaitSweep,
    recommend_nwait,
    recovered_work_per_s,
    sweep_code_rate,
    sweep_harvest_k,
    sweep_hedge,
    sweep_hierarchical,
    sweep_nwait,
    sweep_router_policy,
    sweep_spill_capacity,
    sweep_tenant_weights,
    sweep_tier_split,
)
from .workload import (
    Arrival,
    CoordinatorKill,
    FleetResize,
    ReplicaPartition,
    RetryPolicy,
    SimFleetCache,
    SimPrompt,
    SimReplica,
    SimRequest,
    SimTicket,
    WorkloadReport,
    arrivals_from_jsonl,
    diurnal_arrivals,
    dump_arrivals_jsonl,
    lognormal_ticks,
    poisson_arrivals,
    run_router_day,
    service_ticks_per_request,
)

__all__ = [
    "VirtualClock",
    "SimBackend",
    "SimEvent",
    "model_delay_fn",
    "ReplayTrace",
    "ReplayResult",
    "replay",
    "compare",
    "replay_router_day",
    "ArrivalBatch",
    "poisson_arrival_batch",
    "diurnal_arrival_batch",
    "fastpath_supported",
    "run_router_day_fast",
    "NwaitSweep",
    "sweep_nwait",
    "sweep_code_rate",
    "sweep_harvest_k",
    "sweep_hedge",
    "sweep_hierarchical",
    "sweep_router_policy",
    "sweep_spill_capacity",
    "sweep_tenant_weights",
    "sweep_tier_split",
    "recommend_nwait",
    "recovered_work_per_s",
    "Arrival",
    "CoordinatorKill",
    "FleetResize",
    "ReplicaPartition",
    "RetryPolicy",
    "SimFleetCache",
    "SimPrompt",
    "SimRequest",
    "SimReplica",
    "SimTicket",
    "WorkloadReport",
    "poisson_arrivals",
    "diurnal_arrivals",
    "arrivals_from_jsonl",
    "dump_arrivals_jsonl",
    "lognormal_ticks",
    "run_router_day",
    "service_ticks_per_request",
]
