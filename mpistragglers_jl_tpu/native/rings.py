"""Slot-ring bookkeeping for the zero-copy transports (stdlib + numpy).

The round-12 transport moves bulk tensor bytes onto PERSISTENT shared
memory — a broadcast arena on the coordinator side and per-worker result
rings on the worker side (native/transport.py), plus a
``multiprocessing.shared_memory`` twin for :class:`~..backends.process.
ProcessBackend`. All three share the same discipline, factored here:

* a region is mapped ONCE per peer (fd / name passed once), then reused
  across epochs — the per-epoch memfd + 2 mmaps + fd-pass setup the old
  ``isend_shm`` path paid (transport.py round-6 note) disappears;
* the region is divided into fixed **slots**; a producer acquires a
  slot, writes the payload bytes, and ships only a small control frame
  (slot, length, generation);
* consumers read the bytes **in place** (``np.frombuffer`` views) and a
  slot is only reclaimed once every consumer has RELEASED it — the
  pin-count generalization of PR 6's keep-window semantics: a held view
  defers reuse, it never dangles;
* when no slot is free (every one still pinned), the producer FALLS
  BACK to the copying transport for that payload — correctness never
  waits on a consumer's garbage collector.

Release detection rides CPython destruction: served views are numpy
arrays registered with :func:`track_release`; when the last derived
view dies, the finalizer fires and the slot's pin drops. A consumer
that holds a view forever simply keeps that slot pinned (and the
high-water gauge honest).
"""

from __future__ import annotations

import mmap as _mmap
import os as _os
import weakref as _weakref

import numpy as np

__all__ = [
    "next_pow2",
    "RingAlloc",
    "MemfdRegion",
    "HeapRegion",
    "region_create",
    "track_release",
    "as_u8",
]


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << max(int(n) - 1, 0).bit_length()


def as_u8(buf) -> np.ndarray:
    """Any contiguous readable buffer as a flat uint8 view (no copy for
    contiguous ndarrays/bytes; a copy only for non-contiguous input)."""
    if isinstance(buf, np.ndarray):
        a = buf if buf.flags.c_contiguous else np.ascontiguousarray(buf)
        return a.reshape(-1).view(np.uint8)
    mv = memoryview(buf)
    if not mv.c_contiguous:  # pragma: no cover - codec always gives C
        mv = memoryview(bytes(mv))
    return np.frombuffer(mv.cast("B"), np.uint8)


class RingAlloc:
    """Generation-counted slot states for one ring.

    A slot is FREE when it has no holders. ``acquire`` hands out the
    next free slot with a fresh generation; ``add_holder``/``release``
    manage the pin set (holders are opaque hashables: consumer ranks
    for the broadcast arena, the literal ``"view"`` token — one per
    served view — for result rings). Stale releases (old generation)
    are ignored: an ack that raced a slot's reuse must not free the new
    occupant. Not thread-safe by itself; callers serialize (the
    transport's callers all do — see transport.py)."""

    __slots__ = ("slots", "_gen", "_holders", "_clock")

    def __init__(self, slots: int):
        self.slots = int(slots)
        self._gen = [0] * self.slots
        self._holders: list[set] = [set() for _ in range(self.slots)]
        self._clock = 0

    def acquire(self, holders) -> "tuple[int, int] | None":
        """Next free slot as ``(slot, gen)`` with ``holders`` installed
        as its pin set, or None when every slot is pinned."""
        for s in range(self.slots):
            if not self._holders[s]:
                self._clock += 1
                self._gen[s] = self._clock
                self._holders[s] = set(holders)
                return s, self._clock
        return None

    def add_holder(self, slot: int, gen: int, holder) -> bool:
        if self._gen[slot] != gen:
            return False
        self._holders[slot].add(holder)
        return True

    def release(self, slot: int, gen: int, holder) -> None:
        if 0 <= slot < self.slots and self._gen[slot] == gen:
            self._holders[slot].discard(holder)

    def release_holder_everywhere(self, holder) -> None:
        """Drop ``holder`` from every slot (a dead/replaced consumer
        will never ack; its pins must not strand slots forever)."""
        for hs in self._holders:
            hs.discard(holder)

    @property
    def pinned(self) -> int:
        return sum(1 for hs in self._holders if hs)


class MemfdRegion:
    """One anonymous shared-memory region: memfd + a writable mapping
    (+ a flat uint8 numpy view). ``fd`` is what crosses the socket via
    SCM_RIGHTS; the receiving side maps the same pages read-only.
    ``MemfdRegion.create`` returns None where ``memfd_create`` is
    unavailable (callers fall back to the copying transport)."""

    __slots__ = ("fd", "nbytes", "mm", "view")

    def __init__(self, fd: int, nbytes: int):
        self.fd = fd
        self.nbytes = int(nbytes)
        self.mm = _mmap.mmap(fd, self.nbytes, _mmap.MAP_SHARED,
                             _mmap.PROT_READ | _mmap.PROT_WRITE)
        self.view = np.frombuffer(self.mm, np.uint8)
        # np.frombuffer over a writable mmap yields a READ-ONLY array
        # (mmap's buffer export is const on some Python builds); get a
        # writable alias explicitly
        if not self.view.flags.writeable:  # pragma: no cover - build dep
            self.view = np.frombuffer(
                memoryview(self.mm), np.uint8
            )

    @classmethod
    def create(cls, nbytes: int, name: str = "msgt-ring"):
        if not hasattr(_os, "memfd_create"):  # pragma: no cover
            return None
        try:
            fd = _os.memfd_create(name)
            _os.ftruncate(fd, int(nbytes))
            return cls(fd, nbytes)
        except OSError:  # pragma: no cover - exotic kernel/limits
            return None

    def close(self) -> None:
        """Release the producer-side mapping and fd. Pages live on
        while any consumer mapping (or in-flight SCM_RIGHTS fd) exists.
        A mapping pinned by live local views is left in place (same
        BufferError discipline as the worker's shm keep-window)."""
        self.view = None
        try:
            self.mm.close()
        except BufferError:  # views alive; drop our refs, GC finishes
            pass
        if self.fd >= 0:
            _os.close(self.fd)
            self.fd = -1


class HeapRegion:
    """The copy-fallback twin of :class:`MemfdRegion`: one anonymous
    heap-backed buffer with the same ``nbytes``/``view``/``close``
    surface but no ``fd`` — nothing can cross a process boundary
    zero-copy, which is exactly the degradation the callers already
    handle (``MemfdRegion.create`` returning None routes here instead
    of forcing every consumer to grow a second code path). In-process
    consumers still get zero-copy ``view`` slices; cross-process ones
    see ``fd is None`` and fall back to copying frames."""

    __slots__ = ("nbytes", "view")

    fd = None

    def __init__(self, nbytes: int):
        self.nbytes = int(nbytes)
        self.view = np.zeros(self.nbytes, np.uint8)

    def close(self) -> None:
        self.view = None


def region_create(nbytes: int, name: str = "msgt-ring"):
    """A shared-memory region where the platform has ``memfd_create``,
    the heap twin everywhere else — the one-call spelling of the
    fallback dance every ring consumer performs."""
    region = MemfdRegion.create(nbytes, name)
    return HeapRegion(nbytes) if region is None else region


def track_release(view: np.ndarray, callback, *args) -> None:
    """Fire ``callback(*args)`` once, when ``view`` (and every derived
    view keeping it alive) has been destroyed. This is the pin-release
    hook: decoders build ``np.frombuffer`` chains whose base is
    ``view``, so the finalizer fires exactly when no live array can
    read the slot anymore. Callbacks run wherever the last reference
    dies (any thread, possibly interpreter shutdown) — they must be
    exception-safe and lock-free or self-locking."""
    _weakref.finalize(view, callback, *args)
