// Native host-side message transport: framed non-blocking point-to-point
// messaging between a coordinator and n worker processes over Unix-domain
// sockets, driven by an epoll progress thread.
//
// This is the framework's analog of the reference's one native component:
// libmpi reached through MPI.jl (SURVEY component C8 — the reference's
// entire transport is MPI_Isend/Irecv/Test/Waitany/Waitall, see
// src/MPIAsyncPools.jl:99,113,137-138,161,171,182-183,212). The mapping:
//
//   MPI primitive        here
//   -----------------    ------------------------------------------------
//   MPI_Isend            coord_isend: copy payload into a per-peer send
//                        queue (the snapshot discipline of reference
//                        src/MPIAsyncPools.jl:130 lives in the transport),
//                        kick the progress thread via eventfd, return
//                        immediately.
//   progress engine      one epoll thread handling partial reads/writes on
//                        every peer socket (libmpi's progress engine).
//   MPI_Test             coord_poll/coord_take: non-blocking completion
//                        probe + payload harvest.
//   MPI_Waitany          coord_waitany: condvar sleep until any peer in a
//                        caller-supplied set has a completed inbound frame
//                        (or died), with optional timeout.
//   dead rank            peer HUP/EOF marks the rank dead (sticky); polls
//                        on a dead rank surface a death marker instead of
//                        hanging the way a dead rank hangs MPI_Waitall
//                        (SURVEY §5 'Failure detection').
//
// Wire format, both directions: a 40-byte header of five little-endian
// int64s {payload_len, seq, epoch, tag, kind} followed by payload_len raw
// bytes. kind: 0=data, 1=control/shutdown, 2=hello (worker->coordinator,
// seq carries the rank), 3=death marker (synthesized locally, never on
// the wire), 4=worker-error (payload is a serialized exception).
//
// Exposed as a flat C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <sys/time.h>
#include <sys/un.h>
#include <unistd.h>

namespace {

struct Header {
  int64_t len;
  int64_t seq;
  int64_t epoch;
  int64_t tag;
  int64_t kind;
};
static_assert(sizeof(Header) == 40, "header must be 5 packed int64s");

constexpr int64_t KIND_DATA = 0;
constexpr int64_t KIND_CONTROL = 1;
constexpr int64_t KIND_HELLO = 2;
constexpr int64_t KIND_DEATH = 3;
constexpr int64_t KIND_ERROR = 4;
// Same-host zero-copy broadcast: the frame's wire payload is
// [int64 shm_id, int64 body_len, codec prefix...] and the BODY lives in
// a memfd region mapped by both sides; the memfd crosses the socket as
// SCM_RIGHTS ancillary data attached to the frame's first byte. The
// receiving transport resolves the region and presents the frame as
// KIND_DATA with an out-of-band body view.
constexpr int64_t KIND_SHM = 5;

struct Frame {
  Header hdr;
  std::vector<uint8_t> payload;  // inbound frames / simple sends
  // Outbound zero-copy path: an optional codec prefix written after the
  // header, and an optional SHARED body — the pool broadcasts one
  // payload to every worker per epoch, so the snapshot is taken once
  // and the n send queues hold references, not copies.
  std::vector<uint8_t> prefix;
  std::shared_ptr<std::vector<uint8_t>> shared;
  // fd to pass via SCM_RIGHTS with the frame's first byte (shm frames);
  // owned by the frame until attached (or the frame is destroyed)
  int pass_fd = -1;

  Frame() = default;
  Frame(const Frame&) = delete;
  Frame& operator=(const Frame&) = delete;
  Frame(Frame&& o) noexcept
      : hdr(o.hdr), payload(std::move(o.payload)),
        prefix(std::move(o.prefix)), shared(std::move(o.shared)),
        pass_fd(o.pass_fd) {
    o.pass_fd = -1;
  }
  Frame& operator=(Frame&& o) noexcept {
    if (this != &o) {
      if (pass_fd >= 0) ::close(pass_fd);
      hdr = o.hdr;
      payload = std::move(o.payload);
      prefix = std::move(o.prefix);
      shared = std::move(o.shared);
      pass_fd = o.pass_fd;
      o.pass_fd = -1;
    }
    return *this;
  }
  ~Frame() {
    if (pass_fd >= 0) ::close(pass_fd);
  }

  size_t body_size() const {
    return shared ? shared->size() : payload.size();
  }
  const uint8_t* body_data() const {
    return shared ? shared->data() : payload.data();
  }
};

// A coordinator-side shared-memory broadcast payload: one memfd, one
// memcpy, any number of per-worker frames referencing it by id.
struct ShmPayload {
  int fd = -1;
  void* addr = nullptr;
  size_t len = 0;
  int64_t id = 0;
  ~ShmPayload() {
    if (addr) ::munmap(addr, len);
    if (fd >= 0) ::close(fd);
  }
};

// Blocking full read/write on a (blocking-mode) fd. Used worker-side and
// during the coordinator's hello handshake.
// Address forms: a filesystem path (Unix-domain socket, single host) or
// "tcp://host:port" (TCP with TCP_NODELAY, multi-host). Port 0 binds an
// ephemeral port readable via msgt_coord_port. Returns 0 = not a tcp://
// address, 1 = parsed, -1 = malformed (tcp:// prefix but bad host/port
// — a hard error, NOT a fallback to a unix path named "tcp://...").
int parse_tcp(const char* addr, std::string* host, int* port) {
  const char* kPrefix = "tcp://";
  if (std::strncmp(addr, kPrefix, 6) != 0) return 0;
  const char* rest = addr + 6;
  const char* colon = std::strrchr(rest, ':');
  if (!colon || colon == rest || colon[1] == '\0') return -1;
  for (const char* p = colon + 1; *p; p++)
    if (*p < '0' || *p > '9') return -1;  // "5O55" must not atoi to 0
  long pt = std::atol(colon + 1);
  if (pt < 0 || pt > 65535) return -1;
  *host = std::string(rest, colon - rest);
  *port = static_cast<int>(pt);
  return 1;
}

void tune_tcp(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// Large socket buffers: the pool ships multi-MiB coded shards, and the
// default ~208 KiB buffers force a wakeup/context-switch per fraction of
// a frame. SO_*BUFFORCE (root) ignores wmem_max/rmem_max caps; the
// plain options are the unprivileged fallback. Best effort by design.
void tune_bufs(int fd) {
  int sz = 8 * 1024 * 1024;
#ifdef SO_SNDBUFFORCE
  if (setsockopt(fd, SOL_SOCKET, SO_SNDBUFFORCE, &sz, sizeof(sz)) != 0)
#endif
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &sz, sizeof(sz));
#ifdef SO_RCVBUFFORCE
  if (setsockopt(fd, SOL_SOCKET, SO_RCVBUFFORCE, &sz, sizeof(sz)) != 0)
#endif
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &sz, sizeof(sz));
}

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r == 0) return false;  // EOF
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

// ---------------------------------------------------------------- auth
// Shared-secret hello authentication (challenge-response, HMAC-SHA256 —
// the multiprocessing-authkey pattern). Without it, any process that can
// reach the listen socket can complete a hello and feed frames to the
// coordinator's deserializer. The secret itself never crosses the wire:
// the coordinator sends a random challenge, the worker proves knowledge
// of the key by returning HMAC(key, challenge). SHA-256 per FIPS 180-4;
// implemented inline because this image links no crypto library.

struct Sha256 {
  uint32_t h[8];
  uint64_t total = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
        0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u};
    std::memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void block(const uint8_t* p) {
    static const uint32_t K[64] = {
        0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
        0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
        0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
        0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
        0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
        0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
        0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
        0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
        0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
        0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
        0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
        0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
        0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    total += n;
    if (buflen > 0) {
      size_t take = 64 - buflen < n ? 64 - buflen : n;
      std::memcpy(buf + buflen, p, take);
      buflen += take;
      p += take;
      n -= take;
      if (buflen == 64) {
        block(buf);
        buflen = 0;
      }
    }
    while (n >= 64) {
      block(p);
      p += 64;
      n -= 64;
    }
    if (n > 0) {
      std::memcpy(buf, p, n);
      buflen = n;
    }
  }

  void digest(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    // bypass `total` accounting for the length field itself
    std::memcpy(buf + 56, lenb, 8);
    block(buf);
    for (int i = 0; i < 8; i++) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

void hmac_sha256(const uint8_t* key, size_t keylen, const uint8_t* msg,
                 size_t msglen, uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (keylen > 64) {
    Sha256 kh;
    kh.update(key, keylen);
    kh.digest(k);
  } else {
    std::memcpy(k, key, keylen);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 hi;
  hi.update(ipad, 64);
  hi.update(msg, msglen);
  hi.digest(inner);
  Sha256 ho;
  ho.update(opad, 64);
  ho.update(inner, 32);
  ho.digest(out);
}

bool fill_random(uint8_t* buf, size_t n) {
  // getrandom(2) first: no fd needed, works in empty containers and
  // cannot be starved by a chroot without /dev (ADVICE r2: a clock-
  // seeded fallback makes challenges predictable, enabling MAC replay
  // — when no strong entropy exists the HANDSHAKE must fail, not
  // degrade; callers with a token configured treat false as fatal)
  size_t got = 0;
  while (got < n) {
    long r = ::syscall(SYS_getrandom, buf + got, n - got, 0);
    if (r > 0) {
      got += static_cast<size_t>(r);
      continue;
    }
    if (r < 0 && errno == EINTR) continue;
    break;  // ENOSYS (pre-3.17 kernel) or other failure: try urandom
  }
  if (got == n) return true;
  int fd = ::open("/dev/urandom", O_RDONLY | O_CLOEXEC);
  if (fd >= 0) {
    bool ok = read_full(fd, buf, n);
    ::close(fd);
    if (ok) return true;
  }
  return false;
}

constexpr size_t kChallengeLen = 16;
constexpr size_t kMacLen = 32;

// Direction tags for the mutual handshake's domain separation: the
// worker proves HMAC(token, 0x01||C), the coordinator proves
// HMAC(token, 0x02||W) — a transcript from one direction can never be
// replayed as the other's proof.
constexpr uint8_t kTagWorkerProof = 0x01;
constexpr uint8_t kTagCoordProof = 0x02;

void hmac_tagged(const std::string& token, uint8_t tag,
                 const uint8_t* challenge, size_t len, uint8_t out[32]) {
  uint8_t buf[1 + kChallengeLen];
  buf[0] = tag;
  std::memcpy(buf + 1, challenge, len);
  hmac_sha256(reinterpret_cast<const uint8_t*>(token.data()),
              token.size(), buf, 1 + len, out);
}

// Per-peer connection state owned by the progress thread.
struct Peer {
  int fd = -1;
  bool dead = false;

  // SCM_RIGHTS fds received from this worker (result-ring regions —
  // the worker passes each ring's memfd once, attached to the ring's
  // first control frame). Captured by the progress thread during
  // pump_read, consumed by the caller thread via msgt_coord_take_fd;
  // guarded by the coordinator mutex.
  std::deque<int> in_fds;

  // inbound reassembly state machine
  Header rhdr{};
  size_t rgot = 0;       // bytes of header received so far
  bool rin_payload = false;
  std::vector<uint8_t> rbuf;
  size_t rpayload_got = 0;

  // outbound queue: frames waiting to be written, partial-write cursor
  std::deque<Frame> sendq;
  size_t sent = 0;  // bytes of sendq.front() already written (hdr+payload)
};

struct Coordinator {
  int n = 0;
  int listen_fd = -1;
  int epfd = -1;
  int wake_fd = -1;  // eventfd: kicks the progress thread for sends/stop
  bool tcp = false;
  int port = 0;      // bound TCP port (after create), 0 for unix
  std::string path;  // unix socket path to unlink, empty for tcp
  std::thread progress;
  std::atomic<bool> stopping{false};

  std::string token;  // shared secret; empty = no authentication

  std::mutex mu;                   // guards peers' queues + completed
  std::condition_variable cv;      // notified on arrival / death
  std::vector<Peer> peers;
  std::vector<int> parked;  // authenticated reconnects awaiting reaccept
  std::vector<std::deque<Frame>> completed;  // inbound frames per rank
  std::string error;  // first fatal progress-engine error, for diagnostics

  ~Coordinator() {
    if (listen_fd >= 0) ::close(listen_fd);
    if (epfd >= 0) ::close(epfd);
    if (wake_fd >= 0) ::close(wake_fd);
    for (auto& p : peers) {
      if (p.fd >= 0) ::close(p.fd);
      for (int fd : p.in_fds) ::close(fd);
    }
    for (int fd : parked)
      if (fd >= 0) ::close(fd);
    if (!path.empty()) ::unlink(path.c_str());
  }
};

// Total outbound bytes of a frame (header + prefix + body); the
// partial-write cursor is a single offset over that concatenation.
size_t frame_bytes(const Frame& f) {
  return sizeof(Header) + f.prefix.size() + f.body_size();
}

// Map the partial-write offset to (segment pointer, bytes available):
// the frame is written as header, then prefix, then body, without ever
// materializing the concatenation.
const uint8_t* frame_segment(const Frame& f, size_t off, size_t* avail) {
  if (off < sizeof(Header)) {
    *avail = sizeof(Header) - off;
    return reinterpret_cast<const uint8_t*>(&f.hdr) + off;
  }
  off -= sizeof(Header);
  if (off < f.prefix.size()) {
    *avail = f.prefix.size() - off;
    return f.prefix.data() + off;
  }
  off -= f.prefix.size();
  *avail = f.body_size() - off;
  return f.body_data() + off;
}

void mark_dead(Coordinator* c, int rank) {
  // caller holds c->mu
  Peer& p = c->peers[rank];
  if (p.dead) return;
  p.dead = true;
  if (p.fd >= 0) {
    epoll_ctl(c->epfd, EPOLL_CTL_DEL, p.fd, nullptr);
    ::close(p.fd);
    p.fd = -1;
  }
  p.sendq.clear();
  for (int fd : p.in_fds) ::close(fd);
  p.in_fds.clear();
  c->cv.notify_all();
}

// recvmsg wrapper for the coordinator's inbound pump: any SCM_RIGHTS
// fds riding the stream (worker result-ring announcements) are queued
// per peer instead of silently discarded. Same return contract as
// ::read.
ssize_t coord_recv(Coordinator* c, int rank, void* buf, size_t n) {
  Peer& p = c->peers[rank];
  msghdr mh{};
  iovec iov{buf, n};
  mh.msg_iov = &iov;
  mh.msg_iovlen = 1;
  alignas(cmsghdr) char cbuf[CMSG_SPACE(4 * sizeof(int))];
  mh.msg_control = cbuf;
  mh.msg_controllen = sizeof(cbuf);
  ssize_t r = ::recvmsg(p.fd, &mh, MSG_CMSG_CLOEXEC);
  if (r > 0) {
    for (cmsghdr* cm = CMSG_FIRSTHDR(&mh); cm != nullptr;
         cm = CMSG_NXTHDR(&mh, cm)) {
      if (cm->cmsg_level == SOL_SOCKET && cm->cmsg_type == SCM_RIGHTS) {
        size_t nfds = (cm->cmsg_len - CMSG_LEN(0)) / sizeof(int);
        int fds[4];
        size_t take = nfds < size_t(4) ? nfds : size_t(4);
        std::memcpy(fds, CMSG_DATA(cm), take * sizeof(int));
        std::lock_guard<std::mutex> lk(c->mu);
        for (size_t i = 0; i < take; i++) p.in_fds.push_back(fds[i]);
      }
    }
  }
  return r;
}

// Drain as many inbound bytes as available on peer `rank`; push completed
// frames. Returns false if the peer died.
bool pump_read(Coordinator* c, int rank) {
  Peer& p = c->peers[rank];
  while (true) {
    if (!p.rin_payload) {
      auto* dst = reinterpret_cast<uint8_t*>(&p.rhdr) + p.rgot;
      ssize_t r = coord_recv(c, rank, dst, sizeof(Header) - p.rgot);
      if (r == 0) return false;
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
        if (errno == EINTR) continue;
        return false;
      }
      p.rgot += static_cast<size_t>(r);
      if (p.rgot < sizeof(Header)) continue;
      if (p.rhdr.len < 0) return false;  // corrupt frame
      p.rin_payload = true;
      p.rbuf.resize(static_cast<size_t>(p.rhdr.len));
      p.rpayload_got = 0;
    }
    while (p.rpayload_got < p.rbuf.size()) {
      ssize_t r = coord_recv(c, rank, p.rbuf.data() + p.rpayload_got,
                             p.rbuf.size() - p.rpayload_got);
      if (r == 0) return false;
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
        if (errno == EINTR) continue;
        return false;
      }
      p.rpayload_got += static_cast<size_t>(r);
    }
    {
      std::lock_guard<std::mutex> lk(c->mu);
      Frame f;
      f.hdr = p.rhdr;
      f.payload = std::move(p.rbuf);
      c->completed[rank].push_back(std::move(f));
      c->cv.notify_all();
    }
    p.rbuf = {};
    p.rgot = 0;
    p.rin_payload = false;
  }
}

// Write as much of the send queue as the socket accepts. Returns false on
// a fatal write error (peer treated as dead).
bool pump_write(Coordinator* c, int rank) {
  Peer& p = c->peers[rank];
  while (true) {
    Frame* f;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      if (p.sendq.empty()) break;
      f = &p.sendq.front();
    }
    size_t total = frame_bytes(*f);
    while (p.sent < total) {
      size_t avail;
      const uint8_t* src = frame_segment(*f, p.sent, &avail);
      ssize_t r;
      if (f->pass_fd >= 0 && p.sent == 0) {
        // attach the shm fd to the frame's first byte (SCM_RIGHTS)
        msghdr mh{};
        iovec iov{const_cast<uint8_t*>(src), avail};
        mh.msg_iov = &iov;
        mh.msg_iovlen = 1;
        alignas(cmsghdr) char cbuf[CMSG_SPACE(sizeof(int))];
        std::memset(cbuf, 0, sizeof(cbuf));
        mh.msg_control = cbuf;
        mh.msg_controllen = sizeof(cbuf);
        cmsghdr* cm = CMSG_FIRSTHDR(&mh);
        cm->cmsg_level = SOL_SOCKET;
        cm->cmsg_type = SCM_RIGHTS;
        cm->cmsg_len = CMSG_LEN(sizeof(int));
        std::memcpy(CMSG_DATA(cm), &f->pass_fd, sizeof(int));
        r = ::sendmsg(p.fd, &mh, 0);
        if (r > 0) {
          ::close(f->pass_fd);  // in flight; kernel holds its own ref
          f->pass_fd = -1;
        }
      } else {
        r = ::write(p.fd, src, avail);
      }
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
        if (errno == EINTR) continue;
        return false;
      }
      p.sent += static_cast<size_t>(r);
    }
    {
      std::lock_guard<std::mutex> lk(c->mu);
      p.sendq.pop_front();
    }
    p.sent = 0;
  }
  // nothing left to write: stop watching EPOLLOUT
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLRDHUP;
  ev.data.u32 = static_cast<uint32_t>(rank);
  epoll_ctl(c->epfd, EPOLL_CTL_MOD, p.fd, &ev);
  return true;
}

void progress_main(Coordinator* c) {
  constexpr uint32_t WAKE_TOKEN = 0xffffffffu;
  epoll_event events[64];
  while (!c->stopping.load(std::memory_order_acquire)) {
    int nev = epoll_wait(c->epfd, events, 64, 200);
    if (nev < 0) {
      if (errno == EINTR) continue;
      // fatal: the progress engine cannot continue. Mark every peer dead
      // (polls surface death markers) and wake all waiters so nothing
      // blocks forever on a condvar nobody will notify again.
      std::lock_guard<std::mutex> lk(c->mu);
      c->error = std::string("epoll_wait: ") + strerror(errno);
      for (int r = 0; r < c->n; r++) mark_dead(c, r);
      c->cv.notify_all();
      return;
    }
    // sends may have been enqueued since the last pass: arm EPOLLOUT for
    // any peer with a non-empty queue (cheap: n is small)
    bool kicked = false;
    for (int i = 0; i < nev; i++) {
      if (events[i].data.u32 == WAKE_TOKEN) {
        uint64_t tok;
        (void)!::read(c->wake_fd, &tok, sizeof(tok));
        kicked = true;
      }
    }
    if (kicked) {
      std::lock_guard<std::mutex> lk(c->mu);
      for (int r = 0; r < c->n; r++) {
        Peer& p = c->peers[r];
        if (!p.dead && p.fd >= 0 && !p.sendq.empty()) {
          epoll_event ev{};
          ev.events = EPOLLIN | EPOLLOUT | EPOLLRDHUP;
          ev.data.u32 = static_cast<uint32_t>(r);
          epoll_ctl(c->epfd, EPOLL_CTL_MOD, p.fd, &ev);
        }
      }
    }
    for (int i = 0; i < nev; i++) {
      uint32_t id = events[i].data.u32;
      if (id == WAKE_TOKEN) continue;
      int rank = static_cast<int>(id);
      Peer& p = c->peers[rank];
      {
        // peer liveness is mutated by reaccept() on the caller thread;
        // take the lock for the check so the read is ordered (a stale
        // event for a since-replaced fd then pumps the NEW nonblocking
        // fd, which just returns EAGAIN — benign)
        std::lock_guard<std::mutex> lk(c->mu);
        if (p.dead || p.fd < 0) continue;
      }
      bool ok = true;
      if (events[i].events & (EPOLLIN | EPOLLRDHUP | EPOLLHUP | EPOLLERR))
        ok = pump_read(c, rank);
      if (ok && (events[i].events & EPOLLOUT)) ok = pump_write(c, rank);
      if (!ok) {
        std::lock_guard<std::mutex> lk(c->mu);
        mark_dead(c, rank);
      }
    }
  }
}

struct WorkerCtx {
  int fd = -1;
  // shm broadcast state: fds received via SCM_RIGHTS awaiting their
  // frame. Region mapping/lifetime lives PYTHON-side (mmap objects),
  // where eviction can be refused while views are still exported —
  // a C-side munmap under a live numpy view would be a silent segfault.
  std::deque<int> pending_fds;

  ~WorkerCtx() {
    if (fd >= 0) ::close(fd);
    for (int f : pending_fds) ::close(f);
  }
};

// read_full for the worker's data phase: recvmsg so SCM_RIGHTS fds
// riding any byte land in pending_fds instead of being discarded.
bool worker_read_full(WorkerCtx* w, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    msghdr mh{};
    iovec iov{p, n};
    mh.msg_iov = &iov;
    mh.msg_iovlen = 1;
    alignas(cmsghdr) char cbuf[CMSG_SPACE(4 * sizeof(int))];
    mh.msg_control = cbuf;
    mh.msg_controllen = sizeof(cbuf);
    ssize_t r = ::recvmsg(w->fd, &mh, MSG_CMSG_CLOEXEC);
    if (r == 0) return false;  // EOF
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    for (cmsghdr* cm = CMSG_FIRSTHDR(&mh); cm != nullptr;
         cm = CMSG_NXTHDR(&mh, cm)) {
      if (cm->cmsg_level == SOL_SOCKET && cm->cmsg_type == SCM_RIGHTS) {
        size_t nfds = (cm->cmsg_len - CMSG_LEN(0)) / sizeof(int);
        int fds[4];
        std::memcpy(fds, CMSG_DATA(cm),
                    std::min(nfds, size_t(4)) * sizeof(int));
        for (size_t i = 0; i < std::min(nfds, size_t(4)); i++)
          w->pending_fds.push_back(fds[i]);
      }
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}



// Coordinator side of the hello auth exchange, run with SO_RCVTIMEO
// still armed on `fd`. Always sends an ack frame telling the worker
// whether a proof is required (len = challenge size, or 0 for open
// transports). The exchange is MUTUAL (ADVICE r2: one-way auth let a
// rogue listener that issues a fake challenge feed the worker pickled
// frames): the worker returns HMAC(token, 0x01||C) plus its own
// challenge W, and the coordinator must answer HMAC(token, 0x02||W)
// before the worker enters the data phase — the multiprocessing-
// authkey pattern, both directions.
bool verify_hello_auth(Coordinator* c, int fd) {
  if (c->token.empty()) {
    Header ack{0, 0, 0, 0, KIND_HELLO};
    return write_full(fd, &ack, sizeof(ack));
  }
  uint8_t challenge[kChallengeLen];
  if (!fill_random(challenge, sizeof(challenge)))
    return false;  // no strong entropy + token configured: fail closed
  Header ack{kChallengeLen, 0, 0, 0, KIND_HELLO};
  if (!write_full(fd, &ack, sizeof(ack))) return false;
  if (!write_full(fd, challenge, sizeof(challenge))) return false;
  Header resp{};
  if (!read_full(fd, &resp, sizeof(resp))) return false;
  if (resp.kind != KIND_HELLO ||
      resp.len != static_cast<int64_t>(kMacLen + kChallengeLen))
    return false;
  uint8_t mac[kMacLen], wchal[kChallengeLen], expect[kMacLen];
  if (!read_full(fd, mac, sizeof(mac))) return false;
  if (!read_full(fd, wchal, sizeof(wchal))) return false;
  hmac_tagged(c->token, kTagWorkerProof, challenge, kChallengeLen, expect);
  uint8_t diff = 0;  // constant-time compare
  for (size_t i = 0; i < kMacLen; i++) diff |= mac[i] ^ expect[i];
  if (diff != 0) return false;
  // prove ourselves back: the worker rejects the transport otherwise
  uint8_t proof[kMacLen];
  hmac_tagged(c->token, kTagCoordProof, wchal, kChallengeLen, proof);
  Header ph{kMacLen, 0, 0, 0, KIND_HELLO};
  if (!write_full(fd, &ph, sizeof(ph))) return false;
  return write_full(fd, proof, sizeof(proof));
}

// Accept one connection, read its hello frame, and run the auth
// exchange, all before `deadline`. `expected_rank` = -1 accepts any rank
// not yet connected; otherwise the hello must carry exactly that rank —
// authenticated reconnects from OTHER currently-dead ranks are *parked*
// (not closed) so two concurrently restarting external workers cannot
// lose each other's handshake (their reaccept() picks the parked socket
// up). On success returns the rank and stores the (still blocking-mode)
// fd in *fd_out; on timeout/failure returns -1.
int accept_hello(Coordinator* c,
                 std::chrono::steady_clock::time_point deadline,
                 int expected_rank, int* fd_out) {
  auto remaining_ms = [&]() -> int64_t {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               deadline - std::chrono::steady_clock::now())
        .count();
  };
  while (true) {
    int64_t left = remaining_ms();
    if (left <= 0) return -1;
    pollfd pfd{c->listen_fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(left));
    if (pr <= 0) return -1;
    int fd = ::accept(c->listen_fd, nullptr, nullptr);
    if (fd < 0) continue;
    if (c->tcp) tune_tcp(fd);
    tune_bufs(fd);
    // cap the per-hello exchange at 2 s: a silent stray connection
    // (scanner, health check that sends no bytes) must burn seconds, not
    // the whole handshake deadline while real workers wait in the backlog
    left = remaining_ms();
    if (left > 2000) left = 2000;
    timeval tv{};
    tv.tv_sec = left > 0 ? left / 1000 : 0;
    tv.tv_usec = left > 0 ? (left % 1000) * 1000 : 1;
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    Header hello{};
    bool ok = read_full(fd, &hello, sizeof(hello));
    bool valid = ok && hello.kind == KIND_HELLO && hello.len == 0 &&
                 hello.seq >= 0 && hello.seq < c->n;
    // the auth exchange runs under the same read timeout; an
    // unauthenticated peer never gets past this point
    if (valid) valid = verify_hello_auth(c, fd);
    timeval off{};  // back to no timeout before the caller takes over
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
    if (!valid) {
      // drop and keep waiting: on a public TCP listener a stray
      // connection (port scanner, bad secret) must not abort the
      // handshake — only the deadline ends it
      ::close(fd);
      continue;
    }
    int rank = static_cast<int>(hello.seq);
    if (expected_rank >= 0 && rank != expected_rank) {
      // someone else's reconnect. If that rank is currently dead this is
      // a legitimate concurrent restart: park the authenticated socket
      // for its own reaccept() call instead of dropping it.
      bool parked = false;
      {
        std::lock_guard<std::mutex> lk(c->mu);
        if (c->peers[rank].dead) {
          if (c->parked[rank] >= 0) ::close(c->parked[rank]);
          c->parked[rank] = fd;
          parked = true;
        }
      }
      if (!parked) ::close(fd);
      continue;
    }
    if (expected_rank < 0 && c->peers[rank].fd >= 0) {
      ::close(fd);  // duplicate rank during initial handshake
      continue;
    }
    *fd_out = fd;
    return rank;
  }
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------- coordinator

// Create the coordinator: bind + listen at `addr` — a Unix-socket path,
// or "tcp://host:port" for multi-host (port 0 = ephemeral; read it back
// with msgt_coord_port). `token`/`token_len` install a shared secret:
// every hello must then prove knowledge of it via challenge-response
// (HMAC-SHA256) before the rank is admitted; pass token_len = 0 for an
// unauthenticated transport (trusted-network deployments only).
// Returns an opaque handle, or nullptr on failure.
void* msgt_coord_create(const char* addr_str, int n_workers,
                        const uint8_t* token, int token_len) {
  auto* c = new Coordinator();
  c->n = n_workers;
  c->peers = std::vector<Peer>(n_workers);  // in-place default
  // construction: Frame is move-only, so resize's
  // move-if-noexcept fallback to copying Peers cannot compile
  c->parked.assign(n_workers, -1);
  c->completed.resize(n_workers);
  if (token != nullptr && token_len > 0)
    c->token.assign(reinterpret_cast<const char*>(token),
                    static_cast<size_t>(token_len));
  std::string host;
  int port = 0;
  int ptcp = parse_tcp(addr_str, &host, &port);
  if (ptcp < 0) {  // malformed tcp:// — refuse, don't bind a unix path
    delete c;
    return nullptr;
  }
  if (ptcp == 1) {
    c->tcp = true;
    c->listen_fd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (c->listen_fd < 0) {
      delete c;
      return nullptr;
    }
    int one = 1;
    setsockopt(c->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_port = htons(static_cast<uint16_t>(port));
    if (host.empty() || host == "0.0.0.0")
      a.sin_addr.s_addr = INADDR_ANY;
    else if (inet_pton(AF_INET, host.c_str(), &a.sin_addr) != 1) {
      delete c;
      return nullptr;
    }
    if (::bind(c->listen_fd, reinterpret_cast<sockaddr*>(&a), sizeof(a)) !=
            0 ||
        ::listen(c->listen_fd, n_workers) != 0) {
      delete c;
      return nullptr;
    }
    sockaddr_in bound{};
    socklen_t blen = sizeof(bound);
    if (getsockname(c->listen_fd, reinterpret_cast<sockaddr*>(&bound),
                    &blen) == 0)
      c->port = ntohs(bound.sin_port);
    return c;
  }
  c->path = addr_str;
  ::unlink(addr_str);
  c->listen_fd = ::socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (c->listen_fd < 0) {
    delete c;
    return nullptr;
  }
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  if (c->path.size() >= sizeof(addr.sun_path)) {
    delete c;
    return nullptr;
  }
  std::strncpy(addr.sun_path, addr_str, sizeof(addr.sun_path) - 1);
  if (::bind(c->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(c->listen_fd, n_workers) != 0) {
    delete c;
    return nullptr;
  }
  return c;
}

// Bound TCP port of the coordinator's listen socket (0 for unix sockets)
// — needed when created with port 0 (ephemeral).
int msgt_coord_port(void* h) {
  return static_cast<Coordinator*>(h)->port;
}

// Accept all n workers (each opens with a hello frame carrying its rank in
// hdr.seq), then start the progress thread. Returns 0 on success, -1 on
// timeout/handshake failure. timeout_ms bounds the WHOLE handshake (one
// shared deadline), including each hello read — a worker that connects
// but never sends its hello cannot wedge the coordinator.
int msgt_coord_accept(void* h, int64_t timeout_ms) {
  auto* c = static_cast<Coordinator*>(h);
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  for (int accepted = 0; accepted < c->n; accepted++) {
    int fd = -1;
    int rank = accept_hello(c, deadline, /*expected_rank=*/-1, &fd);
    if (rank < 0) return -1;
    set_nonblocking(fd);
    c->peers[rank].fd = fd;
  }
  c->epfd = epoll_create1(EPOLL_CLOEXEC);
  c->wake_fd = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
  if (c->epfd < 0 || c->wake_fd < 0) return -1;
  epoll_event wev{};
  wev.events = EPOLLIN;
  wev.data.u32 = 0xffffffffu;
  epoll_ctl(c->epfd, EPOLL_CTL_ADD, c->wake_fd, &wev);
  for (int r = 0; r < c->n; r++) {
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLRDHUP;
    ev.data.u32 = static_cast<uint32_t>(r);
    epoll_ctl(c->epfd, EPOLL_CTL_ADD, c->peers[r].fd, &ev);
  }
  c->progress = std::thread(progress_main, c);
  return 0;
}

// Non-blocking send: snapshot `data` into rank's send queue and kick the
// progress thread (MPI_Isend). Returns 0, or -1 if the rank is dead.
int msgt_coord_isend(void* h, int rank, int64_t seq, int64_t epoch,
                     int64_t tag, int64_t kind, const uint8_t* data,
                     int64_t len) {
  auto* c = static_cast<Coordinator*>(h);
  {
    std::lock_guard<std::mutex> lk(c->mu);
    Peer& p = c->peers[rank];
    if (p.dead) return -1;
    Frame f;
    f.hdr = Header{len, seq, epoch, tag, kind};
    f.payload.assign(data, data + len);
    p.sendq.push_back(std::move(f));
  }
  uint64_t one = 1;
  (void)!::write(c->wake_fd, &one, sizeof(one));
  return 0;
}

// Non-blocking send with a file descriptor attached to the frame's
// first byte via SCM_RIGHTS (round-12 zero-copy transport: the
// coordinator passes a broadcast-arena memfd to each worker ONCE, on
// the first arena frame that rank sees; subsequent arena frames are
// tiny fd-less control frames). `fd` is dup'd — the caller keeps
// ownership of its copy. Unix-socket transports only (SCM_RIGHTS does
// not cross TCP; the Python layer gates on the address family).
// Returns 0 ok, -1 dead rank, -2 fd duplication failed (caller should
// fall back to a copying send).
int msgt_coord_isend_fd(void* h, int rank, int64_t seq, int64_t epoch,
                        int64_t tag, int64_t kind, const uint8_t* data,
                        int64_t len, int fd) {
  auto* c = static_cast<Coordinator*>(h);
  int dupfd = ::fcntl(fd, F_DUPFD_CLOEXEC, 0);
  if (dupfd < 0) return -2;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    Peer& p = c->peers[rank];
    if (p.dead) {
      ::close(dupfd);
      return -1;
    }
    Frame f;
    f.hdr = Header{len, seq, epoch, tag, kind};
    f.payload.assign(data, data + len);
    f.pass_fd = dupfd;
    p.sendq.push_back(std::move(f));
  }
  uint64_t one = 1;
  (void)!::write(c->wake_fd, &one, sizeof(one));
  return 0;
}

// Pop the next SCM_RIGHTS fd received from `rank` (worker result-ring
// announcements), -1 if none. Python owns the mapping and its lifetime.
int msgt_coord_take_fd(void* h, int rank) {
  auto* c = static_cast<Coordinator*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  Peer& p = c->peers[rank];
  if (p.in_fds.empty()) return -1;
  int fd = p.in_fds.front();
  p.in_fds.pop_front();
  return fd;
}

// Two-buffer non-blocking send: `pre` (a small codec header) and `body`
// are snapshotted as separate segments — the caller never concatenates,
// so a raw ndarray payload costs exactly one copy (into the queue).
int msgt_coord_isend2(void* h, int rank, int64_t seq, int64_t epoch,
                      int64_t tag, int64_t kind, const uint8_t* pre,
                      int64_t pre_len, const uint8_t* body,
                      int64_t body_len) {
  auto* c = static_cast<Coordinator*>(h);
  {
    std::lock_guard<std::mutex> lk(c->mu);
    Peer& p = c->peers[rank];
    if (p.dead) return -1;
    Frame f;
    f.hdr = Header{pre_len + body_len, seq, epoch, tag, kind};
    f.prefix.assign(pre, pre + pre_len);
    f.payload.assign(body, body + body_len);
    p.sendq.push_back(std::move(f));
  }
  uint64_t one = 1;
  (void)!::write(c->wake_fd, &one, sizeof(one));
  return 0;
}

// ---- shared broadcast payloads -------------------------------------
// The pool broadcasts ONE payload to every idle worker per epoch
// (reference src/MPIAsyncPools.jl:118-139). A shared payload snapshots
// the bytes once; isend_shared enqueues references, so an n-worker
// broadcast is one memcpy total instead of n.

void* msgt_payload_create(const uint8_t* data, int64_t len) {
  return new std::shared_ptr<std::vector<uint8_t>>(
      std::make_shared<std::vector<uint8_t>>(data, data + len));
}

void msgt_payload_release(void* ph) {
  // frames still queued keep the underlying vector alive via their own
  // shared_ptr copies; this only drops the creator's reference
  delete static_cast<std::shared_ptr<std::vector<uint8_t>>*>(ph);
}

int msgt_coord_isend_shared(void* h, int rank, int64_t seq, int64_t epoch,
                            int64_t tag, int64_t kind, const uint8_t* pre,
                            int64_t pre_len, void* ph) {
  auto* c = static_cast<Coordinator*>(h);
  auto* sp = static_cast<std::shared_ptr<std::vector<uint8_t>>*>(ph);
  {
    std::lock_guard<std::mutex> lk(c->mu);
    Peer& p = c->peers[rank];
    if (p.dead) return -1;
    Frame f;
    f.hdr = Header{
        pre_len + static_cast<int64_t>((*sp)->size()), seq, epoch, tag,
        kind};
    f.prefix.assign(pre, pre + pre_len);
    f.shared = *sp;
    p.sendq.push_back(std::move(f));
  }
  uint64_t one = 1;
  (void)!::write(c->wake_fd, &one, sizeof(one));
  return 0;
}

// ---- shared-memory broadcast payloads (same-host zero-copy) ---------
// One memfd holds the body; every worker maps it. An n-worker broadcast
// is ONE memcpy (into the region) + tiny descriptor frames — no payload
// bytes cross the sockets at all.

void* msgt_payload_create_shm(const uint8_t* data, int64_t len) {
  static std::atomic<int64_t> next_id{1};
  int fd = ::memfd_create("msgt-shm", MFD_CLOEXEC);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, len) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* addr = nullptr;
  if (len > 0) {
    addr = ::mmap(nullptr, static_cast<size_t>(len),
                  PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (addr == MAP_FAILED) {
      ::close(fd);
      return nullptr;
    }
    std::memcpy(addr, data, static_cast<size_t>(len));
  }
  auto* sp = new ShmPayload();
  sp->fd = fd;
  sp->addr = addr;
  sp->len = static_cast<size_t>(len);
  sp->id = next_id.fetch_add(1);
  return sp;
}

void msgt_payload_release_shm(void* ph) {
  // frames already queued carry their own dup'd fds; the region's pages
  // live until every mapping and fd is gone
  delete static_cast<ShmPayload*>(ph);
}

int msgt_coord_isend_shm(void* h, int rank, int64_t seq, int64_t epoch,
                         int64_t tag, const uint8_t* pre, int64_t pre_len,
                         void* ph) {
  auto* c = static_cast<Coordinator*>(h);
  auto* sp = static_cast<ShmPayload*>(ph);
  int dupfd = ::fcntl(sp->fd, F_DUPFD_CLOEXEC, 0);
  if (dupfd < 0) {
    // fd exhaustion is not a dead rank: degrade to an ordinary in-frame
    // copy straight out of the mapping, same wire semantics
    std::lock_guard<std::mutex> lk(c->mu);
    Peer& p = c->peers[rank];
    if (p.dead) return -1;
    Frame f;
    f.hdr = Header{
        pre_len + static_cast<int64_t>(sp->len), seq, epoch, tag,
        KIND_DATA};
    f.prefix.assign(pre, pre + pre_len);
    auto* base = static_cast<const uint8_t*>(sp->addr);
    f.payload.assign(base, base + sp->len);
    p.sendq.push_back(std::move(f));
    uint64_t one = 1;
    (void)!::write(c->wake_fd, &one, sizeof(one));
    return 0;
  }
  {
    std::lock_guard<std::mutex> lk(c->mu);
    Peer& p = c->peers[rank];
    if (p.dead) {
      ::close(dupfd);
      return -1;
    }
    Frame f;
    // wire payload: [shm_id, body_len, prefix...]; body stays in shm
    f.hdr = Header{
        static_cast<int64_t>(2 * sizeof(int64_t)) + pre_len, seq, epoch,
        tag, KIND_SHM};
    f.payload.resize(2 * sizeof(int64_t) + pre_len);
    int64_t meta[2] = {sp->id, static_cast<int64_t>(sp->len)};
    std::memcpy(f.payload.data(), meta, sizeof(meta));
    std::memcpy(f.payload.data() + sizeof(meta), pre,
                static_cast<size_t>(pre_len));
    f.pass_fd = dupfd;
    p.sendq.push_back(std::move(f));
  }
  uint64_t one = 1;
  (void)!::write(c->wake_fd, &one, sizeof(one));
  return 0;
}

// Non-blocking completion probe (MPI_Test). If rank has a completed
// inbound frame, fills `hdr_out` (without consuming the payload) and
// returns 1. If the rank is dead and its queue empty, fills a death
// marker and returns 1 (sticky — a dead rank always polls ready, so no
// wait can hang on it). Otherwise returns 0.
int msgt_coord_poll(void* h, int rank, Header* hdr_out) {
  auto* c = static_cast<Coordinator*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  auto& q = c->completed[rank];
  if (!q.empty()) {
    *hdr_out = q.front().hdr;
    return 1;
  }
  if (c->peers[rank].dead) {
    *hdr_out = Header{0, -1, -1, 0, KIND_DEATH};
    return 1;
  }
  return 0;
}

// Consume the frame previously reported by msgt_coord_poll: copy its
// payload into `buf` (caller sized it from hdr.len) and pop it. Returns
// the payload length, or -1 if nothing was available.
int64_t msgt_coord_take(void* h, int rank, uint8_t* buf, int64_t bufcap) {
  auto* c = static_cast<Coordinator*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  auto& q = c->completed[rank];
  if (q.empty()) {
    // death markers are synthesized, not queued; nothing to pop
    return c->peers[rank].dead ? 0 : -1;
  }
  Frame& f = q.front();
  int64_t n = static_cast<int64_t>(f.payload.size());
  if (n > bufcap) return -1;
  std::memcpy(buf, f.payload.data(), static_cast<size_t>(n));
  q.pop_front();
  return n;
}

// Block until any rank in `ranks` has a completed frame or is dead
// (MPI_Waitany). Returns the ready rank, or -1 on timeout (-1 timeout_ms
// blocks forever).
int msgt_coord_waitany(void* h, const int32_t* ranks, int nranks,
                       int64_t timeout_ms) {
  auto* c = static_cast<Coordinator*>(h);
  std::unique_lock<std::mutex> lk(c->mu);
  auto ready = [&]() -> int {
    for (int i = 0; i < nranks; i++) {
      int r = ranks[i];
      if (!c->completed[r].empty() || c->peers[r].dead) return r;
    }
    return -1;
  };
  if (timeout_ms < 0) {
    int r;
    c->cv.wait(lk, [&] { return (r = ready()) >= 0; });
    return r;
  }
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  int r = -1;
  c->cv.wait_until(lk, deadline, [&] { return (r = ready()) >= 0; });
  return r;
}

// Re-accept a connection for a dead rank (elastic recovery: a respawned
// worker process reconnects and sends a fresh hello carrying the same
// rank). Clears the dead flag and the peer's I/O state, re-registers the
// socket with the progress engine. Frames completed by the previous
// incarnation stay queued (the layer above drops stale seqs). Returns 0
// on success, -1 on timeout / wrong-rank hello / rank not dead.
int msgt_coord_reaccept(void* h, int rank, int64_t timeout_ms) {
  auto* c = static_cast<Coordinator*>(h);
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  if (rank < 0 || rank >= c->n) return -1;
  // Tolerate a rank whose HUP the progress engine hasn't processed yet
  // (the worker process can be observed dead by the OS before the EOF is
  // drained): wait for the dead mark within the same deadline.
  while (true) {
    {
      std::lock_guard<std::mutex> lk(c->mu);
      if (c->peers[rank].dead) break;
    }
    if (std::chrono::steady_clock::now() >= deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  // an authenticated reconnect may already be parked (it arrived while a
  // DIFFERENT rank's reaccept was listening — concurrent restarts)
  int fd = -1;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    if (c->parked[rank] >= 0) {
      fd = c->parked[rank];
      c->parked[rank] = -1;
    }
  }
  if (fd < 0 && accept_hello(c, deadline, rank, &fd) != rank) return -1;
  set_nonblocking(fd);
  {
    std::lock_guard<std::mutex> lk(c->mu);
    Peer& p = c->peers[rank];
    p.fd = fd;
    p.dead = false;
    p.rhdr = Header{};
    p.rgot = 0;
    p.rin_payload = false;
    p.rbuf = {};
    p.rpayload_got = 0;
    p.sendq.clear();
    p.sent = 0;
  }
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLRDHUP;
  ev.data.u32 = static_cast<uint32_t>(rank);
  epoll_ctl(c->epfd, EPOLL_CTL_ADD, fd, &ev);
  return 0;
}

// Copy the first fatal progress-engine error (empty string if none) into
// buf; returns its length.
int msgt_coord_error(void* h, char* buf, int cap) {
  auto* c = static_cast<Coordinator*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  int n = static_cast<int>(c->error.size());
  if (n >= cap) n = cap - 1;
  if (n > 0) std::memcpy(buf, c->error.data(), static_cast<size_t>(n));
  if (cap > 0) buf[n] = '\0';
  return n;
}

// 1 if the rank has been marked dead (EOF/HUP/write error), else 0.
int msgt_coord_is_dead(void* h, int rank) {
  auto* c = static_cast<Coordinator*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  return c->peers[rank].dead ? 1 : 0;
}

// Stop the progress thread, close every socket, remove the socket file.
void msgt_coord_destroy(void* h) {
  auto* c = static_cast<Coordinator*>(h);
  c->stopping.store(true, std::memory_order_release);
  uint64_t one = 1;
  if (c->wake_fd >= 0) (void)!::write(c->wake_fd, &one, sizeof(one));
  if (c->progress.joinable()) c->progress.join();
  delete c;
}

// ------------------------------------------------------------------- worker

// Connect to the coordinator (Unix path or "tcp://host:port"), send the
// hello frame carrying this worker's rank, and answer the coordinator's
// auth challenge with HMAC(token, challenge) when one is issued. Returns
// an opaque handle or nullptr (bad address, connection refused, or the
// coordinator requires a secret this worker doesn't hold).
void* msgt_worker_connect(const char* addr_str, int rank,
                          const uint8_t* token, int token_len) {
  auto* w = new WorkerCtx();
  std::string host;
  int port = 0;
  int ptcp = parse_tcp(addr_str, &host, &port);
  if (ptcp < 0) {
    delete w;
    return nullptr;
  }
  if (ptcp == 1) {
    w->fd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (w->fd < 0) {
      delete w;
      return nullptr;
    }
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_port = htons(static_cast<uint16_t>(port));
    const char* h = (host.empty() || host == "0.0.0.0")
                        ? "127.0.0.1"  // bound-any coordinator, same host
                        : host.c_str();
    if (inet_pton(AF_INET, h, &a.sin_addr) != 1) {
      // not an IPv4 literal: resolve the hostname
      addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      if (getaddrinfo(h, nullptr, &hints, &res) != 0 || res == nullptr) {
        delete w;
        return nullptr;
      }
      a.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    if (::connect(w->fd, reinterpret_cast<sockaddr*>(&a), sizeof(a)) != 0) {
      delete w;
      return nullptr;
    }
    tune_tcp(w->fd);
    tune_bufs(w->fd);
  } else {
    w->fd = ::socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (w->fd < 0) {
      delete w;
      return nullptr;
    }
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    std::strncpy(addr.sun_path, addr_str, sizeof(addr.sun_path) - 1);
    if (::connect(w->fd, reinterpret_cast<sockaddr*>(&addr),
                  sizeof(addr)) != 0) {
      delete w;
      return nullptr;
    }
    tune_bufs(w->fd);
  }
  Header hello{0, rank, 0, 0, KIND_HELLO};
  if (!write_full(w->fd, &hello, sizeof(hello))) {
    delete w;
    return nullptr;
  }
  // the coordinator always acks the hello: len == 0 means open transport,
  // len == kChallengeLen means prove knowledge of the shared secret
  Header ack{};
  if (!read_full(w->fd, &ack, sizeof(ack)) || ack.kind != KIND_HELLO) {
    delete w;
    return nullptr;
  }
  if (token_len > 0 && ack.len == 0) {
    // fail closed: this worker was configured with a secret, so an
    // "open transport" ack means the peer is NOT the coordinator we
    // were told to trust (e.g. a rogue listener that won the bind race
    // against our connect-retry loop). Downgrading would hand it
    // pickled payloads to execute.
    delete w;
    return nullptr;
  }
  if (ack.len > 0) {
    if (ack.len != static_cast<int64_t>(kChallengeLen) || token == nullptr ||
        token_len <= 0) {
      delete w;  // auth demanded but we can't answer
      return nullptr;
    }
    uint8_t challenge[kChallengeLen], mac[kMacLen];
    if (!read_full(w->fd, challenge, sizeof(challenge))) {
      delete w;
      return nullptr;
    }
    const std::string tok(reinterpret_cast<const char*>(token),
                          static_cast<size_t>(token_len));
    hmac_tagged(tok, kTagWorkerProof, challenge, sizeof(challenge), mac);
    // mutual auth (ADVICE r2): attach our own challenge and demand the
    // peer prove knowledge of the token before we unpickle anything it
    // sends — a rogue listener that merely issues a 16-byte challenge
    // must not pass. No strong entropy for W => abort, never degrade.
    uint8_t wchal[kChallengeLen];
    if (!fill_random(wchal, sizeof(wchal))) {
      delete w;
      return nullptr;
    }
    Header resp{kMacLen + kChallengeLen, rank, 0, 0, KIND_HELLO};
    if (!write_full(w->fd, &resp, sizeof(resp)) ||
        !write_full(w->fd, mac, sizeof(mac)) ||
        !write_full(w->fd, wchal, sizeof(wchal))) {
      delete w;
      return nullptr;
    }
    Header ph{};
    uint8_t proof[kMacLen], expect[kMacLen];
    if (!read_full(w->fd, &ph, sizeof(ph)) || ph.kind != KIND_HELLO ||
        ph.len != static_cast<int64_t>(kMacLen) ||
        !read_full(w->fd, proof, sizeof(proof))) {
      delete w;
      return nullptr;
    }
    hmac_tagged(tok, kTagCoordProof, wchal, sizeof(wchal), expect);
    uint8_t diff = 0;  // constant-time compare
    for (size_t i = 0; i < kMacLen; i++) diff |= proof[i] ^ expect[i];
    if (diff != 0) {
      delete w;  // peer holds the socket but not the secret
      return nullptr;
    }
  }
  return w;
}

// Standalone HMAC-SHA256 (exposed for conformance testing against a
// reference implementation; the handshake above depends on it).
void msgt_hmac_sha256(const uint8_t* key, int keylen, const uint8_t* msg,
                      int msglen, uint8_t* out32) {
  hmac_sha256(key, static_cast<size_t>(keylen), msg,
              static_cast<size_t>(msglen), out32);
}

// Blocking read of the next frame header. Returns 0 on success, -1 on
// EOF/error (coordinator gone).
int msgt_worker_recv_hdr(void* h, Header* hdr_out) {
  auto* w = static_cast<WorkerCtx*>(h);
  return worker_read_full(w, hdr_out, sizeof(Header)) ? 0 : -1;
}

// Blocking read of `len` payload bytes following a header.
int msgt_worker_recv_payload(void* h, uint8_t* buf, int64_t len) {
  auto* w = static_cast<WorkerCtx*>(h);
  return worker_read_full(w, buf, static_cast<size_t>(len)) ? 0 : -1;
}

// Pop the next SCM_RIGHTS fd received with a shm frame (-1 if none).
// The Python side owns the mapping and its lifetime (mmap module).
int msgt_worker_take_fd(void* h) {
  auto* w = static_cast<WorkerCtx*>(h);
  if (w->pending_fds.empty()) return -1;
  int fd = w->pending_fds.front();
  w->pending_fds.pop_front();
  return fd;
}

// Blocking send of one frame (header + payload).
int msgt_worker_send(void* h, int64_t seq, int64_t epoch, int64_t tag,
                     int64_t kind, const uint8_t* data, int64_t len) {
  auto* w = static_cast<WorkerCtx*>(h);
  Header hdr{len, seq, epoch, tag, kind};
  if (!write_full(w->fd, &hdr, sizeof(hdr))) return -1;
  if (len > 0 && !write_full(w->fd, data, static_cast<size_t>(len)))
    return -1;
  return 0;
}

// Two-buffer blocking send: header, codec prefix, then the body written
// straight from the caller's buffer (e.g. an ndarray's memory) — the
// worker result path is zero-copy in user space.
int msgt_worker_send2(void* h, int64_t seq, int64_t epoch, int64_t tag,
                      int64_t kind, const uint8_t* pre, int64_t pre_len,
                      const uint8_t* body, int64_t body_len) {
  auto* w = static_cast<WorkerCtx*>(h);
  Header hdr{pre_len + body_len, seq, epoch, tag, kind};
  if (!write_full(w->fd, &hdr, sizeof(hdr))) return -1;
  if (pre_len > 0 && !write_full(w->fd, pre, static_cast<size_t>(pre_len)))
    return -1;
  if (body_len > 0 &&
      !write_full(w->fd, body, static_cast<size_t>(body_len)))
    return -1;
  return 0;
}

// Blocking send of one frame with a file descriptor attached to the
// header's first byte via SCM_RIGHTS (round-12 result rings: the
// worker passes its ring memfd to the coordinator ONCE, on the ring's
// first control frame). The fd is not dup'd — the blocking send
// completes before return and the kernel holds its own reference for
// the in-flight message; the caller keeps its copy. Unix sockets only.
int msgt_worker_send_fd(void* h, int64_t seq, int64_t epoch, int64_t tag,
                        int64_t kind, const uint8_t* data, int64_t len,
                        int fd) {
  auto* w = static_cast<WorkerCtx*>(h);
  Header hdr{len, seq, epoch, tag, kind};
  const uint8_t* hp = reinterpret_cast<const uint8_t*>(&hdr);
  size_t sent = 0;
  bool fd_attached = false;
  while (sent < sizeof(hdr)) {
    ssize_t r;
    if (!fd_attached) {
      msghdr mh{};
      iovec iov{const_cast<uint8_t*>(hp + sent), sizeof(hdr) - sent};
      mh.msg_iov = &iov;
      mh.msg_iovlen = 1;
      alignas(cmsghdr) char cbuf[CMSG_SPACE(sizeof(int))];
      std::memset(cbuf, 0, sizeof(cbuf));
      mh.msg_control = cbuf;
      mh.msg_controllen = sizeof(cbuf);
      cmsghdr* cm = CMSG_FIRSTHDR(&mh);
      cm->cmsg_level = SOL_SOCKET;
      cm->cmsg_type = SCM_RIGHTS;
      cm->cmsg_len = CMSG_LEN(sizeof(int));
      std::memcpy(CMSG_DATA(cm), &fd, sizeof(int));
      r = ::sendmsg(w->fd, &mh, 0);
      if (r > 0) fd_attached = true;
    } else {
      r = ::write(w->fd, hp + sent, sizeof(hdr) - sent);
    }
    if (r < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    sent += static_cast<size_t>(r);
  }
  if (len > 0 && !write_full(w->fd, data, static_cast<size_t>(len)))
    return -1;
  return 0;
}

void msgt_worker_close(void* h) {
  delete static_cast<WorkerCtx*>(h);
}

}  // extern "C"
