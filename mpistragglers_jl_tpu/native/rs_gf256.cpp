// Systematic Cauchy Reed-Solomon erasure codec over GF(2^8).
//
// The float-field MDS code in ops/coding.py is the TPU compute path
// (encode/decode are MXU matmuls) but is only numerically exact; this
// codec is the byte-exact companion for arbitrary host-side payloads —
// checkpoint shards, serialized buffers, control messages — where
// bit-identical recovery is required. The reference has no coding layer
// at all (its payloads are raw bytes over MPI, reference
// src/MPIAsyncPools.jl:82-84); this is north-star capability.
//
// Construction: generator G = [I_k ; P] (n x k) with P the Cauchy matrix
// P[i][j] = 1/(x_i ^ y_j), x_i = k+i, y_j = j over GF(256) with the
// AES-adjacent primitive polynomial 0x11D. Every square submatrix of a
// Cauchy matrix is nonsingular, so [I ; P] is MDS: any k of the n coded
// rows reconstruct the k source rows exactly (the property the pool's
// repochs arrival mask selects shards by).
//
// Build: g++ -O3 -shared -fPIC (driven by native/__init__.py); consumed
// via ctypes from utils/rs_gf256.py. No external dependencies.

#include <cstdint>
#include <cstring>

namespace {

uint8_t GF_EXP[512];
uint8_t GF_LOG[256];
// full 256x256 product table: one L1-resident lookup per byte in the
// row-update inner loop below
uint8_t GF_MUL[256][256];

struct TableInit {
    TableInit() {
        // generator 2 is primitive for 0x11D
        int x = 1;
        for (int i = 0; i < 255; ++i) {
            GF_EXP[i] = static_cast<uint8_t>(x);
            GF_LOG[x] = static_cast<uint8_t>(i);
            x <<= 1;
            if (x & 0x100) x ^= 0x11D;
        }
        for (int i = 255; i < 512; ++i) GF_EXP[i] = GF_EXP[i - 255];
        GF_LOG[0] = 0;  // log(0) undefined; guarded at use sites
        for (int a = 0; a < 256; ++a) {
            GF_MUL[0][a] = 0;
            GF_MUL[a][0] = 0;
        }
        for (int a = 1; a < 256; ++a)
            for (int b = 1; b < 256; ++b)
                GF_MUL[a][b] = GF_EXP[GF_LOG[a] + GF_LOG[b]];
    }
} table_init;

inline uint8_t gf_mul(uint8_t a, uint8_t b) { return GF_MUL[a][b]; }

inline uint8_t gf_inv(uint8_t a) {
    // a != 0 required
    return GF_EXP[255 - GF_LOG[a]];
}

// out[len] ^= c * src[len] — the codec's hot loop. With -O3 the
// per-byte table lookup sustains ~1 GB/s; payloads here are control-
// plane sized (checkpoints, messages), not the TPU data path.
inline void addmul_row(uint8_t* out, const uint8_t* src, uint8_t c,
                       long len) {
    if (c == 0) return;
    const uint8_t* mul = GF_MUL[c];
    if (c == 1) {
        for (long i = 0; i < len; ++i) out[i] ^= src[i];
        return;
    }
    for (long i = 0; i < len; ++i) out[i] ^= mul[src[i]];
}

}  // namespace

extern "C" {

// Fill G (n*k, row-major) with the systematic Cauchy generator.
// Returns 0, or -1 if the construction is out of range (n > 256 or
// k <= 0 or k > n).
int rs_make_generator(int n, int k, uint8_t* G) {
    if (k <= 0 || n < k || n > 256) return -1;
    std::memset(G, 0, static_cast<size_t>(n) * k);
    for (int j = 0; j < k; ++j) G[j * k + j] = 1;  // I_k
    for (int i = 0; i < n - k; ++i) {
        for (int j = 0; j < k; ++j) {
            uint8_t x = static_cast<uint8_t>(k + i);
            uint8_t y = static_cast<uint8_t>(j);
            G[(k + i) * k + j] = gf_inv(x ^ y);  // x != y since x >= k > j
        }
    }
    return 0;
}

// out (rows*len) = M (rows*k) * data (k*len) over GF(256).
int rs_matmul(const uint8_t* M, int rows, int k, const uint8_t* data,
              uint8_t* out, long len) {
    if (rows <= 0 || k <= 0 || len < 0) return -1;
    std::memset(out, 0, static_cast<size_t>(rows) * len);
    for (int i = 0; i < rows; ++i)
        for (int j = 0; j < k; ++j)
            addmul_row(out + static_cast<size_t>(i) * len,
                       data + static_cast<size_t>(j) * len, M[i * k + j],
                       len);
    return 0;
}

// Invert a k x k matrix over GF(256) (Gauss-Jordan with partial pivot
// by nonzero search). Returns 0, or -1 if singular.
int rs_invert(const uint8_t* A, int k, uint8_t* Ainv) {
    if (k <= 0 || k > 256) return -1;
    // augmented [work | inv] on the stack-free heap-lite path: k <= 256
    uint8_t work[256][256];
    for (int i = 0; i < k; ++i) {
        std::memcpy(work[i], A + static_cast<size_t>(i) * k, k);
        std::memset(Ainv + static_cast<size_t>(i) * k, 0, k);
        Ainv[static_cast<size_t>(i) * k + i] = 1;
    }
    for (int col = 0; col < k; ++col) {
        int piv = -1;
        for (int r = col; r < k; ++r)
            if (work[r][col] != 0) { piv = r; break; }
        if (piv < 0) return -1;
        if (piv != col) {
            for (int j = 0; j < k; ++j) {
                uint8_t t = work[col][j];
                work[col][j] = work[piv][j];
                work[piv][j] = t;
                t = Ainv[static_cast<size_t>(col) * k + j];
                Ainv[static_cast<size_t>(col) * k + j] =
                    Ainv[static_cast<size_t>(piv) * k + j];
                Ainv[static_cast<size_t>(piv) * k + j] = t;
            }
        }
        uint8_t inv_p = gf_inv(work[col][col]);
        for (int j = 0; j < k; ++j) {
            work[col][j] = gf_mul(work[col][j], inv_p);
            Ainv[static_cast<size_t>(col) * k + j] =
                gf_mul(Ainv[static_cast<size_t>(col) * k + j], inv_p);
        }
        for (int r = 0; r < k; ++r) {
            if (r == col) continue;
            uint8_t c = work[r][col];
            if (c == 0) continue;
            for (int j = 0; j < k; ++j) {
                work[r][j] = static_cast<uint8_t>(
                    work[r][j] ^ gf_mul(c, work[col][j]));
                Ainv[static_cast<size_t>(r) * k + j] = static_cast<uint8_t>(
                    Ainv[static_cast<size_t>(r) * k + j] ^
                    gf_mul(c, Ainv[static_cast<size_t>(col) * k + j]));
            }
        }
    }
    return 0;
}

// Encode: data (k*len) -> coded (n*len) using generator G (n*k).
int rs_encode(int n, int k, const uint8_t* G, const uint8_t* data,
              uint8_t* coded, long len) {
    return rs_matmul(G, n, k, data, coded, len);
}

// Decode: shards (k*len) carrying coded rows indices[0..k-1] -> source
// (k*len). Returns 0; -1 on bad args; -2 if the index set is not
// invertible (cannot happen for distinct indices of an MDS generator,
// but guarded for caller-supplied G).
int rs_decode(int n, int k, const uint8_t* G, const int32_t* indices,
              const uint8_t* shards, uint8_t* out, long len) {
    if (k <= 0 || k > 256 || n < k || n > 256 || len < 0) return -1;
    uint8_t sub[256 * 256];
    for (int i = 0; i < k; ++i) {
        int32_t idx = indices[i];
        if (idx < 0 || idx >= n) return -1;
        std::memcpy(sub + static_cast<size_t>(i) * k,
                    G + static_cast<size_t>(idx) * k, k);
    }
    uint8_t inv[256 * 256];
    if (rs_invert(sub, k, inv) != 0) return -2;
    return rs_matmul(inv, k, k, shards, out, len);
}

}  // extern "C"
