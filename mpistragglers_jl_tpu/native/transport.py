"""ctypes bindings for the native message transport (transport.cpp).

The C++ library supplies the MPI-shaped primitives (isend / test / waitany
/ dead-rank detection over Unix-domain sockets with an epoll progress
thread — the reference's libmpi role, SURVEY component C8); this module
wraps them in two small classes:

* :class:`Coordinator` — rank-indexed non-blocking sends, completion
  polls, waitany, payload harvest.
* :class:`Worker` — blocking receive/send loop primitives for worker
  processes.

Payloads are opaque bytes at this layer; the backend above
(:mod:`..backends.native`) owns serialization. No fallback exists here on
purpose — consumers (the backend) catch :class:`NativeBuildError` and use
the pure-Python :class:`~..backends.process.ProcessBackend` instead.
"""

from __future__ import annotations

import ctypes
import mmap as _mmap
import os as _os
import struct as _struct
from dataclasses import dataclass

import numpy as np

KIND_DATA = 0
KIND_CONTROL = 1
KIND_HELLO = 2
KIND_DEATH = 3
KIND_ERROR = 4
KIND_SHM = 5  # transport-internal: body rides shared memory, not the wire


class _Header(ctypes.Structure):
    _fields_ = [
        ("len", ctypes.c_int64),
        ("seq", ctypes.c_int64),
        ("epoch", ctypes.c_int64),
        ("tag", ctypes.c_int64),
        ("kind", ctypes.c_int64),
    ]


@dataclass(frozen=True)
class Message:
    """One received frame: bookkeeping header + raw payload bytes.

    ``payload`` is a ``bytearray`` (or ``bytes``): the receive path
    copies the frame exactly once, socket -> this buffer, and decoders
    (``np.frombuffer``, ``pickle.loads``) consume it without further
    copies."""

    seq: int
    epoch: int
    tag: int
    kind: int
    payload: "bytes | bytearray"
    # out-of-band body (shared-memory broadcasts): the codec prefix is in
    # ``payload`` and the bytes live in a mapped region. Holding the
    # view PINS the region: keep-window eviction defers until the view
    # is released (mmap.close() raises BufferError while buffers are
    # exported — Worker._evict_shm catches it and retries on a later
    # resolve), so the view never dangles; it just keeps the mapping
    # resident. Release or copy when done to let the window shrink.
    body: "memoryview | None" = None


def _addr_len(buf) -> tuple[int, int, object]:
    """(address, nbytes, keepalive) of any contiguous readable buffer.

    ``keepalive`` is whatever object OWNS the memory behind ``address``
    (a temporary copy for non-contiguous/readonly inputs) — the caller
    must hold it until the native call returns, or the address dangles.
    """
    if isinstance(buf, np.ndarray):
        if not buf.flags.c_contiguous:
            buf = np.ascontiguousarray(buf)
        return buf.ctypes.data, buf.nbytes, buf
    if isinstance(buf, bytes):
        return ctypes.cast(buf, ctypes.c_void_p).value or 0, len(buf), buf
    if isinstance(buf, bytearray):
        if not buf:
            return 0, 0, buf
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        return addr, len(buf), buf
    mv = memoryview(buf)
    if not mv.c_contiguous:
        mv = memoryview(bytes(mv))
    if mv.nbytes == 0:
        return 0, 0, mv
    if mv.readonly:
        b = bytes(mv)
        return ctypes.cast(b, ctypes.c_void_p).value or 0, len(b), b
    export = (ctypes.c_char * mv.nbytes).from_buffer(mv)
    return ctypes.addressof(export), mv.nbytes, export


def _configure(lib):
    lib.msgt_coord_create.restype = ctypes.c_void_p
    lib.msgt_coord_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
    ]
    lib.msgt_coord_port.restype = ctypes.c_int
    lib.msgt_coord_port.argtypes = [ctypes.c_void_p]
    lib.msgt_coord_accept.restype = ctypes.c_int
    lib.msgt_coord_accept.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.msgt_coord_isend.restype = ctypes.c_int
    lib.msgt_coord_isend.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.msgt_coord_poll.restype = ctypes.c_int
    lib.msgt_coord_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(_Header)
    ]
    lib.msgt_coord_take.restype = ctypes.c_int64
    lib.msgt_coord_take.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.msgt_coord_waitany.restype = ctypes.c_int
    lib.msgt_coord_waitany.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.c_int64,
    ]
    lib.msgt_coord_is_dead.restype = ctypes.c_int
    lib.msgt_coord_is_dead.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.msgt_coord_reaccept.restype = ctypes.c_int
    lib.msgt_coord_reaccept.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64
    ]
    lib.msgt_coord_error.restype = ctypes.c_int
    lib.msgt_coord_error.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
    ]
    lib.msgt_coord_destroy.restype = None
    lib.msgt_coord_destroy.argtypes = [ctypes.c_void_p]
    lib.msgt_worker_connect.restype = ctypes.c_void_p
    lib.msgt_worker_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
    ]
    lib.msgt_hmac_sha256.restype = None
    lib.msgt_hmac_sha256.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.msgt_worker_recv_hdr.restype = ctypes.c_int
    lib.msgt_worker_recv_hdr.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_Header)
    ]
    lib.msgt_worker_recv_payload.restype = ctypes.c_int
    lib.msgt_worker_recv_payload.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64
    ]
    lib.msgt_worker_send.restype = ctypes.c_int
    lib.msgt_worker_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    # zero-copy path: two-buffer sends + shared broadcast payloads. The
    # buffer args are c_void_p (NOT c_char_p) so writable buffers and
    # raw ndarray memory pass without a bytes conversion copy.
    lib.msgt_coord_isend2.restype = ctypes.c_int
    lib.msgt_coord_isend2.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.msgt_payload_create.restype = ctypes.c_void_p
    lib.msgt_payload_create.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.msgt_payload_release.restype = None
    lib.msgt_payload_release.argtypes = [ctypes.c_void_p]
    lib.msgt_coord_isend_shared.restype = ctypes.c_int
    lib.msgt_coord_isend_shared.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p,
    ]
    lib.msgt_worker_send2.restype = ctypes.c_int
    lib.msgt_worker_send2.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.msgt_worker_close.restype = None
    lib.msgt_worker_close.argtypes = [ctypes.c_void_p]
    # shared-memory broadcast payloads (same-host zero-copy)
    lib.msgt_payload_create_shm.restype = ctypes.c_void_p
    lib.msgt_payload_create_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_int64
    ]
    lib.msgt_payload_release_shm.restype = None
    lib.msgt_payload_release_shm.argtypes = [ctypes.c_void_p]
    lib.msgt_coord_isend_shm.restype = ctypes.c_int
    lib.msgt_coord_isend_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.msgt_worker_take_fd.restype = ctypes.c_int
    lib.msgt_worker_take_fd.argtypes = [ctypes.c_void_p]


def load_lib():
    """Compile (if stale) and load the transport library; success and
    failure both memoized process-wide by :func:`..native.load`."""
    from . import load

    return load("transport", _configure)


class TransportError(RuntimeError):
    pass


def hmac_sha256(key: bytes, msg: bytes) -> bytes:
    """The native HMAC-SHA256 the hello handshake authenticates with,
    exposed so tests can check conformance against :mod:`hmac`."""
    lib = load_lib()
    out = (ctypes.c_uint8 * 32)()
    lib.msgt_hmac_sha256(key, len(key), msg, len(msg), out)
    return bytes(out)


class Coordinator:
    """Coordinator endpoint: owns the listening socket and the native
    progress thread; one connection per worker rank."""

    def __init__(self, path: str, n_workers: int, *, token: bytes = b""):
        """``path`` is a Unix-socket filesystem path (single host) or
        ``tcp://host:port`` (multi-host; port 0 binds an ephemeral port,
        see :attr:`port`). A non-empty ``token`` turns on hello
        authentication: every worker must present the same secret
        (proved by HMAC-SHA256 challenge-response; the secret never
        crosses the wire) before its rank is admitted. An empty token
        admits any connector — acceptable only on trusted networks."""
        self._lib = load_lib()
        self.n_workers = int(n_workers)
        self.path = path
        self.token = bytes(token)
        self._h = self._lib.msgt_coord_create(
            path.encode(), self.n_workers, self.token, len(self.token)
        )
        if not self._h:
            raise TransportError(f"could not bind coordinator socket {path}")
        self.port = int(self._lib.msgt_coord_port(self._h))

    @property
    def address(self) -> str:
        """The address workers should connect to (ephemeral TCP ports
        resolved to the actual bound port)."""
        if self.path.startswith("tcp://"):
            host = self.path[6:].rsplit(":", 1)[0]
            return f"tcp://{host}:{self.port}"
        return self.path

    def _handle(self):
        # a NULL handle into the C ABI would segfault, not raise
        if not self._h:
            raise TransportError("coordinator is closed")
        return self._h

    def accept(self, timeout: float = 30.0) -> None:
        """Wait for all workers to connect and complete the hello
        handshake, then start the progress engine. ``timeout`` bounds
        the whole handshake, stalled hellos included."""
        rc = self._lib.msgt_coord_accept(self._handle(), int(timeout * 1000))
        if rc != 0:
            raise TransportError(
                f"workers failed to connect within {timeout}s"
            )

    def isend(
        self, rank: int, payload: bytes, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        """Non-blocking send; payload is snapshotted into the native send
        queue. Returns False if the rank is dead."""
        if not isinstance(payload, bytes):
            payload = bytes(payload)  # c_char_p wants immutable bytes
        rc = self._lib.msgt_coord_isend(
            self._handle(), int(rank), seq, epoch, tag, kind, payload,
            len(payload),
        )
        return rc == 0

    def isend2(
        self, rank: int, prefix: bytes, body, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        """Two-buffer non-blocking send: ``prefix`` (small codec header)
        and ``body`` (any contiguous buffer — ndarray memory passes
        directly) are snapshotted as separate segments; the wire frame
        is header+prefix+body with no Python-side concatenation."""
        paddr, plen, pkeep = _addr_len(prefix)
        baddr, blen, bkeep = _addr_len(body)
        rc = self._lib.msgt_coord_isend2(
            self._handle(), int(rank), seq, epoch, tag, kind,
            paddr, plen, baddr, blen,
        )
        del pkeep, bkeep  # held across the (synchronously copying) call
        return rc == 0

    def payload(self, body) -> "SharedPayload | ShmPayload":
        """Snapshot ``body`` ONCE for a broadcast; pass to
        :meth:`isend_shared` for each rank (the pool's per-epoch
        pattern). On same-host (Unix-socket) transports the snapshot is
        a shared-memory region: workers map the SAME pages, so the
        body's bytes never cross the sockets at all — one memcpy per
        broadcast, total. TCP transports snapshot into a native buffer
        shared across the n send queues (one memcpy instead of n)."""
        _, n, _keep = _addr_len(body)
        # shm pays a fixed per-epoch setup (memfd + 2 mmaps + fd pass);
        # it wins when the broadcast is wide and the body large, loses
        # for single workers / small frames where socket copies are cheap
        if (
            not self.path.startswith("tcp://")
            and self.n_workers >= 2
            and n >= (1 << 20)
        ):
            shm = ShmPayload(self._lib, body)
            if shm._h is not None:  # memfd unavailable -> socket path
                return shm
        return SharedPayload(self._lib, body)

    def isend_shared(
        self, rank: int, prefix: bytes, payload, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        if payload._h is None:
            raise TransportError("shared payload already released")
        paddr, plen, pkeep = _addr_len(prefix)
        if isinstance(payload, ShmPayload):
            if kind != KIND_DATA:
                raise ValueError("shm payloads carry data frames only")
            rc = self._lib.msgt_coord_isend_shm(
                self._handle(), int(rank), seq, epoch, tag,
                paddr, plen, payload._h,
            )
            return rc == 0
        rc = self._lib.msgt_coord_isend_shared(
            self._handle(), int(rank), seq, epoch, tag, kind,
            paddr, plen, payload._h,
        )
        return rc == 0

    def poll(self, rank: int) -> Message | None:
        """Non-blocking probe-and-take (``MPI.Test!``): returns the next
        completed message for ``rank`` (a ``KIND_DEATH`` message if the
        rank died), or None."""
        hdr = _Header()
        if not self._lib.msgt_coord_poll(
            self._handle(), int(rank), ctypes.byref(hdr)
        ):
            return None
        return self._take(rank, hdr)

    def _take(self, rank: int, hdr: _Header) -> Message:
        n = int(hdr.len)
        buf = bytearray(n)
        cbuf = (
            (ctypes.c_uint8 * n).from_buffer(buf) if n
            else (ctypes.c_uint8 * 1)()
        )
        got = self._lib.msgt_coord_take(self._handle(), int(rank), cbuf, n)
        del cbuf  # release the buffer export
        if got < 0:
            raise TransportError(f"take({rank}) raced: nothing available")
        return Message(
            seq=int(hdr.seq), epoch=int(hdr.epoch), tag=int(hdr.tag),
            kind=int(hdr.kind),
            payload=buf if got == n else bytes(buf[:got]),
        )

    def waitany(
        self, ranks, timeout: float | None = None
    ) -> tuple[int, Message] | None:
        """Block until any rank in ``ranks`` has a message (or died);
        returns ``(rank, message)``, or None on timeout
        (``MPI.Waitany!``)."""
        arr = (ctypes.c_int32 * len(ranks))(*[int(r) for r in ranks])
        t = -1 if timeout is None else max(int(timeout * 1000), 0)
        rank = self._lib.msgt_coord_waitany(self._handle(), arr, len(ranks), t)
        if rank < 0:
            return None
        msg = self.poll(rank)
        if msg is None:  # pragma: no cover - single-consumer coordinator
            raise TransportError(f"waitany({rank}) raced with another take")
        return rank, msg

    def is_dead(self, rank: int) -> bool:
        return bool(self._lib.msgt_coord_is_dead(self._handle(), int(rank)))

    def reaccept(self, rank: int, timeout: float = 30.0) -> None:
        """Accept a reconnect for a dead rank (elastic recovery): a
        respawned worker sends a fresh hello with the same rank and
        the progress engine picks its socket back up."""
        rc = self._lib.msgt_coord_reaccept(
            self._handle(), int(rank), int(timeout * 1000)
        )
        if rc != 0:
            raise TransportError(
                f"rank {rank} did not reconnect within {timeout}s "
                "(or was not dead)"
            )

    def error(self) -> str:
        """First fatal progress-engine error, or ''. When non-empty,
        every rank has been marked dead with this as the cause."""
        buf = ctypes.create_string_buffer(1024)
        n = self._lib.msgt_coord_error(self._handle(), buf, len(buf))
        return buf.raw[:n].decode(errors='replace')

    def close(self) -> None:
        if self._h:
            self._lib.msgt_coord_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.close()
        except Exception:
            pass


class Worker:
    """Worker endpoint: blocking framed recv/send on one socket.

    The constructor is a round trip: it sends the hello and then blocks
    until the coordinator's ``accept``/``reaccept`` admits the rank
    (answering the auth challenge when one is issued). Construct it on
    a thread/process other than the one that will call ``accept`` —
    which is how workers run anyway (worker.py)."""

    def __init__(self, path: str, rank: int, *, token: bytes = b""):
        self._lib = load_lib()
        self.rank = int(rank)
        token = bytes(token)
        self._h = self._lib.msgt_worker_connect(
            path.encode(), self.rank, token, len(token)
        )
        if not self._h:
            raise TransportError(
                f"worker {rank} could not connect to {path} (refused, "
                "or the coordinator rejected the auth token)"
            )
        # shm broadcast regions, id -> mmap, insertion-ordered. Owned
        # HERE (not in C++) so eviction can be REFUSED while numpy views
        # of a region are still alive: mmap.close() raises BufferError
        # when buffers are exported, which downgrades "use-after-unmap
        # segfault" to "old region stays mapped a little longer".
        self._shm_regions: dict[int, _mmap.mmap] = {}
        self._shm_keep = 4

    def _shm_view(self, sid: int, blen: int) -> "memoryview | None":
        """Resolve a shm region id to a read-only view, adopting the fd
        that rode in with the frame (SCM_RIGHTS) on first sight."""
        region = self._shm_regions.get(sid)
        if region is not None:
            fd = self._lib.msgt_worker_take_fd(self._h)
            if fd >= 0:
                _os.close(fd)  # duplicate announce of a known region
        else:
            fd = self._lib.msgt_worker_take_fd(self._h)
            if fd < 0:
                return None
            try:
                region = _mmap.mmap(
                    fd, blen, _mmap.MAP_SHARED, _mmap.PROT_READ
                )
            except (OSError, ValueError):
                return None
            finally:
                _os.close(fd)  # mmap holds its own reference
            self._shm_regions[sid] = region
            self._evict_shm()
        return memoryview(region)[:blen]

    def _evict_shm(self) -> None:
        """Bound the region dict to the keep window, oldest first. A
        region whose views are still referenced raises ``BufferError``
        from ``mmap.close()`` and is RETAINED — payload views can never
        dangle; eviction of a pinned region simply defers to a later
        resolve (every new region triggers another sweep, so the dict
        shrinks back to the window as soon as the views are released).
        Pinned regions do not shield newer closable ones: the sweep
        walks every over-window candidate, not just the first."""
        excess = len(self._shm_regions) - self._shm_keep
        if excess <= 0:
            return
        # the newest `keep` regions stay regardless; everything older
        # is a candidate, evicted unless a live view pins it
        for old_sid in list(self._shm_regions)[:excess]:
            old = self._shm_regions[old_sid]
            try:
                old.close()
            except BufferError:
                continue  # views alive; keep the mapping, retry later
            del self._shm_regions[old_sid]

    def recv(self) -> Message | None:
        """Block for the next frame; None means the coordinator is gone."""
        hdr = _Header()
        if self._lib.msgt_worker_recv_hdr(self._h, ctypes.byref(hdr)) != 0:
            return None
        n = int(hdr.len)
        buf = bytearray(n)
        if n > 0:
            cbuf = (ctypes.c_uint8 * n).from_buffer(buf)
            ok = self._lib.msgt_worker_recv_payload(self._h, cbuf, n)
            del cbuf
            if ok != 0:
                return None
        if int(hdr.kind) == KIND_SHM:
            # wire payload = [shm_id, body_len, codec prefix...]; the
            # body lives in a mapped region — zero bytes on the wire
            sid, blen = _struct.unpack_from("<qq", buf, 0)
            view = self._shm_view(sid, blen)
            if view is None:
                return None  # region lost; coordinator sees the death
            return Message(
                seq=int(hdr.seq), epoch=int(hdr.epoch),
                tag=int(hdr.tag), kind=KIND_DATA,
                payload=bytes(memoryview(buf)[16:]), body=view,
            )
        return Message(
            seq=int(hdr.seq), epoch=int(hdr.epoch), tag=int(hdr.tag),
            kind=int(hdr.kind), payload=buf,
        )

    def send(
        self, payload: bytes, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        if not isinstance(payload, bytes):
            payload = bytes(payload)  # c_char_p wants immutable bytes
        rc = self._lib.msgt_worker_send(
            self._h, seq, epoch, tag, kind, payload, len(payload)
        )
        return rc == 0

    def send2(
        self, prefix: bytes, body, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        """Two-buffer blocking send; ``body`` is written straight from
        the caller's buffer (zero-copy in user space for ndarrays)."""
        paddr, plen, pkeep = _addr_len(prefix)
        baddr, blen, bkeep = _addr_len(body)
        rc = self._lib.msgt_worker_send2(
            self._h, seq, epoch, tag, kind, paddr, plen, baddr, blen
        )
        del pkeep, bkeep  # held until the blocking write finished
        return rc == 0

    def close(self) -> None:
        if self._h:
            self._lib.msgt_worker_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.close()
        except Exception:
            pass


class ShmPayload:
    """A broadcast payload in a memfd region: every worker maps the same
    physical pages, so broadcasting n ways moves the bytes zero times
    over the sockets. ``_h`` is None when memfd creation failed (caller
    falls back to :class:`SharedPayload`)."""

    __slots__ = ("_lib", "_h", "nbytes")

    def __init__(self, lib, body):
        addr, n, keep = _addr_len(body)
        self._lib = lib
        self.nbytes = n
        self._h = lib.msgt_payload_create_shm(addr, n)
        del keep  # create copies synchronously

    def release(self) -> None:
        if self._h is not None:
            self._lib.msgt_payload_release_shm(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.release()
        except Exception:
            pass


class SharedPayload:
    """A broadcast payload snapshotted once in native memory; frames
    enqueue shared references instead of copies. Frames still in a send
    queue keep the bytes alive after :meth:`release`."""

    __slots__ = ("_lib", "_h", "nbytes")

    def __init__(self, lib, body):
        addr, n, keep = _addr_len(body)
        self._lib = lib
        self.nbytes = n
        self._h = lib.msgt_payload_create(addr, n)
        del keep  # create copies synchronously

    def release(self) -> None:
        if self._h is not None:
            self._lib.msgt_payload_release(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.release()
        except Exception:
            pass
