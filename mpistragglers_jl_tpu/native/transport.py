"""ctypes bindings for the native message transport (transport.cpp).

The C++ library supplies the MPI-shaped primitives (isend / test / waitany
/ dead-rank detection over Unix-domain sockets with an epoll progress
thread — the reference's libmpi role, SURVEY component C8); this module
wraps them in two small classes:

* :class:`Coordinator` — rank-indexed non-blocking sends, completion
  polls, waitany, payload harvest.
* :class:`Worker` — blocking receive/send loop primitives for worker
  processes.

Payloads are opaque bytes at this layer; the backend above
(:mod:`..backends.native`) owns serialization. No fallback exists here on
purpose — consumers (the backend) catch :class:`NativeBuildError` and use
the pure-Python :class:`~..backends.process.ProcessBackend` instead.
"""

from __future__ import annotations

import ctypes
import itertools as _itertools
import mmap as _mmap
import os as _os
import struct as _struct
import threading as _threading
import time as _time
from dataclasses import dataclass

import numpy as np

from . import rings as _rings

KIND_DATA = 0
KIND_CONTROL = 1
KIND_HELLO = 2
KIND_DEATH = 3
KIND_ERROR = 4
KIND_SHM = 5  # transport-internal: body rides shared memory, not the wire
# Round-12 persistent zero-copy paths (transport-internal kinds; both
# resolve to KIND_DATA messages with out-of-band bodies):
KIND_ARENA = 6  # body in the coordinator's persistent broadcast arena
KIND_RING = 7   # body in the sending worker's persistent result ring
KIND_ACK = 8    # slot-release acknowledgements (either direction)

# Bodies below these ride the legacy copying paths (tiny frames are
# cheaper through the socket than a shm slot + control frame + ack).
ARENA_MIN = 1 << 20
RING_MIN = 1 << 16
ARENA_SLOTS = 4  # double-buffering generalized: in-flight + harvest +
RING_SLOTS = 4   # one retained view + one spare before fallback

# Control-frame headers for the persistent paths (little-endian):
# (object id, region capacity, slot count, slot, generation, body len)
_RING_HDR = _struct.Struct("<6q")
# One ack record: (object id, slot, generation). id == -1 is a
# worker->coordinator ring-full stall report (count rides in `slot`).
_ACK_REC = _struct.Struct("<3q")


class _Header(ctypes.Structure):
    _fields_ = [
        ("len", ctypes.c_int64),
        ("seq", ctypes.c_int64),
        ("epoch", ctypes.c_int64),
        ("tag", ctypes.c_int64),
        ("kind", ctypes.c_int64),
    ]


@dataclass(frozen=True)
class Message:
    """One received frame: bookkeeping header + raw payload bytes.

    ``payload`` is a ``bytearray`` (or ``bytes``): the receive path
    copies the frame exactly once, socket -> this buffer, and decoders
    (``np.frombuffer``, ``pickle.loads``) consume it without further
    copies."""

    seq: int
    epoch: int
    tag: int
    kind: int
    payload: "bytes | bytearray"
    # out-of-band body (shared-memory broadcasts, arena frames, result
    # rings): the codec prefix is in ``payload`` and the bytes live in
    # a mapped region. Holding the view PINS its backing: keep-window
    # eviction of one-shot shm regions defers until the view is
    # released (mmap.close() raises BufferError while buffers are
    # exported), and a persistent arena/ring SLOT is not reused until
    # the release ack fires (weakref finalizer on the served view) —
    # the view never dangles and never tears; it just keeps memory
    # resident. Release or copy when done.
    body: "memoryview | np.ndarray | None" = None


def _addr_len(buf) -> tuple[int, int, object]:
    """(address, nbytes, keepalive) of any contiguous readable buffer.

    ``keepalive`` is whatever object OWNS the memory behind ``address``
    (a temporary copy for non-contiguous/readonly inputs) — the caller
    must hold it until the native call returns, or the address dangles.
    """
    if isinstance(buf, np.ndarray):
        if not buf.flags.c_contiguous:
            buf = np.ascontiguousarray(buf)
        return buf.ctypes.data, buf.nbytes, buf
    if isinstance(buf, bytes):
        return ctypes.cast(buf, ctypes.c_void_p).value or 0, len(buf), buf
    if isinstance(buf, bytearray):
        if not buf:
            return 0, 0, buf
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        return addr, len(buf), buf
    mv = memoryview(buf)
    if not mv.c_contiguous:
        mv = memoryview(bytes(mv))
    if mv.nbytes == 0:
        return 0, 0, mv
    if mv.readonly:
        b = bytes(mv)
        return ctypes.cast(b, ctypes.c_void_p).value or 0, len(b), b
    export = (ctypes.c_char * mv.nbytes).from_buffer(mv)
    return ctypes.addressof(export), mv.nbytes, export


def _configure(lib):
    lib.msgt_coord_create.restype = ctypes.c_void_p
    lib.msgt_coord_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
    ]
    lib.msgt_coord_port.restype = ctypes.c_int
    lib.msgt_coord_port.argtypes = [ctypes.c_void_p]
    lib.msgt_coord_accept.restype = ctypes.c_int
    lib.msgt_coord_accept.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.msgt_coord_isend.restype = ctypes.c_int
    lib.msgt_coord_isend.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.msgt_coord_poll.restype = ctypes.c_int
    lib.msgt_coord_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(_Header)
    ]
    lib.msgt_coord_take.restype = ctypes.c_int64
    lib.msgt_coord_take.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.msgt_coord_waitany.restype = ctypes.c_int
    lib.msgt_coord_waitany.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.c_int64,
    ]
    lib.msgt_coord_is_dead.restype = ctypes.c_int
    lib.msgt_coord_is_dead.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.msgt_coord_reaccept.restype = ctypes.c_int
    lib.msgt_coord_reaccept.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64
    ]
    lib.msgt_coord_error.restype = ctypes.c_int
    lib.msgt_coord_error.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
    ]
    lib.msgt_coord_destroy.restype = None
    lib.msgt_coord_destroy.argtypes = [ctypes.c_void_p]
    lib.msgt_worker_connect.restype = ctypes.c_void_p
    lib.msgt_worker_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
    ]
    lib.msgt_hmac_sha256.restype = None
    lib.msgt_hmac_sha256.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.msgt_worker_recv_hdr.restype = ctypes.c_int
    lib.msgt_worker_recv_hdr.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_Header)
    ]
    lib.msgt_worker_recv_payload.restype = ctypes.c_int
    lib.msgt_worker_recv_payload.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64
    ]
    lib.msgt_worker_send.restype = ctypes.c_int
    lib.msgt_worker_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    # zero-copy path: two-buffer sends + shared broadcast payloads. The
    # buffer args are c_void_p (NOT c_char_p) so writable buffers and
    # raw ndarray memory pass without a bytes conversion copy.
    lib.msgt_coord_isend2.restype = ctypes.c_int
    lib.msgt_coord_isend2.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.msgt_payload_create.restype = ctypes.c_void_p
    lib.msgt_payload_create.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.msgt_payload_release.restype = None
    lib.msgt_payload_release.argtypes = [ctypes.c_void_p]
    lib.msgt_coord_isend_shared.restype = ctypes.c_int
    lib.msgt_coord_isend_shared.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p,
    ]
    lib.msgt_worker_send2.restype = ctypes.c_int
    lib.msgt_worker_send2.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.msgt_worker_close.restype = None
    lib.msgt_worker_close.argtypes = [ctypes.c_void_p]
    # shared-memory broadcast payloads (same-host zero-copy)
    lib.msgt_payload_create_shm.restype = ctypes.c_void_p
    lib.msgt_payload_create_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_int64
    ]
    lib.msgt_payload_release_shm.restype = None
    lib.msgt_payload_release_shm.argtypes = [ctypes.c_void_p]
    lib.msgt_coord_isend_shm.restype = ctypes.c_int
    lib.msgt_coord_isend_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.msgt_worker_take_fd.restype = ctypes.c_int
    lib.msgt_worker_take_fd.argtypes = [ctypes.c_void_p]
    # persistent zero-copy paths (round 12): fd-carrying sends + the
    # coordinator-side fd queue for worker result rings
    lib.msgt_coord_isend_fd.restype = ctypes.c_int
    lib.msgt_coord_isend_fd.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int,
    ]
    lib.msgt_coord_take_fd.restype = ctypes.c_int
    lib.msgt_coord_take_fd.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.msgt_worker_send_fd.restype = ctypes.c_int
    lib.msgt_worker_send_fd.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
    ]


def load_lib():
    """Compile (if stale) and load the transport library; success and
    failure both memoized process-wide by :func:`..native.load`."""
    from . import load

    return load("transport", _configure)


class TransportError(RuntimeError):
    pass


def hmac_sha256(key: bytes, msg: bytes) -> bytes:
    """The native HMAC-SHA256 the hello handshake authenticates with,
    exposed so tests can check conformance against :mod:`hmac`."""
    lib = load_lib()
    out = (ctypes.c_uint8 * 32)()
    lib.msgt_hmac_sha256(key, len(key), msg, len(msg), out)
    return bytes(out)


class Coordinator:
    """Coordinator endpoint: owns the listening socket and the native
    progress thread; one connection per worker rank."""

    def __init__(
        self, path: str, n_workers: int, *, token: bytes = b"",
        zero_copy: bool = True,
    ):
        """``path`` is a Unix-socket filesystem path (single host) or
        ``tcp://host:port`` (multi-host; port 0 binds an ephemeral port,
        see :attr:`port`). A non-empty ``token`` turns on hello
        authentication: every worker must present the same secret
        (proved by HMAC-SHA256 challenge-response; the secret never
        crosses the wire) before its rank is admitted. An empty token
        admits any connector — acceptable only on trusted networks.

        ``zero_copy=False`` disables every shared-memory path (the
        persistent broadcast arena, worker result rings, AND the legacy
        per-epoch shm payloads) — the copying socket transport only,
        for baselines and debugging. Shared memory is same-host only;
        TCP transports are copying regardless."""
        self._lib = load_lib()
        self.n_workers = int(n_workers)
        self.path = path
        self.token = bytes(token)
        self._h = self._lib.msgt_coord_create(
            path.encode(), self.n_workers, self.token, len(self.token)
        )
        if not self._h:
            raise TransportError(f"could not bind coordinator socket {path}")
        self.port = int(self._lib.msgt_coord_port(self._h))
        self.zero_copy = bool(zero_copy) and not path.startswith("tcp://")
        # persistent zero-copy state. RLock, not Lock: slot releases
        # fire from weakref finalizers, which can run via GC on a
        # thread that already holds the lock.
        self._zlock = _threading.RLock()
        self._arena: "_BroadcastArena | None" = None       # current
        self._arenas: dict[int, _BroadcastArena] = {}      # id -> live
        self._arena_ids = _itertools.count(1)
        self._arena_fd_sent: set[tuple[int, int]] = set()  # (rank, id)
        self._rings: dict[tuple[int, int], _mmap.mmap] = {}
        self._ring_orphans: list[_mmap.mmap] = []
        # transport-level telemetry, sampled by the backend's opt-in
        # registry wiring (backends/native.py): bytes served without a
        # userspace copy, allocation stalls (every slot pinned), and
        # the pinned-slot gauge/high-water for harvested ring views
        self.stats = {
            "arena_bytes": 0, "ring_bytes": 0, "arena_stalls": 0,
            "ring_stalls": 0, "ring_pinned": 0, "pinned_peak": 0,
        }

    @property
    def address(self) -> str:
        """The address workers should connect to (ephemeral TCP ports
        resolved to the actual bound port)."""
        if self.path.startswith("tcp://"):
            host = self.path[6:].rsplit(":", 1)[0]
            return f"tcp://{host}:{self.port}"
        return self.path

    def _handle(self):
        # a NULL handle into the C ABI would segfault, not raise
        if not self._h:
            raise TransportError("coordinator is closed")
        return self._h

    def accept(self, timeout: float = 30.0) -> None:
        """Wait for all workers to connect and complete the hello
        handshake, then start the progress engine. ``timeout`` bounds
        the whole handshake, stalled hellos included."""
        rc = self._lib.msgt_coord_accept(self._handle(), int(timeout * 1000))
        if rc != 0:
            raise TransportError(
                f"workers failed to connect within {timeout}s"
            )

    def isend(
        self, rank: int, payload: bytes, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        """Non-blocking send; payload is snapshotted into the native send
        queue. Returns False if the rank is dead."""
        if not isinstance(payload, bytes):
            payload = bytes(payload)  # c_char_p wants immutable bytes
        rc = self._lib.msgt_coord_isend(
            self._handle(), int(rank), seq, epoch, tag, kind, payload,
            len(payload),
        )
        return rc == 0

    def isend2(
        self, rank: int, prefix: bytes, body, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        """Two-buffer non-blocking send: ``prefix`` (small codec header)
        and ``body`` (any contiguous buffer — ndarray memory passes
        directly) are snapshotted as separate segments; the wire frame
        is header+prefix+body with no Python-side concatenation."""
        paddr, plen, pkeep = _addr_len(prefix)
        baddr, blen, bkeep = _addr_len(body)
        rc = self._lib.msgt_coord_isend2(
            self._handle(), int(rank), seq, epoch, tag, kind,
            paddr, plen, baddr, blen,
        )
        del pkeep, bkeep  # held across the (synchronously copying) call
        return rc == 0

    def payload(self, body) -> "SharedPayload | ShmPayload":
        """Snapshot ``body`` ONCE for a broadcast; pass to
        :meth:`isend_shared` for each rank (the pool's per-epoch
        pattern). On same-host (Unix-socket) transports the snapshot is
        a shared-memory region: workers map the SAME pages, so the
        body's bytes never cross the sockets at all — one memcpy per
        broadcast, total. TCP transports snapshot into a native buffer
        shared across the n send queues (one memcpy instead of n)."""
        _, n, _keep = _addr_len(body)
        # shm pays a fixed per-epoch setup (memfd + 2 mmaps + fd pass);
        # it wins when the broadcast is wide and the body large, loses
        # for single workers / small frames where socket copies are cheap.
        # (The PERSISTENT broadcast arena — arena_payload — removes that
        # per-epoch setup entirely; this one-shot path remains the
        # fallback when every arena slot is pinned.)
        if self.zero_copy and self.n_workers >= 2 and n >= ARENA_MIN:
            shm = ShmPayload(self._lib, body)
            if shm._h is not None:  # memfd unavailable -> socket path
                return shm
        return SharedPayload(self._lib, body)

    def arena_payload(self, body) -> "ArenaPayload | None":
        """Stage ``body`` in the persistent broadcast arena: one memcpy
        into a slot of a memfd region that every worker maps ONCE (the
        fd crosses the socket a single time per worker, on the first
        arena frame that rank sees) — the per-epoch memfd + 2 mmaps +
        fd-pass setup of the one-shot :class:`ShmPayload` path is gone.

        Returns None when the arena path does not apply (TCP/single
        worker/small body/no memfd) or when every slot is still pinned
        by unreleased worker views — callers fall back to
        :meth:`payload`, so correctness never waits on a consumer's
        garbage collector. A slot is reclaimed only after every rank it
        was sent to acks release (worker-side weakref finalizers on the
        served views, piggybacked on the worker's next send), the
        pin-count generalization of the keep-window discipline."""
        if not self.zero_copy or self.n_workers < 2:
            return None
        u8 = _rings.as_u8(body)
        n = u8.nbytes
        if n < ARENA_MIN:
            return None
        with self._zlock:
            arena = self._arena
            if arena is None or arena.slot_bytes < n:
                region = _rings.MemfdRegion.create(
                    _rings.next_pow2(n) * ARENA_SLOTS, "msgt-arena"
                )
                if region is None:  # no memfd on this kernel
                    return None
                arena = _BroadcastArena(
                    next(self._arena_ids), region, ARENA_SLOTS
                )
                self._arena = arena
                self._arenas[arena.id] = arena
                self._gc_arenas_locked()
            got = arena.alloc.acquire(("coord",))
            if got is None:
                # dead ranks never ack: reap their pins, then retry
                for r in range(self.n_workers):
                    if self._h and self._lib.msgt_coord_is_dead(
                        self._h, r
                    ):
                        arena.alloc.release_holder_everywhere(r)
                got = arena.alloc.acquire(("coord",))
            if got is None:
                self.stats["arena_stalls"] += 1
                return None
            slot, gen = got
        off = slot * arena.slot_bytes
        arena.region.view[off:off + n] = u8  # slot exclusively ours
        return ArenaPayload(self, arena, slot, gen, n)

    def _isend_arena(
        self, rank: int, prefix: bytes, p: "ArenaPayload", *,
        seq: int, epoch: int, tag: int,
    ) -> bool:
        arena = p.arena
        data = _RING_HDR.pack(
            arena.id, arena.region.nbytes, arena.slots, p.slot, p.gen,
            p.nbytes,
        ) + (prefix if isinstance(prefix, bytes) else bytes(prefix))
        with self._zlock:
            arena.alloc.add_holder(p.slot, p.gen, int(rank))
            first = (int(rank), arena.id) not in self._arena_fd_sent
            if first:
                self._arena_fd_sent.add((int(rank), arena.id))
        if first:
            rc = self._lib.msgt_coord_isend_fd(
                self._handle(), int(rank), seq, epoch, tag, KIND_ARENA,
                data, len(data), arena.region.fd,
            )
            if rc == -2:  # fd table full: copying send, same semantics
                with self._zlock:
                    self._arena_fd_sent.discard((int(rank), arena.id))
                    arena.alloc.release(p.slot, p.gen, int(rank))
                off = p.slot * arena.slot_bytes
                return self.isend2(
                    rank, prefix, arena.region.view[off:off + p.nbytes],
                    seq=seq, epoch=epoch, tag=tag,
                )
        else:
            rc = self._lib.msgt_coord_isend(
                self._handle(), int(rank), seq, epoch, tag, KIND_ARENA,
                data, len(data),
            )
        with self._zlock:
            if rc != 0:
                arena.alloc.release(p.slot, p.gen, int(rank))
                return False
            self.stats["arena_bytes"] += p.nbytes
        return True

    def _gc_arenas_locked(self) -> None:
        """Close superseded arenas once fully drained (caller holds
        ``_zlock``). Worker-side mappings are independent and follow
        their own keep-window eviction."""
        for aid in list(self._arenas):
            a = self._arenas[aid]
            if a is not self._arena and a.alloc.pinned == 0:
                a.region.close()
                del self._arenas[aid]
                self._arena_fd_sent = {
                    k for k in self._arena_fd_sent if k[1] != aid
                }

    def _handle_ack(self, rank: int, payload) -> None:
        """Worker ack frame: release arena slots this rank held, and
        absorb its ring-full stall reports."""
        mv = memoryview(payload)
        usable = len(mv) - len(mv) % _ACK_REC.size
        with self._zlock:
            for off in range(0, usable, _ACK_REC.size):
                oid, slot, gen = _ACK_REC.unpack_from(mv, off)
                if oid == -1:
                    self.stats["ring_stalls"] += int(slot)
                    continue
                arena = self._arenas.get(oid)
                if arena is not None:
                    arena.alloc.release(int(slot), int(gen), int(rank))
            self._gc_arenas_locked()

    def isend_shared(
        self, rank: int, prefix: bytes, payload, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        if payload._h is None:
            raise TransportError("shared payload already released")
        if isinstance(payload, ArenaPayload):
            if kind != KIND_DATA:
                raise ValueError("arena payloads carry data frames only")
            return self._isend_arena(
                rank, prefix, payload, seq=seq, epoch=epoch, tag=tag
            )
        paddr, plen, pkeep = _addr_len(prefix)
        if isinstance(payload, ShmPayload):
            if kind != KIND_DATA:
                raise ValueError("shm payloads carry data frames only")
            rc = self._lib.msgt_coord_isend_shm(
                self._handle(), int(rank), seq, epoch, tag,
                paddr, plen, payload._h,
            )
            return rc == 0
        rc = self._lib.msgt_coord_isend_shared(
            self._handle(), int(rank), seq, epoch, tag, kind,
            paddr, plen, payload._h,
        )
        return rc == 0

    def poll(self, rank: int) -> Message | None:
        """Non-blocking probe-and-take (``MPI.Test!``): returns the next
        completed message for ``rank`` (a ``KIND_DEATH`` message if the
        rank died), or None. Transport-internal frames (slot-release
        acks) are consumed here, invisibly; result-ring frames resolve
        to ``KIND_DATA`` messages whose body is a zero-copy view into
        the worker's ring."""
        while True:
            hdr = _Header()
            if not self._lib.msgt_coord_poll(
                self._handle(), int(rank), ctypes.byref(hdr)
            ):
                return None
            msg = self._take(rank, hdr)
            if msg.kind == KIND_ACK:
                self._handle_ack(rank, msg.payload)
                continue
            if msg.kind == KIND_RING:
                out = self._resolve_ring(rank, msg)
                if out is None:
                    continue  # announce fd lost to a death race; the
                    # sticky death marker surfaces on a later poll
                return out
            return msg

    def _resolve_ring(self, rank: int, msg: Message) -> Message | None:
        """Resolve a result-ring control frame to a message whose body
        is a read-only zero-copy view into the worker's ring, adopting
        the ring fd (SCM_RIGHTS) on first sight. The view is tracked:
        when the last derived array dies, a release ack flows back so
        the worker can reuse the slot."""
        rid, cap, slots, slot, gen, blen = _RING_HDR.unpack_from(
            msg.payload, 0
        )
        prefix = bytes(memoryview(msg.payload)[_RING_HDR.size:])
        key = (int(rank), int(rid))
        with self._zlock:
            mm = self._rings.get(key)
            if mm is None:
                fd = self._lib.msgt_coord_take_fd(self._handle(), int(rank))
                if fd < 0:
                    # the fd rides the announcing frame's first byte, so
                    # it can only be missing if the rank died and its fd
                    # queue was reaped
                    if self._lib.msgt_coord_is_dead(self._h, int(rank)):
                        return None
                    raise TransportError(
                        f"ring {rid} of rank {rank}: announce carried "
                        "no fd"
                    )
                try:
                    mm = _mmap.mmap(
                        fd, int(cap), _mmap.MAP_SHARED, _mmap.PROT_READ
                    )
                finally:
                    _os.close(fd)
                self._rings[key] = mm
                self._evict_rings_locked(int(rank), int(rid))
            view = np.frombuffer(mm, np.uint8)[
                slot * (cap // slots): slot * (cap // slots) + blen
            ]
            self.stats["ring_bytes"] += int(blen)
            self.stats["ring_pinned"] += 1
            if self.stats["ring_pinned"] > self.stats["pinned_peak"]:
                self.stats["pinned_peak"] = self.stats["ring_pinned"]
        _rings.track_release(
            view, self._ring_released, int(rank), int(rid), int(slot),
            int(gen),
        )
        # served as a MEMORYVIEW of the tracked slice, not the slice
        # itself: np.frombuffer(ndarray) does NOT keep the ndarray
        # object in its base chain (only the root buffer), so a
        # consumer re-wrapping the raw slice would let the finalizer
        # fire — and the slot recycle — while its view was still live.
        # A memoryview's managed buffer holds the slice strongly, and
        # every derived buffer (numpy or memoryview) shares it.
        return Message(
            seq=int(msg.seq), epoch=int(msg.epoch), tag=int(msg.tag),
            kind=KIND_DATA, payload=prefix, body=memoryview(view),
        )

    def _ring_released(self, rank: int, rid: int, slot: int, gen: int):
        """Finalizer for a served ring view (any thread, possibly at
        interpreter teardown): ack the slot back to the worker."""
        try:
            with self._zlock:
                self.stats["ring_pinned"] -= 1
            if self._h:
                self._lib.msgt_coord_isend(
                    self._h, rank, 0, 0, 0, KIND_ACK,
                    _ACK_REC.pack(rid, slot, gen), _ACK_REC.size,
                )
        except Exception:  # pragma: no cover - teardown ordering
            pass

    def _evict_rings_locked(self, rank: int, keep_rid: int) -> None:
        """A rank's superseded rings (it grew into a bigger one, or it
        reconnected) move to the orphan list and close once no served
        view pins them (caller holds ``_zlock``)."""
        for key in [
            k for k in self._rings if k[0] == rank and k[1] != keep_rid
        ]:
            self._ring_orphans.append(self._rings.pop(key))
        self._sweep_orphans_locked()

    def _sweep_orphans_locked(self) -> None:
        still = []
        for mm in self._ring_orphans:
            try:
                mm.close()
            except BufferError:  # a served view is alive; retry later
                still.append(mm)
        self._ring_orphans = still

    def pinned_slots(self) -> int:
        """Currently pinned zero-copy slots: harvested ring views still
        alive coordinator-side plus arena slots awaiting worker acks."""
        with self._zlock:
            n = self.stats["ring_pinned"]
            n += sum(a.alloc.pinned for a in self._arenas.values())
            return n

    def _take(self, rank: int, hdr: _Header) -> Message:
        n = int(hdr.len)
        buf = bytearray(n)
        cbuf = (
            (ctypes.c_uint8 * n).from_buffer(buf) if n
            else (ctypes.c_uint8 * 1)()
        )
        got = self._lib.msgt_coord_take(self._handle(), int(rank), cbuf, n)
        del cbuf  # release the buffer export
        if got < 0:
            raise TransportError(f"take({rank}) raced: nothing available")
        return Message(
            seq=int(hdr.seq), epoch=int(hdr.epoch), tag=int(hdr.tag),
            kind=int(hdr.kind),
            payload=buf if got == n else bytes(buf[:got]),
        )

    def waitany(
        self, ranks, timeout: float | None = None
    ) -> tuple[int, Message] | None:
        """Block until any rank in ``ranks`` has a message (or died);
        returns ``(rank, message)``, or None on timeout
        (``MPI.Waitany!``). Frames consumed internally by :meth:`poll`
        (slot-release acks) re-arm the wait instead of surfacing."""
        arr = (ctypes.c_int32 * len(ranks))(*[int(r) for r in ranks])
        deadline = (
            None if timeout is None
            else _time.perf_counter() + max(timeout, 0.0)
        )
        while True:
            if deadline is None:
                t = -1
            else:
                t = max(
                    int((deadline - _time.perf_counter()) * 1000), 0
                )
            rank = self._lib.msgt_coord_waitany(
                self._handle(), arr, len(ranks), t
            )
            if rank < 0:
                return None
            msg = self.poll(rank)
            if msg is None:
                # the ready frame was transport-internal (ack) or a
                # concurrent prober took it; re-arm on the remaining
                # deadline
                if (
                    deadline is not None
                    and _time.perf_counter() >= deadline
                ):
                    return None
                continue
            return rank, msg

    def is_dead(self, rank: int) -> bool:
        return bool(self._lib.msgt_coord_is_dead(self._handle(), int(rank)))

    def reaccept(self, rank: int, timeout: float = 30.0) -> None:
        """Accept a reconnect for a dead rank (elastic recovery): a
        respawned worker sends a fresh hello with the same rank and
        the progress engine picks its socket back up."""
        rc = self._lib.msgt_coord_reaccept(
            self._handle(), int(rank), int(timeout * 1000)
        )
        if rc != 0:
            raise TransportError(
                f"rank {rank} did not reconnect within {timeout}s "
                "(or was not dead)"
            )
        self._forget_rank(int(rank))

    def _forget_rank(self, rank: int) -> None:
        """A rank reconnected as a fresh process: re-announce arena fds
        to it, reap the old incarnation's arena pins (it will never
        ack), and orphan its result-ring mappings (new incarnation ring
        ids start over, so stale mappings must not shadow them; held
        views keep the old pages alive until released)."""
        with self._zlock:
            self._arena_fd_sent = {
                k for k in self._arena_fd_sent if k[0] != rank
            }
            for a in self._arenas.values():
                a.alloc.release_holder_everywhere(rank)
            for key in [k for k in self._rings if k[0] == rank]:
                self._ring_orphans.append(self._rings.pop(key))
            self._sweep_orphans_locked()
            self._gc_arenas_locked()

    def error(self) -> str:
        """First fatal progress-engine error, or ''. When non-empty,
        every rank has been marked dead with this as the cause."""
        buf = ctypes.create_string_buffer(1024)
        n = self._lib.msgt_coord_error(self._handle(), buf, len(buf))
        return buf.raw[:n].decode(errors='replace')

    def close(self) -> None:
        if self._h:
            self._lib.msgt_coord_destroy(self._h)
            self._h = None
            with self._zlock:
                for a in self._arenas.values():
                    a.region.close()
                self._arenas.clear()
                self._arena = None
                for key in list(self._rings):
                    self._ring_orphans.append(self._rings.pop(key))
                self._sweep_orphans_locked()  # pinned mappings linger
                # until their views die (finalizers guard on _h)

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.close()
        except Exception:
            pass


class _BroadcastArena:
    """Coordinator side of the persistent broadcast arena: one memfd
    region of ``slots`` equal slots, allocator holders = the ranks a
    slot's broadcast was sent to (plus the transient ``"coord"`` hold
    between :meth:`Coordinator.arena_payload` and the payload's
    release)."""

    __slots__ = ("id", "region", "alloc", "slots", "slot_bytes")

    def __init__(self, aid: int, region, slots: int):
        self.id = int(aid)
        self.region = region
        self.slots = int(slots)
        self.slot_bytes = region.nbytes // self.slots
        self.alloc = _rings.RingAlloc(self.slots)


class ArenaPayload:
    """One staged broadcast body in the persistent arena. Pass to
    :meth:`Coordinator.isend_shared` per rank, then :meth:`release` —
    the slot itself is reclaimed only after every receiving rank acks
    its views released (see ``native/rings.py``). ``_h`` mirrors the
    Shared/ShmPayload handle convention (None = released)."""

    __slots__ = ("_coord", "arena", "slot", "gen", "nbytes", "_h")

    def __init__(self, coord, arena, slot: int, gen: int, nbytes: int):
        self._coord = coord
        self.arena = arena
        self.slot = int(slot)
        self.gen = int(gen)
        self.nbytes = int(nbytes)
        self._h = arena.id  # non-None marker for isend_shared's guard

    def release(self) -> None:
        if self._h is None:
            return
        self._h = None
        with self._coord._zlock:
            self.arena.alloc.release(self.slot, self.gen, "coord")

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.release()
        except Exception:
            pass


class Worker:
    """Worker endpoint: blocking framed recv/send on one socket.

    The constructor is a round trip: it sends the hello and then blocks
    until the coordinator's ``accept``/``reaccept`` admits the rank
    (answering the auth challenge when one is issued). Construct it on
    a thread/process other than the one that will call ``accept`` —
    which is how workers run anyway (worker.py)."""

    def __init__(
        self, path: str, rank: int, *, token: bytes = b"",
        ring_min: "int | None" = RING_MIN,
    ):
        """``ring_min``: result bodies of at least this many bytes ride
        the persistent shared-memory result ring (``send_result``);
        None disables the ring (copying ``send2`` only — TCP workers
        disable it automatically, SCM_RIGHTS being unix-only)."""
        self._lib = load_lib()
        self.rank = int(rank)
        token = bytes(token)
        self._h = self._lib.msgt_worker_connect(
            path.encode(), self.rank, token, len(token)
        )
        if not self._h:
            raise TransportError(
                f"worker {rank} could not connect to {path} (refused, "
                "or the coordinator rejected the auth token)"
            )
        # shm broadcast regions, id -> mmap, insertion-ordered. Owned
        # HERE (not in C++) so eviction can be REFUSED while numpy views
        # of a region are still alive: mmap.close() raises BufferError
        # when buffers are exported, which downgrades "use-after-unmap
        # segfault" to "old region stays mapped a little longer".
        self._shm_regions: dict[int, _mmap.mmap] = {}
        self._shm_keep = 4
        # persistent broadcast-arena mappings (id -> mmap): mapped once,
        # reused every epoch; a superseded arena is evicted with the
        # same BufferError pin discipline as the one-shot regions
        self._arena_regions: dict[int, _mmap.mmap] = {}
        # slot-release acks owed to the coordinator, appended by view
        # finalizers (single-threaded worker: no lock needed) and
        # flushed as one KIND_ACK frame at the next recv/send boundary
        self._pending_acks: list[tuple[int, int, int]] = []
        self._stall_count = 0
        if path.startswith("tcp://"):
            ring_min = None
        self._ring_min = ring_min if ring_min is None else int(ring_min)
        self._ring: "_WorkerRing | None" = None
        self._ring_ids = _itertools.count(1)

    def _shm_view(self, sid: int, blen: int) -> "memoryview | None":
        """Resolve a shm region id to a read-only view, adopting the fd
        that rode in with the frame (SCM_RIGHTS) on first sight."""
        region = self._shm_regions.get(sid)
        if region is not None:
            fd = self._lib.msgt_worker_take_fd(self._h)
            if fd >= 0:
                _os.close(fd)  # duplicate announce of a known region
        else:
            fd = self._lib.msgt_worker_take_fd(self._h)
            if fd < 0:
                return None
            try:
                region = _mmap.mmap(
                    fd, blen, _mmap.MAP_SHARED, _mmap.PROT_READ
                )
            except (OSError, ValueError):
                return None
            finally:
                _os.close(fd)  # mmap holds its own reference
            self._shm_regions[sid] = region
            self._evict_shm()
        return memoryview(region)[:blen]

    def _evict_shm(self) -> None:
        """Bound the region dict to the keep window, oldest first. A
        region whose views are still referenced raises ``BufferError``
        from ``mmap.close()`` and is RETAINED — payload views can never
        dangle; eviction of a pinned region simply defers to a later
        resolve (every new region triggers another sweep, so the dict
        shrinks back to the window as soon as the views are released).
        Pinned regions do not shield newer closable ones: the sweep
        walks every over-window candidate, not just the first."""
        excess = len(self._shm_regions) - self._shm_keep
        if excess <= 0:
            return
        # the newest `keep` regions stay regardless; everything older
        # is a candidate, evicted unless a live view pins it
        for old_sid in list(self._shm_regions)[:excess]:
            old = self._shm_regions[old_sid]
            try:
                old.close()
            except BufferError:
                continue  # views alive; keep the mapping, retry later
            del self._shm_regions[old_sid]

    def recv(self) -> Message | None:
        """Block for the next frame; None means the coordinator is gone.
        Transport-internal frames (result-ring slot acks) are consumed
        invisibly; arena frames resolve to ``KIND_DATA`` messages with
        zero-copy bodies."""
        self._flush_acks()
        while True:
            hdr = _Header()
            if self._lib.msgt_worker_recv_hdr(
                self._h, ctypes.byref(hdr)
            ) != 0:
                return None
            n = int(hdr.len)
            buf = bytearray(n)
            if n > 0:
                cbuf = (ctypes.c_uint8 * n).from_buffer(buf)
                ok = self._lib.msgt_worker_recv_payload(self._h, cbuf, n)
                del cbuf
                if ok != 0:
                    return None
            kind = int(hdr.kind)
            if kind == KIND_ACK:
                self._handle_ring_acks(buf)
                continue
            if kind == KIND_ARENA:
                msg = self._resolve_arena(hdr, buf)
                if msg is None:
                    return None  # region lost; coordinator sees death
                return msg
            if kind == KIND_SHM:
                # wire payload = [shm_id, body_len, codec prefix...]; the
                # body lives in a mapped region — zero bytes on the wire
                sid, blen = _struct.unpack_from("<qq", buf, 0)
                view = self._shm_view(sid, blen)
                if view is None:
                    return None  # region lost; coordinator sees the death
                return Message(
                    seq=int(hdr.seq), epoch=int(hdr.epoch),
                    tag=int(hdr.tag), kind=KIND_DATA,
                    payload=bytes(memoryview(buf)[16:]), body=view,
                )
            return Message(
                seq=int(hdr.seq), epoch=int(hdr.epoch), tag=int(hdr.tag),
                kind=kind, payload=buf,
            )

    def _resolve_arena(self, hdr: _Header, buf) -> Message | None:
        """Resolve a broadcast-arena frame: adopt the arena fd on first
        sight (mapped ONCE; every later epoch is a tiny fd-less control
        frame), serve a read-only zero-copy slot view, and register its
        release so the coordinator can reuse the slot."""
        aid, cap, slots, slot, gen, blen = _RING_HDR.unpack_from(buf, 0)
        mm = self._arena_regions.get(aid)
        if mm is None:
            fd = self._lib.msgt_worker_take_fd(self._h)
            if fd < 0:
                return None
            try:
                mm = _mmap.mmap(
                    fd, int(cap), _mmap.MAP_SHARED, _mmap.PROT_READ
                )
            except (OSError, ValueError):
                return None
            finally:
                _os.close(fd)  # mmap holds its own reference
            self._arena_regions[aid] = mm
            self._evict_arenas(keep=aid)
        off = slot * (cap // slots)
        view = np.frombuffer(mm, np.uint8)[off:off + blen]
        _rings.track_release(
            view, self._pending_acks.append, (int(aid), int(slot), int(gen))
        )
        # memoryview-wrapped for the same reason as the coordinator's
        # ring serve: every derived buffer must hold the tracked slice
        return Message(
            seq=int(hdr.seq), epoch=int(hdr.epoch), tag=int(hdr.tag),
            kind=KIND_DATA,
            payload=bytes(memoryview(buf)[_RING_HDR.size:]),
            body=memoryview(view),
        )

    def _evict_arenas(self, keep: int) -> None:
        """Superseded arena mappings close unless a live slot view pins
        them (BufferError), in which case they retry on the next arena
        handoff — the keep-window discipline, window = the current
        arena."""
        for aid in [a for a in self._arena_regions if a != keep]:
            try:
                self._arena_regions[aid].close()
            except BufferError:
                continue  # views alive; keep the mapping, retry later
            del self._arena_regions[aid]

    def _flush_acks(self) -> None:
        """Ship owed slot releases (and ring-full stall reports) as one
        KIND_ACK frame. Called at frame boundaries on the worker's own
        thread — finalizers only append to the pending list, so there
        is no I/O interleaving hazard."""
        if not self._pending_acks and not self._stall_count:
            return
        if not self._h:
            return
        # drain IN PLACE: view finalizers were registered with this
        # exact list object bound into their callbacks (rings.py), so
        # rebinding the attribute would strand every finalizer created
        # before the flush on a detached list — acks would silently
        # stop and slots pin forever (the bug the first cut had)
        recs = self._pending_acks[:]
        del self._pending_acks[:len(recs)]
        parts = [_ACK_REC.pack(*r) for r in recs]
        if self._stall_count:
            parts.append(_ACK_REC.pack(-1, self._stall_count, 0))
            self._stall_count = 0
        payload = b"".join(parts)
        self._lib.msgt_worker_send(
            self._h, 0, 0, 0, KIND_ACK, payload, len(payload)
        )

    def _handle_ring_acks(self, buf) -> None:
        """Coordinator released result-ring slots: free them for reuse.
        Acks for a superseded ring are ignored (its slots died with
        it)."""
        usable = len(buf) - len(buf) % _ACK_REC.size
        for off in range(0, usable, _ACK_REC.size):
            rid, slot, gen = _ACK_REC.unpack_from(buf, off)
            ring = self._ring
            if ring is not None and ring.id == rid:
                ring.alloc.release(int(slot), int(gen), "coord")

    def send(
        self, payload: bytes, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        self._flush_acks()
        if not isinstance(payload, bytes):
            payload = bytes(payload)  # c_char_p wants immutable bytes
        rc = self._lib.msgt_worker_send(
            self._h, seq, epoch, tag, kind, payload, len(payload)
        )
        return rc == 0

    def send2(
        self, prefix: bytes, body, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        """Two-buffer blocking send; ``body`` is written straight from
        the caller's buffer (zero-copy in user space for ndarrays)."""
        self._flush_acks()
        paddr, plen, pkeep = _addr_len(prefix)
        baddr, blen, bkeep = _addr_len(body)
        rc = self._lib.msgt_worker_send2(
            self._h, seq, epoch, tag, kind, paddr, plen, baddr, blen
        )
        del pkeep, bkeep  # held until the blocking write finished
        return rc == 0

    def send_result(
        self, prefix: bytes, body, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        """Result send, zero-copy where it pays: bodies of at least
        ``ring_min`` bytes are written into this worker's persistent
        result ring (one memcpy into shared pages the coordinator maps
        once; only a tiny control frame crosses the socket) — the
        coordinator serves ``np.frombuffer`` views straight off the
        ring. Smaller bodies, non-buffer bodies, error frames, and a
        fully pinned ring (every slot's coordinator view still alive)
        fall back to :meth:`send2`, so delivery never waits on the
        coordinator's garbage collector."""
        if kind == KIND_DATA and self._ring_min is not None:
            try:
                u8 = _rings.as_u8(body)
            except (TypeError, ValueError):
                u8 = None
            if u8 is not None and u8.nbytes >= self._ring_min:
                if self._send_ring(
                    prefix, u8, seq=seq, epoch=epoch, tag=tag
                ):
                    return True
        return self.send2(
            prefix, body, seq=seq, epoch=epoch, tag=tag, kind=kind
        )

    def _send_ring(self, prefix, u8, *, seq, epoch, tag) -> bool:
        n = u8.nbytes
        ring = self._ring
        if ring is None or ring.slot_bytes < n:
            region = _rings.MemfdRegion.create(
                _rings.next_pow2(n) * RING_SLOTS, "msgt-result-ring"
            )
            if region is None:  # no memfd: stop probing on every send
                self._ring_min = None
                return False
            old, ring = ring, _WorkerRing(
                next(self._ring_ids), region, RING_SLOTS
            )
            self._ring = ring
            if old is not None:
                # worker-side mapping only; the coordinator's mapping
                # (and any held views) keep the old pages alive
                old.region.close()
        got = ring.alloc.acquire(("coord",))
        if got is None:
            self._stall_count += 1  # every slot pinned: socket fallback
            return False
        slot, gen = got
        off = slot * ring.slot_bytes
        ring.region.view[off:off + n] = u8
        data = _RING_HDR.pack(
            ring.id, ring.region.nbytes, ring.slots, slot, gen, n
        ) + (prefix if isinstance(prefix, bytes) else bytes(prefix))
        self._flush_acks()
        if not ring.announced:
            rc = self._lib.msgt_worker_send_fd(
                self._h, seq, epoch, tag, KIND_RING, data, len(data),
                ring.region.fd,
            )
            if rc == 0:
                ring.announced = True
        else:
            rc = self._lib.msgt_worker_send(
                self._h, seq, epoch, tag, KIND_RING, data, len(data)
            )
        if rc != 0:
            ring.alloc.release(slot, gen, "coord")
            return False
        return True

    def close(self) -> None:
        if self._h:
            self._lib.msgt_worker_close(self._h)
            self._h = None
            if self._ring is not None:
                self._ring.region.close()
                self._ring = None
            for aid in list(self._arena_regions):
                try:
                    self._arena_regions.pop(aid).close()
                except BufferError:  # held view outlives the worker
                    pass

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.close()
        except Exception:
            pass


class _WorkerRing:
    """Worker side of a persistent result ring: one memfd region of
    ``slots`` equal slots; the coordinator holds each slot (holder
    ``"coord"``) from send until its served view's release ack."""

    __slots__ = ("id", "region", "alloc", "slots", "slot_bytes",
                 "announced")

    def __init__(self, rid: int, region, slots: int):
        self.id = int(rid)
        self.region = region
        self.slots = int(slots)
        self.slot_bytes = region.nbytes // self.slots
        self.alloc = _rings.RingAlloc(self.slots)
        self.announced = False  # fd passed with the first control frame


class ShmPayload:
    """A broadcast payload in a memfd region: every worker maps the same
    physical pages, so broadcasting n ways moves the bytes zero times
    over the sockets. ``_h`` is None when memfd creation failed (caller
    falls back to :class:`SharedPayload`)."""

    __slots__ = ("_lib", "_h", "nbytes")

    def __init__(self, lib, body):
        addr, n, keep = _addr_len(body)
        self._lib = lib
        self.nbytes = n
        self._h = lib.msgt_payload_create_shm(addr, n)
        del keep  # create copies synchronously

    def release(self) -> None:
        if self._h is not None:
            self._lib.msgt_payload_release_shm(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.release()
        except Exception:
            pass


class SharedPayload:
    """A broadcast payload snapshotted once in native memory; frames
    enqueue shared references instead of copies. Frames still in a send
    queue keep the bytes alive after :meth:`release`."""

    __slots__ = ("_lib", "_h", "nbytes")

    def __init__(self, lib, body):
        addr, n, keep = _addr_len(body)
        self._lib = lib
        self.nbytes = n
        self._h = lib.msgt_payload_create(addr, n)
        del keep  # create copies synchronously

    def release(self) -> None:
        if self._h is not None:
            self._lib.msgt_payload_release(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.release()
        except Exception:
            pass
