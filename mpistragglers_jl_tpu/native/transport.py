"""ctypes bindings for the native message transport (transport.cpp).

The C++ library supplies the MPI-shaped primitives (isend / test / waitany
/ dead-rank detection over Unix-domain sockets with an epoll progress
thread — the reference's libmpi role, SURVEY component C8); this module
wraps them in two small classes:

* :class:`Coordinator` — rank-indexed non-blocking sends, completion
  polls, waitany, payload harvest.
* :class:`Worker` — blocking receive/send loop primitives for worker
  processes.

Payloads are opaque bytes at this layer; the backend above
(:mod:`..backends.native`) owns serialization. No fallback exists here on
purpose — consumers (the backend) catch :class:`NativeBuildError` and use
the pure-Python :class:`~..backends.process.ProcessBackend` instead.
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass

KIND_DATA = 0
KIND_CONTROL = 1
KIND_HELLO = 2
KIND_DEATH = 3
KIND_ERROR = 4


class _Header(ctypes.Structure):
    _fields_ = [
        ("len", ctypes.c_int64),
        ("seq", ctypes.c_int64),
        ("epoch", ctypes.c_int64),
        ("tag", ctypes.c_int64),
        ("kind", ctypes.c_int64),
    ]


@dataclass(frozen=True)
class Message:
    """One received frame: bookkeeping header + raw payload bytes."""

    seq: int
    epoch: int
    tag: int
    kind: int
    payload: bytes


def _configure(lib):
    lib.msgt_coord_create.restype = ctypes.c_void_p
    lib.msgt_coord_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
    ]
    lib.msgt_coord_port.restype = ctypes.c_int
    lib.msgt_coord_port.argtypes = [ctypes.c_void_p]
    lib.msgt_coord_accept.restype = ctypes.c_int
    lib.msgt_coord_accept.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.msgt_coord_isend.restype = ctypes.c_int
    lib.msgt_coord_isend.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.msgt_coord_poll.restype = ctypes.c_int
    lib.msgt_coord_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(_Header)
    ]
    lib.msgt_coord_take.restype = ctypes.c_int64
    lib.msgt_coord_take.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.msgt_coord_waitany.restype = ctypes.c_int
    lib.msgt_coord_waitany.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.c_int64,
    ]
    lib.msgt_coord_is_dead.restype = ctypes.c_int
    lib.msgt_coord_is_dead.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.msgt_coord_reaccept.restype = ctypes.c_int
    lib.msgt_coord_reaccept.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64
    ]
    lib.msgt_coord_error.restype = ctypes.c_int
    lib.msgt_coord_error.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
    ]
    lib.msgt_coord_destroy.restype = None
    lib.msgt_coord_destroy.argtypes = [ctypes.c_void_p]
    lib.msgt_worker_connect.restype = ctypes.c_void_p
    lib.msgt_worker_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
    ]
    lib.msgt_hmac_sha256.restype = None
    lib.msgt_hmac_sha256.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.msgt_worker_recv_hdr.restype = ctypes.c_int
    lib.msgt_worker_recv_hdr.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_Header)
    ]
    lib.msgt_worker_recv_payload.restype = ctypes.c_int
    lib.msgt_worker_recv_payload.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64
    ]
    lib.msgt_worker_send.restype = ctypes.c_int
    lib.msgt_worker_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.msgt_worker_close.restype = None
    lib.msgt_worker_close.argtypes = [ctypes.c_void_p]


def load_lib():
    """Compile (if stale) and load the transport library; success and
    failure both memoized process-wide by :func:`..native.load`."""
    from . import load

    return load("transport", _configure)


class TransportError(RuntimeError):
    pass


def hmac_sha256(key: bytes, msg: bytes) -> bytes:
    """The native HMAC-SHA256 the hello handshake authenticates with,
    exposed so tests can check conformance against :mod:`hmac`."""
    lib = load_lib()
    out = (ctypes.c_uint8 * 32)()
    lib.msgt_hmac_sha256(key, len(key), msg, len(msg), out)
    return bytes(out)


class Coordinator:
    """Coordinator endpoint: owns the listening socket and the native
    progress thread; one connection per worker rank."""

    def __init__(self, path: str, n_workers: int, *, token: bytes = b""):
        """``path`` is a Unix-socket filesystem path (single host) or
        ``tcp://host:port`` (multi-host; port 0 binds an ephemeral port,
        see :attr:`port`). A non-empty ``token`` turns on hello
        authentication: every worker must present the same secret
        (proved by HMAC-SHA256 challenge-response; the secret never
        crosses the wire) before its rank is admitted. An empty token
        admits any connector — acceptable only on trusted networks."""
        self._lib = load_lib()
        self.n_workers = int(n_workers)
        self.path = path
        self.token = bytes(token)
        self._h = self._lib.msgt_coord_create(
            path.encode(), self.n_workers, self.token, len(self.token)
        )
        if not self._h:
            raise TransportError(f"could not bind coordinator socket {path}")
        self.port = int(self._lib.msgt_coord_port(self._h))

    @property
    def address(self) -> str:
        """The address workers should connect to (ephemeral TCP ports
        resolved to the actual bound port)."""
        if self.path.startswith("tcp://"):
            host = self.path[6:].rsplit(":", 1)[0]
            return f"tcp://{host}:{self.port}"
        return self.path

    def _handle(self):
        # a NULL handle into the C ABI would segfault, not raise
        if not self._h:
            raise TransportError("coordinator is closed")
        return self._h

    def accept(self, timeout: float = 30.0) -> None:
        """Wait for all workers to connect and complete the hello
        handshake, then start the progress engine. ``timeout`` bounds
        the whole handshake, stalled hellos included."""
        rc = self._lib.msgt_coord_accept(self._handle(), int(timeout * 1000))
        if rc != 0:
            raise TransportError(
                f"workers failed to connect within {timeout}s"
            )

    def isend(
        self, rank: int, payload: bytes, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        """Non-blocking send; payload is snapshotted into the native send
        queue. Returns False if the rank is dead."""
        rc = self._lib.msgt_coord_isend(
            self._handle(), int(rank), seq, epoch, tag, kind, payload,
            len(payload),
        )
        return rc == 0

    def poll(self, rank: int) -> Message | None:
        """Non-blocking probe-and-take (``MPI.Test!``): returns the next
        completed message for ``rank`` (a ``KIND_DEATH`` message if the
        rank died), or None."""
        hdr = _Header()
        if not self._lib.msgt_coord_poll(
            self._handle(), int(rank), ctypes.byref(hdr)
        ):
            return None
        return self._take(rank, hdr)

    def _take(self, rank: int, hdr: _Header) -> Message:
        n = int(hdr.len)
        buf = (ctypes.c_uint8 * max(n, 1))()
        got = self._lib.msgt_coord_take(self._handle(), int(rank), buf, n)
        if got < 0:
            raise TransportError(f"take({rank}) raced: nothing available")
        return Message(
            seq=int(hdr.seq), epoch=int(hdr.epoch), tag=int(hdr.tag),
            kind=int(hdr.kind), payload=ctypes.string_at(buf, got),
        )

    def waitany(
        self, ranks, timeout: float | None = None
    ) -> tuple[int, Message] | None:
        """Block until any rank in ``ranks`` has a message (or died);
        returns ``(rank, message)``, or None on timeout
        (``MPI.Waitany!``)."""
        arr = (ctypes.c_int32 * len(ranks))(*[int(r) for r in ranks])
        t = -1 if timeout is None else max(int(timeout * 1000), 0)
        rank = self._lib.msgt_coord_waitany(self._handle(), arr, len(ranks), t)
        if rank < 0:
            return None
        msg = self.poll(rank)
        if msg is None:  # pragma: no cover - single-consumer coordinator
            raise TransportError(f"waitany({rank}) raced with another take")
        return rank, msg

    def is_dead(self, rank: int) -> bool:
        return bool(self._lib.msgt_coord_is_dead(self._handle(), int(rank)))

    def reaccept(self, rank: int, timeout: float = 30.0) -> None:
        """Accept a reconnect for a dead rank (elastic recovery): a
        respawned worker sends a fresh hello with the same rank and
        the progress engine picks its socket back up."""
        rc = self._lib.msgt_coord_reaccept(
            self._handle(), int(rank), int(timeout * 1000)
        )
        if rc != 0:
            raise TransportError(
                f"rank {rank} did not reconnect within {timeout}s "
                "(or was not dead)"
            )

    def error(self) -> str:
        """First fatal progress-engine error, or ''. When non-empty,
        every rank has been marked dead with this as the cause."""
        buf = ctypes.create_string_buffer(1024)
        n = self._lib.msgt_coord_error(self._handle(), buf, len(buf))
        return buf.raw[:n].decode(errors='replace')

    def close(self) -> None:
        if self._h:
            self._lib.msgt_coord_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.close()
        except Exception:
            pass


class Worker:
    """Worker endpoint: blocking framed recv/send on one socket.

    The constructor is a round trip: it sends the hello and then blocks
    until the coordinator's ``accept``/``reaccept`` admits the rank
    (answering the auth challenge when one is issued). Construct it on
    a thread/process other than the one that will call ``accept`` —
    which is how workers run anyway (worker.py)."""

    def __init__(self, path: str, rank: int, *, token: bytes = b""):
        self._lib = load_lib()
        self.rank = int(rank)
        token = bytes(token)
        self._h = self._lib.msgt_worker_connect(
            path.encode(), self.rank, token, len(token)
        )
        if not self._h:
            raise TransportError(
                f"worker {rank} could not connect to {path} (refused, "
                "or the coordinator rejected the auth token)"
            )

    def recv(self) -> Message | None:
        """Block for the next frame; None means the coordinator is gone."""
        hdr = _Header()
        if self._lib.msgt_worker_recv_hdr(self._h, ctypes.byref(hdr)) != 0:
            return None
        n = int(hdr.len)
        buf = (ctypes.c_uint8 * max(n, 1))()
        if n > 0 and self._lib.msgt_worker_recv_payload(self._h, buf, n) != 0:
            return None
        return Message(
            seq=int(hdr.seq), epoch=int(hdr.epoch), tag=int(hdr.tag),
            kind=int(hdr.kind), payload=ctypes.string_at(buf, n),
        )

    def send(
        self, payload: bytes, *,
        seq: int = 0, epoch: int = 0, tag: int = 0, kind: int = KIND_DATA,
    ) -> bool:
        rc = self._lib.msgt_worker_send(
            self._h, seq, epoch, tag, kind, payload, len(payload)
        )
        return rc == 0

    def close(self) -> None:
        if self._h:
            self._lib.msgt_worker_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering dependent
        try:
            self.close()
        except Exception:
            pass
