"""Native (C++) runtime components, compiled on demand.

The reference's only native code is the external ``libmpi`` it reaches
through MPI.jl (SURVEY §2, component C8); the TPU data path's native
runtime is XLA itself. What lives here is the host-side native layer
this framework adds: currently the GF(256) Reed-Solomon codec
(rs_gf256.cpp) used for byte-exact erasure coding of host payloads.

Libraries are compiled with ``g++ -O3 -shared -fPIC`` on first use and
cached next to the source (gitignored). Consumers fall back to a pure
NumPy implementation when no compiler is available, so the package never
hard-fails on import.
"""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()


class NativeBuildError(RuntimeError):
    pass


_loaded: dict[str, object] = {}
_load_lock = threading.Lock()


def load(name: str, configure=None):
    """Build + ``ctypes.CDLL``-load ``_lib<name>.so`` once per process.

    Success *and* failure are memoized: a broken toolchain is probed
    exactly once, not re-probed with a fresh (120 s-timeout) g++
    subprocess on every call from a hot path. ``configure(lib)``, if
    given, sets up argtypes/restypes on first load.
    """
    import ctypes

    with _load_lock:
        cached = _loaded.get(name)
        if cached is not None:
            if isinstance(cached, Exception):
                raise cached
            return cached
        try:
            lib = ctypes.CDLL(build(name))
            if configure is not None:
                configure(lib)
        except Exception as e:
            _loaded[name] = e
            raise
        _loaded[name] = lib
        return lib


def lib_path(name: str) -> str:
    return os.path.join(_DIR, f"_lib{name}.so")


def build(name: str, *, force: bool = False) -> str:
    """Compile ``<name>.cpp`` into ``_lib<name>.so`` if stale; return the
    library path. Thread-safe; cheap when the library is current."""
    src = os.path.join(_DIR, f"{name}.cpp")
    out = lib_path(name)
    with _LOCK:
        if (
            not force
            and os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)
        ):
            return out
        # pid-suffixed tmp keeps concurrent builds from separate
        # processes from clobbering each other; os.replace is atomic
        tmp = f"{out}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            "-o", tmp, src,
        ]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            raise NativeBuildError(f"g++ unavailable or hung: {e}") from e
        if proc.returncode != 0:
            raise NativeBuildError(
                f"g++ failed for {src}:\n{proc.stderr}"
            )
        os.replace(tmp, out)
    return out
