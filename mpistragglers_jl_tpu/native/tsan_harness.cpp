// ThreadSanitizer harness for the native transport (transport.cpp).
//
// The transport's concurrency surface — epoll progress thread vs caller
// threads (isend/poll/waitany/reaccept), worker threads doing blocking
// frame I/O, death marking under the mutex — is exactly the kind of
// code where a "benign" unlocked read becomes real UB (ADVICE round 1
// flagged one such race, fixed since). This harness compiles the whole
// transport with -fsanitize=thread and drives the hot paths end to end:
//
//   1. coordinator + 4 worker threads over a Unix socket, HMAC auth on;
//   2. 200 epochs of broadcast -> compute-echo -> waitany harvest, with
//      concurrent poll() probes from a second coordinator-side thread
//      (the pool's phase-1 drain running against the progress engine);
//   3. one worker killed mid-run (socket closed), death observed via the
//      sticky marker, then re-admitted through reaccept while traffic
//      continues on the survivors;
//   4. shared + shm broadcast payload paths (payload handles are
//      created/released by the caller thread while the progress thread
//      writes frames referencing them);
//   5. clean shutdown (control frames, worker exits, destroy);
//   6. round-12 ring phase: a second coordinator + 2 producer workers
//      exercising the persistent result-ring protocol end to end —
//      memfd ring announced once via SCM_RIGHTS (msgt_worker_send_fd
//      -> recvmsg capture -> msgt_coord_take_fd), concurrent
//      producer writes / consumer reads on the SAME mapped pages (the
//      producer-address read makes a protocol violation a TSAN race,
//      not just a byte mismatch), ack-frame slot reclamation, and a
//      deliberately PINNED slot whose ack is withheld while the
//      producer wraps the ring — reuse-before-ack is caught both ways.
//
// Any data race TSAN finds aborts the process non-zero
// (halt_on_error=1 is set by the pytest driver); exit 0 means the run
// completed with a clean report. Built on demand by
// tests/test_tsan_transport.py; no Python in the loop — TSAN must own
// the whole address space, which it cannot do as a .so loaded into a
// non-TSAN interpreter.

#include <sys/mman.h>
#include <unistd.h>

#include <array>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <deque>
#include <string>
#include <thread>
#include <utility>
#include <vector>

// The transport's C ABI (declared here rather than a header; the .cpp
// is compiled into this binary directly).
extern "C" {
void* msgt_coord_create(const char* addr, int n, const uint8_t* token,
                        int token_len);
int msgt_coord_accept(void* h, int64_t timeout_ms);
int msgt_coord_isend(void* h, int rank, int64_t seq, int64_t epoch,
                     int64_t tag, int64_t kind, const uint8_t* data,
                     int64_t len);
void* msgt_payload_create(const uint8_t* data, int64_t len);
void msgt_payload_release(void* ph);
int msgt_coord_isend_shared(void* h, int rank, int64_t seq, int64_t epoch,
                            int64_t tag, int64_t kind, const uint8_t* pre,
                            int64_t pre_len, void* ph);
void* msgt_payload_create_shm(const uint8_t* data, int64_t len);
void msgt_payload_release_shm(void* ph);
int msgt_coord_isend_shm(void* h, int rank, int64_t seq, int64_t epoch,
                         int64_t tag, const uint8_t* pre, int64_t pre_len,
                         void* ph);
struct Hdr {
  int64_t len, seq, epoch, tag, kind;
};
int msgt_coord_poll(void* h, int rank, Hdr* out);
int64_t msgt_coord_take(void* h, int rank, uint8_t* buf, int64_t cap);
int msgt_coord_waitany(void* h, const int32_t* ranks, int n,
                       int64_t timeout_ms);
int msgt_coord_is_dead(void* h, int rank);
int msgt_coord_reaccept(void* h, int rank, int64_t timeout_ms);
void msgt_coord_destroy(void* h);
void* msgt_worker_connect(const char* addr, int rank, const uint8_t* token,
                          int token_len);
int msgt_worker_recv_hdr(void* h, Hdr* out);
int msgt_worker_recv_payload(void* h, uint8_t* buf, int64_t len);
int msgt_worker_send(void* h, int64_t seq, int64_t epoch, int64_t tag,
                     int64_t kind, const uint8_t* data, int64_t len);
int msgt_worker_send_fd(void* h, int64_t seq, int64_t epoch, int64_t tag,
                        int64_t kind, const uint8_t* data, int64_t len,
                        int fd);
int msgt_worker_take_fd(void* h);
int msgt_coord_take_fd(void* h, int rank);
void msgt_worker_close(void* h);
}

namespace {

constexpr int64_t KIND_DATA = 0;
constexpr int64_t KIND_CONTROL = 1;
constexpr int64_t KIND_SHM = 5;
const uint8_t kToken[] = "tsan-secret";
constexpr int kTokenLen = sizeof(kToken) - 1;

void worker_main(const std::string& path, int rank, int die_after) {
  void* w = msgt_worker_connect(path.c_str(), rank, kToken, kTokenLen);
  if (!w) {
    std::fprintf(stderr, "worker %d: connect failed\n", rank);
    std::abort();
  }
  int served = 0;
  while (true) {
    Hdr hdr{};
    if (msgt_worker_recv_hdr(w, &hdr) != 0) break;
    std::vector<uint8_t> payload(hdr.len > 0 ? hdr.len : 1);
    if (hdr.len > 0 &&
        msgt_worker_recv_payload(w, payload.data(), hdr.len) != 0)
      break;
    if (hdr.kind == KIND_CONTROL) break;
    if (hdr.kind == KIND_SHM) {
      // adopt + immediately drop the region fd: the harness checks the
      // fd-passing plumbing for races, not the mapping contents
      int fd = msgt_worker_take_fd(w);
      if (fd >= 0) ::close(fd);
    }
    uint8_t echo[8];
    std::memcpy(echo, &hdr.epoch, sizeof(int64_t));
    if (msgt_worker_send(w, hdr.seq, hdr.epoch, hdr.tag, KIND_DATA, echo,
                         sizeof(echo)) != 0)
      break;
    served++;
    if (die_after > 0 && served >= die_after) break;  // simulated crash
  }
  msgt_worker_close(w);
}

constexpr int64_t KIND_RING = 7;
constexpr int64_t KIND_ACK = 8;
constexpr int kRingSlots = 4;
constexpr size_t kSlotBytes = 4096;
constexpr int kRingRounds = 40;

// Ring producer: the worker half of the round-12 result-ring protocol.
// Creates a memfd ring, publishes its base pointer for the consumer's
// same-address reads (TSAN visibility), writes each round's pattern
// into a free slot, announces the fd once (msgt_worker_send_fd on the
// first control frame), and reuses a slot only after the
// coordinator's KIND_ACK releases it — blocking on acks when all four
// slots are outstanding (the ring-full path).
void ring_worker(const std::string& path, int rank,
                 std::atomic<uint8_t*>* base_out) {
  void* w = msgt_worker_connect(path.c_str(), rank, kToken, kTokenLen);
  if (!w) {
    std::fprintf(stderr, "ring worker %d: connect failed\n", rank);
    std::abort();
  }
  int fd = ::memfd_create("tsan-ring", MFD_CLOEXEC);
  if (fd < 0 || ::ftruncate(fd, kRingSlots * kSlotBytes) != 0) std::abort();
  auto* base = static_cast<uint8_t*>(
      ::mmap(nullptr, kRingSlots * kSlotBytes, PROT_READ | PROT_WRITE,
             MAP_SHARED, fd, 0));
  if (base == MAP_FAILED) std::abort();
  base_out->store(base, std::memory_order_release);
  bool announced = false;
  bool busy[kRingSlots] = {false, false, false, false};
  auto drain_one = [&]() -> bool {  // one frame; false = shutdown/EOF
    Hdr h{};
    if (msgt_worker_recv_hdr(w, &h) != 0) return false;
    std::vector<uint8_t> p(h.len > 0 ? h.len : 1);
    if (h.len > 0 && msgt_worker_recv_payload(w, p.data(), h.len) != 0)
      return false;
    if (h.kind == KIND_CONTROL) return false;
    if (h.kind == KIND_ACK && h.len >= 24) {
      int64_t rec[3];
      std::memcpy(rec, p.data(), 24);
      if (rec[1] >= 0 && rec[1] < kRingSlots) busy[rec[1]] = false;
    }
    return true;
  };
  int64_t gen = 0;
  bool alive = true;
  for (int r = 0; alive && r < kRingRounds; r++) {
    int slot = -1;
    while (slot < 0) {
      for (int s = 0; s < kRingSlots; s++)
        if (!busy[s]) {
          slot = s;
          break;
        }
      if (slot < 0 && !(alive = drain_one())) break;  // ring full: wait
    }
    if (!alive) break;
    ++gen;
    // the write the pinned-view discipline protects: only ever into a
    // slot the consumer has acked (or never seen)
    std::memset(base + slot * kSlotBytes, static_cast<uint8_t>(gen),
                kSlotBytes);
    int64_t meta[3] = {slot, gen, static_cast<int64_t>(kSlotBytes)};
    int rc;
    if (!announced) {
      rc = msgt_worker_send_fd(w, gen, r, 0, KIND_RING,
                               reinterpret_cast<uint8_t*>(meta), 24, fd);
      announced = true;
    } else {
      rc = msgt_worker_send(w, gen, r, 0, KIND_RING,
                            reinterpret_cast<uint8_t*>(meta), 24);
    }
    if (rc != 0) break;
    busy[slot] = true;
  }
  while (alive) alive = drain_one();  // until the control broadcast
  msgt_worker_close(w);
  base_out->store(nullptr, std::memory_order_release);
  ::munmap(base, kRingSlots * kSlotBytes);
  ::close(fd);
}

// Coordinator half of the ring phase. Returns true on success.
bool run_ring_phase(const std::string& path) {
  constexpr int NR = 2;
  void* c = msgt_coord_create(path.c_str(), NR, kToken, kTokenLen);
  if (!c) return false;
  std::atomic<uint8_t*> bases[NR];
  for (auto& b : bases) b.store(nullptr);
  std::vector<std::thread> workers;
  for (int r = 0; r < NR; r++)
    workers.emplace_back(ring_worker, path, r, &bases[r]);
  bool ok = msgt_coord_accept(c, 10000) == 0;
  // concurrent prober (phase-1 discipline): non-blocking polls racing
  // the progress engine and the harvester's takes
  std::atomic<bool> stop{false};
  std::thread prober([&] {
    Hdr hdr{};
    while (!stop.load(std::memory_order_relaxed)) {
      for (int r = 0; r < NR; r++) (void)msgt_coord_poll(c, r, &hdr);
      std::this_thread::sleep_for(std::chrono::microseconds(100));
    }
  });
  uint8_t* maps[NR] = {nullptr, nullptr};
  // the deliberately pinned slot: its ack is withheld across several
  // harvests while the producer keeps wrapping the other slots
  std::deque<std::pair<int, std::array<int64_t, 3>>> pinned;
  int expect = kRingRounds * NR, got = 0;
  while (ok && got < expect) {
    int32_t ranks[NR] = {0, 1};
    int r = msgt_coord_waitany(c, ranks, NR, 10000);
    if (r < 0) {
      std::fprintf(stderr, "ring waitany timeout\n");
      ok = false;
      break;
    }
    Hdr h{};
    if (!msgt_coord_poll(c, r, &h)) continue;  // prober peeked
    uint8_t buf[64];
    if (msgt_coord_take(c, r, buf, sizeof buf) < 0) continue;
    if (h.kind == 3) {  // KIND_DEATH: a producer crashed
      std::fprintf(stderr, "ring worker %d died\n", r);
      ok = false;
      break;
    }
    if (h.kind != KIND_RING) continue;
    int64_t meta[3];
    std::memcpy(meta, buf, 24);
    if (!maps[r]) {
      int fd = msgt_coord_take_fd(c, r);
      if (fd < 0) {
        std::fprintf(stderr, "ring announce carried no fd\n");
        ok = false;
        break;
      }
      maps[r] = static_cast<uint8_t*>(::mmap(
          nullptr, kRingSlots * kSlotBytes, PROT_READ, MAP_SHARED, fd, 0));
      ::close(fd);
      if (maps[r] == MAP_FAILED) {
        ok = false;
        break;
      }
    }
    auto want = static_cast<uint8_t>(meta[1]);
    const uint8_t* slot_p = maps[r] + meta[0] * kSlotBytes;
    for (size_t k = 0; k < kSlotBytes; k += 512)
      if (slot_p[k] != want) {
        std::fprintf(stderr, "ring slot bytes torn\n");
        ok = false;
      }
    // same-address read through the PRODUCER's mapping: if the
    // protocol ever let the producer reuse this slot early, TSAN sees
    // a racing write/read pair here, not only a byte mismatch
    uint8_t* shared = bases[r].load(std::memory_order_acquire);
    if (shared && shared[meta[0] * kSlotBytes] != want) {
      std::fprintf(stderr, "ring shared view torn\n");
      ok = false;
    }
    got++;
    int64_t rec[3] = {0, meta[0], meta[1]};
    if (r == 0 && pinned.empty()) {
      // hold this slot's ack: the producer must wrap around it
      pinned.push_back({r, {rec[0], rec[1], rec[2]}});
    } else {
      msgt_coord_isend(c, r, 0, 0, 0, KIND_ACK,
                       reinterpret_cast<uint8_t*>(rec), 24);
    }
    if (!pinned.empty() && got % 8 == 0) {
      auto pr = pinned.front();
      pinned.pop_front();
      // the pinned slot must still hold ITS generation right up to the
      // release (reclaim-vs-pinned-view)
      if (maps[pr.first] &&
          maps[pr.first][pr.second[1] * kSlotBytes] !=
              static_cast<uint8_t>(pr.second[2])) {
        std::fprintf(stderr, "pinned ring slot reused before ack\n");
        ok = false;
      }
      msgt_coord_isend(c, pr.first, 0, 0, 0, KIND_ACK,
                       reinterpret_cast<uint8_t*>(pr.second.data()), 24);
    }
  }
  // release any ack still withheld so producers drain, then shut down
  for (auto& pr : pinned)
    msgt_coord_isend(c, pr.first, 0, 0, 0, KIND_ACK,
                     reinterpret_cast<uint8_t*>(pr.second.data()), 24);
  uint8_t z[1] = {0};
  for (int r = 0; r < NR; r++)
    msgt_coord_isend(c, r, 0, 0, 0, KIND_CONTROL, z, 0);
  for (auto& t : workers) t.join();
  stop.store(true);
  prober.join();
  for (auto* m : maps)
    if (m) ::munmap(m, kRingSlots * kSlotBytes);
  msgt_coord_destroy(c);
  return ok;
}

}  // namespace

int main() {
  const std::string path =
      "/tmp/msgt-tsan-" + std::to_string(::getpid()) + ".sock";
  constexpr int N = 4;
  constexpr int EPOCHS = 200;
  void* c = msgt_coord_create(path.c_str(), N, kToken, kTokenLen);
  if (!c) {
    std::fprintf(stderr, "coordinator create failed\n");
    return 2;
  }
  std::vector<std::thread> workers;
  for (int r = 0; r < N; r++)
    workers.emplace_back(worker_main, path, r, r == 1 ? 40 : 0);
  auto bail = [&](const char* why) {
    std::fprintf(stderr, "%s\n", why);
    // detach in-scope threads: destroying a joinable std::thread calls
    // std::terminate, which would replace rc=2 with SIGABRT and bury
    // the diagnostic
    for (auto& t : workers)
      if (t.joinable()) t.detach();
    std::_Exit(2);
  };
  if (msgt_coord_accept(c, 10000) != 0) bail("accept failed");

  // concurrent phase-1-style prober: non-blocking polls racing the
  // progress engine's completions (results are harvested by the main
  // loop's waitany; the prober only peeks headers)
  std::atomic<bool> stop{false};
  std::thread prober([&] {
    Hdr hdr{};
    while (!stop.load(std::memory_order_relaxed)) {
      for (int r = 0; r < N; r++) (void)msgt_coord_poll(c, r, &hdr);
      std::this_thread::sleep_for(std::chrono::microseconds(200));
    }
  });

  int64_t seq = 0;
  bool reaccepted = false;
  uint8_t small[16] = {1};
  for (int epoch = 1; epoch <= EPOCHS; epoch++) {
    // rotate payload styles: direct, shared-buffer, shm
    int style = epoch % 3;
    void* ph = nullptr;
    for (int r = 0; r < N; r++) {
      if (msgt_coord_is_dead(c, r)) continue;
      ++seq;
      if (style == 0) {
        msgt_coord_isend(c, r, seq, epoch, 0, KIND_DATA, small,
                         sizeof(small));
      } else if (style == 1) {
        if (!ph) ph = msgt_payload_create(small, sizeof(small));
        msgt_coord_isend_shared(c, r, seq, epoch, 0, KIND_DATA, small, 4,
                                ph);
      } else {
        if (!ph) ph = msgt_payload_create_shm(small, sizeof(small));
        msgt_coord_isend_shm(c, r, seq, epoch, 0, small, 4, ph);
      }
    }
    if (ph && style == 1) msgt_payload_release(ph);
    if (ph && style == 2) msgt_payload_release_shm(ph);
    // harvest whatever the live set produces this epoch
    int32_t ranks[N];
    int live = 0;
    for (int r = 0; r < N; r++)
      if (!msgt_coord_is_dead(c, r)) ranks[live++] = r;
    int got = 0;
    while (got < live) {
      int r = msgt_coord_waitany(c, ranks, live, 5000);
      if (r < 0) bail("waitany timeout");
      Hdr hdr{};
      if (!msgt_coord_poll(c, r, &hdr)) continue;  // prober peeked; retry
      uint8_t buf[64];
      if (msgt_coord_take(c, r, buf, sizeof(buf)) < 0) continue;
      got++;  // data frame, or a death marker settling the slot
    }
    // mid-run: worker 1 died around epoch ~40; re-admit it once
    if (!reaccepted && msgt_coord_is_dead(c, 1)) {
      std::thread w(worker_main, path, 1, 0);
      if (msgt_coord_reaccept(c, 1, 10000) != 0) {
        w.detach();
        bail("reaccept failed");
      }
      w.detach();  // serves until the shutdown broadcast
      reaccepted = true;
    }
  }
  if (!reaccepted) bail("worker 1 never died/reaccepted");
  stop.store(true);
  prober.join();
  for (int r = 0; r < N; r++)
    msgt_coord_isend(c, r, 0, 0, 0, KIND_CONTROL, small, 0);
  for (auto& t : workers)
    if (t.joinable()) t.join();
  // give the detached reaccepted worker a beat to exit on the control
  // frame before the coordinator (and its socket) is destroyed
  std::this_thread::sleep_for(std::chrono::milliseconds(200));
  msgt_coord_destroy(c);
  std::printf("tsan harness: %d epochs, reaccept ok\n", EPOCHS);
  // phase 6: persistent result-ring protocol (fresh coordinator)
  const std::string ring_path =
      "/tmp/msgt-tsan-ring-" + std::to_string(::getpid()) + ".sock";
  if (!run_ring_phase(ring_path)) {
    std::fprintf(stderr, "ring phase failed\n");
    return 2;
  }
  std::printf("ring ok: %d rounds x 2 producers, pinned-slot holds\n",
              kRingRounds);
  return 0;
}
