// ThreadSanitizer harness for the native transport (transport.cpp).
//
// The transport's concurrency surface — epoll progress thread vs caller
// threads (isend/poll/waitany/reaccept), worker threads doing blocking
// frame I/O, death marking under the mutex — is exactly the kind of
// code where a "benign" unlocked read becomes real UB (ADVICE round 1
// flagged one such race, fixed since). This harness compiles the whole
// transport with -fsanitize=thread and drives the hot paths end to end:
//
//   1. coordinator + 4 worker threads over a Unix socket, HMAC auth on;
//   2. 200 epochs of broadcast -> compute-echo -> waitany harvest, with
//      concurrent poll() probes from a second coordinator-side thread
//      (the pool's phase-1 drain running against the progress engine);
//   3. one worker killed mid-run (socket closed), death observed via the
//      sticky marker, then re-admitted through reaccept while traffic
//      continues on the survivors;
//   4. shared + shm broadcast payload paths (payload handles are
//      created/released by the caller thread while the progress thread
//      writes frames referencing them);
//   5. clean shutdown (control frames, worker exits, destroy).
//
// Any data race TSAN finds aborts the process non-zero
// (halt_on_error=1 is set by the pytest driver); exit 0 means the run
// completed with a clean report. Built on demand by
// tests/test_tsan_transport.py; no Python in the loop — TSAN must own
// the whole address space, which it cannot do as a .so loaded into a
// non-TSAN interpreter.

#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

// The transport's C ABI (declared here rather than a header; the .cpp
// is compiled into this binary directly).
extern "C" {
void* msgt_coord_create(const char* addr, int n, const uint8_t* token,
                        int token_len);
int msgt_coord_accept(void* h, int64_t timeout_ms);
int msgt_coord_isend(void* h, int rank, int64_t seq, int64_t epoch,
                     int64_t tag, int64_t kind, const uint8_t* data,
                     int64_t len);
void* msgt_payload_create(const uint8_t* data, int64_t len);
void msgt_payload_release(void* ph);
int msgt_coord_isend_shared(void* h, int rank, int64_t seq, int64_t epoch,
                            int64_t tag, int64_t kind, const uint8_t* pre,
                            int64_t pre_len, void* ph);
void* msgt_payload_create_shm(const uint8_t* data, int64_t len);
void msgt_payload_release_shm(void* ph);
int msgt_coord_isend_shm(void* h, int rank, int64_t seq, int64_t epoch,
                         int64_t tag, const uint8_t* pre, int64_t pre_len,
                         void* ph);
struct Hdr {
  int64_t len, seq, epoch, tag, kind;
};
int msgt_coord_poll(void* h, int rank, Hdr* out);
int64_t msgt_coord_take(void* h, int rank, uint8_t* buf, int64_t cap);
int msgt_coord_waitany(void* h, const int32_t* ranks, int n,
                       int64_t timeout_ms);
int msgt_coord_is_dead(void* h, int rank);
int msgt_coord_reaccept(void* h, int rank, int64_t timeout_ms);
void msgt_coord_destroy(void* h);
void* msgt_worker_connect(const char* addr, int rank, const uint8_t* token,
                          int token_len);
int msgt_worker_recv_hdr(void* h, Hdr* out);
int msgt_worker_recv_payload(void* h, uint8_t* buf, int64_t len);
int msgt_worker_send(void* h, int64_t seq, int64_t epoch, int64_t tag,
                     int64_t kind, const uint8_t* data, int64_t len);
int msgt_worker_take_fd(void* h);
void msgt_worker_close(void* h);
}

namespace {

constexpr int64_t KIND_DATA = 0;
constexpr int64_t KIND_CONTROL = 1;
constexpr int64_t KIND_SHM = 5;
const uint8_t kToken[] = "tsan-secret";
constexpr int kTokenLen = sizeof(kToken) - 1;

void worker_main(const std::string& path, int rank, int die_after) {
  void* w = msgt_worker_connect(path.c_str(), rank, kToken, kTokenLen);
  if (!w) {
    std::fprintf(stderr, "worker %d: connect failed\n", rank);
    std::abort();
  }
  int served = 0;
  while (true) {
    Hdr hdr{};
    if (msgt_worker_recv_hdr(w, &hdr) != 0) break;
    std::vector<uint8_t> payload(hdr.len > 0 ? hdr.len : 1);
    if (hdr.len > 0 &&
        msgt_worker_recv_payload(w, payload.data(), hdr.len) != 0)
      break;
    if (hdr.kind == KIND_CONTROL) break;
    if (hdr.kind == KIND_SHM) {
      // adopt + immediately drop the region fd: the harness checks the
      // fd-passing plumbing for races, not the mapping contents
      int fd = msgt_worker_take_fd(w);
      if (fd >= 0) ::close(fd);
    }
    uint8_t echo[8];
    std::memcpy(echo, &hdr.epoch, sizeof(int64_t));
    if (msgt_worker_send(w, hdr.seq, hdr.epoch, hdr.tag, KIND_DATA, echo,
                         sizeof(echo)) != 0)
      break;
    served++;
    if (die_after > 0 && served >= die_after) break;  // simulated crash
  }
  msgt_worker_close(w);
}

}  // namespace

int main() {
  const std::string path =
      "/tmp/msgt-tsan-" + std::to_string(::getpid()) + ".sock";
  constexpr int N = 4;
  constexpr int EPOCHS = 200;
  void* c = msgt_coord_create(path.c_str(), N, kToken, kTokenLen);
  if (!c) {
    std::fprintf(stderr, "coordinator create failed\n");
    return 2;
  }
  std::vector<std::thread> workers;
  for (int r = 0; r < N; r++)
    workers.emplace_back(worker_main, path, r, r == 1 ? 40 : 0);
  auto bail = [&](const char* why) {
    std::fprintf(stderr, "%s\n", why);
    // detach in-scope threads: destroying a joinable std::thread calls
    // std::terminate, which would replace rc=2 with SIGABRT and bury
    // the diagnostic
    for (auto& t : workers)
      if (t.joinable()) t.detach();
    std::_Exit(2);
  };
  if (msgt_coord_accept(c, 10000) != 0) bail("accept failed");

  // concurrent phase-1-style prober: non-blocking polls racing the
  // progress engine's completions (results are harvested by the main
  // loop's waitany; the prober only peeks headers)
  std::atomic<bool> stop{false};
  std::thread prober([&] {
    Hdr hdr{};
    while (!stop.load(std::memory_order_relaxed)) {
      for (int r = 0; r < N; r++) (void)msgt_coord_poll(c, r, &hdr);
      std::this_thread::sleep_for(std::chrono::microseconds(200));
    }
  });

  int64_t seq = 0;
  bool reaccepted = false;
  uint8_t small[16] = {1};
  for (int epoch = 1; epoch <= EPOCHS; epoch++) {
    // rotate payload styles: direct, shared-buffer, shm
    int style = epoch % 3;
    void* ph = nullptr;
    for (int r = 0; r < N; r++) {
      if (msgt_coord_is_dead(c, r)) continue;
      ++seq;
      if (style == 0) {
        msgt_coord_isend(c, r, seq, epoch, 0, KIND_DATA, small,
                         sizeof(small));
      } else if (style == 1) {
        if (!ph) ph = msgt_payload_create(small, sizeof(small));
        msgt_coord_isend_shared(c, r, seq, epoch, 0, KIND_DATA, small, 4,
                                ph);
      } else {
        if (!ph) ph = msgt_payload_create_shm(small, sizeof(small));
        msgt_coord_isend_shm(c, r, seq, epoch, 0, small, 4, ph);
      }
    }
    if (ph && style == 1) msgt_payload_release(ph);
    if (ph && style == 2) msgt_payload_release_shm(ph);
    // harvest whatever the live set produces this epoch
    int32_t ranks[N];
    int live = 0;
    for (int r = 0; r < N; r++)
      if (!msgt_coord_is_dead(c, r)) ranks[live++] = r;
    int got = 0;
    while (got < live) {
      int r = msgt_coord_waitany(c, ranks, live, 5000);
      if (r < 0) bail("waitany timeout");
      Hdr hdr{};
      if (!msgt_coord_poll(c, r, &hdr)) continue;  // prober peeked; retry
      uint8_t buf[64];
      if (msgt_coord_take(c, r, buf, sizeof(buf)) < 0) continue;
      got++;  // data frame, or a death marker settling the slot
    }
    // mid-run: worker 1 died around epoch ~40; re-admit it once
    if (!reaccepted && msgt_coord_is_dead(c, 1)) {
      std::thread w(worker_main, path, 1, 0);
      if (msgt_coord_reaccept(c, 1, 10000) != 0) {
        w.detach();
        bail("reaccept failed");
      }
      w.detach();  // serves until the shutdown broadcast
      reaccepted = true;
    }
  }
  if (!reaccepted) bail("worker 1 never died/reaccepted");
  stop.store(true);
  prober.join();
  for (int r = 0; r < N; r++)
    msgt_coord_isend(c, r, 0, 0, 0, KIND_CONTROL, small, 0);
  for (auto& t : workers)
    if (t.joinable()) t.join();
  // give the detached reaccepted worker a beat to exit on the control
  // frame before the coordinator (and its socket) is destroyed
  std::this_thread::sleep_for(std::chrono::milliseconds(200));
  msgt_coord_destroy(c);
  std::printf("tsan harness: %d epochs, reaccept ok\n", EPOCHS);
  return 0;
}
