// LT (Luby transform) peeling decoder over real-valued blocks.
//
// The host-side hot path of LT-coded GEMM decode (ops/lt.py): given m
// arrived coded shards — each the real-field sum of a few source blocks
// — repeatedly release degree-1 shards and subtract the resolved block
// from every other shard containing it, until all k source blocks are
// recovered. The graph schedule is tiny; the cost is the block
// subtractions, which here run as a single in-place C pass per release
// (the NumPy fallback in ops/lt.py allocates and re-walks Python-side
// per release). The reference has no coding layer at all (SURVEY §2);
// this is north-star capability, and the native layer exists because
// decode latency sits on the coordinator's critical path between
// "enough shards fresh" and "product available".
//
// Inputs use a CSR layout for shard supports: shard r's source-block
// ids are sup[off[r] .. off[r+1]). Shard data is modified IN PLACE.
// Returns the number of resolved source blocks (k on success; < k means
// peeling stalled — callers gate on the decodability predicate, so a
// stall is caller error, reported not crashed).
//
// Build: g++ -O3 -shared -fPIC (native/__init__.py); consumed via
// ctypes from ops/lt.py. No external dependencies.

#include <cstdint>
#include <vector>

namespace {

template <typename T>
long peel(int m, int k, long block_elems, const int32_t* sup,
          const int32_t* off, T* shards, T* out, uint8_t* resolved) {
    // live degree per shard; inverted index block -> shards holding it
    std::vector<int> degree(m);
    std::vector<std::vector<int>> holders(k);
    for (int r = 0; r < m; ++r) {
        degree[r] = static_cast<int>(off[r + 1] - off[r]);
        for (int32_t p = off[r]; p < off[r + 1]; ++p)
            holders[sup[p]].push_back(r);
    }
    // a block is "live in shard r" iff not yet subtracted; track with a
    // per-shard bitmap over its own support via a resolved-block flag:
    // subtraction happens exactly once per (shard, block) because a
    // block resolves once and we subtract from all holders right then.
    std::vector<int> stack;
    for (int r = 0; r < m; ++r)
        if (degree[r] == 1) stack.push_back(r);

    long nresolved = 0;
    std::vector<uint8_t> consumed(m, 0);  // shard already released
    while (!stack.empty() && nresolved < k) {
        int r = stack.back();
        stack.pop_back();
        if (consumed[r] || degree[r] != 1) continue;
        // find the single live block of shard r
        int j = -1;
        for (int32_t p = off[r]; p < off[r + 1]; ++p)
            if (!resolved[sup[p]]) { j = sup[p]; break; }
        if (j < 0) continue;  // all its blocks resolved elsewhere
        consumed[r] = 1;
        resolved[j] = 1;
        ++nresolved;
        T* oj = out + static_cast<long>(j) * block_elems;
        const T* sr = shards + static_cast<long>(r) * block_elems;
        for (long e = 0; e < block_elems; ++e) oj[e] = sr[e];
        // release: subtract block j from every shard holding it
        for (int r2 : holders[j]) {
            if (r2 == r) { --degree[r]; continue; }
            T* s2 = shards + static_cast<long>(r2) * block_elems;
            for (long e = 0; e < block_elems; ++e) s2[e] -= oj[e];
            if (--degree[r2] == 1 && !consumed[r2]) stack.push_back(r2);
        }
    }
    return nresolved;
}

}  // namespace

extern "C" {

long lt_peel_f32(int m, int k, long block_elems, const int32_t* sup,
                 const int32_t* off, float* shards, float* out,
                 uint8_t* resolved) {
    return peel<float>(m, k, block_elems, sup, off, shards, out, resolved);
}

long lt_peel_f64(int m, int k, long block_elems, const int32_t* sup,
                 const int32_t* off, double* shards, double* out,
                 uint8_t* resolved) {
    return peel<double>(m, k, block_elems, sup, off, shards, out, resolved);
}

}  // extern "C"
