"""Payload codec for the native transport: raw-ndarray fast path.

The round-1 transport pickled every payload (backends/native.py), which
put serialization — an extra full copy plus object framing — on the hot
path and capped broadcast throughput around 0.5 GiB/s. This codec keeps
pickle only as the fallback for arbitrary objects; contiguous ndarrays
of plain dtypes travel as a 1-magic-byte + dtype/shape header prefix and
their raw bytes:

* **encode** returns ``(prefix, body)`` where ``body`` is the array
  itself — the transport's two-buffer sends (``isend2`` /
  ``isend_shared`` / ``send2``) write it straight from the array's
  memory, so the send side is zero-copy in user space (the coordinator's
  send queue snapshot is the one required copy: in-flight sends must
  survive caller mutation, the reference's ``isendbuf`` discipline at
  src/MPIAsyncPools.jl:63-66).
* **decode** returns ``np.frombuffer`` over the received frame buffer —
  a view, not a copy; the frame's ``bytearray`` stays alive as the
  array's base.

Wire format (little-endian): ``0x02 | u8 dtype_len | u8 ndim |
dtype_str | i64 shape[ndim] | raw bytes`` for arrays; ``0x01 |
pickle5`` for everything else. Structured dtypes, object dtypes, and
dtypes that don't round-trip through ``dtype.str`` (e.g. ml_dtypes
extension types) take the pickle path — correctness first, the fast
path is an optimization.
"""

from __future__ import annotations

import pickle
import struct

import numpy as np

__all__ = ["encode", "decode", "MAGIC_PICKLE", "MAGIC_RAW"]

MAGIC_PICKLE = 0x01
MAGIC_RAW = 0x02


def _raw_eligible(arr: np.ndarray) -> bool:
    if arr.dtype.hasobject or arr.dtype.names is not None:
        return False
    try:
        # extension dtypes (bfloat16, ...) stringify to opaque void
        # descriptors that do not round-trip; verify before trusting
        return np.dtype(arr.dtype.str) == arr.dtype
    except TypeError:  # pragma: no cover - exotic dtype
        return False


def encode(obj) -> tuple[bytes, object]:
    """``obj`` -> ``(prefix, body)`` for a two-buffer transport send.

    ``body`` is either the (contiguous) ndarray itself — send it
    zero-copy — or pickled bytes.
    """
    arr = None
    if isinstance(obj, np.ndarray):
        arr = obj
    elif hasattr(obj, "__array__") and not isinstance(
        obj, (str, bytes, bytearray, memoryview)
    ):
        # device arrays: np.asarray is the D2H transfer, unavoidable
        # for a host transport
        arr = np.asarray(obj)
    if arr is not None and _raw_eligible(arr):
        shape = arr.shape  # before ascontiguousarray: it promotes 0-d to 1-d
        arr = np.ascontiguousarray(arr)
        dstr = arr.dtype.str.encode()
        prefix = (
            struct.pack("<BBB", MAGIC_RAW, len(dstr), len(shape))
            + dstr
            + struct.pack(f"<{len(shape)}q", *shape)
        )
        return prefix, arr
    return bytes([MAGIC_PICKLE]), pickle.dumps(obj, protocol=5)


def decode(buf, body=None):
    """Inverse of :func:`encode` over a received frame buffer.

    ``buf`` holds the codec prefix; the body either follows it in the
    same buffer (socket frames) or arrives out-of-band in ``body``
    (shared-memory frames — Message.body). Raw arrays come back as
    ``np.frombuffer`` views (no copy; writable iff the buffer is).
    """
    mv = memoryview(buf)
    if mv.nbytes == 0:
        raise ValueError("empty payload has no codec magic")
    magic = mv[0]
    if magic == MAGIC_RAW:
        dlen, ndim = struct.unpack_from("<BB", mv, 1)
        dstr = bytes(mv[3 : 3 + dlen]).decode("ascii")
        shape = struct.unpack_from(f"<{ndim}q", mv, 3 + dlen)
        off = 3 + dlen + 8 * ndim
        data = memoryview(body) if body is not None else mv[off:]
        out = np.frombuffer(data, dtype=np.dtype(dstr)).reshape(shape)
        if out.flags.writeable:
            # uniform contract: decoded payloads are READ-ONLY views of
            # transport memory on every path. Shared-memory bodies are
            # physically read-only (all workers map the same pages);
            # making socket bodies writable would let the same work_fn
            # pass or crash depending on payload size and transport.
            out.flags.writeable = False
        return out
    if magic == MAGIC_PICKLE:
        data = memoryview(body) if body is not None else mv[1:]
        return pickle.loads(data)
    raise ValueError(f"unknown payload codec magic {magic:#x}")
