"""Checkpoint / resume of pool epoch state.

The reference has no serialization at all — the only resume hooks are the
``epoch0``/``epoch`` keyword arguments by which a caller could manually
re-seed a numbering scheme (reference src/MPIAsyncPools.jl:35,:68; SURVEY
§5 "Checkpoint / resume: absent"). Here pool state round-trips through a
plain dict (JSON-able) or an ``.npz`` file, so an iterative workload can
resume with its epoch counter, freshness mask and latency estimates
intact after a coordinator restart.

Only *quiescent* state is checkpointable: in-flight dispatches live in
the backend (device queues, threads) and cannot meaningfully be
serialized — callers drain with ``waitall`` first, mirroring how any MPI
checkpoint must first quiesce communication. ``save`` enforces this.

Model/optimizer state belongs to orbax (standard JAX checkpointing), not
here; this module covers the piece orbax does not know about — the
pool's straggler bookkeeping.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from ..pool import AsyncPool

__all__ = ["state_dict", "load_state_dict", "save", "restore"]

_FORMAT = "mpistragglers_jl_tpu.pool-v1"


def state_dict(pool: AsyncPool, *, allow_active: bool = False) -> dict[str, Any]:
    """Snapshot pool bookkeeping as a JSON-able dict.

    Raises if any worker is active (in-flight work is not serializable)
    unless ``allow_active``; then active workers are recorded as inactive
    — on restore their last *received* epoch is still correct, the
    in-flight task is simply dropped, which is exactly what a coordinator
    crash does anyway.
    """
    if pool.active.any() and not allow_active:
        raise RuntimeError(
            f"workers {np.flatnonzero(pool.active).tolist()} still active; "
            "drain with waitall() before checkpointing, or pass "
            "allow_active=True to drop in-flight work"
        )
    return {
        "format": _FORMAT,
        "ranks": list(pool.ranks),
        "epoch": int(pool.epoch),
        "epoch0": int(pool.epoch0),
        "nwait": int(pool.nwait),
        "sepochs": [int(x) for x in pool.sepochs],
        "repochs": [int(x) for x in pool.repochs],
        "latency": [float(x) for x in pool.latency],
    }


def load_state_dict(state: dict[str, Any]) -> AsyncPool:
    """Reconstruct a quiescent pool from :func:`state_dict` output."""
    if state.get("format") != _FORMAT:
        raise ValueError(
            f"unrecognized checkpoint format {state.get('format')!r}"
        )
    pool = AsyncPool(
        state["ranks"], epoch0=state["epoch0"], nwait=state["nwait"]
    )
    pool.epoch = int(state["epoch"])
    pool.sepochs[:] = state["sepochs"]
    pool.repochs[:] = state["repochs"]
    pool.latency[:] = state["latency"]
    # all workers inactive; pool.results is transport state, not restored
    return pool


def save(pool: AsyncPool, path, *, allow_active: bool = False) -> None:
    """Write pool state to ``path`` (JSON)."""
    with open(path, "w") as f:
        json.dump(state_dict(pool, allow_active=allow_active), f, indent=1)


def restore(path) -> AsyncPool:
    """Load a pool previously written by :func:`save`."""
    with open(path) as f:
        return load_state_dict(json.load(f))
