"""Training checkpoint/resume: model pytrees + pool bookkeeping together.

:mod:`.checkpoint` covers the piece standard JAX checkpointing does not
know about — the pool's straggler bookkeeping (epoch counter, freshness
mask, latency estimates). This module couples that with the model and
optimizer state of a training loop under one step-numbered directory
layout, so a coordinator restart resumes *both* the learning state and
the epoch numbering (the reference's only resume hook is the ``epoch0``
kwarg, SURVEY §5 "Checkpoint / resume: absent").

Model/optimizer pytrees go through orbax (the standard TPU checkpoint
path — async-friendly, sharding-aware); when orbax is unavailable the
fallback is a flat ``.npz`` of the tree leaves. The layout:

    <dir>/step_<N>/state/...     orbax pytree (or state.npz fallback)
    <dir>/step_<N>/pool.json     pool bookkeeping (optional)

>>> ckpt = TrainCheckpointer(dir)
>>> ckpt.save(12, {"w": w, "opt": opt_state}, pool=pool)
>>> state, pool_state, step = ckpt.restore()     # latest step
>>> pool = load_state_dict(pool_state)           # quiescent pool back
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any

import jax
import numpy as np

from ..pool import AsyncPool
from .checkpoint import state_dict as pool_state_dict

__all__ = ["TrainCheckpointer"]

_STEP_RE = re.compile(r"^step_(\d+)$")


def _flatten_for_npz(tree) -> dict[str, np.ndarray]:
    # structure is NOT stored: restore() requires a `target` tree to
    # unflatten against, so only the leaves go in the archive
    leaves = jax.tree_util.tree_leaves(tree)
    return {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}


class TrainCheckpointer:
    """Step-numbered checkpoints of (pytree state, pool bookkeeping).

    ``keep`` bounds how many step directories are retained (oldest
    pruned after each save); ``backend`` is ``"orbax"`` or ``"npz"``
    (auto-selected).
    """

    def __init__(self, directory, *, keep: int | None = None):
        self.directory = os.path.abspath(os.fspath(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self.backend = "orbax"
        except Exception:  # pragma: no cover - orbax is baked into CI env
            self._ocp = None
            self.backend = "npz"

    # -- paths -------------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{int(step)}")

    def steps(self) -> list[int]:
        """Existing checkpoint steps, ascending."""
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    # -- save --------------------------------------------------------------
    def save(
        self,
        step: int,
        state,
        *,
        pool: AsyncPool | None = None,
        allow_active: bool = False,
    ) -> str:
        """Write ``state`` (any pytree) and optional pool bookkeeping as
        step ``step``. The pool must be quiescent (``waitall`` first)
        unless ``allow_active``. Returns the step directory."""
        d = self._step_dir(step)
        tmp = d + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        state_path = os.path.join(tmp, "state")
        if self._ocp is not None:
            self._ocp.PyTreeCheckpointer().save(state_path, state)
        else:  # pragma: no cover - fallback path
            np.savez(state_path + ".npz", **_flatten_for_npz(state))
        if pool is not None:
            with open(os.path.join(tmp, "pool.json"), "w") as f:
                json.dump(
                    pool_state_dict(pool, allow_active=allow_active), f
                )
        if os.path.exists(d):
            shutil.rmtree(d)
        os.replace(tmp, d)
        if self.keep is not None:
            # retain the `keep` highest-numbered steps, but never the one
            # just written (a rollback re-save must not self-destruct)
            steps = self.steps()
            excess = len(steps) - self.keep
            if excess > 0:
                victims = [s for s in steps if s != int(step)][:excess]
                for old in victims:
                    shutil.rmtree(self._step_dir(old), ignore_errors=True)
        return d

    # -- restore -----------------------------------------------------------
    def restore(
        self, step: int | None = None, *, target=None
    ) -> tuple[Any, dict | None, int]:
        """Load ``(state, pool_state_dict_or_None, step)``.

        ``step=None`` loads the latest. ``target`` (a matching pytree of
        arrays) restores leaves with the target's types/shardings where
        the backend supports it. Feed the pool dict to
        :func:`.checkpoint.load_state_dict`.
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        d = self._step_dir(step)
        state_path = os.path.join(d, "state")
        if self._ocp is not None and os.path.isdir(state_path):
            kw = {"item": target} if target is not None else {}
            state = self._ocp.PyTreeCheckpointer().restore(state_path, **kw)
        else:  # pragma: no cover - fallback path
            with np.load(state_path + ".npz") as z:
                keys = sorted(
                    (k for k in z.files if re.fullmatch(r"leaf_\d+", k)),
                    key=lambda k: int(k.split("_")[1]),
                )
                leaves = [z[k] for k in keys]
            if target is None:
                raise ValueError(
                    "npz fallback needs `target` to rebuild the tree"
                )
            state = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(target), leaves
            )
        pool_state = None
        pool_path = os.path.join(d, "pool.json")
        if os.path.exists(pool_path):
            with open(pool_path) as f:
                pool_state = json.load(f)
        return state, pool_state, int(step)
