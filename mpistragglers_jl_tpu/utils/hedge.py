"""Hedged requests: first-response-wins replicated dispatch.

The serving-side dual of fastest-k training. The reference's pool
primitive — dispatch to several workers, return at the first
satisfactory arrival (``nwait=1``; src/MPIAsyncPools.jl:148-158 with
the minimal quorum) — is exactly the classic tail-latency hedge
("The Tail at Scale"): send the same request to ``hedge`` replicas and
take whichever answers first, so one stalled replica costs nothing.

:class:`HedgedServer` packages that on top of subset pools
(``AsyncPool(ranks=[...])`` routing, pool.py): each request runs on its
own 2-or-more-replica subset of one shared backend, so independent
requests hedge over disjoint replicas concurrently. The pieces the
pool already provides:

* **first-wins** is ``asyncmap(nwait=1)`` — phase 3 returns at the
  first fresh arrival;
* **losers cost nothing** — the slower replica's result arrives later,
  is harvested by the next phase-1 drain on that pool (stale, stored,
  worker freed), and the server's busy map keeps the rank out of new
  subsets until then;
* **exactly-once bookkeeping** — ``fresh_indices`` distinguishes the
  winner from the drained losers.

The server never blocks on a loser: ``request`` blocks only for its own
winner; ``drain`` (shutdown) is the one full barrier.
"""

from __future__ import annotations

import heapq
from typing import Sequence

import numpy as np

from ..backends.base import Backend, WorkerFailure
from ..pool import AsyncPool, asyncmap, waitall

__all__ = ["HedgedServer", "RequestHedge"]


class RequestHedge:
    """Deadline bookkeeping for REQUEST-level hedging: the serving-tier
    counterpart of :class:`HedgedServer`'s task-level first-wins.

    A :class:`~..models.router.RequestRouter` running the ``hedge_p99``
    policy arms one TTFT deadline per routed request; when the deadline
    passes without a first token the router re-dispatches the request
    onto a second scheduler replica, and whichever replica produces the
    first token wins (the loser is cancelled). This class is the
    bookkeeping half of that machinery — which requests are armed, which
    are due, fire-exactly-once — kept here next to ``HedgedServer`` so
    both hedging layers share one home and one semantics doc:

    * **arm(obj, deadline)** — start watching ``obj`` (any hashable-by-
      identity request handle) against an absolute clock time (virtual
      or wall — the caller owns the clock, exactly like the router);
    * **due(now)** — every armed entry whose deadline has passed, in
      (deadline, arm-sequence) order (deterministic — never set-hash
      order: sim replays must be bit-identical), each handed out
      EXACTLY ONCE (firing disarms);
    * **disarm(obj)** — the first token arrived (or the request was
      re-routed) before the deadline: stop watching;
    * **next_deadline()** — the earliest pending deadline, so a
      virtual-time driver can advance straight to the next hedge fire.

    Internally a (deadline, seq) heap over a liveness dict with lazy
    tombstones: ``due``/``next_deadline`` run once per router step of a
    million-event simulated day, and a full scan of the armed set per
    event is O(events x in-flight) — the scaling cliff this class must
    not have. Disarm/re-arm leave stale heap entries that the next
    heap touch discards by seq mismatch; every armed entry is pushed
    exactly once, so total heap work is O(arms log arms) per day.

    Single-threaded by design: the router mutates it only between
    scheduler ticks (the tick loop is the one writer), so unlike
    ``HedgedServer`` there is no cross-thread harvest to guard.
    """

    def __init__(self):
        # id(obj) -> (deadline, seq, obj); the heap holds
        # (deadline, seq, id) and an entry is live iff the dict still
        # maps its id to the SAME (deadline, seq)
        self._armed: dict[int, tuple[float, int, object]] = {}
        self._heap: list[tuple[float, int, int]] = []
        self._seq = 0

    def __len__(self) -> int:
        return len(self._armed)

    def arm(self, obj, deadline: float) -> None:
        self._seq += 1
        key = id(obj)
        self._armed[key] = (float(deadline), self._seq, obj)
        heapq.heappush(self._heap, (float(deadline), self._seq, key))

    def disarm(self, obj) -> None:
        self._armed.pop(id(obj), None)  # heap entry becomes a tombstone

    def _drop_tombstones(self) -> None:
        heap, armed = self._heap, self._armed
        while heap:
            d, s, k = heap[0]
            live = armed.get(k)
            if live is not None and live[0] == d and live[1] == s:
                return
            heapq.heappop(heap)

    def due(self, now: float) -> list:
        """Armed entries whose deadline has passed, in (deadline,
        arm-sequence) order; each is disarmed as it is returned (fire
        exactly once)."""
        out = []
        while True:
            self._drop_tombstones()
            if not self._heap or self._heap[0][0] > now:
                return out
            _, _, k = heapq.heappop(self._heap)
            out.append(self._armed.pop(k)[2])

    def next_deadline(self) -> float | None:
        self._drop_tombstones()
        return self._heap[0][0] if self._heap else None


class HedgedServer:
    """First-response-wins dispatch over rank subsets of one backend.

    >>> srv = HedgedServer(backend)
    >>> result, rank, latency = srv.request(payload, hedge=2)

    ``request`` picks ``hedge`` idle replicas (round-robin over the
    backend's ranks, skipping any still busy with a previous request's
    losing dispatch), broadcasts the payload to all of them, and
    returns the first arrival. Explicit ``replicas=[...]`` overrides
    the choice (the caller owns disjointness then — a rank busy in
    another subset raises from the backend's slot check).

    ``registry=`` (an :class:`~..obs.MetricsRegistry`, opt-in like the
    pool's ``tracer=``) exports the hedge's behavior as first-class
    series — requests, dispatched widths (fire rate), narrowed hedges,
    winner latency, per-rank wins, loser failures, dead replicas — so
    operators read the state the server already tracks publicly
    (``history``, ``last_hedge_width``, ``failures``) as live metrics
    instead of reaching into attributes. ``exporter=`` (an
    :class:`~..obs.ObsServer`, same opt-in contract) registers the
    replica-health ``/healthz`` check: unhealthy while any rank is
    benched dead (``dead_replicas``), recovering after ``respawn`` +
    :meth:`reset_dead`.
    """

    def __init__(self, backend: Backend, *, registry=None,
                 exporter=None):
        self.backend = backend
        self._pools: dict[tuple[int, ...], AsyncPool] = {}
        self._rr = 0  # round-robin cursor over backend ranks
        # (winner rank, latency s, dispatched hedge width) per request
        self.history: list[tuple[int, float, int]] = []
        self.last_hedge_width: int = 0
        # replicas whose LOSING dispatch failed: their error must not
        # poison later requests (they already lost — nobody is waiting
        # on the result), but the rank is out of rotation until the
        # caller repairs it (backend.respawn + reset_dead)
        self.failures: list[WorkerFailure] = []
        self._dead: set[int] = set()
        # opt-in metrics, instruments resolved once (None = dark)
        self._m = None
        if registry is not None:
            n = backend.n_workers
            self._m = {
                "requests": registry.counter("hedge_requests_total"),
                "dispatches": registry.counter(
                    "hedge_dispatches_total",
                    help="replica dispatches (sum of hedge widths "
                    "actually fired)",
                ),
                "width": registry.histogram(
                    "hedge_width",
                    help="replicas dispatched per request",
                    buckets=tuple(float(b) for b in range(1, n + 1)),
                ),
                "narrowed": registry.counter(
                    "hedge_narrowed_total",
                    help="requests whose hedge narrowed below the "
                    "requested width (losers held ranks)",
                ),
                "latency": registry.histogram(
                    "hedge_winner_latency_seconds",
                    help="first-arrival round trip per request",
                ),
                "wins": [
                    registry.counter(
                        "hedge_wins_total",
                        help="requests this rank answered first",
                        rank=str(r),
                    )
                    for r in range(n)
                ],
                "loser_failures": registry.counter(
                    "hedge_loser_failures_total",
                    help="losing dispatches that died (rank benched)",
                ),
                "dead": registry.gauge(
                    "hedge_dead_replicas",
                    help="ranks benched until repair",
                ),
            }
        if exporter is not None:
            # replica-health /healthz check on the live telemetry plane
            exporter.register_hedge(self)

    @property
    def dead_replicas(self) -> frozenset[int]:
        """Ranks currently benched dead (losers whose process died) —
        read by the ``/healthz`` hedge check (which runs on ObsServer
        scrape threads while request threads mutate the set, hence the
        retry: the copy is GIL-atomic on CPython, but a concurrent
        resize elsewhere must degrade to a re-read, never a raising
        probe that reports a healthy hedge as failing); repair with
        ``backend.respawn`` + :meth:`reset_dead`."""
        while True:
            try:
                return frozenset(self._dead)
            except RuntimeError:  # pragma: no cover - non-atomic copy
                continue

    # -- busy/harvest bookkeeping ---------------------------------------

    def _harvest(self) -> None:
        """Non-blocking drain of every pool's outstanding losers (the
        pool phase-1 discipline, run across pools): frees their ranks
        for new subsets."""
        from ..pool import _store  # package-internal by design

        for pool in self._pools.values():
            for i in np.flatnonzero(pool.active):
                result = self.backend.test(
                    pool.ranks[i], tag=int(pool.stags[i])
                )
                if result is None:
                    continue
                try:
                    _store(pool, int(i), result, None)
                except WorkerFailure as e:
                    # a LOSER died: its request was already served, so
                    # no caller is owed this error — record it, bench
                    # the rank, keep serving
                    self.failures.append(e)
                    self._dead.add(int(pool.ranks[i]))
                    if self._m is not None:
                        self._m["loser_failures"].inc()
                        self._m["dead"].set(len(self._dead))
                pool.active[int(i)] = False

    def _busy_ranks(self) -> set[int]:
        busy: set[int] = set()
        for pool in self._pools.values():
            busy.update(
                int(pool.ranks[j]) for j in np.flatnonzero(pool.active)
            )
        return busy

    def _pick(self, hedge: int, deadline: float | None) -> list[int]:
        """Up to ``hedge`` idle ranks, round-robin. Best-effort width:
        when losers from earlier requests still hold ranks, the hedge
        NARROWS rather than fails (a thinner hedge is a latency risk;
        a refused request is an outage). Zero idle ranks blocks on the
        harvest loop — bounded by ``deadline`` (an absolute
        ``perf_counter`` time: the caller's single request budget, NOT
        a fresh window) when given."""
        import time as _time

        n = self.backend.n_workers
        if not set(range(n)) - self._dead:
            # dead ranks never come back on their own — waiting on the
            # harvest loop would hang forever; name the actual problem
            raise RuntimeError(
                f"all {n} replicas are dead ({sorted(self._dead)}); "
                "repair them (backend.respawn + reset_dead)"
            )
        while True:
            busy = self._busy_ranks() | self._dead
            picked: list[int] = []
            for d in range(n):
                r = (self._rr + d) % n
                if r not in busy:
                    picked.append(r)
                    if len(picked) == hedge:
                        break
            if picked:
                self._rr = (picked[-1] + 1) % n
                return picked
            if deadline is not None and _time.perf_counter() > deadline:
                raise RuntimeError(
                    f"no idle replica within the request budget (all "
                    f"{n} busy with losing dispatches); add replicas "
                    "or drain()"
                )
            _time.sleep(1e-3)
            self._harvest()

    # -- the request path -----------------------------------------------

    def request(
        self,
        payload,
        *,
        hedge: int = 2,
        replicas: Sequence[int] | None = None,
        timeout: float | None = None,
    ):
        """Dispatch ``payload`` to up to ``hedge`` idle replicas (the
        width narrows when losers still hold ranks — see ``_pick``);
        return ``(result, winner_rank, winner_latency_s)`` of the first
        arrival. The losing replicas keep computing and are recycled
        opportunistically — no request ever waits for them.

        ``timeout`` is ONE wall-clock budget for the whole request:
        waiting for an idle replica and waiting for the winner share
        the same deadline (not a fresh window each). The width actually
        dispatched is observable per call as ``last_hedge_width`` and
        in ``history`` — a narrowed hedge is a latency risk the caller
        may want to react to."""
        import time as _time

        if hedge < 1:
            raise ValueError(f"hedge must be >= 1, got {hedge}")
        deadline = (
            None if timeout is None else _time.perf_counter() + timeout
        )
        self._harvest()
        ranks = (
            list(int(r) for r in replicas) if replicas is not None
            else self._pick(hedge, deadline)
        )
        self.last_hedge_width = len(ranks)
        key = tuple(sorted(ranks))
        pool = self._pools.get(key)
        if pool is None:
            pool = AsyncPool(list(key))
            self._pools[key] = pool
        remaining = (
            None if deadline is None
            else max(deadline - _time.perf_counter(), 1e-9)
        )
        asyncmap(pool, payload, self.backend, nwait=1, timeout=remaining)
        fresh = pool.fresh_indices()
        # >1 fresh iff several replicas answered within the same poll
        # tick; the measured-latency argmin is then the honest winner
        i = int(fresh[np.argmin(pool.latency[fresh])])
        winner = (pool.results[i], int(pool.ranks[i]),
                  float(pool.latency[i]))
        self.history.append(winner[1:] + (len(ranks),))
        if self._m is not None:
            m = self._m
            m["requests"].inc()
            m["dispatches"].inc(len(ranks))
            m["width"].observe(len(ranks))
            if replicas is None and len(ranks) < hedge:
                m["narrowed"].inc()
            m["latency"].observe(winner[2])
            m["wins"][winner[1]].inc()
        return winner

    def reset_dead(self, rank: int) -> None:
        """Return a repaired replica (e.g. after ``backend.respawn``)
        to the rotation."""
        self._dead.discard(int(rank))
        if self._m is not None:
            self._m["dead"].set(len(self._dead))
        for pool in self._pools.values():
            if rank in pool.ranks:
                pool.reset_worker(pool._idx_of_rank[int(rank)])

    def drain(self) -> None:
        """Shutdown barrier: wait for every outstanding loser so the
        backend can be closed (or reused) cleanly. A loser dying during
        the drain is recorded like any other loser death, not raised —
        drain is cleanup, not a request."""
        for pool in self._pools.values():
            while pool.active.any():
                try:
                    waitall(pool, self.backend)
                except WorkerFailure as e:
                    # _store already freed the failed slot, so the
                    # retry drains only the remaining workers
                    self.failures.append(e)
                    self._dead.add(int(e.worker))
                    if self._m is not None:
                        self._m["loser_failures"].inc()
                        self._m["dead"].set(len(self._dead))
