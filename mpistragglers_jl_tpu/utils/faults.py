"""Deterministic fault and latency injection.

The reference induces stragglers with bare randomness — workers
``sleep(rand())`` (reference examples/iterative_example.jl:74) or
``sleep(max(rand()/10, 0.005))`` (reference test/kmap2.jl:95) — which
SURVEY §4/§5 flags as the gap to close: on a real TPU slice stragglers
are rare and ICI is lockstep-fast, so *injection* must be a first-class,
reproducible test subsystem rather than an un-seeded sleep.

Every factory here returns a ``DelayFn`` — ``(worker, epoch) -> seconds``
— consumable by any backend's ``delay_fn`` kwarg (backends/base.py
``MailboxBackend``). All schedules are pure functions of ``(worker,
epoch)`` (seeded hashing, no global RNG state), so a failing test
reproduces bit-for-bit and schedules compose freely.

Failure (as opposed to latency) injection is expressed by wrapping the
workload: :func:`failing` raises inside the worker at chosen epochs,
exercising the coordinator-side :class:`~..backends.base.WorkerFailure`
surfacing path the reference entirely lacks (its worker assertions die
silently inside mpiexec subprocesses — reference test/runtests.jl:47).
"""

from __future__ import annotations

import hashlib
import struct
from typing import Callable, Mapping, Sequence

import numpy as np

from ..backends.base import DelayFn

__all__ = [
    "no_delay",
    "fixed",
    "per_worker",
    "seeded_uniform",
    "seeded_lognormal",
    "straggler",
    "intermittent",
    "dead_from",
    "kill_group",
    "correlated_kill",
    "partition",
    "from_trace",
    "compose",
    "failing",
    "FaultSchedule",
]


def _unit(seed: int, worker: int, epoch: int) -> float:
    """Deterministic uniform [0, 1) from (seed, worker, epoch).

    Uses blake2b so nearby (worker, epoch) pairs decorrelate — the
    reproducible stand-in for the reference's ``rand()``.
    """
    h = hashlib.blake2b(
        struct.pack("<qqq", seed, worker, epoch), digest_size=8
    ).digest()
    return struct.unpack("<Q", h)[0] / 2.0**64


def no_delay(worker: int, epoch: int) -> float:
    """The null schedule (every worker instant)."""
    return 0.0


def fixed(seconds: float) -> DelayFn:
    """Every worker stalls ``seconds`` every epoch."""
    return lambda worker, epoch: float(seconds)


def per_worker(delays: Sequence[float] | Mapping[int, float]) -> DelayFn:
    """Constant per-worker delay; workers absent from a mapping get 0."""
    if isinstance(delays, Mapping):
        table = dict(delays)
        return lambda worker, epoch: float(table.get(worker, 0.0))
    arr = [float(d) for d in delays]
    return lambda worker, epoch: arr[worker]


def seeded_uniform(lo: float, hi: float, *, seed: int = 0) -> DelayFn:
    """Deterministic analog of the reference's ``sleep(rand())``: uniform
    in [lo, hi), reproducible per (worker, epoch)."""
    span = float(hi) - float(lo)
    return lambda worker, epoch: lo + span * _unit(seed, worker, epoch)


def seeded_lognormal(
    median: float, sigma: float = 1.0, *, seed: int = 0
) -> DelayFn:
    """Heavy-tailed straggler model: lognormal with given median.

    Lognormal tails are the standard empirical model for straggler
    latencies (occasional order-of-magnitude outliers), which uniform
    sleeps cannot produce.
    """

    def fn(worker: int, epoch: int) -> float:
        u1 = _unit(seed, worker, epoch)
        u2 = _unit(seed + 0x9E3779B9, worker, epoch)
        # Box-Muller; clamp u1 away from 0
        z = np.sqrt(-2.0 * np.log(max(u1, 1e-12))) * np.cos(2 * np.pi * u2)
        return float(median * np.exp(sigma * z))

    return fn


def straggler(
    workers: int | Sequence[int], delay: float, *, every: int = 1, offset: int = 0
) -> DelayFn:
    """Designated worker(s) stall ``delay`` seconds on epochs where
    ``epoch % every == offset``; everyone else is instant.

    The workhorse for fastest-k tests: make worker j *the* straggler and
    assert the pool returns without it.
    """
    ws = {workers} if isinstance(workers, (int, np.integer)) else set(workers)
    return (
        lambda worker, epoch: float(delay)
        if worker in ws and epoch % every == offset % every
        else 0.0
    )


def intermittent(p: float, delay: float, *, seed: int = 0) -> DelayFn:
    """Each (worker, epoch) independently stalls ``delay`` with
    probability ``p`` — deterministic given the seed."""
    return (
        lambda worker, epoch: float(delay)
        if _unit(seed, worker, epoch) < p
        else 0.0
    )


def dead_from(workers: int | Sequence[int], epoch: int, *, delay: float = 3600.0) -> DelayFn:
    """Worker(s) become unresponsive from ``epoch`` onward.

    A dead worker is modelled as an arbitrarily long stall (default 1 h)
    — exactly how the reference's design treats death ("a dead worker is
    indistinguishable from an infinite straggler", SURVEY §5). Pair with
    ``waitall(timeout=...)`` to exercise :class:`~..pool.DeadWorkerError`.
    """
    ws = {workers} if isinstance(workers, (int, np.integer)) else set(workers)
    return (
        lambda worker, e: float(delay) if worker in ws and e >= epoch else 0.0
    )


class kill_group:
    """Scheduled whole-host failure: every worker of a group goes
    unresponsive from its kill epoch onward.

    The host-loss analog of :func:`dead_from` — death is an arbitrarily
    long stall (same modelling: "a dead worker is indistinguishable
    from an infinite straggler", SURVEY §5), but the unit is a *host
    group* (one entry of the partition
    :func:`~..parallel.multihost.host_groups` /
    :func:`~..ops.outer_code.partition_groups` produce), which is the
    failure mode the hierarchical outer code exists to survive and the
    one `sweep_hierarchical` injects when pricing (outer_rate,
    inner_nwait) pairs.

    ``groups`` is the worker partition (sequence of worker-index
    sequences); ``kills`` maps group id -> first dead epoch (several
    groups may carry schedules; a group killed twice keeps the earliest
    epoch). Pure in ``(worker, epoch)`` like every schedule here, so a
    simulated host loss replays bit-identically, and a class (not a
    closure) so it pickles into process-backend workers.

    >>> sched = faults.kill_group(host_groups(32, n_hosts=4), {2: 10})
    >>> backend = SimBackend(work, 32, delay_fn=sched)   # host 2 dies
    """

    def __init__(self, groups, kills: Mapping[int, int], *, delay: float = 3600.0):
        table: dict[int, int] = {}
        n_groups = len(list(groups))
        for g, e in dict(kills).items():
            if not 0 <= int(g) < n_groups:
                raise ValueError(
                    f"kill schedule names group {g}, but the partition "
                    f"has {n_groups} groups"
                )
            for w in groups[int(g)]:
                w = int(w)
                table[w] = min(int(e), table.get(w, int(e)))
        self._dead_from = table
        self.delay = float(delay)
        self.killed_groups = sorted(int(g) for g in dict(kills))

    def __call__(self, worker: int, epoch: int) -> float:
        e0 = self._dead_from.get(int(worker))
        return self.delay if e0 is not None and epoch >= e0 else 0.0


class correlated_kill:
    """Correlated whole-host failure: a contiguous SPAN of host groups
    dies at one epoch — the blast-radius model of a shared rack, power
    domain, or top-of-rack switch, where "one host died" is the
    fair-weather case and the chaos plane's case is "its neighbors
    went with it".

    ``groups`` is the worker partition (the
    :func:`~..parallel.multihost.host_groups` shape);
    ``epicenter`` names the first dead group and ``span`` how many
    consecutive groups the failure domain covers (clamped at the
    partition's end — a blast at the last rack does not wrap).
    Delegates the per-worker schedule to :class:`kill_group`, so death
    semantics (arbitrarily long stall from ``at_epoch`` onward) and
    picklability are exactly the single-host case's. Pure in
    ``(worker, epoch)``: a correlated-failure episode replays
    bit-identically on :class:`~..sim.backend.SimBackend`.

    >>> sched = faults.correlated_kill(host_groups(32, n_hosts=8),
    ...                                epicenter=2, at_epoch=10, span=3)
    """

    def __init__(self, groups, *, epicenter: int, at_epoch: int,
                 span: int = 2, delay: float = 3600.0):
        n_groups = len(list(groups))
        if not 0 <= int(epicenter) < n_groups:
            raise ValueError(
                f"epicenter names group {epicenter}, but the partition "
                f"has {n_groups} groups"
            )
        if span < 1:
            raise ValueError(f"span must be >= 1, got {span}")
        self.killed_groups = list(
            range(int(epicenter), min(int(epicenter) + int(span),
                                      n_groups))
        )
        self._inner = kill_group(
            groups, {g: int(at_epoch) for g in self.killed_groups},
            delay=delay,
        )
        self.at_epoch = int(at_epoch)
        self.delay = float(delay)

    def __call__(self, worker: int, epoch: int) -> float:
        return self._inner(worker, epoch)


class partition:
    """Network partition: every worker of the named groups is
    unreachable — but NOT dead — for epochs in
    ``[from_epoch, until_epoch)``, then recovers.

    A partition is distinct from :class:`kill_group` death in exactly
    the way the chaos plane needs stated: the workers keep computing,
    their results simply cannot cross the partition, and when it heals
    they answer again with no respawn. Modelled as a stall bounded by
    the partition's width (never the kill schedules' arbitrarily long
    one): a worker dispatched at epoch ``e`` inside the window stalls
    until the window closes. ``groups`` here is the sequence of
    worker-index sequences that ARE partitioned (pass a sub-list of
    the fleet partition). Pure in ``(worker, epoch)`` given
    ``epoch_s`` (the caller's virtual epoch pitch used to convert the
    remaining window width to stall seconds), a class so it pickles
    into process-backend workers.

    >>> sched = faults.partition([hosts[2], hosts[3]], from_epoch=10,
    ...                          until_epoch=16, epoch_s=0.1)
    """

    def __init__(self, groups, from_epoch: int, until_epoch: int, *,
                 epoch_s: float = 1.0):
        if until_epoch <= from_epoch:
            raise ValueError(
                f"need from_epoch < until_epoch, got "
                f"[{from_epoch}, {until_epoch})"
            )
        if epoch_s <= 0:
            raise ValueError(f"epoch_s must be > 0, got {epoch_s}")
        self._members = sorted(
            {int(w) for grp in groups for w in grp}
        )
        self._member_set = frozenset(self._members)
        self.from_epoch = int(from_epoch)
        self.until_epoch = int(until_epoch)
        self.epoch_s = float(epoch_s)

    def __call__(self, worker: int, epoch: int) -> float:
        if int(worker) not in self._member_set:
            return 0.0
        e = int(epoch)
        if not self.from_epoch <= e < self.until_epoch:
            return 0.0
        # stalled until the window closes: the result arrives the
        # moment the partition heals, never sooner and never lost
        return (self.until_epoch - e) * self.epoch_s


class from_trace:
    """Replay recorded per-worker latencies as a delay schedule.

    Closes the record -> replay loop: run a workload with an
    :class:`~.trace.EpochTracer`, ``dump_jsonl`` it, then re-create the
    same straggler pattern deterministically in any backend —
    reproducing a production incident under the thread backend, or
    A/B-ing scheduler changes (e.g. ``AdaptiveNwait``) against the
    exact latency pattern that hurt.

    Arrival times in the trace are measured *round-trips* (dispatch ->
    arrival, the reference's ``pool.latency`` quantity); replaying them
    as injected stalls reproduces the pattern up to the (small) true
    compute time of the replay workload. Workers/epochs absent from the
    trace replay with that worker's median recorded latency; workers
    never heard from at all replay as ``missing`` seconds (default: 10x
    the largest recorded latency), so absences stay stalls.

    A class (not a closure) so it pickles into process-backend workers.
    """

    def __init__(self, path, *, missing: float | None = None):
        import json

        with open(path) as f:
            records = [json.loads(line) for line in f]
        self._init_from_records(records, missing)

    @classmethod
    def from_records(cls, records, *, missing: float | None = None):
        """Build the schedule from already-loaded epoch records — the
        dict form of :meth:`~.trace.EpochRecord.to_dict` (what
        ``dump_jsonl`` writes line-by-line). The in-memory half of the
        record -> replay loop: :mod:`..sim.replay` feeds a live
        :class:`~.trace.EpochTracer`'s records straight in, no file
        round-trip."""
        self = cls.__new__(cls)
        self._init_from_records(list(records), missing)
        return self

    def _init_from_records(self, records, missing: float | None) -> None:
        by_key: dict[tuple[int, int], float] = {}
        longest = 0.0
        for rec in records:
            dispatched: dict[int, float] = {}
            for ev in rec.get("events", []):
                w = int(ev["worker"])
                if ev["kind"] in ("dispatch", "retask"):
                    dispatched[w] = float(ev["t"])
                elif ev["kind"] in ("arrival", "drain"):
                    t0 = dispatched.pop(w, None)
                    if t0 is not None:
                        lat = float(ev["t"]) - t0
                    else:
                        # dispatched in an earlier record (cross-
                        # epoch straggle): the record's latency
                        # snapshot holds this worker's measured
                        # round-trip (reference pool.latency field)
                        try:
                            lat = float(rec["latency_s"][w])
                        except (KeyError, IndexError):
                            continue
                    by_key[(w, int(ev["epoch"]))] = lat
                    longest = max(longest, lat)
        self._by_key = by_key
        # per-worker typical latency: the fallback when replay dispatch
        # epochs drift from the recorded ones (e.g. A/B-ing a different
        # nwait shifts when workers go idle) — the worker still replays
        # with ITS characteristic speed rather than the missing stall
        per_worker: dict[int, list[float]] = {}
        for (w, _e), lat in by_key.items():
            per_worker.setdefault(w, []).append(lat)
        self._per_worker = {
            w: float(np.median(v)) for w, v in per_worker.items()
        }
        # floor the default so a trace with no computable round-trips
        # (all workers stalled/dead) still replays absences as stalls,
        # never as instant workers
        self._missing = (
            max(10.0 * longest, 1.0) if missing is None else float(missing)
        )

    def __call__(self, worker: int, epoch: int) -> float:
        exact = self._by_key.get((worker, epoch))
        if exact is not None:
            return exact
        return self._per_worker.get(worker, self._missing)


def compose(*fns: DelayFn) -> DelayFn:
    """Sum of schedules (e.g. baseline jitter + one designated straggler)."""
    return lambda worker, epoch: sum(f(worker, epoch) for f in fns)


def failing(
    work_fn: Callable,
    *,
    workers: int | Sequence[int],
    epochs: int | Sequence[int] | None = None,
    error: Callable[[], BaseException] = lambda: RuntimeError("injected fault"),
):
    """Wrap a workload so designated workers *raise* at designated epochs.

    Returns a drop-in ``work_fn(worker, payload, epoch)``. ``epochs=None``
    means every epoch. The raise happens inside the worker; the backend
    captures it and the coordinator sees a ``WorkerFailure`` at harvest.
    """
    ws = {workers} if isinstance(workers, (int, np.integer)) else set(workers)
    es = (
        None
        if epochs is None
        else ({epochs} if isinstance(epochs, (int, np.integer)) else set(epochs))
    )

    def wrapped(worker, payload, epoch):
        if worker in ws and (es is None or epoch in es):
            raise error()
        return work_fn(worker, payload, epoch)

    return wrapped


class FaultSchedule:
    """Declarative scenario builder collecting delay + failure injections.

    >>> sched = (FaultSchedule(seed=7)
    ...          .jitter(0.001, 0.005)
    ...          .straggler(2, 0.2, every=3)
    ...          .dead_from(5, epoch=10))
    >>> backend = LocalBackend(work, n, delay_fn=sched.delay_fn)

    Keeps whole scenarios reproducible from one seed and printable for
    failure reports (``repr`` lists the stacked schedules).
    """

    def __init__(self, *, seed: int = 0):
        self.seed = seed
        self._fns: list[DelayFn] = []
        self._desc: list[str] = []

    def _add(self, fn: DelayFn, desc: str) -> "FaultSchedule":
        self._fns.append(fn)
        self._desc.append(desc)
        return self

    def jitter(self, lo: float, hi: float) -> "FaultSchedule":
        return self._add(
            seeded_uniform(lo, hi, seed=self.seed), f"jitter[{lo},{hi})"
        )

    def lognormal(self, median: float, sigma: float = 1.0) -> "FaultSchedule":
        return self._add(
            seeded_lognormal(median, sigma, seed=self.seed),
            f"lognormal(median={median},sigma={sigma})",
        )

    def straggler(
        self, workers, delay: float, *, every: int = 1, offset: int = 0
    ) -> "FaultSchedule":
        return self._add(
            straggler(workers, delay, every=every, offset=offset),
            f"straggler({workers},{delay}s,every={every})",
        )

    def intermittent(self, p: float, delay: float) -> "FaultSchedule":
        return self._add(
            intermittent(p, delay, seed=self.seed),
            f"intermittent(p={p},{delay}s)",
        )

    def dead_from(self, workers, epoch: int) -> "FaultSchedule":
        return self._add(
            dead_from(workers, epoch), f"dead_from({workers},epoch={epoch})"
        )

    def kill_group(self, groups, kills: Mapping[int, int]) -> "FaultSchedule":
        return self._add(
            kill_group(groups, kills),
            f"kill_group({dict(kills)})",
        )

    def correlated_kill(
        self, groups, *, epicenter: int, at_epoch: int, span: int = 2
    ) -> "FaultSchedule":
        return self._add(
            correlated_kill(groups, epicenter=epicenter,
                            at_epoch=at_epoch, span=span),
            f"correlated_kill(epicenter={epicenter},"
            f"at={at_epoch},span={span})",
        )

    def partition(
        self, groups, from_epoch: int, until_epoch: int, *,
        epoch_s: float = 1.0
    ) -> "FaultSchedule":
        return self._add(
            partition(groups, from_epoch, until_epoch,
                      epoch_s=epoch_s),
            f"partition([{from_epoch},{until_epoch}))",
        )

    @property
    def delay_fn(self) -> DelayFn:
        fns = list(self._fns)
        return lambda worker, epoch: sum(f(worker, epoch) for f in fns)

    def __repr__(self) -> str:
        return f"FaultSchedule(seed={self.seed}, [{', '.join(self._desc)}])"
