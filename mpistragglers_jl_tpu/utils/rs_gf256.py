"""Byte-exact systematic Reed-Solomon erasure codec over GF(2^8).

The complement to the float-field MDS code in ops/coding.py: that one
keeps encode/decode on the MXU (matmuls over reals) and is exact only to
float precision; this one is bit-exact for arbitrary byte payloads —
checkpoint shards, serialized host buffers, control messages. The pool's
``repochs`` arrival mask selects which k of the n coded shards feed the
decoder, exactly as in the float path (SURVEY §2.1: repochs is the
per-shard freshness oracle).

Backed by the native C++ codec (native/rs_gf256.cpp, compiled on first
use via ctypes); a pure-NumPy table-lookup implementation is the
automatic fallback when no compiler is available, selected at
construction and exposed as ``RSGF256.impl``.
"""

from __future__ import annotations

import ctypes
import warnings
from typing import Sequence

import numpy as np

__all__ = ["RSGF256"]

_PRIM = 0x11D


def _tables():
    """(exp[512], log[256], mul[256,256]) for GF(256), poly 0x11D."""
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.uint8)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _PRIM
    exp[255:510] = exp[:255]
    ia, ib = np.meshgrid(
        np.arange(256, dtype=np.int32), np.arange(256, dtype=np.int32),
        indexing="ij",
    )
    mul = exp[(log[ia].astype(np.int32) + log[ib].astype(np.int32))]
    mul[0, :] = 0
    mul[:, 0] = 0
    return exp, log, mul


_EXP, _LOG, _MUL = _tables()


def _gf_inv(a: int) -> int:
    return int(_EXP[255 - int(_LOG[a])])


def _np_matmul(M: np.ndarray, data: np.ndarray) -> np.ndarray:
    """(rows, k) x (k, len) over GF(256), via the 64 KiB product table."""
    rows, k = M.shape
    out = np.zeros((rows, data.shape[1]), dtype=np.uint8)
    for i in range(rows):
        for j in range(k):
            c = int(M[i, j])
            if c:
                out[i] ^= _MUL[c][data[j]]
    return out


def _np_invert(A: np.ndarray) -> np.ndarray:
    k = A.shape[0]
    work = A.astype(np.uint8).copy()
    inv = np.eye(k, dtype=np.uint8)
    for col in range(k):
        piv = next((r for r in range(col, k) if work[r, col]), None)
        if piv is None:
            raise np.linalg.LinAlgError("singular over GF(256)")
        if piv != col:
            work[[col, piv]] = work[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        ip = _gf_inv(int(work[col, col]))
        work[col] = _MUL[ip][work[col]]
        inv[col] = _MUL[ip][inv[col]]
        for r in range(k):
            if r == col:
                continue
            c = int(work[r, col])
            if c:
                work[r] ^= _MUL[c][work[col]]
                inv[r] ^= _MUL[c][inv[col]]
    return inv


def _configure(lib):
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.rs_make_generator.argtypes = [ctypes.c_int, ctypes.c_int, u8p]
    lib.rs_make_generator.restype = ctypes.c_int
    lib.rs_encode.argtypes = [
        ctypes.c_int, ctypes.c_int, u8p, u8p, u8p, ctypes.c_long,
    ]
    lib.rs_encode.restype = ctypes.c_int
    lib.rs_decode.argtypes = [
        ctypes.c_int, ctypes.c_int, u8p, i32p, u8p, u8p, ctypes.c_long,
    ]
    lib.rs_decode.restype = ctypes.c_int


def _load_native():
    """Memoized (success and failure) via :func:`..native.load`."""
    from .. import native

    return native.load("rs_gf256", _configure)


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class RSGF256:
    """Systematic (n, k) Cauchy-RS codec over bytes.

    >>> rs = RSGF256(n=8, k=6)
    >>> coded = rs.encode(data)            # (6, L) uint8 -> (8, L)
    >>> back = rs.decode(coded[idx], idx)  # any 6 distinct rows -> (6, L)

    ``impl`` is ``"native"`` (C++ via ctypes) or ``"numpy"`` (fallback).
    The generator is identical for both, so shards encoded by one decode
    bit-exactly under the other.
    """

    def __init__(self, n: int, k: int, *, prefer_native: bool = True):
        if not 0 < k <= n or n > 256:
            raise ValueError(
                f"need 0 < k <= n <= 256, got n={n}, k={k}"
            )
        self.n, self.k = int(n), int(k)
        self._lib = None
        if prefer_native:
            try:
                self._lib = _load_native()
            except Exception as e:  # no compiler / bad toolchain
                warnings.warn(
                    f"native rs_gf256 unavailable ({e}); using numpy "
                    "fallback",
                    RuntimeWarning,
                    stacklevel=2,
                )
        self.G = self._make_generator()

    @property
    def impl(self) -> str:
        return "native" if self._lib is not None else "numpy"

    def _make_generator(self) -> np.ndarray:
        n, k = self.n, self.k
        if self._lib is not None:
            G = np.zeros((n, k), dtype=np.uint8)
            rc = self._lib.rs_make_generator(n, k, _u8p(G))
            if rc != 0:
                raise RuntimeError(f"rs_make_generator failed rc={rc}")
            return G
        G = np.zeros((n, k), dtype=np.uint8)
        G[:k] = np.eye(k, dtype=np.uint8)
        for i in range(n - k):
            for j in range(k):
                G[k + i, j] = _gf_inv((k + i) ^ j)
        return G

    def _check_data(self, data, rows: int) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.ndim != 2 or data.shape[0] != rows:
            raise ValueError(
                f"expected ({rows}, L) uint8 array, got {data.shape}"
            )
        return data

    def encode(self, data) -> np.ndarray:
        """(k, L) source bytes -> (n, L) coded shards (first k = source)."""
        data = self._check_data(data, self.k)
        L = data.shape[1]
        if self._lib is not None:
            coded = np.empty((self.n, L), dtype=np.uint8)  # rs_encode memsets
            rc = self._lib.rs_encode(
                self.n, self.k, _u8p(self.G), _u8p(data), _u8p(coded), L
            )
            if rc != 0:
                raise RuntimeError(f"rs_encode failed rc={rc}")
            return coded
        return _np_matmul(self.G, data)

    def decode(self, shards, indices: Sequence[int]) -> np.ndarray:
        """Any k distinct coded rows -> the (k, L) source bytes, exactly."""
        idx = np.asarray(indices, dtype=np.int32)
        if idx.shape[0] != self.k or len(set(idx.tolist())) != self.k:
            raise ValueError(
                f"need exactly k={self.k} distinct indices, got {idx}"
            )
        if idx.min() < 0 or idx.max() >= self.n:
            raise ValueError(f"indices out of range [0, {self.n}): {idx}")
        shards = self._check_data(shards, self.k)
        L = shards.shape[1]
        if self._lib is not None:
            out = np.zeros((self.k, L), dtype=np.uint8)
            rc = self._lib.rs_decode(
                self.n, self.k, _u8p(self.G),
                idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                _u8p(shards), _u8p(out), L,
            )
            if rc != 0:
                raise RuntimeError(f"rs_decode failed rc={rc}")
            return out
        inv = _np_invert(self.G[idx])
        return _np_matmul(inv, shards)

    def encode_bytes(self, payload: bytes) -> tuple[np.ndarray, int]:
        """Pad+split a byte string into k source rows and encode.
        Returns (coded (n, L), original length) for :meth:`decode_bytes`."""
        raw = np.frombuffer(payload, dtype=np.uint8)
        L = -(-max(raw.size, 1) // self.k)
        data = np.zeros((self.k, L), dtype=np.uint8)
        data.reshape(-1)[: raw.size] = raw
        return self.encode(data), raw.size

    def decode_bytes(self, shards, indices, length: int) -> bytes:
        """Inverse of :meth:`encode_bytes`."""
        return self.decode(shards, indices).reshape(-1)[:length].tobytes()
