"""Structured per-epoch tracing and metrics.

The reference's only observability is a per-worker round-trip latency
field (``pool.latency``, reference src/MPIAsyncPools.jl:104-105,:136,
:163-164) — no tracer, no timeline, no export (SURVEY §5 "Metrics /
logging: absent"). This module is the replacement subsystem: an
:class:`EpochTracer` passed to ``asyncmap``/``waitall`` records every
dispatch and arrival with monotonic timestamps, per-epoch wall-clock,
freshness outcomes and re-task counts, and exports JSONL timelines plus
aggregate straggler statistics.

Zero overhead when unused: the pool only calls the tracer if one is
passed, and every hook is a plain method call recording into Python
lists (no locks — the coordinator loop is single-threaded, mirroring the
reference's single-threaded design, SURVEY §5 "Race detection").
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

__all__ = ["EpochTracer", "EpochRecord", "Event"]


@dataclass
class Event:
    """One dispatch/arrival/re-task, timestamped relative to epoch begin."""

    t: float  # seconds since epoch begin
    kind: str  # "dispatch" | "arrival" | "retask" | "drain"
    worker: int
    epoch: int  # epoch the payload/result is stamped with
    fresh: bool | None = None  # arrivals only: stamped with current epoch?

    def to_dict(self) -> dict[str, Any]:
        d = {
            "t": round(self.t, 9),
            "kind": self.kind,
            "worker": self.worker,
            "epoch": self.epoch,
        }
        if self.fresh is not None:
            d["fresh"] = self.fresh
        return d


@dataclass
class EpochRecord:
    """Everything that happened inside one ``asyncmap``/``waitall`` call."""

    epoch: int
    call: str  # "asyncmap" | "waitall"
    nwait: Any  # int or "<callable>"
    t_begin: float  # monotonic clock at call entry
    events: list[Event] = field(default_factory=list)
    wall: float = 0.0  # call duration, seconds
    n_fresh: int = 0  # arrivals stamped with this epoch
    n_stale: int = 0  # arrivals carrying an older stamp
    n_retask: int = 0  # immediate re-dispatches after stale arrivals
    repochs: list[int] = field(default_factory=list)  # snapshot at return
    latency: list[float] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return {
            "epoch": self.epoch,
            "call": self.call,
            "nwait": self.nwait,
            "wall_s": round(self.wall, 9),
            "n_fresh": self.n_fresh,
            "n_stale": self.n_stale,
            "n_retask": self.n_retask,
            "repochs": self.repochs,
            "latency_s": [round(x, 9) for x in self.latency],
            "events": [e.to_dict() for e in self.events],
        }


class EpochTracer:
    """Records a timeline of pool activity across epochs.

    >>> tracer = EpochTracer()
    >>> asyncmap(pool, payload, backend, tracer=tracer)
    >>> tracer.records[-1].n_fresh
    >>> tracer.dump_jsonl("trace.jsonl")
    >>> tracer.summary()["straggler_rate"]
    """

    def __init__(self) -> None:
        self.records: list[EpochRecord] = []
        self._open: EpochRecord | None = None

    # -- hooks called by pool.asyncmap / pool.waitall ---------------------
    def begin(self, call: str, epoch: int, nwait: Any) -> None:
        self._open = EpochRecord(
            epoch=int(epoch),
            call=call,
            nwait=int(nwait) if isinstance(nwait, (int, np.integer))
            else "<callable>",
            t_begin=time.perf_counter(),
        )

    def _now(self) -> float:
        assert self._open is not None
        return time.perf_counter() - self._open.t_begin

    def dispatch(self, worker: int, epoch: int, *, retask: bool = False) -> None:
        r = self._open
        if r is None:
            return
        kind = "retask" if retask else "dispatch"
        r.events.append(Event(self._now(), kind, int(worker), int(epoch)))
        if retask:
            r.n_retask += 1

    def arrival(
        self, worker: int, repoch: int, *, fresh: bool, drain: bool = False
    ) -> None:
        r = self._open
        if r is None:
            return
        kind = "drain" if drain else "arrival"
        fresh = bool(fresh)
        r.events.append(
            Event(self._now(), kind, int(worker), int(repoch), fresh=fresh)
        )
        if fresh:
            r.n_fresh += 1
        else:
            r.n_stale += 1

    def end(self, pool) -> None:
        r = self._open
        if r is None:
            return
        r.wall = self._now()
        r.repochs = [int(x) for x in pool.repochs]
        r.latency = [float(x) for x in pool.latency]
        self.records.append(r)
        self._open = None

    # -- export / analysis ------------------------------------------------
    def dump_jsonl(self, path) -> None:
        """One JSON object per epoch record."""
        with open(path, "w") as f:
            for r in self.records:
                f.write(json.dumps(r.to_dict()) + "\n")

    def chrome_events(
        self, pid: int = 0
    ) -> tuple[list[dict[str, Any]], list[dict[str, Any]]]:
        """(metadata events, span events) in Chrome trace-event form
        under process ``pid`` — the merge contract consumed by
        :func:`~..obs.timeline.dump_merged_chrome_trace`, which lays a
        pool timeline beside scheduler/training span recorders. One
        track per worker with a span per task (dispatch -> arrival,
        stale spans flagged), plus a coordinator track with one span
        per ``asyncmap``/``waitall`` call.

        Spans may cross record boundaries: a payload dispatched in epoch
        N and drained in epoch N+1 (the reference's late-arrival harvest,
        src/MPIAsyncPools.jl:91-114) is drawn over its true lifetime.
        """
        us = 1e6
        events: list[dict[str, Any]] = []
        open_dispatch: dict[int, tuple[float, int]] = {}  # worker -> (t_abs, epoch)
        for r in self.records:
            events.append({
                "name": f"{r.call}(epoch={r.epoch}, nwait={r.nwait})",
                "ph": "X", "pid": pid, "tid": -1,
                "ts": r.t_begin * us, "dur": r.wall * us,
                "args": {"n_fresh": r.n_fresh, "n_stale": r.n_stale,
                         "n_retask": r.n_retask},
            })
            for e in r.events:
                t_abs = r.t_begin + e.t
                if e.kind in ("dispatch", "retask"):
                    open_dispatch[e.worker] = (t_abs, e.epoch)
                else:  # arrival / drain
                    start = open_dispatch.pop(e.worker, None)
                    if start is None:
                        continue
                    t0, sepoch = start
                    events.append({
                        "name": f"epoch {sepoch}"
                        + ("" if e.fresh else " (stale)"),
                        "ph": "X", "pid": pid, "tid": e.worker,
                        "ts": t0 * us, "dur": (t_abs - t0) * us,
                        "args": {"fresh": bool(e.fresh), "kind": e.kind},
                    })
        meta = [
            {"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": "pool"}},
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": -1,
             "args": {"name": "coordinator"}},
        ] + [
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": w,
             "args": {"name": f"worker {w}"}}
            for w in sorted({e["tid"] for e in events if e["tid"] >= 0})
        ]
        return meta, events

    def dump_chrome_trace(self, path) -> int:
        """Export the timeline in Chrome trace-event format (open in
        ui.perfetto.dev or chrome://tracing) — see :meth:`chrome_events`
        for the track layout. Returns the number of events written."""
        meta, events = self.chrome_events()
        with open(path, "w") as f:
            json.dump({"traceEvents": meta + events}, f)
        return len(events)

    def summary(self) -> dict[str, Any]:
        """Aggregate statistics over ALL recorded calls.

        Arrival totals span asyncmap AND waitall records: a dispatch
        harvested only by a later ``waitall`` used to vanish from the
        accounting entirely (counted dispatched, its arrival dropped),
        so a traced ``fit()`` loop under-reported stale results by
        exactly its shutdown drain. ``n_waitall_arrivals`` breaks those
        drains out, and ``delivered_rate`` is the fraction of
        dispatches that eventually produced ANY arrival in the trace
        (< 1 means tasks were still in flight when tracing stopped).

        ``straggler_rate`` keeps its original meaning — the fraction of
        dispatches that did NOT come back fresh within their own
        ``asyncmap`` epoch (the straggle the pool absorbed); a waitall
        drain arriving after the fastest-k cut is still a straggle, it
        just no longer disappears from ``n_fresh``/``n_stale``.

        Asyncmap-only fields (a waitall drains whatever is in flight —
        its wall measures the drain, and its arrivals' round-trips span
        call boundaries): ``epochs``, ``wall_total/mean/p95_s``,
        ``arrival_p50/p95_s`` (fresh within-epoch round-trips). A
        waitall-only trace (a tracer attached just to a shutdown drain)
        still reports the full key set — ``epochs`` 0, wall/arrival
        fields None, the arrival totals real.
        """
        if not self.records:
            return {"epochs": 0}
        maps = [r for r in self.records if r.call == "asyncmap"]
        waits = [r for r in self.records if r.call == "waitall"]
        walls = np.array([r.wall for r in maps])
        lat = np.array(
            [
                e.t
                for r in maps
                for e in r.events
                if e.kind == "arrival" and e.fresh
            ]
        )
        dispatched = sum(
            1 for r in maps for e in r.events if e.kind in ("dispatch", "retask")
        )
        fresh_in_epoch = sum(r.n_fresh for r in maps)
        fresh = fresh_in_epoch + sum(r.n_fresh for r in waits)
        stale = sum(r.n_stale for r in self.records)
        return {
            "epochs": len(maps),
            "wall_total_s": float(walls.sum()) if maps else None,
            "wall_mean_s": float(walls.mean()) if maps else None,
            "wall_p95_s": float(np.percentile(walls, 95))
            if maps else None,
            "n_dispatched": dispatched,
            "n_fresh": fresh,
            "n_stale": stale,
            "n_retask": sum(r.n_retask for r in self.records),
            "n_waitall_arrivals": sum(
                r.n_fresh + r.n_stale for r in waits
            ),
            "straggler_rate": float(1.0 - fresh_in_epoch / dispatched)
            if dispatched
            else 0.0,
            "delivered_rate": float(min(fresh + stale, dispatched) / dispatched)
            if dispatched
            else 0.0,
            "arrival_p50_s": float(np.percentile(lat, 50)) if lat.size else None,
            "arrival_p95_s": float(np.percentile(lat, 95)) if lat.size else None,
        }

    def __repr__(self) -> str:
        return f"EpochTracer({len(self.records)} records)"
