"""Straggler latency modeling and straggle-aware scheduling.

The reference measures per-worker round-trip latency (``pool.latency``,
reference src/MPIAsyncPools.jl:104-105,:163-164) and then leaves every
scheduling decision to the caller: ``nwait`` is a constant the user picks
by hand in every test and example (test/kmap2.jl:32, :57,
examples/iterative_example.jl:40). This module closes that loop — it
turns the latency samples the pool already produces into decisions:

* :class:`PoolLatencyModel` — online per-worker shifted-exponential fits
  (the standard model for straggling compute nodes: a deterministic
  service floor plus an exponential tail) from ``pool.latency``.
* :meth:`PoolLatencyModel.expected_epoch_time` — E[time until the k
  fastest of the n heterogeneous workers respond] (k-th order statistic),
  by Monte-Carlo over the fitted per-worker distributions.
* :meth:`PoolLatencyModel.optimal_nwait` — the ``nwait`` minimizing
  expected time per fresh result (or any caller utility), the knob that
  trades straggler-avoidance against discarded work in coded workloads.
* :meth:`PoolLatencyModel.proportional_shares` — load-balanced work
  splits proportional to fitted worker speed, for uncoded workloads
  where shard sizes are free parameters.
* :class:`AdaptiveNwait` — drop-in controller: observe after each
  ``asyncmap``, pass ``controller.nwait`` to the next one.

Everything is coordinator-side numpy over data the pool already tracks;
no backend cooperation needed, deterministic given a seeded generator.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

__all__ = ["WorkerStats", "PoolLatencyModel", "AdaptiveNwait"]


class WorkerStats:
    """Online latency statistics for one worker (Welford + running min).

    The fitted model is a shifted exponential ``shift + Exp(rate)``:
    ``shift`` is the service floor (estimated by the sample minimum,
    which converges at rate 1/m, much faster than the mean), and the
    exponential tail rate comes from the residual mean
    ``1 / (mean - shift)``.

    ``change_detect=True`` arms a two-sided CUSUM on standardized
    residuals (Page's test: ``S+ <- max(0, S+ + r - drift)`` and
    symmetrically for ``S-``). When either side crosses ``threshold``
    the worker's regime has shifted — a straggler moved onto or off
    this rank — and the fit restarts from the triggering sample
    instead of averaging two regimes forever (the round-2 failure
    mode: the controller paid 1.65x the oracle on a rotating straggler
    because Welford means lag a moved straggler by their whole
    history — VERDICT r2 weak #4).

    Default drift/threshold are tuned for the *skewed* exponential
    tail, not the gaussian textbook values: at (drift=0.5, h=5) the
    one-sided residual skew fires falsely on 97% of 500-sample
    stationary shifted-exp traces; (drift=1.5, h=8, warmup 8) measures
    0/100 false alarms at 50 samples, 4/100 at 500, while still
    detecting a straggler-sized (15x) shift on the very next sample.
    """

    def __init__(
        self,
        *,
        change_detect: bool = False,
        cusum_drift: float = 1.5,
        cusum_threshold: float = 8.0,
        cusum_min_count: int = 8,
    ) -> None:
        self.count = 0
        self.mean = 0.0
        self._m2 = 0.0
        self.min = np.inf
        self.change_detect = bool(change_detect)
        self.cusum_drift = float(cusum_drift)
        self.cusum_threshold = float(cusum_threshold)
        self.cusum_min_count = int(cusum_min_count)
        self._sp = 0.0
        self._sn = 0.0
        self.resets = 0  # change-points detected over this stats' life

    def _restart(self) -> None:
        self.count = 0
        self.mean = 0.0
        self._m2 = 0.0
        self.min = np.inf
        self._sp = 0.0
        self._sn = 0.0

    def observe(self, latency: float) -> bool:
        """Ingest one sample; returns True iff a change-point fired
        (the fit was restarted — the triggering sample becomes the
        first of the new regime)."""
        x = float(latency)
        if not np.isfinite(x) or x < 0:
            return False
        shifted = False
        if self.change_detect and self.count >= self.cusum_min_count:
            # std floor: a worker whose samples sit at the service
            # floor has var ~ 0; 5% of mean keeps r finite while still
            # firing within a couple of samples on a real regime shift
            std = max(
                float(np.sqrt(self.var)), 0.05 * max(self.mean, 1e-9)
            )
            r = (x - self.mean) / std
            self._sp = max(0.0, self._sp + r - self.cusum_drift)
            self._sn = max(0.0, self._sn - r - self.cusum_drift)
            if (
                self._sp > self.cusum_threshold
                or self._sn > self.cusum_threshold
            ):
                self._restart()
                self.resets += 1
                shifted = True
        self.count += 1
        delta = x - self.mean
        self.mean += delta / self.count
        self._m2 += delta * (x - self.mean)
        if x < self.min:
            self.min = x
        return shifted

    @property
    def var(self) -> float:
        return self._m2 / (self.count - 1) if self.count > 1 else 0.0

    @property
    def shift(self) -> float:
        return 0.0 if self.count == 0 else float(self.min)

    @property
    def rate(self) -> float:
        """Exponential tail rate; inf for a worker with no observed tail
        (all samples at the floor)."""
        if self.count == 0:
            return np.inf
        tail = self.mean - self.shift
        return np.inf if tail <= 0 else 1.0 / tail

    def sample(self, rng: np.random.Generator, size: int) -> np.ndarray:
        if self.count == 0:
            return np.zeros(size)
        rate = self.rate
        if not np.isfinite(rate):
            return np.full(size, self.shift)
        return self.shift + rng.exponential(1.0 / rate, size)

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "mean_s": self.mean,
            "std_s": float(np.sqrt(self.var)),
            "shift_s": self.shift if self.count else None,
            "rate_hz": None if not np.isfinite(self.rate) else self.rate,
        }


class PoolLatencyModel:
    """Per-worker latency models for an n-worker pool.

    Feed it after every ``asyncmap``/``waitall`` with
    :meth:`observe_pool` (it reads ``pool.latency`` for workers that
    delivered since the last call) or directly with :meth:`observe`.

    >>> model = PoolLatencyModel(pool.n_workers)
    >>> repochs = asyncmap(pool, payload, backend, nwait=model_k)
    >>> model.observe_pool(pool)
    >>> model.optimal_nwait()          # nwait minimizing time/result
    >>> model.expected_epoch_time(6)   # predicted wall for nwait=6
    """

    def __init__(
        self, n_workers: int, *, seed: int = 0,
        change_detect: bool = False,
    ):
        self.n_workers = int(n_workers)
        self.workers = [
            WorkerStats(change_detect=change_detect)
            for _ in range(self.n_workers)
        ]
        self._seed = int(seed)
        # repochs snapshot from the previous observe_pool: only workers
        # whose repochs advanced have a *new* latency sample
        self._last_repochs = None
        # workers whose CUSUM fired during the last observe/observe_pool
        # — only THAT worker's fit restarted, everyone else's history
        # stands (the per-worker reset VERDICT r2 item 7 asked for)
        self.shifted_last_observe: list[int] = []

    # -- data intake -------------------------------------------------------
    def observe(self, worker: int, latency: float) -> None:
        self.shifted_last_observe = (
            [worker] if self.workers[worker].observe(latency) else []
        )

    def observe_pool(self, pool) -> int:
        """Record latency samples for workers whose ``repochs`` advanced
        since the previous call; returns how many samples were taken."""
        rep = np.asarray(pool.repochs)
        if self._last_repochs is None:
            newly = [i for i in range(self.n_workers) if pool.results[i] is not None]
        else:
            newly = [
                i for i in range(self.n_workers)
                if rep[i] != self._last_repochs[i]
            ]
        self.shifted_last_observe = [
            i for i in newly if self.workers[i].observe(pool.latency[i])
        ]
        self._last_repochs = rep.copy()
        return len(newly)

    # -- prediction --------------------------------------------------------
    def sample_latencies(self, n_draws: int) -> np.ndarray:
        """(n_draws, n_workers) matrix of sampled per-worker latencies.

        Workers never heard from sample from the pooled prior (mean
        shift/rate of the observed workers) rather than zero — a silent
        worker must not look infinitely fast to ``optimal_nwait``.

        Determinism contract (ISSUE 5 satellite — the original
        implementation FAILED it and was fixed): predictions are pure
        functions of the fitted state and the constructor ``seed``. The
        draw generator is re-seeded per call, so calling
        ``sample_latencies`` / ``expected_epoch_time`` /
        ``optimal_nwait`` twice on an unchanged model returns identical
        results (previously a shared generator advanced across calls,
        so two consecutive ``optimal_nwait`` calls could disagree near
        a utility tie — non-reproducible nwait decisions). This also
        makes ``optimal_nwait``'s SLO sweep monotonic: every candidate
        k is priced on the SAME draw matrix.
        """
        rng = np.random.default_rng(self._seed)
        observed = [w for w in self.workers if w.count > 0]
        prior = None
        if observed:
            prior = WorkerStats()
            for w in observed:
                # moment-match the pool average: same mean and floor
                prior.count += 1
                prior.mean += (w.mean - prior.mean) / prior.count
                prior.min = min(prior.min, w.min)
        cols = [
            (w if w.count > 0 else prior or w).sample(rng, n_draws)
            for w in self.workers
        ]
        return np.stack(cols, axis=1)

    def expected_epoch_time(
        self, nwait: int, *, n_draws: int = 4000
    ) -> float:
        """E[wall-clock until the ``nwait`` fastest workers respond] —
        the mean ``nwait``-th order statistic over the heterogeneous
        fitted distributions (Monte Carlo; closed forms only exist for
        the iid case)."""
        if not (0 <= nwait <= self.n_workers):
            raise ValueError(f"nwait must be in [0, {self.n_workers}]")
        if nwait == 0:
            return 0.0
        draws = self.sample_latencies(n_draws)
        kth = np.partition(draws, nwait - 1, axis=1)[:, nwait - 1]
        return float(kth.mean())

    def optimal_nwait(
        self,
        *,
        utility: Callable[[int], float] | None = None,
        kmin: int = 1,
        kmax: int | None = None,
        slo: float | None = None,
        n_draws: int = 4000,
    ) -> int:
        """The ``nwait`` maximizing ``utility(k) / E[T_(k)]`` (utility per
        second). Default utility is ``k`` — fresh results per epoch — so
        the default objective is minimum expected time per fresh result,
        the natural knob for (n, k)-coded workloads where waiting for
        more shards amortizes the service floor but exposes the epoch to
        deeper order statistics.

        ``kmin`` is the decodability floor: the returned ``nwait`` is
        NEVER below it, under any ``slo`` — fewer than k fresh shards
        cannot decode, so a floor violation would trade latency for
        correctness.

        ``slo`` (seconds, optional) caps expected epoch time: only
        candidates with ``E[T_(k)] <= slo`` compete; if none qualifies
        (the SLO is unachievable even at the floor), the floor ``kmin``
        — the cheapest decodable wait — is returned rather than an
        infeasible pretense. Because ``E[T_(k)]`` is non-decreasing in
        k and every candidate is priced on the same deterministic draw
        matrix (see :meth:`sample_latencies`), the result is monotonic
        non-decreasing in ``slo``: loosening a latency target can only
        admit deeper waits, never retract one (seeded property test in
        tests/test_straggle.py).
        """
        kmax = self.n_workers if kmax is None else int(kmax)
        if not (1 <= kmin <= kmax <= self.n_workers):
            raise ValueError(
                f"need 1 <= kmin <= kmax <= {self.n_workers}, "
                f"got [{kmin}, {kmax}]"
            )
        u = (lambda k: float(k)) if utility is None else utility
        draws = self.sample_latencies(n_draws)
        draws.sort(axis=1)
        best_k, best_score = kmin, -np.inf
        for k in range(kmin, kmax + 1):
            t = float(draws[:, k - 1].mean())
            if slo is not None and t > slo and k > kmin:
                # E[T_(k)] is non-decreasing in k on the sorted draw
                # matrix: every deeper candidate busts the SLO too
                break
            score = u(k) / t if t > 0 else np.inf
            if slo is not None and t > slo:
                # the floor itself busts the SLO: it stays the fallback
                # (decodability beats the latency target) but must not
                # outscore a feasible deeper candidate
                score = -np.inf
            if score > best_score:
                best_k, best_score = k, score
        return best_k

    def proportional_shares(self, total: int) -> np.ndarray:
        """Split ``total`` work units across workers proportional to
        fitted speed (1/mean latency), by largest remainder — the
        load-balancing split for uncoded workloads. Workers without
        samples get the mean share."""
        means = np.array(
            [w.mean if w.count else np.nan for w in self.workers]
        )
        if np.isnan(means).all():
            means = np.ones(self.n_workers)
        else:
            means = np.where(np.isnan(means), np.nanmean(means), means)
        speed = 1.0 / np.maximum(means, 1e-12)
        ideal = total * speed / speed.sum()
        shares = np.floor(ideal).astype(np.int64)
        rem = int(total - shares.sum())
        if rem > 0:
            order = np.argsort(-(ideal - shares))
            shares[order[:rem]] += 1
        return shares

    def summary(self) -> list[dict]:
        return [w.to_dict() for w in self.workers]

    def publish(self, registry, *, prefix: str = "pool_worker") -> None:
        """Write the current per-worker fits into ``registry`` gauges
        (one ``worker=<i>``-labeled series per instrument): sample
        count, fitted mean, service floor (``shift``), exponential tail
        rate, and CUSUM change-point resets. Call after
        :meth:`observe_pool` at whatever cadence the scrape needs —
        gauges overwrite, so the registry always shows the live fit and
        the model's internals stay private. A worker with no tail
        (``rate == inf``) publishes rate 0 (Prometheus has no inf
        convention for "all samples at the floor")."""
        for i, w in enumerate(self.workers):
            lbl = {"worker": str(i)}
            registry.gauge(
                f"{prefix}_latency_samples",
                help="latency samples in the current fit", **lbl,
            ).set(w.count)
            registry.gauge(
                f"{prefix}_latency_mean_seconds",
                help="fitted mean round-trip", **lbl,
            ).set(w.mean)
            registry.gauge(
                f"{prefix}_latency_floor_seconds",
                help="fitted service floor (shift)", **lbl,
            ).set(w.shift)
            rate = w.rate
            registry.gauge(
                f"{prefix}_latency_tail_rate_hz",
                help="fitted exponential tail rate (0 = no tail "
                "observed)", **lbl,
            ).set(0.0 if not np.isfinite(rate) else rate)
            registry.gauge(
                f"{prefix}_cusum_resets",
                help="change-points detected on this worker", **lbl,
            ).set(w.resets)


class AdaptiveNwait:
    """Epoch-to-epoch ``nwait`` controller.

    Starts at ``nwait0`` (default n), refits every ``refit_every``
    observed epochs once ``min_samples`` per-worker samples exist, and
    exposes the current choice as ``.nwait``:

    >>> ctl = AdaptiveNwait(pool.n_workers, kmin=code.k)
    >>> for step in range(epochs):
    ...     asyncmap(pool, payload, backend, nwait=ctl.nwait)
    ...     ctl.observe(pool)

    ``kmin`` is the correctness floor — for an (n, k) code, fewer than k
    fresh shards cannot decode, so the controller never goes below it.
    """

    def __init__(
        self,
        n_workers: int,
        *,
        kmin: int = 1,
        kmax: int | None = None,
        nwait0: int | None = None,
        utility: Callable[[int], float] | None = None,
        min_samples: int = 3,
        refit_every: int = 5,
        seed: int = 0,
        change_detect: bool = True,
    ):
        self.model = PoolLatencyModel(
            n_workers, seed=seed, change_detect=change_detect
        )
        self.kmin = int(kmin)
        self.kmax = n_workers if kmax is None else int(kmax)
        self.utility = utility
        self.min_samples = int(min_samples)
        self.refit_every = int(refit_every)
        self.nwait = self.kmax if nwait0 is None else int(nwait0)
        self._observed = 0
        self._shift_boost = 0  # epochs of forced refitting after a shift
        self._fitted_once = False  # first fit fires at quorum, not cadence

    def observe(self, pool) -> int:
        """Feed the model; periodically re-pick ``nwait``. Returns the
        current choice.

        Refitting needs a *quorum* of fitted workers — at least
        ``max(kmin, 2)`` with ``min_samples`` each — not all of them: a
        rank that dies early (or is never heard from) must not disable
        adaptation in exactly the failure regime the controller exists
        for; silent workers are modeled by the pooled prior.

        A CUSUM change-point on any worker (``change_detect``, default
        on) restarts only that worker's fit and switches the controller
        to refit-every-epoch for the next ``refit_every`` epochs, so
        the decision catches up with the new regime at sample speed
        instead of waiting out the cadence (VERDICT r2 item 7)."""
        self.model.observe_pool(pool)
        self._observed += 1
        if self.model.shifted_last_observe:
            self._shift_boost = self.refit_every
        fitted = sum(
            w.count >= self.min_samples for w in self.model.workers
        )
        ready = fitted >= max(self.kmin, 2)
        due = self._observed % self.refit_every == 0
        if ready and (due or self._shift_boost > 0 or not self._fitted_once):
            # the FIRST fit fires the moment the quorum exists — gating
            # it on the cadence would leave the controller at nwait0
            # (full gather) for up to refit_every straggler-priced
            # epochs of pure startup cost
            self.nwait = self.model.optimal_nwait(
                utility=self.utility, kmin=self.kmin, kmax=self.kmax
            )
            self._fitted_once = True
        if self._shift_boost > 0:
            self._shift_boost -= 1
        return self.nwait
