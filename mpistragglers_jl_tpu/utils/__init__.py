"""Auxiliary subsystems: tracing, fault injection, checkpoint, codecs.

All of these are new capability relative to the reference, which has no
observability beyond ``pool.latency``, no deterministic fault injection
(random ``sleep`` only), and no checkpointing (SURVEY §5).
"""

from . import faults
from .trace import EpochTracer, EpochRecord, Event
from .checkpoint import state_dict, load_state_dict, save, restore
from .rs_gf256 import RSGF256
from .straggle import AdaptiveNwait, PoolLatencyModel, WorkerStats
from .coded_checkpoint import CodedCheckpoint, CheckpointCorrupt
from .hedge import HedgedServer, RequestHedge

__all__ = [
    "faults",
    "HedgedServer",
    "RequestHedge",
    "AdaptiveNwait",
    "PoolLatencyModel",
    "WorkerStats",
    "EpochTracer",
    "EpochRecord",
    "Event",
    "state_dict",
    "load_state_dict",
    "save",
    "restore",
    "RSGF256",
    "CodedCheckpoint",
    "CheckpointCorrupt",
    "TrainCheckpointer",
]


def __getattr__(name):
    # lazy: TrainCheckpointer pulls in jax (and orbax); the rest of the
    # utils package stays importable numpy-only
    if name == "TrainCheckpointer":
        from .train_checkpoint import TrainCheckpointer

        return TrainCheckpointer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
