"""Erasure-coded checkpoints: survive losing any n-k shard files.

The coded-computation layer protects *compute* against stragglers; this
module applies the same any-k-of-n idea to checkpoint *storage*. A
pytree is packed to bytes, split into k source blocks, RS(n, k)-encoded
with the byte-exact GF(256) codec (bit-identical native/NumPy/device
implementations — utils/rs_gf256.py, ops/gf256_device.py), and written
as n shard files plus a manifest. Restore reads whichever shards are
present and uncorrupted (each shard carries a CRC32; bad files are
detected and excluded like stale pool results are masked by ``repochs``)
and decodes from any k of them.

Use cases: one shard per worker host (no host is critical), or n shards
on one flaky filesystem (tolerates n-k lost/corrupt files) — capability
the reference does not have in any form (SURVEY §5 "Checkpoint /
resume: absent").

>>> cc = CodedCheckpoint(n=5, k=3)
>>> cc.save(dir, {"w": w, "step": 7})
>>> # delete/corrupt any 2 of the 5 shard files...
>>> state = cc.restore(dir, target={"w": w_like, "step": 0})
"""

from __future__ import annotations

import io
import json
import os
import uuid
import zlib
from typing import Any

import numpy as np

from .rs_gf256 import RSGF256

__all__ = ["CodedCheckpoint", "CheckpointCorrupt"]

_MANIFEST = "manifest.json"
_FORMAT = "mpistragglers_jl_tpu.coded-ckpt-v1"


class CheckpointCorrupt(RuntimeError):
    """Too few intact shards to decode (``have`` < ``need``)."""

    def __init__(self, have: int, need: int, detail: str):
        self.have, self.need = have, need
        super().__init__(
            f"only {have} intact shards, need {need}: {detail}"
        )


def _pack(tree) -> bytes:
    """Pytree -> npz bytes (leaves only; structure comes from ``target``
    at restore, matching TrainCheckpointer's npz convention)."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})
    return buf.getvalue()


def _unpack(data: bytes, target):
    import jax

    with np.load(io.BytesIO(data)) as z:
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
    if target is None:
        return leaves
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(target), leaves
    )


class CodedCheckpoint:
    """(n, k) Reed-Solomon-coded checkpoint writer/reader."""

    def __init__(self, n: int, k: int):
        self.n, self.k = int(n), int(k)
        self.rs = RSGF256(n, k)

    # -- save --------------------------------------------------------------
    def save(self, directory, state) -> list[str]:
        """Pack ``state`` (any pytree), encode, write
        ``shard_<i>.<suffix>.rs`` files + manifest; returns the shard
        paths.

        Crash-atomic over an existing checkpoint: shard filenames carry
        a fresh suffix and the manifest replace is the single commit
        point — a crash before it leaves the previous manifest + its
        (untouched) shards fully restorable; a crash after it leaves the
        new checkpoint complete, with at worst stale shard files from
        the previous generation lying around (cleaned on the next
        successful save)."""
        directory = os.fspath(directory)
        os.makedirs(directory, exist_ok=True)
        payload = _pack(state)
        coded, payload_bytes = self.rs.encode_bytes(payload)
        suffix = uuid.uuid4().hex[:8]
        # exclusive advisory lock for the whole save: without it, a
        # concurrent saver's prune step could delete this save's
        # not-yet-committed shards (single-host writers; cross-host
        # coordination is the caller's job)
        lock_fd = os.open(
            os.path.join(directory, ".save.lock"),
            os.O_CREAT | os.O_RDWR, 0o644,
        )
        try:
            import fcntl

            fcntl.flock(lock_fd, fcntl.LOCK_EX)
            return self._save_locked(
                directory, coded, payload_bytes, suffix
            )
        finally:
            os.close(lock_fd)  # closing releases the flock

    def _save_locked(
        self, directory: str, coded, payload_bytes: int, suffix: str
    ) -> list[str]:
        paths = []
        crcs = []
        for i in range(self.n):
            p = os.path.join(directory, f"shard_{i}.{suffix}.rs")
            raw = coded[i].tobytes()
            with open(p + ".tmp", "wb") as f:
                f.write(raw)
            os.replace(p + ".tmp", p)
            paths.append(p)
            crcs.append(zlib.crc32(raw))
        manifest = {
            "format": _FORMAT,
            "n": self.n,
            "k": self.k,
            "suffix": suffix,
            "payload_bytes": int(payload_bytes),
            "shard_bytes": int(coded.shape[1]),
            "crc32": crcs,
        }
        mpath = os.path.join(directory, _MANIFEST)
        with open(mpath + ".tmp", "w") as f:
            json.dump(manifest, f)
        os.replace(mpath + ".tmp", mpath)  # commit point
        for name in os.listdir(directory):  # prune previous generations
            stale_shard = (
                name.endswith(".rs") and f".{suffix}." not in name
            )
            if stale_shard or name.endswith(".rs.tmp"):
                try:
                    os.remove(os.path.join(directory, name))
                except OSError:  # pragma: no cover - concurrent cleanup
                    pass
        return paths

    # -- restore -----------------------------------------------------------
    def restore(self, directory, *, target=None) -> Any:
        """Decode from whichever shards are present AND intact (CRC32
        verified — a corrupt shard is excluded exactly like a stale pool
        result is masked by ``repochs``). Raises
        :class:`CheckpointCorrupt` below k intact shards."""
        directory = os.fspath(directory)
        with open(os.path.join(directory, _MANIFEST)) as f:
            man = json.load(f)
        if man.get("format") != _FORMAT:
            raise ValueError(f"unrecognized manifest format {man.get('format')!r}")
        if (man["n"], man["k"]) != (self.n, self.k):
            raise ValueError(
                f"checkpoint is ({man['n']}, {man['k']})-coded, "
                f"decoder is ({self.n}, {self.k})"
            )
        L = man["shard_bytes"]
        suffix = man["suffix"]
        rows, idx, problems = [], [], []
        for i in range(self.n):
            if len(idx) == self.k:
                break  # any k suffice
            p = os.path.join(directory, f"shard_{i}.{suffix}.rs")
            try:
                with open(p, "rb") as f:
                    raw = f.read()
            except OSError as e:
                problems.append(f"shard {i}: {e}")
                continue
            if len(raw) != L or zlib.crc32(raw) != man["crc32"][i]:
                problems.append(f"shard {i}: corrupt (crc/length mismatch)")
                continue
            rows.append(np.frombuffer(raw, dtype=np.uint8))
            idx.append(i)
        if len(idx) < self.k:
            raise CheckpointCorrupt(
                len(idx), self.k, "; ".join(problems) or "no shards found"
            )
        payload = self.rs.decode_bytes(
            np.stack(rows), idx, man["payload_bytes"]
        )
        return _unpack(payload, target)
