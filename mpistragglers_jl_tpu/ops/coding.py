"""Systematic MDS erasure coding over the reals, for coded computation.

The reference's ``repochs`` freshness mask (src/MPIAsyncPools.jl:109,:168)
is exactly the arrival mask an erasure decoder needs: encode k source
blocks into n coded blocks, hand one to each pool worker, and decode the
full result from *any* k fresh arrivals — stragglers carry zero
information loss. This module supplies the code; ops/coded_gemm.py wires
it to the pool (BASELINE config 3: (n=8, k=6) MDS-coded GEMM).

Design (TPU-first):

* **Generator** ``G = [I; P]`` (n×k), systematic — the first k coded
  blocks *are* the source blocks, so with zero stragglers decode is a
  no-op for the systematic part.
* **Parity** ``P``:
  - ``"cauchy"`` (default): Cauchy matrix on interleaved points — every
    square submatrix of a Cauchy matrix is nonsingular, so ``[I; P]`` is
    provably MDS (any k of n rows invertible);
  - ``"gaussian"``: i.i.d. Gaussian parity — MDS with probability 1 and
    better conditioned for large k.
  Real-field coding (vs GF(2^8) in classical RS) keeps encode/decode as
  *matmuls on the MXU* — the TPU-native choice; exact byte-level RS for
  arbitrary payloads lives in the native GF(256) codec (utils/rs_gf256).
* **Encode** is one einsum over the block axis — an MXU matmul fused by
  XLA. **Decode** is a k×k solve plus a (k×k)·(k×blocklen) matmul.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["MDSCode", "nwait_decodable"]


def _cauchy_parity(n_parity: int, k: int) -> np.ndarray:
    """Cauchy matrix P[i, j] = 1 / (x_i - y_j) on interleaved points.

    x and y are distinct points in [-1, 1]; interleaving keeps the
    denominators away from zero and the conditioning reasonable.
    """
    pts = np.linspace(-1.0, 1.0, n_parity + k, endpoint=True)
    x, y = pts[k:], pts[:k]  # disjoint -> all denominators nonzero
    return 1.0 / (x[:, None] - y[None, :])


@partial(jax.jit, static_argnames=("precision",))
def _encode(G: jax.Array, blocks: jax.Array, precision) -> jax.Array:
    # blocks: (k, rows, cols) -> coded: (n, rows, cols)
    return jnp.einsum("nk,krc->nrc", G, blocks, precision=precision)


@partial(jax.jit, static_argnames=("precision",))
def _decode(G_S: jax.Array, shards: jax.Array, precision) -> jax.Array:
    # shards: (k, rows, cols) from the k arrived workers; solve
    # G_S @ X = shards for the source blocks X
    k = G_S.shape[0]
    flat = shards.reshape(k, -1)
    X = jax.scipy.linalg.solve(G_S, flat)
    return X.reshape(shards.shape)


class MDSCode:
    """Systematic (n, k) MDS code over float32/float64 block vectors.

    >>> code = MDSCode(n=8, k=6)
    >>> coded = code.encode(blocks)          # (k,r,c) -> (8,r,c)
    >>> out = code.decode(coded[idx], idx)   # any 6 shards -> (6,r,c)
    """

    def __init__(
        self,
        n: int,
        k: int,
        *,
        parity: str = "cauchy",
        dtype=np.float32,
        seed: int = 0,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
    ):
        if not 0 < k <= n:
            raise ValueError(f"need 0 < k <= n, got n={n}, k={k}")
        self.n, self.k = int(n), int(k)
        self.precision = precision
        if n == k:
            P = np.zeros((0, k))
        elif parity == "cauchy":
            P = _cauchy_parity(n - k, k)
        elif parity == "gaussian":
            rng = np.random.default_rng(seed)
            P = rng.standard_normal((n - k, k)) / np.sqrt(k)
        else:
            raise ValueError(f"unknown parity kind {parity!r}")
        self.G = np.concatenate([np.eye(k), P], axis=0).astype(dtype)

    # -- encode ----------------------------------------------------------
    def encode(self, blocks) -> jax.Array:
        """(k, rows, cols) source blocks -> (n, rows, cols) coded blocks.
        Runs on whatever device ``blocks`` lives on (one MXU einsum)."""
        blocks = jnp.asarray(blocks)
        if blocks.shape[0] != self.k:
            raise ValueError(
                f"expected {self.k} source blocks, got {blocks.shape[0]}"
            )
        return _encode(jnp.asarray(self.G), blocks, self.precision)

    def encode_array(self, A) -> jax.Array:
        """Row-partition a 2-D array into k blocks and encode -> (n,
        rows/k, cols)."""
        A = jnp.asarray(A)
        m = A.shape[0]
        if m % self.k != 0:
            raise ValueError(f"rows {m} not divisible by k={self.k}")
        return self.encode(A.reshape(self.k, m // self.k, *A.shape[1:]))

    # -- decode ----------------------------------------------------------
    def decode(self, shards, indices) -> jax.Array:
        """Recover the k source blocks from any k coded shards.

        ``shards``: (k, rows, cols) stacked coded results;
        ``indices``: which coded block each shard is (len k, distinct).
        """
        idx = np.asarray(indices)
        if idx.shape[0] != self.k or len(set(idx.tolist())) != self.k:
            raise ValueError(
                f"need exactly k={self.k} distinct shard indices, got {idx}"
            )
        shards = jnp.asarray(shards)
        if shards.shape[0] != self.k:
            raise ValueError(
                f"expected {self.k} shards, got {shards.shape[0]}"
            )
        G_S = jnp.asarray(self.G[idx])
        return _decode(G_S, shards, self.precision)

    def decode_array(self, shards, indices) -> jax.Array:
        """Like :meth:`decode` but restacks blocks into the flat (k*rows,
        cols) array layout of :meth:`encode_array`'s input."""
        blocks = self.decode(shards, indices)
        return blocks.reshape(-1, *blocks.shape[2:])


def nwait_decodable(k: int):
    """Predicate factory for ``asyncmap(nwait=...)``: return True once at
    least k workers have fresh results — the decodability condition of an
    (n, k) MDS code. The reference's functional-``nwait`` mechanism
    (src/MPIAsyncPools.jl:152-154) evaluated over the live arrival mask.
    """

    def pred(epoch: int, repochs: np.ndarray) -> bool:
        return int((repochs == epoch).sum()) >= k

    return pred
