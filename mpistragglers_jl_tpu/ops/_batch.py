"""Shared coalesced-dispatch machinery for pool GEMM workloads.

One device = one fused stacked-matmul program per epoch
(`XLADeviceBackend(batch_fn=...)`): the helpers here build the
per-device stacks and dispatch against them, shared by
:class:`~.gemm.DistributedGemm` and :class:`~.coded_gemm.CodedGemm`
so the group-building and re-task-subset logic exist exactly once.

In batch mode the per-worker blocks stay HOST-resident (the fused
stacks are the only device copy — the per-worker dispatch path never
runs, so device-resident individual blocks would be dead HBM).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("precision",))
def _stacked_matmul(blocks, payload, precision):
    # (w, r, c) x (c, d) -> (w, r, d) as ONE large 2-D matmul: a batched
    # einsum leaves the MXU tiling a small per-batch M (r rows); folding
    # the worker axis into M runs at plain-matmul rate
    w, r, c = blocks.shape
    flat = jnp.matmul(
        blocks.reshape(w * r, c), payload, precision=precision
    )
    return flat.reshape(w, r, payload.shape[1])


@partial(jax.jit, static_argnames=("precision",))
def _stacked_matmul_gather(blocks_all, sel, payload, precision):
    # re-task subsets: gather the members' blocks, then the fused matmul
    blocks = blocks_all[sel]
    w, r, c = blocks.shape
    flat = jnp.matmul(
        blocks.reshape(w * r, c), payload, precision=precision
    )
    return flat.reshape(w, r, payload.shape[1])


def build_device_groups(host_blocks, n: int, devices) -> dict:
    """Group worker ids by their DEVICE and place ONE stacked array of
    each group's blocks on it.

    ``devices`` maps worker i to ``devices[i % len(devices)]`` — a
    short list is the round-robin layout, a length-n list is an
    explicit per-worker map (the fused folded pool uses a blocked one).
    Grouping is by device identity, matching how the backend coalesces
    dispatches, so both layouts produce the same groups the batch_fn
    will be called with.

    Returns ``{worker: (ids_tuple, stacked, {worker: position})}`` —
    every member maps to its group entry. Blocks must be equal-shaped
    within a group (callers enforce their own split constraints).
    """
    by_dev: dict = {}
    for i in range(n):
        by_dev.setdefault(devices[i % len(devices)], []).append(i)
    group_of: dict = {}
    for dev, ids in by_dev.items():
        stacked = jax.device_put(
            np.stack([np.asarray(host_blocks[i]) for i in ids]), dev
        )
        entry = (tuple(ids), stacked, {w: p for p, w in enumerate(ids)})
        for i in ids:
            group_of[i] = entry
    return group_of


def batch_dispatch(group_of: dict, ids, payload, precision):
    """The shared ``batch_fn`` body: whole-group broadcasts use the
    stack as-is; re-task subsets gather their members' positions."""
    group_ids, stacked, pos = group_of[int(ids[0])]
    if tuple(ids) == group_ids:
        return _stacked_matmul(stacked, payload, precision)
    sel = jnp.asarray([pos[int(i)] for i in ids])
    return _stacked_matmul_gather(stacked, sel, payload, precision)
