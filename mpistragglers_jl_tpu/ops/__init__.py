__all__ = ["DistributedGemm", "gather_rows"]


def __getattr__(name):
    # lazy: ops pull in jax; keep the core package importable without it
    if name in __all__:
        from . import gemm

        return getattr(gemm, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
