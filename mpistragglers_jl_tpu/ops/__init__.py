_HOME = {
    "DistributedGemm": "gemm",
    "gather_rows": "gemm",
    "MDSCode": "coding",
    "nwait_decodable": "coding",
    "CodedGemm": "coded_gemm",
    "LTCodedGemm": "coded_gemm",
    "LTCode": "lt",
    "nwait_lt_decodable": "lt",
    "HierarchicalCodedGemm": "hierarchical",
    "ParityOuter": "outer_code",
    "LTOuter": "outer_code",
    "make_outer": "outer_code",
    "hierarchical_nwait": "outer_code",
    "partition_groups": "outer_code",
    "GradientCode": "gradcode",
    "PolynomialCode": "polynomial",
    "PolyCodedGemm": "polynomial",
    "MatDotCode": "matdot",
    "MatDotGemm": "matdot",
    "DeviceRSGF256": "gf256_device",
    "gf256_matmul": "gf256_device",
    "flash_attention": "flash_attention",
}

__all__ = list(_HOME)


def __getattr__(name):
    # lazy: most ops pull in jax; keep the core package importable
    # without it
    if name in _HOME:
        import importlib

        mod = importlib.import_module(f".{_HOME[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
