"""LT (Luby transform) rateless codes over the reals, with peeling decode.

BASELINE config 4: LT-coded GEMM on 16 workers with a *variable*
``nwait(epoch, repochs)`` predicate — return not after a fixed count but
as soon as the arrived shard set is actually decodable. This exercises
the reference's functional-``nwait`` mechanism
(src/MPIAsyncPools.jl:152-154) with a real decoder in the loop, which is
exactly what it exists for: the predicate sees the live ``repochs``
vector after every arrival.

Rateless-ness: shard ids are unbounded — shard ``s`` is a deterministic
pseudo-random sum of a few source blocks (degree drawn from the robust
soliton distribution, then that many blocks chosen uniformly), so any
number of workers can each take a distinct shard id and more shards only
help. Over the reals the XOR of classical LT becomes a sum, and peeling
subtracts instead of XORs; releases are numerically benign (coefficients
are 0/1, no amplification beyond degree-many subtractions).
"""

from __future__ import annotations

import ctypes
import warnings

import numpy as np

__all__ = ["LTCode", "nwait_lt_decodable"]


def _configure(lib):
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    for name, fltp in (
        ("lt_peel_f32", ctypes.POINTER(ctypes.c_float)),
        ("lt_peel_f64", ctypes.POINTER(ctypes.c_double)),
    ):
        fn = getattr(lib, name)
        fn.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_long,
            i32p, i32p, fltp, fltp, u8p,
        ]
        fn.restype = ctypes.c_long


def _load_native():
    """The C++ peeling decoder (native/lt_peel.cpp), compiled on first
    use; raises if no toolchain — callers fall back to NumPy. Success
    and failure are both memoized by :func:`..native.load`."""
    from .. import native

    return native.load("lt_peel", _configure)


def patch_distribution(k: int) -> np.ndarray:
    """Degree distribution for the coded tail of a SYSTEMATIC LT code:
    uniform over degrees ceil(k/4)+1 .. ceil(k/2).

    Classic LT needs the soliton shape because peeling must
    bootstrap itself from degree-1 shards; a systematic stream's
    identity prefix already resolves every delivered block, so coded
    shards exist to PATCH the few missing ones — the optimal patch has
    moderate degree (cover a missing block with high probability
    without binding several missing blocks together and stalling the
    peel). Measured over the straggler ensembles in docs/PERF.md:
    beats the robust-soliton tail at every k/straggler count tried
    (e.g. k=16, 2 stragglers: 1.13x vs 1.29x shards consumed) and
    degrades gracefully when half the workers are lost."""
    import math

    if k == 1:  # degree-1 is the only degree; an empty [lo, hi) slice
        return np.ones(1)  # here would yield 0/0 = NaN probabilities
    lo = min(math.ceil(k / 4) + 1, k)
    hi = max(math.ceil(k / 2), lo)
    mu = np.zeros(k)
    mu[lo - 1 : hi] = 1.0
    return mu / mu.sum()


def robust_soliton(k: int, c: float = 0.1, delta: float = 0.5) -> np.ndarray:
    """Robust soliton degree distribution over degrees 1..k."""
    d = np.arange(1, k + 1)
    rho = np.zeros(k)
    rho[0] = 1.0 / k
    rho[1:] = 1.0 / (d[1:] * (d[1:] - 1.0))
    R = c * np.log(k / delta) * np.sqrt(k)
    tau = np.zeros(k)
    kR = int(np.floor(k / R)) if R > 0 else k
    kR = max(1, min(kR, k))
    for i in range(1, kR):
        tau[i - 1] = R / (i * k)
    tau[kR - 1] = R * np.log(R / delta) / k if R > delta else 0.0
    mu = rho + tau
    return mu / mu.sum()


class LTCode:
    """Rateless LT code over k source blocks.

    ``shard_indices(s)`` is the deterministic support of shard ``s``;
    workers compute real-field sums of those source blocks.

    ``systematic=True`` makes shards ``0..k-1`` the source blocks
    themselves (degree-1, support ``{s}``) and draws soliton supports
    only from shard ``k`` on. In the common deployment — the first
    window of shard ids is ``0..n-1`` with ``n >= k`` — a straggler-free
    epoch then peels trivially from the k systematic arrivals, and with
    a straggler only the *missing* block must be covered by a coded
    shard whose other neighbors are already resolved, dropping expected
    shards-consumed from ~1.6k toward ~1.25k at k=8 (VERDICT r2 item 4;
    standard systematic-fountain construction, cf. Raptor/RFC 5053's
    systematic design goal — implemented here as plain LT with an
    identity prefix, not a copy of any implementation)."""

    def __init__(self, k: int, *, seed: int = 0, c: float = 0.1,
                 delta: float = 0.5, systematic: bool = False):
        self.k = int(k)
        self.seed = int(seed)
        self.systematic = bool(systematic)
        # systematic streams draw their coded tail from the patch
        # distribution (see patch_distribution); classic streams keep
        # the robust soliton peeling needs to bootstrap
        self._mu = (
            patch_distribution(self.k) if self.systematic
            else robust_soliton(self.k, c, delta)
        )

    def shard_indices(self, s: int) -> np.ndarray:
        """Deterministic support (sorted source-block ids) of shard s."""
        if self.systematic and s < self.k:
            return np.asarray([int(s)])
        rng = np.random.default_rng((self.seed, int(s)))
        d = 1 + rng.choice(self.k, p=self._mu)
        return np.sort(rng.choice(self.k, size=d, replace=False))

    def generator_rows(self, shard_ids) -> np.ndarray:
        """0/1 generator rows (len(shard_ids) × k) for the given shards."""
        G = np.zeros((len(shard_ids), self.k), dtype=np.float32)
        for r, s in enumerate(shard_ids):
            G[r, self.shard_indices(s)] = 1.0
        return G

    # -- decodability (pure graph logic, no data) ------------------------
    def peelable(self, shard_ids) -> bool:
        """True iff peeling decodes all k source blocks from these shards."""
        supports = [set(self.shard_indices(s).tolist()) for s in shard_ids]
        resolved: set[int] = set()
        progress = True
        while progress and len(resolved) < self.k:
            progress = False
            for sup in supports:
                live = sup - resolved
                if len(live) == 1:
                    resolved.add(next(iter(live)))
                    progress = True
        return len(resolved) == self.k

    # -- decode ----------------------------------------------------------
    def decode(self, shards, shard_ids, *, prefer_native: bool = True
               ) -> np.ndarray:
        """Peel: recover the k source blocks from arrived shards.

        ``shards``: (m, rows, cols) arrived coded sums, ``shard_ids``:
        their shard ids. Raises ``ValueError`` if peeling stalls (use
        :meth:`peelable` / the nwait predicate to avoid). The peel runs
        in the native C++ decoder (native/lt_peel.cpp) when a toolchain
        is available — one in-place pass per release, no per-release
        Python/alloc overhead — falling back to the NumPy loop
        otherwise. Release order may differ between the two (worklist
        vs rescan), so results agree to float rounding, not bitwise.
        """
        if prefer_native:
            try:
                lib = _load_native()
            except Exception as e:  # no compiler / bad toolchain
                warnings.warn(
                    f"native lt_peel unavailable ({e}); using numpy "
                    "fallback",
                    RuntimeWarning,
                    stacklevel=2,
                )
            else:
                return self._decode_native(lib, shards, shard_ids)
        shards = [np.array(s, copy=True) for s in np.asarray(shards)]
        supports = [set(self.shard_indices(s).tolist()) for s in shard_ids]
        out = [None] * self.k
        nresolved = 0
        progress = True
        while progress and nresolved < self.k:
            progress = False
            for sh, sup in zip(shards, supports):
                if len(sup) != 1:
                    continue
                j = next(iter(sup))
                if out[j] is None:
                    out[j] = sh.copy()
                    nresolved += 1
                sup.clear()
                progress = True
                # release: subtract the resolved block everywhere
                for sh2, sup2 in zip(shards, supports):
                    if j in sup2:
                        sh2 -= out[j]
                        sup2.discard(j)
        if nresolved < self.k:
            raise ValueError(
                f"peeling stalled at {nresolved}/{self.k} blocks; "
                "shard set not decodable"
            )
        return np.stack(out)

    def _decode_native(self, lib, shards, shard_ids) -> np.ndarray:
        shards = np.asarray(shards)
        m = shards.shape[0]
        block_shape = shards.shape[1:]
        orig_dtype = shards.dtype
        if orig_dtype == np.float32:
            fn, cty, dtype = lib.lt_peel_f32, ctypes.c_float, np.float32
        elif orig_dtype == np.float64:
            fn, cty, dtype = lib.lt_peel_f64, ctypes.c_double, np.float64
        else:  # ints etc.: exactness in f64 up to 2^53, then cast back
            fn, cty, dtype = lib.lt_peel_f64, ctypes.c_double, np.float64
        # exactly one owned working copy, peeled in place (astype with
        # copy=True covers the dtype == orig_dtype case too)
        shards = np.ascontiguousarray(
            shards.reshape(m, -1).astype(dtype, copy=True)
        )
        supports = [self.shard_indices(s) for s in shard_ids]
        off = np.zeros(m + 1, dtype=np.int32)
        off[1:] = np.cumsum([len(s) for s in supports])
        sup = np.concatenate(supports).astype(np.int32)
        out = np.zeros((self.k, shards.shape[1]), dtype=dtype)
        resolved = np.zeros(self.k, dtype=np.uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        fltp = ctypes.POINTER(cty)
        n = fn(
            m, self.k, shards.shape[1],
            sup.ctypes.data_as(i32p), off.ctypes.data_as(i32p),
            shards.ctypes.data_as(fltp), out.ctypes.data_as(fltp),
            resolved.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)
            ),
        )
        if n < self.k:
            raise ValueError(
                f"peeling stalled at {n}/{self.k} blocks; "
                "shard set not decodable"
            )
        if dtype != orig_dtype:
            out = out.astype(orig_dtype)
        return out.reshape(self.k, *block_shape)

    def decode_array(self, shards, shard_ids) -> np.ndarray:
        blocks = self.decode(shards, shard_ids)
        return blocks.reshape(-1, *blocks.shape[2:])


def nwait_lt_decodable(code: LTCode, shard_of_worker):
    """Predicate factory: True once the fresh workers' shards peel.

    ``shard_of_worker[i]`` maps pool worker i to its shard id. The
    predicate runs after every arrival (reference
    src/MPIAsyncPools.jl:152-154), so the pool returns at the *first*
    decodable arrival set — the variable-nwait behavior of BASELINE
    config 4.
    """
    shard_of_worker = np.asarray(shard_of_worker)

    def pred(epoch: int, repochs: np.ndarray) -> bool:
        fresh = np.flatnonzero(repochs == epoch)
        if fresh.size == 0:
            return False
        return code.peelable(shard_of_worker[fresh].tolist())

    return pred
