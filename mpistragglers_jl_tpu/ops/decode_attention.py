"""Pallas TPU decode attention over the int8 KV cache.

Why this kernel exists (measured, docs/PERF.md "int8 KV cache"): the
einsum-form dequantization — int8 cache ``.astype(bf16)`` feeding the
attention dots — is *expressed* as a fused rank-1 correction, but XLA
materializes the converted operand in HBM, so the int8 cache read half
the bytes and then paid them back with interest (0.70x vs the bf16
cache). The fix is the standard Pallas move: stream the int8 blocks
through VMEM and dequantize in registers, so HBM traffic really is the
int8 bytes plus scales.

Layout lesson (both dead ends measured on the chip, docs/PERF.md):
a head-major kernel layout needs a transpose of the whole cache —
XLA materializes it per layer per step and the win drowns (0.82x);
slicing one head's D-chunk per grid row from the native layout makes
every DMA a strided 128-lane gather (0.53x). The kernel therefore
reads the cache EXACTLY as it is laid out — contiguous
``(bk, Hkv*D)`` blocks of the native ``(B, L, Hkv, D)`` cache — and
handles the GQA grouping *inside* the kernel with a static loop over
kv heads (static row/lane slices, one MXU dot per head group):

* grid ``(B, k_blocks)``, k innermost-sequential — batch rows are
  independent ("parallel"), and within a row Mosaic double-buffers the
  sequential k-blocks: block j+1's int8 K/V DMA overlaps block j's
  dots, so the stream never stalls on HBM;
* the q heads ride the sublane axis, each GQA group zero-padded to
  the 8-row tile (``(Hkv * 8, D)`` total); padding rows compute
  garbage that is sliced off at the end, never normalized;
* per-(position, head) f32 scales arrive in their native
  ``(B, L, Hkv)`` layout too (whole-trailing-dim blocks are
  tile-legal) — NOTHING is transposed or copied outside the kernel;
* positions are PER ROW: ``pos`` may be a scalar (every row at the
  same step — the ``generate_*`` scan) or a ``(B,)`` vector (every
  serving slot at its own global position — the continuous-batching
  scheduler). Either way it rides SMEM and one compiled kernel serves
  every decode step; blocks entirely outside a row's visible range are
  predicated off grid-level.
* two cache layouts share the kernel: the POSITIONAL cache (slot s
  holds position s; validity ``kpos <= pos`` plus the sliding band
  when ``window`` is set) and the O(W) RING cache (``ring=True``:
  slot s holds ``kpos(s) = pos - ((pos - s) mod W)``, valid iff
  ``kpos >= 0`` — which reduces to ``s <= pos or pos >= W``, the same
  one-predicate mask models/decode.py's ring reads use).
* the ring cache additionally supports a PAGED layout
  (``page_table=``): K/V live in a pool of ``(page_tokens,
  Hkv*D)``-row pages shared by every serving slot, and each row's
  ``(max_pages,)`` int32 page-index vector rides scalar-prefetch SMEM
  so the BLOCK INDEX MAP itself dereferences the page table — block
  ``(b, j)`` DMAs page ``page_table[b, j]`` straight out of the pool.
  The k-block size becomes ``page_tokens`` and the math is otherwise
  the identical ring-mode online softmax (``W = max_pages *
  page_tokens``), so the paged serving tick and the dense gather
  fallback (models/serving.py ``_paged_gather`` + the einsum rows)
  stay numerically interchangeable.

Inference-only: no VJP (the cache is never differentiated through).
Interpret mode on non-TPU backends keeps the path testable on the CI
mesh, same as the flash kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _CompilerParams, _sds, _use_interpret

_NEG = -1e30
_LANE = 128
_SUB = 8  # TPU sublane tile: each GQA group pads to this many q rows

__all__ = ["quantized_decode_attention", "paged_block_viable"]


# Scoped-VMEM budget per (block row x kv head), CALIBRATED on the
# bench chip: Mosaic's stack allocation for this kernel measured
# ~1435 B/(row*head) at D=128 (bk=5632, Hkv=2 hit 16.16 MiB against
# the 16 MiB scoped limit) — double-buffered int8 K/V plus the f32
# score/probability intermediates and allocator slack.
_VMEM_PER_ROW_HEAD = 11.3  # bytes per (row, head, D/128 lane group)
_VMEM_CAP = 12 * 2 ** 20
# default k-block budget; the models/decode.py routing gate imports
# THIS constant so the two call sites cannot drift
DEFAULT_BLOCK_K = 8192


def paged_block_viable(page_tokens: int) -> bool:
    """Could the kernel stream ``page_tokens``-row k-blocks? Pages ride
    the sublane axis of the ``(1, page_tokens, Hkv*D)`` block, so a
    compiled TPU kernel needs the int8 sublane tile (32 rows); the
    interpreter has no tiling and accepts any 8-row multiple (the CI
    parity surface — PAGE_TOKENS=16 tests run interpreted). The
    routing gates in models/serving.py consult THIS predicate so the
    call sites cannot drift from the kernel's real constraint."""
    P = int(page_tokens)
    if P < 8 or P % 8 != 0:
        return False
    return _use_interpret() or P % 32 == 0


def _paged_kernel(pos_ref, pt_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref,
                  o_ref, acc, m_sc, l_sc, **kw):
    """Scalar-prefetch entry: the page table is consumed ENTIRELY by
    the block index maps (it decides which page each (b, j) step DMAs);
    the online-softmax body is the ring-mode ``_kernel`` unchanged."""
    del pt_ref
    _kernel(pos_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
            acc, m_sc, l_sc, **kw)


def _pick_block_128(L: int, block: int, Hkv: int = 2,
                    D: int = 128) -> int | None:
    """Largest lane-aligned block (multiple of 128) <= ``block``
    dividing L whose calibrated working set fits scoped VMEM. Lengths
    with no such divisor fall back to the whole dimension in one block
    (block == dim is always tile-legal) when IT fits; otherwise None —
    the caller keeps the einsum path."""
    cap = int(_VMEM_CAP / (Hkv * D * _VMEM_PER_ROW_HEAD))
    b = min(block, L, max(cap, 128))
    b -= b % 128
    while b >= 128:
        if L % b == 0:
            return b
        b -= 128
    if L <= max(cap, 128):  # whole-dim fallback
        return L
    return None


def _kernel(pos_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
            acc, m_sc, l_sc, *, scale, window, bk, nk, Hkv, D, ring):
    b = pl.program_id(0)
    j = pl.program_id(1)
    pos = pos_ref[b]  # this row's global decode position

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)

    # any slot of this block visible? Positional: the causal frontier
    # (plus the band's lower edge). Ring: slots [0, min(pos, W-1)] are
    # valid, so the same frontier predicate covers warmup, and once
    # pos >= W every block runs (j*bk <= W - bk < W <= pos).
    run = j * bk <= pos
    if window is not None and not ring:
        run = jnp.logical_and(run, pos - (j * bk + bk - 1) < window)

    @pl.when(run)
    def _update():
        kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        if ring:
            # slot s holds position pos - ((pos - s) mod W); kpos >= 0
            # iff s <= pos or pos >= W (W == bk * nk, the whole cache)
            mask = jnp.logical_or(kpos <= pos, pos >= bk * nk)
        else:
            mask = kpos <= pos
            if window is not None:
                mask = jnp.logical_and(mask, pos - kpos < window)
        kblk = k_ref[0]  # (bk, Hkv*D) int8, one contiguous DMA
        vblk = v_ref[0]
        ksb = ks_ref[0].astype(jnp.float32)  # (bk, Hkv)
        vsb = vs_ref[0].astype(jnp.float32)
        # static loop over kv heads: static row/lane slices, one MXU
        # dot per GQA group — the grouping costs index math, not DMA
        for h in range(Hkv):
            rows = slice(h * _SUB, (h + 1) * _SUB)
            q = q_ref[0][rows]  # (SUB, D): g live rows + padding
            kb = kblk[:, h * D:(h + 1) * D].astype(q.dtype)
            s = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (SUB, bk)
            s = s * ksb[:, h][None, :]
            s = jnp.where(mask, s, _NEG)
            m_prev = m_sc[rows, :1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            p = jnp.where(mask, p, 0.0)
            corr = jnp.exp(m_prev - m_new)
            l_sc[rows] = jnp.broadcast_to(
                l_sc[rows, :1] * corr + p.sum(axis=-1, keepdims=True),
                (_SUB, _LANE),
            )
            vb = vblk[:, h * D:(h + 1) * D].astype(jnp.float32)
            pv = p * vsb[:, h][None, :]
            acc[rows] = acc[rows] * corr + jax.lax.dot_general(
                pv, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_sc[rows] = jnp.broadcast_to(m_new, (_SUB, _LANE))

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_sc[:, :1], 1e-20)
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)


def quantized_decode_attention(
    q, cache_l: dict, pos, scale, window=None, *, ring: bool = False,
    block_k: int = DEFAULT_BLOCK_K, interpret: bool | None = None,
    page_table=None, page_tokens: int | None = None,
):
    """Single-query grouped attention against an int8 cache layer.

    q: (B, 1, H, D); ``cache_l``: {"k","v"} int8 (B, L, Hkv, D) +
    {"k_s","v_s"} f32 (B, L, Hkv); ``pos``: scalar current position,
    or a ``(B,)`` vector of PER-ROW positions (the serving scheduler's
    slots each decode at their own step). Returns (B, 1, H, D) in q's
    dtype — numerically the online-softmax evaluation of the same
    masked attention ``models/decode.py::_cached_attention`` computes
    in einsum form (pinned by tests/test_decode_attention.py).

    ``ring=True`` reads the O(W) ring layout instead (L == W; slot s
    holds ``kpos(s) = pos - ((pos - s) mod W)``): validity is the one
    ``kpos >= 0`` predicate of ``_ring_cached_attention`` /
    ``_ring_attention_rows``, so the batched serving tick and the ring
    generate scan route the exact same kernel. ``window`` must be None
    in ring mode — the ring IS the window.

    ``page_table=`` (ring mode only) reads the PAGED ring layout:
    ``cache_l`` leaves are page pools — {"k","v"} int8 ``(n_pages *
    page_tokens, Hkv, D)`` + scales ``(n_pages * page_tokens, Hkv)``
    shared by all rows — and ``page_table`` is the ``(B, max_pages)``
    int32 table mapping row b's ring page j to its pool page. The
    table rides scalar-prefetch SMEM and is dereferenced by the block
    index maps, so each (b, j) grid step DMAs exactly the page the
    table names — the HBM traffic of a decode step is the W live rows,
    never the pool (see module docstring). ``W = max_pages *
    page_tokens`` and the validity mask is ring mode's unchanged.
    """
    if interpret is None:
        interpret = _use_interpret()
    if ring and window is not None:
        raise ValueError(
            "ring mode encodes the window in the cache layout; pass "
            "window=None (the ring length IS the window)"
        )
    if page_table is not None:
        if not ring:
            raise ValueError("page_table is a ring-layout feature; "
                             "pass ring=True")
        if page_tokens is None:
            raise ValueError("page_table needs page_tokens")
        return _paged_call(q, cache_l, pos, scale, page_table,
                           int(page_tokens), interpret)
    B, T, H, D = q.shape
    if T != 1:
        raise ValueError(f"decode kernel is single-query, got T={T}")
    kc, vc = cache_l["k"], cache_l["v"]
    ks, vs = cache_l["k_s"], cache_l["v_s"]
    L, Hkv = kc.shape[1], kc.shape[2]
    g = H // Hkv
    bk = _pick_block_128(L, block_k, Hkv, D)
    if bk is None:
        raise ValueError(
            f"cache length {L} has no multiple-of-128 divisor <= "
            f"{block_k} and is too long for a whole-dimension block; "
            "size the cache (prompt + n_new) to a multiple of 128, or "
            "use the einsum path"
        )
    nk = L // bk
    if g > _SUB:
        raise ValueError(
            f"GQA group {g} exceeds the kernel's {_SUB}-row group tile"
        )

    # (B, 1, H, D) -> (B, Hkv*SUB, D): each kv head's g q-rows padded
    # to the 8-row tile (tiny — no cache-sized copies anywhere here)
    q3 = q.reshape(B, Hkv, g, D)
    if g < _SUB:
        q3 = jnp.pad(q3, ((0, 0), (0, 0), (0, _SUB - g), (0, 0)))
    q3 = q3.reshape(B, Hkv * _SUB, D)
    rows = Hkv * _SUB
    kf = kc.reshape(B, L, Hkv * D)  # free: (Hkv, D) tail is contiguous
    vf = vc.reshape(B, L, Hkv * D)
    # scalar pos broadcasts to every row; a (B,) vector rides as-is
    posv = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32).reshape(-1), (B,)
    )

    kern = functools.partial(
        _kernel, scale=scale, window=window, bk=bk, nk=nk, Hkv=Hkv,
        D=D, ring=ring,
    )
    o3 = pl.pallas_call(
        kern,
        grid=(B, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, rows, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, Hkv * D), lambda b, j: (b, j, 0)),
            # whole-trailing-dim blocks are tile-legal at any Hkv
            pl.BlockSpec((1, bk, Hkv), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, Hkv * D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, Hkv), lambda b, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, rows, D), lambda b, j: (b, 0, 0)),
        out_shape=_sds((B, rows, D), q.dtype, q),
        scratch_shapes=[
            pltpu.VMEM((rows, D), jnp.float32),
            pltpu.VMEM((rows, _LANE), jnp.float32),
            pltpu.VMEM((rows, _LANE), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(posv, q3, kf, ks, vf, vs)
    # (B, Hkv*SUB, D) -> drop each group's padding rows -> (B, 1, H, D)
    return o3.reshape(B, Hkv, _SUB, D)[:, :, :g].reshape(B, 1, H, D)


def _paged_call(q, cache_l: dict, pos, scale, page_table, P: int,
                interpret: bool):
    """Paged-ring pallas_call: grid (B, max_pages), k-block = one page,
    block index maps dereference the scalar-prefetched page table."""
    B, T, H, D = q.shape
    if T != 1:
        raise ValueError(f"decode kernel is single-query, got T={T}")
    kc, vc = cache_l["k"], cache_l["v"]
    ks, vs = cache_l["k_s"], cache_l["v_s"]
    Nphys, Hkv = kc.shape[0], kc.shape[1]
    g = H // Hkv
    if g > _SUB:
        raise ValueError(
            f"GQA group {g} exceeds the kernel's {_SUB}-row group tile"
        )
    if Nphys % P != 0:
        raise ValueError(
            f"page pool of {Nphys} rows is not a multiple of "
            f"page_tokens {P}"
        )
    npages = Nphys // P
    max_pages = page_table.shape[1]

    q3 = q.reshape(B, Hkv, g, D)
    if g < _SUB:
        q3 = jnp.pad(q3, ((0, 0), (0, 0), (0, _SUB - g), (0, 0)))
    q3 = q3.reshape(B, Hkv * _SUB, D)
    rows = Hkv * _SUB
    # pool leaves reshaped page-major — free (the trailing dims are
    # contiguous), and each block below is one page's rows
    kf = kc.reshape(npages, P, Hkv * D)
    vf = vc.reshape(npages, P, Hkv * D)
    ksr = ks.reshape(npages, P, Hkv)
    vsr = vs.reshape(npages, P, Hkv)
    posv = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32).reshape(-1), (B,)
    )
    ptv = page_table.astype(jnp.int32)

    kern = functools.partial(
        _paged_kernel, scale=scale, window=None, bk=P, nk=max_pages,
        Hkv=Hkv, D=D, ring=True,
    )

    def _page(b, j, pos_ref, pt_ref):
        del pos_ref
        return (pt_ref[b, j], 0, 0)

    def _row(b, j, pos_ref, pt_ref):
        del pos_ref, pt_ref
        return (b, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec((1, rows, D), _row),
            pl.BlockSpec((1, P, Hkv * D), _page),
            pl.BlockSpec((1, P, Hkv), _page),
            pl.BlockSpec((1, P, Hkv * D), _page),
            pl.BlockSpec((1, P, Hkv), _page),
        ],
        out_specs=pl.BlockSpec((1, rows, D), _row),
        scratch_shapes=[
            pltpu.VMEM((rows, D), jnp.float32),
            pltpu.VMEM((rows, _LANE), jnp.float32),
            pltpu.VMEM((rows, _LANE), jnp.float32),
        ],
    )
    o3 = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=_sds((B, rows, D), q.dtype, q),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(posv, ptv, q3, kf, ksr, vf, vsr)
    return o3.reshape(B, Hkv, _SUB, D)[:, :, :g].reshape(B, 1, H, D)
