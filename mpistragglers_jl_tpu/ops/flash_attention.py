"""Pallas TPU flash attention: fused online-softmax attention kernels.

The reference has no attention code at all (SURVEY §5 'Long-context');
this op is part of the framework's long-context story. The per-device
attention inside Ulysses sequence parallelism and the dense transformer
forward materialize an (L, L) score matrix per head
(parallel/ring_attention.py ``reference_attention``) — O(L^2) HBM
traffic and memory. This module replaces that hot op with a Pallas
kernel that streams K/V blocks through VMEM and keeps the softmax
normalizer in on-chip scratch, the standard flash-attention scheme
mapped to the TPU memory hierarchy (HBM -> VMEM -> MXU):

* forward: grid (batch*heads, q-blocks, k-blocks), k innermost; online
  softmax accumulators (o_acc, m, l) live in VMEM scratch across the
  k sweep; causal blocks entirely above the diagonal are skipped via
  predication; saves per-row logsumexp for the backward;
* backward: two kernels (dq over the k sweep; dk/dv over the q sweep)
  recompute probabilities from the saved logsumexp, the
  recomputation-based flash backward — no (L, L) residual is ever
  stored;
* wrapped in ``jax.custom_vjp`` so it differentiates inside the model
  train steps.

On non-TPU backends (the CI mesh is 8 virtual CPU devices) the kernels
run in Pallas interpret mode automatically, so the same code path is
testable everywhere.

Layout matches the rest of the framework: (batch, seq, heads, head_dim).
"""

from __future__ import annotations

import functools

import jax
from .. import _jax_compat  # noqa: F401  (installs older-JAX aliases)
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # matches parallel/ring_attention.py: large-negative mask
_LANE = 128  # TPU lane width; m/l scratch is broadcast across lanes

# pltpu.CompilerParams is the current spelling; older toolchains (the
# CPU-only CI image lags the chip host) ship it as TPUCompilerParams —
# same kwargs, so the kernels stay loadable on both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _grid_params():
    """Mosaic grid semantics: batch*heads and the outer block axis are
    embarrassingly parallel; only the innermost sweep (k blocks in the
    forward/dq, q blocks in dk/dv) carries loop state through scratch
    and must run in order. Without this annotation Mosaic assumes every
    grid axis is sequential — measured 20% slower on the round-3 chip
    (docs/PERF.md)."""
    return _CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _use_interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


def _pick_block(L: int, block: int) -> int:
    """Largest TPU-legal block <= ``block`` dividing L: sublane-aligned
    (multiple of 8) or spanning the whole dimension (both are legal
    Mosaic tilings; anything else compiles only in interpret mode).
    When L has no 8-aligned divisor <= ``block`` (odd/prime lengths),
    the fallback is the whole dimension in one block — legal but VMEM-
    bounded; :func:`_check_vmem` rejects fallback blocks whose working
    set cannot fit the 16 MiB scoped budget instead of letting Mosaic
    OOM mid-compile."""
    b = min(block, L)
    while b > 0:
        if L % b == 0 and (b % 8 == 0 or b == L):
            return b
        b -= 1
    return L


_VMEM_BUDGET = 16 * 2 ** 20  # Mosaic's scoped VMEM allocation (bytes)


def _check_vmem(bq: int, bk: int, D: int, itemsize: int) -> None:
    """Reject block choices that cannot fit VMEM, with a clear error
    instead of an opaque Mosaic mid-compile allocation failure.

    Covers both the odd-length whole-dimension fallback (see
    :func:`_pick_block`) and explicitly tuned oversize blocks (e.g.
    ``block_q=2048`` at head_dim 128 — the PERF round-4 block sweep hit
    exactly that OOM). The estimate is the per-grid-step working set of
    the heaviest kernel (dk/dv backward): f32 scratch accumulators +
    m/l lanes + the (bq, bk) score/probability intermediates + resident
    q/k/v/do blocks. The tuned 1024x1024 default at head_dim 128
    estimates ~11.5 MiB — inside the 16 MiB budget with the same
    headroom Mosaic's double-buffering eats in practice."""
    est = 4 * (2 * bk * D + 2 * bq * _LANE + 2 * bq * bk) + itemsize * (
        2 * bq * D + 2 * bk * D
    )
    if est > _VMEM_BUDGET:
        aligned = bq % 8 == 0 and bk % 8 == 0
        why = (
            "lower block_q/block_k"
            if aligned
            else "the sequence length has no 8-aligned divisor, so the "
            "kernel would take it in one block; pad the sequence to a "
            "multiple of 8 (ideally 1024) upstream"
        )
        raise ValueError(
            f"flash attention block ({bq}x{bk}, head_dim {D}) needs "
            f"~{est / 2**20:.0f} MiB of VMEM, over the "
            f"{_VMEM_BUDGET // 2**20} MiB scoped budget: {why}."
        )


def _block_run(i, j, bq, bk, causal, window):
    """Grid-level predication: does block (i, j) intersect the visible
    band? Causal skips blocks entirely above the diagonal; a sliding
    window additionally skips blocks entirely LEFT of the band
    (min possible qpos - max possible kpos >= window). Returns a traced
    bool (or True when nothing is masked)."""
    run = True
    if causal:
        run = j * bk <= i * bq + bq - 1
    if window is not None:
        in_band = i * bq - (j * bk + bk - 1) < window
        run = in_band if run is True else jnp.logical_and(run, in_band)
    return run


def _block_mask(i, j, bq, bk, causal, window):
    """In-block (bq, bk) visibility mask for block (i, j), or None when
    nothing is masked (mirrors parallel/ring_attention._band_mask)."""
    if not causal and window is None:
        return None
    qpos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = None
    if causal:
        mask = kpos <= qpos
    if window is not None:
        band = qpos - kpos < window
        mask = band if mask is None else jnp.logical_and(mask, band)
    return mask


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying ``like``'s varying-mesh-axes, so the
    kernels are callable inside ``shard_map`` (e.g. as the per-device
    attention of Ulysses) where outputs must declare their vma.
    Toolchains without ``jax.typeof`` have no vma tracking either, so
    the plain struct is the correct degradation there."""
    typeof = getattr(jax, "typeof", None)
    vma = getattr(typeof(like), "vma", None) if typeof else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_sc, l_sc,
                *, scale, causal, window, bq, bk, nk):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)

    # skip blocks outside the visible band (above the causal diagonal,
    # or left of the sliding window)
    run = _block_run(i, j, bq, bk, causal, window)

    @pl.when(run)
    def _update():
        q = q_ref[0]  # (bq, D)
        kb = k_ref[0]  # (bk, D)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (bq, bk)
        mask = _block_mask(i, j, bq, bk, causal, window)
        if mask is not None:
            s = jnp.where(mask, s, _NEG)
        m_prev = m_sc[:, :1]  # (bq, 1)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)  # (bq, 1)
        l_sc[:] = jnp.broadcast_to(
            l_sc[:, :1] * corr + p.sum(axis=-1, keepdims=True),
            l_sc.shape,
        )
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_sc[:, :1], 1e-20)
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_sc[:, :1] + jnp.log(l)).astype(jnp.float32)


def _fwd(q3, k3, v3, scale, causal, window, bq, bk, g, interpret):
    """q3: (B*H, L, D); k3/v3: (B*Hkv, L, D) -> (o (B*H, L, D),
    lse (B*H, L, 1)). GQA costs nothing here: the grid runs over q
    heads and the K/V BlockSpec index maps divide the flattened
    batch*head index by the group size ``g`` — flattened q index
    b = batch*H + h reads k3[b // g] = batch*Hkv + h // g, so grouped
    K/V blocks are simply fetched g times from the same HBM pages, no
    repeated/materialized K ever exists."""
    BH, Lq, D = q3.shape
    Lk = k3.shape[1]
    nq, nk = Lq // bq, Lk // bk
    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, window=window, bq=bq,
        bk=bk, nk=nk,
    )
    return pl.pallas_call(
        kern,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // g, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            # lse is (BH, L, 1): a trailing singleton keeps the TPU block
            # tiling legal ((1, bq, 1): bq sublane-divisible, 1 == whole
            # trailing dim) and broadcasts cleanly in the backward
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _sds((BH, Lq, D), q3.dtype, q3),
            _sds((BH, Lq, 1), jnp.float32, q3),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
        ],
        compiler_params=_grid_params(),
        interpret=interpret,
    )(q3, k3, v3)


# --------------------------------------------------------------------------
# backward kernels (recompute from lse)
# --------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc, *, scale, causal, window, bq, bk, nk):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    run = _block_run(i, j, bq, bk, causal, window)

    @pl.when(run)
    def _update():
        q = q_ref[0]
        kb = k_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        mask = _block_mask(i, j, bq, bk, causal, window)
        if mask is not None:
            s = jnp.where(mask, s, _NEG)
        p = jnp.exp(s - lse_ref[0])  # (bq, bk); masked rows -> 0
        dp = jax.lax.dot_general(
            do_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        acc[:] = acc[:] + jax.lax.dot_general(
            ds, kb.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = (acc[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, window, bq, bk, nq):
    j, i = pl.program_id(1), pl.program_id(2)  # k block major, q innermost

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _block_run(i, j, bq, bk, causal, window)

    @pl.when(run)
    def _update():
        q = q_ref[0]
        kb = k_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        mask = _block_mask(i, j, bq, bk, causal, window)
        if mask is not None:
            s = jnp.where(mask, s, _NEG)
        p = jnp.exp(s - lse_ref[0])  # (bq, bk)
        do = do_ref[0].astype(jnp.float32)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bk, D)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])  # (bq, bk)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dkp_ref, dvp_ref, dq_acc,
                      *, scale, causal, window, bq, bk, nk):
    """Single-pass backward: one (i, j) sweep computes dq (accumulated
    over the inner j sweep in scratch) AND per-q-block dk/dv partials
    (reduced outside). The split kernels recompute s and dp twice —
    7 block-dots + 2 exps per (i, j); this shares them: 5 dots + 1 exp,
    a ~25% executed-FLOP cut exactly where the short-sequence
    attention tax lives (docs/PERF.md round-4 phase table)."""
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _block_run(i, j, bq, bk, causal, window)

    @pl.when(run)
    def _update():
        q = q_ref[0]
        kb = k_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        mask = _block_mask(i, j, bq, bk, causal, window)
        if mask is not None:
            s = jnp.where(mask, s, _NEG)
        p = jnp.exp(s - lse_ref[0])  # (bq, bk)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, kb.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dvp_ref[0, 0] = jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dvp_ref.dtype)
        dkp_ref[0, 0] = (
            jax.lax.dot_general(
                ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
        ).astype(dkp_ref.dtype)

    if causal or window is not None:
        @pl.when(jnp.logical_not(run))
        def _zero():
            # skipped band-exterior blocks still own their partial block
            dkp_ref[0, 0] = jnp.zeros_like(dkp_ref[0, 0])
            dvp_ref[0, 0] = jnp.zeros_like(dvp_ref[0, 0])

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _bwd_fused(q3, k3, v3, o3, lse, do3, scale, causal, window, bq, bk,
               g, interpret):
    """Fused backward dispatch: dq + f32 dk/dv partials per q block,
    reduced by one XLA sum (and group-summed for GQA). Partial HBM is
    (BH, nq, Lk, D) f32 — the traffic that made this variant measure
    SLOWER than the split kernels on the chip (``_use_fused_bwd``);
    it runs only under an explicit ``bwd_impl="fused"``."""
    BH, Lq, D = q3.shape
    Lk = k3.shape[1]
    nq, nk = Lq // bq, Lk // bk
    delta = jnp.sum(
        do3.astype(jnp.float32) * o3.astype(jnp.float32),
        axis=-1, keepdims=True,
    )
    dq, dkp, dvp = pl.pallas_call(
        functools.partial(
            _bwd_fused_kernel, scale=scale, causal=causal, window=window,
            bq=bq, bk=bk, nk=nk,
        ),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // g, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // g, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, i, j: (b, i, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, i, j: (b, i, j, 0)),
        ],
        out_shape=[
            _sds((BH, Lq, D), q3.dtype, q3),
            _sds((BH, nq, Lk, D), jnp.float32, k3),
            _sds((BH, nq, Lk, D), jnp.float32, v3),
        ],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_grid_params(),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    BHkv = BH // g
    dk = (
        dkp.reshape(BHkv, g * nq, Lk, D).sum(axis=1).astype(k3.dtype)
    )
    dv = (
        dvp.reshape(BHkv, g * nq, Lk, D).sum(axis=1).astype(v3.dtype)
    )
    return dq, dk, dv


def _use_fused_bwd() -> bool:
    """auto -> split, always. MEASURED NEGATIVE RESULT (round 4, real
    chip, flagship shape B=8 L=2048 H=8 Dh=128): the fused kernel's
    5-vs-7 block-dot saving is outweighed by its (BH, nq, Lk, D) f32
    partial writes + reduction — 27.5 ms vs the split kernels' 16.6 ms
    for the 8-layer attention phase. The kernel is VPU/HBM-co-bound at
    these shapes, so cutting MXU dots does not pay while the extra
    ~nq x f32 dk/dv traffic does. Kept selectable (bwd_impl="fused")
    so the measurement stays reproducible; docs/PERF.md round 4."""
    return False


def _bwd(q3, k3, v3, o3, lse, do3, scale, causal, window, bq, bk, g,
         interpret):
    BH, Lq, D = q3.shape
    Lk = k3.shape[1]
    nq, nk = Lq // bq, Lk // bk
    delta = jnp.sum(
        do3.astype(jnp.float32) * o3.astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # (BH, Lq, 1), same trailing-singleton layout as lse

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, window=window,
            bq=bq, bk=bk, nk=nk,
        ),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // g, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // g, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=_sds((BH, Lq, D), q3.dtype, q3),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_grid_params(),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    # dk/dv: each grid-b is ONE q head, writing its own (B*H)-indexed
    # output block — per-q-head partials, no cross-head write conflicts
    # under the parallel grid axis. The group-sum down to the B*Hkv kv
    # heads happens outside the kernel: flattened q index b = batch*H +
    # hkv*g + g_idx = (batch*Hkv + hkv)*g + g_idx, so a (B*Hkv, g, Lk,
    # D) reshape puts the group on axis 1 and one XLA reduction
    # finishes the job.
    dkq, dvq = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, window=window,
            bq=bq, bk=bk, nq=nq,
        ),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b // g, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b // g, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _sds((BH, Lk, D), k3.dtype, k3),
            _sds((BH, Lk, D), v3.dtype, v3),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=_grid_params(),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    if g == 1:
        return dq, dkq, dvq
    BHkv = BH // g
    dk = dkq.reshape(BHkv, g, Lk, D).sum(axis=1).astype(k3.dtype)
    dv = dvq.reshape(BHkv, g, Lk, D).sum(axis=1).astype(v3.dtype)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom-vjp wrapper over (BH, L, D) tensors
# --------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10)
)
def _flash3(q3, k3, v3, scale, causal, window, bq, bk, g, fused_bwd,
            interpret):
    o, _ = _fwd(q3, k3, v3, scale, causal, window, bq, bk, g, interpret)
    return o


def _flash3_fwd(q3, k3, v3, scale, causal, window, bq, bk, g, fused_bwd,
                interpret):
    o, lse = _fwd(q3, k3, v3, scale, causal, window, bq, bk, g, interpret)
    return o, (q3, k3, v3, o, lse)


def _flash3_bwd(scale, causal, window, bq, bk, g, fused_bwd, interpret,
                res, do3):
    q3, k3, v3, o3, lse = res
    impl = _bwd_fused if fused_bwd else _bwd
    return impl(
        q3, k3, v3, o3, lse, do3, scale, causal, window, bq, bk, g,
        interpret,
    )


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    window: int | None = None,
    block_q: int = 1024,
    block_k: int = 1024,
    bwd_impl: str = "auto",
    interpret: bool | None = None,
) -> jax.Array:
    """Fused flash attention on (B, L, H, D) tensors; differentiable.

    Drop-in for :func:`~..parallel.ring_attention.reference_attention`
    (same layout, same causal semantics) without materializing (L, L)
    scores. Block sizes shrink automatically to divide the sequence
    lengths; ``interpret`` defaults to compiled on TPU and interpret
    mode elsewhere.

    Block defaults are tuned on the real chip (round 3, docs/PERF.md):
    1024x1024 is ~5x the forward throughput of 128x128 (small blocks
    drown in grid overhead — 16k grid steps at L=2048) and the largest
    size whose backward kernels stay inside the 16 MiB VMEM scoped
    allocation (2048-blocks compile for the forward but OOM the dk/dv
    kernel's scratch).

    ``bwd_impl``: ``"split"`` runs the classic two backward kernels
    (dq over the k sweep; dk/dv over the q sweep — each recomputes
    s/dp, 7 block-dots total); ``"fused"`` runs one kernel sharing the
    recompute (5 block-dots) at the cost of an (BH, nq, Lk, D) f32
    dk/dv-partial buffer reduced outside. ``"auto"`` (default)
    resolves to split: the fused variant measured SLOWER on the chip
    at the flagship shape (27.5 vs 16.6 ms for the 8-layer phase) —
    the partial-buffer HBM traffic outweighs the dot saving on this
    VPU/HBM-co-bound kernel (see ``_use_fused_bwd``; docs/PERF.md).
    """
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    Hkv = k.shape[2]
    if H % Hkv != 0:
        raise ValueError(
            f"q heads ({H}) must be a multiple of kv heads ({Hkv})"
        )
    g = H // Hkv
    if scale is None:
        scale = D ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    bq = _pick_block(Lq, block_q)
    bk = _pick_block(Lk, block_k)
    if not interpret:  # the interpreter has no VMEM to blow
        _check_vmem(bq, bk, D, q.dtype.itemsize)
    if bwd_impl == "auto":
        fused_bwd = _use_fused_bwd()
    elif bwd_impl in ("split", "fused"):
        fused_bwd = bwd_impl == "fused"
    else:
        raise ValueError(
            f"bwd_impl must be 'auto'|'split'|'fused', got {bwd_impl!r}"
        )

    def to3(x, L, h):
        return x.transpose(0, 2, 1, 3).reshape(B * h, L, D)

    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    o3 = _flash3(
        to3(q, Lq, H), to3(k, Lk, Hkv), to3(v, Lk, Hkv),
        float(scale), bool(causal),
        None if window is None else int(window), bq, bk, g, fused_bwd,
        bool(interpret),
    )
    return o3.reshape(B, H, Lq, D).transpose(0, 2, 1, 3)
