"""MDS-coded distributed GEMM: decode ``C = A @ B`` from any k of n chips.

BASELINE config 3: (n=8, k=6) systematic Reed–Solomon-style row blocks,
``nwait=6``. The pipeline:

1. setup: row-partition ``A`` into k source blocks, MDS-encode into n
   coded blocks (one MXU einsum, ops/coding.py), place coded block i on
   worker i's device;
2. per epoch: broadcast ``B`` via ``asyncmap``; worker i computes
   ``Ã_i @ B`` — because encoding is linear, the coded results are the
   same code applied to the true row blocks of ``C``;
3. return when ``nwait >= k`` workers are fresh (integer nwait or the
   :func:`~.coding.nwait_decodable` predicate);
4. decode: pick the first k fresh shards by the ``repochs`` mask, solve
   the k×k system, restack — the *full* product, stragglers ignored.

The reference can express step 3's wait (its fastest-k return) but has no
coded layer (SURVEY §2: no model/workload code of any kind); this module
is the north-star capability BASELINE.json prescribes.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backends.base import DelayFn
from ..backends.xla import XLADeviceBackend
from ..pool import AsyncPool
from .coding import MDSCode, nwait_decodable
from functools import partial

from ._batch import batch_dispatch, build_device_groups
from .gemm import _block_matmul


@partial(jax.jit, static_argnames=("precision",))
def _decode_from_stack(stacked, rows, G_S, precision):
    # one program: gather the k winners out of the fused stack and
    # delegate to the shared k x k decode (ops/coding.py — ONE decode
    # implementation), restacked to the flat (k*r, c) product layout.
    # `rows` is a traced index array: arrival order varies per epoch,
    # and a static tuple would recompile per ordering (P(n,k) programs)
    from .coding import _decode

    shards = stacked[rows]
    blocks = _decode(G_S, shards, precision)
    return blocks.reshape(-1, *blocks.shape[2:])
from .lt import LTCode, nwait_lt_decodable


class CodedGemm:
    """``C = A @ B`` recoverable from any k of n workers.

    >>> cg = CodedGemm(A, n=8, k=6)
    >>> pool = AsyncPool(8, nwait=6)
    >>> repochs = asyncmap(pool, B, cg.backend)      # waits for 6 of 8
    >>> C = cg.result(pool)                          # exact full product
    """

    def __init__(
        self,
        A: np.ndarray,
        n: int,
        k: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        parity: str = "cauchy",
        dtype=None,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
        batch: bool = False,
        batch_arrival: str = "ready",
        registry=None,
    ):
        """``batch=True`` turns on coalesced dispatch: all pool workers
        sharing a device run as ONE fused stacked-einsum program per
        epoch (XLADeviceBackend batch mode) instead of one program per
        worker. On a single chip this removes the per-worker dispatch
        round-trip — the dominant epoch cost — at the price of per-worker
        straggler independence on that chip (which a time-sliced single
        chip does not truly have anyway; a real slice runs one worker
        per device and is unaffected). Incompatible with ``delay_fn``.

        ``registry=`` (an :class:`~..obs.MetricsRegistry`, opt-in like
        the pool's ``tracer=``) counts decodes and records, per worker,
        how often the fastest-k recovery actually consumed its shard —
        the "which k of n fired" series the straggler story needs."""
        if dtype is not None:
            A = np.asarray(A, dtype=dtype)
        m = A.shape[0]
        if m % k != 0:
            raise ValueError(f"rows {m} must divide evenly into k={k} blocks")
        if devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        self.code = MDSCode(n, k, parity=parity, dtype=A.dtype,
                            precision=precision)
        self.n, self.k = n, k
        self.block_rows = m // k
        self.precision = precision
        # encode once (on the default device), then distribute coded
        # blocks to their workers' devices
        coded = self.code.encode_array(A)
        # batch mode: the fused per-device stacks are the ONLY device
        # copy (ops/_batch.py — the per-worker dispatch path never runs
        # there, so device-resident individual blocks would be dead
        # HBM); per-worker blocks stay host-side numpy views. Non-batch
        # mode places each block on its worker's device as before.
        self._group_of: dict[int, tuple] = {}
        if batch:
            coded_host = np.asarray(coded)
            self.blocks = [coded_host[i] for i in range(n)]
            self._group_of = build_device_groups(
                self.blocks, n, self.devices
            )
        else:
            self.blocks = [
                jax.device_put(coded[i], devices[i % len(devices)])
                for i in range(n)
            ]
        self.backend = XLADeviceBackend(
            self._work, n, devices=devices, delay_fn=delay_fn,
            batch_fn=self._batch_work if batch else None,
            batch_arrival=batch_arrival,
        )
        # opt-in decode telemetry (instruments resolved once; None =
        # dark, result_device pays one `is None` check)
        self._m = None
        if registry is not None:
            registry.gauge(
                "coded_gemm_n", help="workers n of the MDS code"
            ).set(n)
            registry.gauge(
                "coded_gemm_k", help="recovery threshold k"
            ).set(k)
            self._m = {
                "decodes": registry.counter(
                    "coded_gemm_decodes_total",
                    help="full products decoded",
                ),
                "fresh_k": registry.gauge(
                    "coded_gemm_last_fresh",
                    help="fresh shards available at the last decode",
                ),
                "recovered": [
                    registry.counter(
                        "coded_gemm_worker_recovered_total",
                        help="decodes that consumed this worker's shard",
                        worker=str(i),
                    )
                    for i in range(n)
                ],
            }

    def _work(self, i: int, payload: jax.Array, epoch: int) -> jax.Array:
        return _block_matmul(self.blocks[i], payload, precision=self.precision)

    def _batch_work(self, ids, payload: jax.Array, epoch: int) -> jax.Array:
        """Fused dispatch: the shards of every worker in ``ids`` in one
        stacked matmul (one MXU program, one dispatch round-trip). All
        ``ids`` share a device (the backend groups by device)."""
        return batch_dispatch(self._group_of, ids, payload, self.precision)

    @property
    def nwait(self):
        """Decodability predicate for ``asyncmap(nwait=...)``."""
        return nwait_decodable(self.k)

    def result_device(
        self, pool: AsyncPool, epoch: int | None = None
    ) -> jax.Array:
        """Decode the full product from the first k fresh shards, leaving
        it device-resident — the TPU-native output form, ready to feed the
        next device computation without a host round-trip (host transfer
        is the expensive edge of the system, not HBM)."""
        fresh = pool.fresh_indices(epoch)
        if fresh.size < self.k:
            raise ValueError(
                f"only {fresh.size} fresh shards at epoch "
                f"{pool.epoch if epoch is None else epoch}, need k={self.k}"
            )
        idx = fresh[: self.k]
        if self._m is not None:
            self._m["decodes"].inc()
            self._m["fresh_k"].set(fresh.size)
            for i in idx:
                self._m["recovered"][int(i)].inc()
        results = [pool.results[i] for i in idx]
        # batch-mode fast path: the k winners are lazy views of ONE
        # fused stack — decode straight off it in a single device
        # program (gather + solve fused), zero per-worker slice ops
        from ..backends.xla import StackedSlice

        if all(isinstance(r, StackedSlice) for r in results) and all(
            r.stacked is results[0].stacked for r in results
        ):
            rows = jnp.asarray([r.index for r in results])
            G_S = jnp.asarray(self.code.G[np.asarray(idx)])
            return _decode_from_stack(
                results[0].stacked, rows, G_S, self.precision
            )
        # general path: stack the k winners' independent results
        shards = jnp.stack([
            jax.device_put(jnp.asarray(r), self.devices[0])
            for r in results
        ])
        return self.code.decode_array(shards, idx)

    def result(self, pool: AsyncPool, epoch: int | None = None) -> np.ndarray:
        """Decode the full product from the first k fresh shards (host copy)."""
        return np.asarray(self.result_device(pool, epoch))

    def coordinator(self, *, delay_fn=None, nwait=None, **kw):
        """A :class:`~..parallel.device_coord.DeviceCoordinator`
        sharing this workload's coded blocks, generator, and backend:
        K epochs of arrival masking + fastest-``nwait`` selection +
        this decode as ONE compiled program, harvested through
        :func:`~..pool.asyncmap_fused` (lazy import — parallel/ sits
        above ops/ in the layer order)."""
        from ..parallel.device_coord import DeviceCoordinator

        return DeviceCoordinator.for_coded_gemm(
            self, delay_fn=delay_fn, nwait=nwait, **kw
        )


class LTCodedGemm:
    """LT/rateless-coded GEMM (BASELINE config 4).

    Each of the n workers takes one rateless shard id; worker i holds the
    real-field sum of its shard's source blocks of ``A`` (device-
    resident). ``nwait`` is the *decodability* predicate: ``asyncmap``
    returns at the first arrival set whose shards peel, not at a fixed
    count. Decode is host-side peeling (ops/lt.py) — cheap 0/1
    subtractions, no solve.
    """

    def __init__(
        self,
        A: np.ndarray,
        n_workers: int,
        k: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        seed: int = 0,
        dtype=None,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
        shard_ids: Sequence[int] | None = None,
        systematic: bool = False,
    ):
        if dtype is not None:
            A = np.asarray(A, dtype=dtype)
        m = A.shape[0]
        if m % k != 0:
            raise ValueError(f"rows {m} must divide evenly into k={k} blocks")
        if devices is None:
            devices = jax.devices()
        self.code = LTCode(k, seed=seed, systematic=systematic)
        self.k = k
        self.n = n_workers
        self.devices = list(devices)
        self.block_rows = m // k
        self.precision = precision
        if shard_ids is None:
            # rateless: any distinct ids work; slide a window over the
            # unbounded shard stream until the full set peels (so
            # nwait=n is always satisfiable)
            shard_ids = list(range(n_workers))
            for _ in range(1000):
                if self.code.peelable(shard_ids):
                    break
                shard_ids = [s + 1 for s in shard_ids]
            else:
                raise ValueError(
                    f"no decodable window of {n_workers} shards found for "
                    f"k={k}; increase n_workers/k ratio"
                )
        elif not self.code.peelable(shard_ids):
            # otherwise the nwait predicate can never fire and the pool
            # would die deep inside wait_any with an opaque error
            raise ValueError(
                f"shard_ids {list(shard_ids)} are not decodable even with "
                f"all workers fresh (peeling stalls); choose a different set"
            )
        self.shard_ids = list(shard_ids)
        G = self.code.generator_rows(self.shard_ids)  # (n, k) 0/1
        blocks = jnp.asarray(A).reshape(k, m // k, *A.shape[1:])
        coded = jnp.einsum("nk,krc->nrc", jnp.asarray(G), blocks,
                           precision=precision)
        self.blocks = [
            jax.device_put(coded[i], devices[i % len(devices)])
            for i in range(n_workers)
        ]
        self.backend = XLADeviceBackend(
            self._work, n_workers, devices=devices, delay_fn=delay_fn
        )

    def _work(self, i: int, payload: jax.Array, epoch: int) -> jax.Array:
        return _block_matmul(self.blocks[i], payload, precision=self.precision)

    @property
    def nwait(self):
        """Variable decodability predicate for ``asyncmap(nwait=...)``."""
        return nwait_lt_decodable(self.code, self.shard_ids)

    def result(self, pool: AsyncPool, epoch: int | None = None) -> np.ndarray:
        fresh = pool.fresh_indices(epoch)
        if fresh.size == 0:
            raise ValueError(f"no fresh shards at epoch {pool.epoch}")
        shards = np.stack([np.asarray(pool.results[i]) for i in fresh])
        ids = [self.shard_ids[i] for i in fresh]
        return self.code.decode_array(shards, ids)

    def result_device(
        self, pool: AsyncPool, epoch: int | None = None
    ) -> jax.Array:
        """Decode on device, leaving the product in HBM.

        Host peeling (:meth:`result`) is the exact LT algorithm but
        forces a D2H gather of every shard — the slow edge. Peelability
        of the arrived set implies the 0/1 generator has full rank, so
        the same system solves as one MXU-friendly k x k linear solve
        over a full-rank row subset, identical math to the MDS decode.
        """
        fresh = pool.fresh_indices(epoch)
        ids = [self.shard_ids[i] for i in fresh]
        if not self.code.peelable(ids):
            raise ValueError(
                f"fresh shards {ids} at epoch "
                f"{pool.epoch if epoch is None else epoch} are not decodable"
            )
        G = self.code.generator_rows(ids)  # (m, k) 0/1, full column rank
        sel: list[int] = []
        for r in range(len(ids)):  # greedy full-rank row subset (tiny G)
            if np.linalg.matrix_rank(G[sel + [r]]) == len(sel) + 1:
                sel.append(r)
                if len(sel) == self.k:
                    break
        G_S = jnp.asarray(G[sel])
        shards = jnp.stack([
            jax.device_put(jnp.asarray(pool.results[fresh[r]]),
                           self.devices[0])
            for r in sel
        ])
        from .coding import _decode

        blocks = _decode(G_S, shards, self.precision)
        return blocks.reshape(-1, *blocks.shape[2:])

    def coordinator(self, *, delay_fn=None, nwait=None, **kw):
        """Fused K-epoch windows for this LT window (see
        :meth:`CodedGemm.coordinator`): the in-scan decode is masked
        normal equations over the fresh 0/1 generator rows, exact
        whenever the fresh set has full column rank."""
        from ..parallel.device_coord import DeviceCoordinator

        return DeviceCoordinator.for_lt_gemm(
            self, delay_fn=delay_fn, nwait=nwait, **kw
        )
