"""Polynomial-coded GEMM: partition BOTH factors, decode from any pq of n.

MDS row-coding (ops/coding.py) replicates the whole payload ``B`` to
every worker — fine when ``A`` dominates, wasteful otherwise. Polynomial
codes (Yu, Maddah-Ali, Avestimehr, 2017 — public technique) partition
``A`` into p row blocks AND ``B`` into q column blocks; worker i
computes the single product ``Ã_i @ B̃_i`` of the polynomial evaluations

    Ã_i = Σ_j A_j x_i^j           (j < p)
    B̃_i = Σ_l B_l x_i^(l·p)      (l < q)

so ``C̃_i = Ã_i @ B̃_i = Σ_{j,l} (A_j @ B_l) x_i^(j + l·p)`` is the
evaluation at ``x_i`` of a matrix polynomial whose pq coefficients are
exactly the blocks of ``C = A @ B``. Any pq distinct evaluations
determine the coefficients — the recovery threshold is pq with every
worker doing only 1/(pq) of the multiply work (vs 1/k of the full-B
product under MDS row coding).

TPU-first choices:

* **Workers encode their own B̃_i** from the *broadcast* raw ``B`` — a
  cheap weighted sum over q column blocks fused in front of the worker
  matmul. This preserves the pool's snapshot-broadcast semantics
  (reference src/MPIAsyncPools.jl:51-61): the coordinator dispatches one
  payload, nothing per-worker crosses the slow edge, and on a slice the
  broadcast rides ICI once instead of shipping n distinct B̃_i.
* **Chebyshev evaluation points** ``x_i = cos((2i+1)π/2n)``: the
  resulting Vandermonde systems are far better conditioned than
  equispaced points, which is what makes real-field (MXU-matmul) decode
  viable — SURVEY §7's "Float64 / conditioning" hard part.
* **Decode is one pq×pq solve** plus block reassembly, device-resident.

The ``repochs`` arrival mask selects which evaluations decode — the same
fastest-k mechanism as every other coded workload here (SURVEY §2.1).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backends.base import DelayFn
from ._evalgemm import EvalPointCodedGemm, chebyshev_points

__all__ = ["PolynomialCode", "PolyCodedGemm"]


@partial(jax.jit, static_argnames=("q", "precision"))
def _poly_worker(A_i, w_i, B, q, precision):
    # B: (kd, nc) -> (kd, q, nc/q) column blocks; B̃_i = Σ_l w_i[l] B_l
    kd, nc = B.shape
    Bq = B.reshape(kd, q, nc // q)
    B_enc = jnp.einsum("l,klw->kw", w_i, Bq, precision=precision)
    return jnp.matmul(A_i, B_enc, precision=precision)


@partial(jax.jit, static_argnames=("precision",))
def _poly_decode(V_S, shards, precision):
    # shards: (pq, r, w) evaluations; solve V_S @ coeffs = shards
    pq = V_S.shape[0]
    flat = shards.reshape(pq, -1)
    coeffs = jax.scipy.linalg.solve(V_S, flat)
    return coeffs.reshape(shards.shape)


class PolynomialCode:
    """(p, q) polynomial code over n workers, recovery threshold pq.

    >>> code = PolynomialCode(p=2, q=2, n=6)
    >>> A_enc = code.encode_A(A_blocks)   # (p,r,c) -> (6,r,c)
    >>> # worker i: A_enc[i] @ (sum_l B_weights[i,l] * B_l)
    >>> C_blocks = code.decode(shards, indices)   # any 4 of 6
    """

    def __init__(
        self,
        p: int,
        q: int,
        n: int,
        *,
        dtype=np.float32,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
    ):
        if p < 1 or q < 1:
            raise ValueError(f"need p, q >= 1, got p={p}, q={q}")
        if n < p * q:
            raise ValueError(
                f"need n >= p*q workers for decodability, got n={n} < "
                f"{p}*{q}={p * q}"
            )
        self.p, self.q, self.n = int(p), int(q), int(n)
        self.k = self.p * self.q  # recovery threshold
        self.precision = precision
        # Chebyshev nodes: well-conditioned real Vandermonde systems
        self.points = chebyshev_points(self.n)
        # A-encode weights x_i^j, B-encode weights x_i^(l*p), decode
        # Vandermonde x_i^t for t < pq
        self.VA = (self.points[:, None] ** np.arange(self.p)).astype(dtype)
        self.VB = (
            self.points[:, None] ** (self.p * np.arange(self.q))
        ).astype(dtype)
        self.VC = (self.points[:, None] ** np.arange(self.k)).astype(dtype)

    def encode_A(self, blocks) -> jax.Array:
        """(p, rows, cols) row blocks of A -> (n, rows, cols) evaluations."""
        blocks = jnp.asarray(blocks)
        if blocks.shape[0] != self.p:
            raise ValueError(
                f"expected {self.p} A-blocks, got {blocks.shape[0]}"
            )
        return jnp.einsum(
            "nj,jrc->nrc", jnp.asarray(self.VA), blocks,
            precision=self.precision,
        )

    def decode(self, shards, indices) -> jax.Array:
        """Recover the pq coefficient blocks from any pq evaluations.

        ``shards``: (pq, rows, w) stacked worker products; ``indices``:
        which worker (= evaluation point) each came from. Returns
        ``(pq, rows, w)`` where entry ``t`` is ``A_{t % p} @ B_{t // p}``.
        """
        idx = np.asarray(indices)
        if idx.shape[0] != self.k or len(set(idx.tolist())) != self.k:
            raise ValueError(
                f"need exactly pq={self.k} distinct shard indices, got {idx}"
            )
        shards = jnp.asarray(shards)
        if shards.shape[0] != self.k:
            raise ValueError(f"expected {self.k} shards, got {shards.shape[0]}")
        return _poly_decode(
            jnp.asarray(self.VC[idx]), shards, self.precision
        )

    def assemble(self, coeffs) -> jax.Array:
        """(pq, r, w) coefficient blocks -> full (p*r, q*w) product."""
        pq, r, w = coeffs.shape
        # t = j + l*p  ->  grid[l, j] = C block at rows j, cols l
        grid = coeffs.reshape(self.q, self.p, r, w)
        return jnp.block([
            [grid[l, j] for l in range(self.q)] for j in range(self.p)
        ])


class PolyCodedGemm(EvalPointCodedGemm):
    """``C = A @ B`` from any pq of n workers, both factors partitioned.

    Worker i holds the static evaluation ``Ã_i`` (m/p × kd) and encodes
    its own ``B̃_i`` from the broadcast payload, so per-worker compute
    and memory are 1/(pq) of the full product (vs 1/k compute with full
    B under :class:`~.coded_gemm.CodedGemm`).

    >>> pg = PolyCodedGemm(A, p=2, q=2, n=6)
    >>> pool = AsyncPool(6)
    >>> repochs = asyncmap(pool, B, pg.backend, nwait=4)
    >>> C = pg.result_device(pool)        # exact A @ B from 4 of 6
    """

    def __init__(
        self,
        A: np.ndarray,
        p: int,
        q: int,
        n: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        dtype=None,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
    ):
        if dtype is not None:
            A = np.asarray(A, dtype=dtype)
        m = A.shape[0]
        if m % p != 0:
            raise ValueError(f"rows {m} must divide evenly into p={p} blocks")
        if devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        self.code = PolynomialCode(p, q, n, dtype=A.dtype, precision=precision)
        self.p, self.q, self.n = p, q, n
        self.block_rows = m // p
        self.precision = precision
        coded = self.code.encode_A(
            jnp.asarray(A).reshape(p, m // p, A.shape[1])
        )
        self._setup_workers(coded, self.code.VB, n, devices, delay_fn)

    def _work(self, i: int, payload: jax.Array, epoch: int) -> jax.Array:
        if payload.shape[1] % self.q != 0:
            raise ValueError(
                f"B cols {payload.shape[1]} must divide evenly into "
                f"q={self.q} blocks"
            )
        return _poly_worker(
            self.A_shards[i], self.B_weights[i], payload, self.q,
            self.precision,
        )

    def _decode_shards(self, shards, idx):
        return self.code.assemble(self.code.decode(shards, idx))
