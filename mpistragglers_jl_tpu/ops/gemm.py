"""Distributed matrix multiplication over an async device pool.

Uncoded row-block GEMM (BASELINE config 2): ``C = A @ B`` with ``A`` row-
partitioned over n workers. Worker ``w`` holds its block ``A_w`` resident
on its device (placed once at setup — the reference's analog is each MPI
worker holding its data slice process-locally) and each epoch receives
``B`` as the broadcast payload, computing ``C_w = A_w @ B`` on the MXU.

The reference library is payload-agnostic and has no model/workload code
at all (SURVEY §5 "Long-context" row: the library is bytes-over-MPI,
src/MPIAsyncPools.jl:82-84); distributed GEMM is the north-star workload
BASELINE.json prescribes on top of the pool primitive. Design notes:

* blocks are placed device-resident once; only ``B`` moves per epoch —
  the HBM-friendly layout (A never re-crosses PCIe/ICI);
* the per-worker program is a single large matmul in the worker's native
  dtype (bf16/f32 on TPU MXU, f64 available on the CPU backend);
* ``nwait < n`` returns a row-partial product with ``repochs`` as the
  per-block freshness mask — the uncoded base case of the coded layer
  (ops/coding.py), which makes missing blocks recoverable.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backends.base import DelayFn
from ..backends.xla import XLADeviceBackend
from ..pool import AsyncPool


from functools import partial


@partial(jax.jit, static_argnames=("precision",))
def _block_matmul(a_block: jax.Array, b: jax.Array, precision=None) -> jax.Array:
    return jnp.matmul(a_block, b, precision=precision)


def gather_rows(
    pool: AsyncPool,
    epoch: int | None = None,
    *,
    row_splits: Sequence[int] | None = None,
) -> np.ndarray:
    """Assemble the row-stacked result from per-worker results.

    Rows from workers whose ``repochs[i] != epoch`` are zero-filled; the
    per-row-block freshness mask is ``pool.repochs == epoch`` (i.e. the
    value ``asyncmap`` returned) — callers needing staleness policy read
    that, this function only stacks. ``row_splits`` gives each worker's
    row count when blocks are heterogeneous (load-balanced splits);
    without it all blocks must be the same shape. Raises ``ValueError``
    if no worker has any result at all for the requested epoch.
    """
    if epoch is None:
        epoch = pool.epoch
    # convert only fresh blocks — stale device-resident results must not
    # pay a D2H transfer just to be replaced by zeros
    blocks = [
        np.asarray(pool.results[i])
        if pool.results[i] is not None and pool.repochs[i] == epoch
        else None
        for i in range(pool.n_workers)
    ]
    proto = next((b for b in blocks if b is not None), None)
    if proto is None:
        if all(r is None for r in pool.results):
            raise ValueError("no worker has returned any result yet")
        raise ValueError(f"no worker has a result for epoch {epoch}")
    if row_splits is None:  # homogeneous blocks: all shaped like proto
        row_splits = [proto.shape[0]] * pool.n_workers
    out = [
        b if b is not None
        else np.zeros((row_splits[i], *proto.shape[1:]), proto.dtype)
        for i, b in enumerate(blocks)
    ]
    return np.concatenate(out, axis=0)


class DistributedGemm:
    """``C = A @ B`` row-partitioned over an async pool of devices.

    >>> g = DistributedGemm(A, n_workers=8)
    >>> pool = AsyncPool(8)
    >>> repochs = asyncmap(pool, B, g.backend)   # broadcast B, fastest-k
    >>> C = g.result(pool)                       # stack fresh row blocks
    """

    def __init__(
        self,
        A: np.ndarray,
        n_workers: int,
        *,
        row_splits: Sequence[int] | None = None,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        dtype=None,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
        batch: bool = False,
        batch_arrival: str = "ready",
    ):
        # HIGHEST by default: the TPU MXU's native matmul accumulates in
        # bf16-ish precision (observed max err ~0.25 on a 512-deep f32
        # contraction vs 5e-5 at HIGHEST); coded decode paths need the
        # accuracy. Benchmarks may pass precision=None for peak MXU rate.
        #
        # ``batch=True``: coalesced dispatch — each device's workers run
        # as ONE fused stacked matmul per epoch (see CodedGemm/PERF.md);
        # requires homogeneous row_splits, incompatible with delay_fn.
        self.precision = precision
        m = A.shape[0]
        if row_splits is None:
            if m % n_workers != 0:
                raise ValueError(
                    f"rows {m} must divide evenly over {n_workers} workers "
                    "(or pass row_splits)"
                )
            row_splits = [m // n_workers] * n_workers
        else:
            row_splits = [int(r) for r in row_splits]
            if len(row_splits) != n_workers:
                raise ValueError(
                    f"row_splits has {len(row_splits)} entries for "
                    f"{n_workers} workers"
                )
            if any(r < 0 for r in row_splits) or sum(row_splits) != m:
                raise ValueError(
                    f"row_splits must be non-negative and sum to {m}, "
                    f"got {row_splits}"
                )
        if devices is None:
            devices = jax.devices()
        if dtype is not None:
            A = np.asarray(A, dtype=dtype)
        self.n_workers = n_workers
        self.row_splits = row_splits
        offsets = np.concatenate([[0], np.cumsum(row_splits)])
        self._group_of: dict[int, tuple] = {}
        if batch:
            if len(set(row_splits)) != 1:
                raise ValueError(
                    "batch=True needs homogeneous row_splits (the fused "
                    "program stacks equal-shaped blocks)"
                )
            from ._batch import build_device_groups

            # fused per-device stacks are the only device copy; the
            # per-worker blocks stay host-side views (ops/_batch.py)
            self.blocks = [
                A[offsets[i] : offsets[i + 1]]
                for i in range(n_workers)
            ]
            self._group_of = build_device_groups(
                self.blocks, n_workers, devices
            )
        else:
            # place each row block on its worker's device once, up front
            self.blocks = [
                jax.device_put(
                    A[offsets[i] : offsets[i + 1]],
                    devices[i % len(devices)],
                )
                for i in range(n_workers)
            ]
        self.backend = XLADeviceBackend(
            self._work, n_workers, devices=devices, delay_fn=delay_fn,
            batch_fn=self._batch_work if batch else None,
            batch_arrival=batch_arrival,
        )

    def _batch_work(self, ids, payload: jax.Array, epoch: int) -> jax.Array:
        """Fused dispatch: every worker's row-block matmul in one MXU
        program (shared machinery, ops/_batch.py)."""
        from ._batch import batch_dispatch

        return batch_dispatch(self._group_of, ids, payload, self.precision)

    @classmethod
    def load_balanced(
        cls, A: np.ndarray, model, **kwargs
    ) -> "DistributedGemm":
        """Split rows proportional to fitted worker speed — the uncoded
        straggler mitigation: slow workers get less work instead of
        being raced (``model`` is a fitted
        :class:`~..utils.straggle.PoolLatencyModel`).

        >>> model.observe_pool(pool)       # ... over some epochs
        >>> g = DistributedGemm.load_balanced(A, model)
        """
        splits = model.proportional_shares(A.shape[0])
        return cls(
            A, model.n_workers, row_splits=splits.tolist(), **kwargs
        )

    def _work(self, i: int, payload: jax.Array, epoch: int) -> jax.Array:
        return _block_matmul(self.blocks[i], payload, precision=self.precision)

    def result(self, pool: AsyncPool, epoch: int | None = None) -> np.ndarray:
        return gather_rows(pool, epoch, row_splits=self.row_splits)
