"""Hierarchical two-level coded GEMM: XOR/LT across hosts, MDS within.

The flat :class:`~.coded_gemm.CodedGemm` pays a full Reed–Solomon-style
solve over the whole fleet and its resilience unit is a single slow
*chip*: with (n, k) over H hosts of ``n_inner`` chips each, surviving a
whole-host failure forces ``k <= (H-1) * n_inner`` — and once a host is
down the decoder needs EVERY surviving chip, so one laggard anywhere
stalls the epoch, and the decode solves a ``k x k`` system with
``k ~ (H-1) * n_inner``. The two-level construction (ROADMAP item 3;
arxiv 1904.11563's Array BP-XOR hierarchy, priced against the
map-shuffle-reduce latency–communication trade-off of arxiv 1808.06583)
fixes both at once:

* **inner**: each host group runs the existing (n_inner, k_inner) MDS
  code (or a fixed-window LT code) over its chip mesh — per-chip
  straggler slack *within every host*;
* **outer**: a cheap sum-parity / LT code (``ops/outer_code.py``, the
  generator machinery :mod:`.rateless` draws from) striped ACROSS the
  H groups — any lost group is reconstructed from the survivors by 0/1
  subtraction chains, O(n) per element, never a solve.

Decode cost drops from one ``O(((H-1) n_inner)^3)`` solve + its
``O(k^2)``-per-row apply to ``L`` small ``O(k_inner^3)`` solves plus an
O(n) outer pass (docs/PERF.md round-14 worked example), and the epoch
returns the moment ``L`` groups each clear their *inner* floor — a
straggling or dead host is simply never waited on.

The pool wiring is the reference's functional-``nwait`` mechanism,
nothing new: :func:`~.outer_code.hierarchical_nwait` evaluates the
two-level completion rule over the live ``repochs`` after every
arrival, so ``asyncmap(pool, B, backend, nwait=hg.nwait)`` is the whole
coordinator loop. Fleet partitions come from
:func:`~..parallel.multihost.host_groups` on a real multi-host mesh
(inner code on ICI, outer stripe across DCN) or an even split in
single-host / simulated runs.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backends.base import DelayFn
from ..backends.xla import XLADeviceBackend
from ..pool import AsyncPool
from .coding import MDSCode, _decode
from .gemm import _block_matmul
from .lt import LTCode
from .outer_code import hierarchical_nwait, make_outer, partition_groups

__all__ = ["HierarchicalCodedGemm", "decode_groups"]


@jax.jit
def _decode_groups(G_S, shards):
    """ALL used groups' inner MDS decodes as ONE program: a vmapped
    batch of small ``k_inner x k_inner`` solves. One decode per group
    (the first cut) paid per-call dispatch overhead L times over —
    measured 0.84x the flat decode at the bench shape; batched, the
    decode does its ``L * O(k_inner^3)`` work in a single dispatch and
    the >= 2x decode-cost win is real (docs/PERF.md round-14).

    ``G_S``: (g, k, k) per-group generator submatrices; ``shards``:
    (g, k, rows, cols) per-group fresh shard stacks."""
    g, k = shards.shape[0], shards.shape[1]
    flat = shards.reshape(g, k, -1)
    X = jax.vmap(jax.scipy.linalg.solve)(G_S, flat)
    return X.reshape(shards.shape)


# Public traceable alias: the fused device-coordination scan body
# (parallel/device_coord.py) embeds this exact vmapped batch per epoch
# — jit-inside-jit inlines, so the round-14 decode arithmetic has ONE
# implementation whether the trigger is the host loop or a compiled
# K-epoch window.
decode_groups = _decode_groups


class HierarchicalCodedGemm:
    """``C = A @ B`` recoverable from any outer-floor-many host groups,
    each recoverable from any ``k_inner`` of its ``n_inner`` chips.

    >>> hg = HierarchicalCodedGemm(A, groups=4, n_inner=8, k_inner=6)
    >>> pool = AsyncPool(hg.n_workers)
    >>> asyncmap(pool, B, hg.backend, nwait=hg.nwait)   # 3 of 4 groups
    >>> C = hg.result(pool)                             # exact product

    ``groups`` is a group count (contiguous split) or an explicit
    partition from :func:`~..parallel.multihost.host_groups`. The outer
    code defaults to the rate-(H-1)/H sum parity (single-host-loss
    tolerance, O(n) recovery); pass ``outer_rate`` below that for LT
    multi-host tolerance. ``inner="mds"`` (any k_inner of n_inner,
    solve decode) or ``"lt"`` (fixed systematic window, peeling
    decode).

    ``device_backend=False`` skips building the
    :class:`~..backends.xla.XLADeviceBackend` (no dispatcher threads):
    simulated fleets drive the same math through
    ``SimBackend(hg.work, hg.n_workers, delay_fn=...)`` — the bench and
    the host-loss tests run exactly this way.

    ``registry=`` / ``flight=`` follow the package-wide opt-in contract
    (GC004; dark paths pay only ``is None`` checks): decode counters
    ``hier_inner_decode_total{group=...}``, ``hier_group_losses_total``,
    ``hier_outer_recoveries_total``, and a flight-recorder instant
    event on every outer-code recovery so host-loss postmortems are
    visible in ``/flight`` dumps.
    """

    def __init__(
        self,
        A: np.ndarray,
        *,
        groups: int | Sequence[Sequence[int]],
        n_inner: int | None = None,
        k_inner: int,
        inner: str = "mds",
        outer: str = "auto",
        outer_rate: float | None = None,
        outer_seed: int = 0,
        inner_seed: int = 0,
        parity: str = "cauchy",
        dtype=None,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        device_backend: bool = True,
        registry=None,
        flight=None,
    ):
        if dtype is not None:
            A = np.asarray(A, dtype=dtype)
        else:
            A = np.asarray(A)
        if isinstance(groups, (int, np.integer)):
            if n_inner is None:
                raise ValueError(
                    "n_inner is required when groups is a count"
                )
            self.group_indices = partition_groups(
                int(groups) * int(n_inner), int(groups)
            )
        else:
            self.group_indices = partition_groups(
                sum(len(g) for g in groups), groups
            )
            if n_inner is not None and n_inner != len(self.group_indices[0]):
                raise ValueError(
                    f"explicit groups of size {len(self.group_indices[0])} "
                    f"contradict n_inner={n_inner}"
                )
        self.H = len(self.group_indices)
        self.n_inner = len(self.group_indices[0])
        self.k_inner = int(k_inner)
        if not 0 < self.k_inner <= self.n_inner:
            raise ValueError(
                f"need 0 < k_inner <= n_inner, got k_inner={k_inner}, "
                f"n_inner={self.n_inner}"
            )
        self.n_workers = self.H * self.n_inner
        self.outer = make_outer(
            self.H, rate=outer_rate, kind=outer, seed=outer_seed
        )
        self.L = self.outer.L
        m = A.shape[0]
        if m % (self.L * self.k_inner) != 0:
            raise ValueError(
                f"rows {m} must divide evenly into L*k_inner = "
                f"{self.L}*{self.k_inner} source blocks"
            )
        if devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        self.precision = precision
        self.block_rows = m // (self.L * self.k_inner)
        # -- outer encode: one host-group block per group, 0/1 sums ----
        # (generator cast to A's dtype so the coded blocks — and the
        # bf16 rounding story — match what the workers will compute in)
        G_out = self.outer.generator_rows().astype(A.dtype)
        src = jnp.asarray(A).reshape(self.L, m // self.L, *A.shape[1:])
        group_blocks = jnp.einsum(
            "hl,lrc->hrc", jnp.asarray(G_out), src, precision=precision
        ).astype(A.dtype)
        # -- inner encode: the existing dense code over each group ----
        self.inner = str(inner)
        if self.inner == "mds":
            self._icode = MDSCode(
                self.n_inner, self.k_inner, parity=parity, dtype=A.dtype,
                precision=precision,
            )
            self._inner_G = self._icode.G
            self._inner_ids = list(range(self.n_inner))
        elif self.inner == "lt":
            self._icode = LTCode(
                self.k_inner, seed=inner_seed, systematic=True
            )
            # fixed shard window, LTCodedGemm discipline: slide until
            # the full window peels so nwait is always satisfiable
            # (systematic streams peel at the first window already)
            ids = list(range(self.n_inner))
            for _ in range(1000):
                if self._icode.peelable(ids):
                    break
                ids = [s + 1 for s in ids]
            else:
                raise ValueError(
                    f"no decodable window of {self.n_inner} LT shards "
                    f"for k_inner={self.k_inner}"
                )
            self._inner_ids = ids
            self._inner_G = self._icode.generator_rows(ids).astype(A.dtype)
        else:
            raise ValueError(f"unknown inner code {inner!r}")
        coded = jnp.einsum(
            "nk,hkrc->hnrc", jnp.asarray(self._inner_G),
            group_blocks.reshape(
                self.H, self.k_inner, self.block_rows, *A.shape[1:]
            ),
            precision=precision,
        ).astype(A.dtype)
        # worker w = group_indices[g][j] holds inner shard j of group g
        self.blocks: list = [None] * self.n_workers
        for g, members in enumerate(self.group_indices):
            for j, w in enumerate(members):
                self.blocks[int(w)] = jax.device_put(
                    coded[g, j], self.devices[int(w) % len(self.devices)]
                )
        # decode runs in at least f32 (bf16 solves are not a thing the
        # LAPACK path supports, and the outer subtraction chain should
        # not round at bf16 either); the generator values stay the
        # encode-time-rounded ones, exactly embedded
        self._decode_dtype = (
            np.float64 if A.dtype == np.float64 else np.float32
        )
        self.backend = (
            XLADeviceBackend(
                self._work, self.n_workers, devices=devices,
                delay_fn=delay_fn,
            )
            if device_backend else None
        )
        # opt-in telemetry (instruments resolved once; None = dark,
        # the decode path pays one `is None` check)
        self._m = None
        self._flight = flight
        if registry is not None:
            registry.gauge(
                "hier_groups", help="host groups H of the outer code"
            ).set(self.H)
            registry.gauge(
                "hier_outer_floor",
                help="groups needed to clear the outer code",
            ).set(self.L)
            self._m = {
                "outer_rec": registry.counter(
                    "hier_outer_recoveries_total",
                    help="source group blocks reconstructed by the "
                         "outer code (a host was lost or skipped)",
                ),
                "losses": registry.counter(
                    "hier_group_losses_total",
                    help="group-epochs not inner-decodable at decode "
                         "time (straggling or dead hosts skipped)",
                ),
                "inner": [
                    registry.counter(
                        "hier_inner_decode_total",
                        help="inner decodes consumed per group",
                        group=str(g),
                    )
                    for g in range(self.H)
                ],
            }

    # -- worker side ------------------------------------------------------
    def _work(self, i: int, payload, epoch: int):
        return _block_matmul(
            self.blocks[int(i)], payload, precision=self.precision
        )

    @property
    def work(self):
        """The ``work_fn(worker, payload, epoch)`` for externally-built
        backends — ``SimBackend(hg.work, hg.n_workers, ...)`` drives
        the identical per-chip math on virtual time."""
        return self._work

    # -- completion rule --------------------------------------------------
    def _group_arrived(self, g: int, fresh_mask: np.ndarray) -> bool:
        """Inner decodability floor of group ``g`` over a freshness
        mask: >= k_inner fresh shards (MDS) / a peelable fresh id set
        (LT)."""
        members = self.group_indices[g]
        local = np.flatnonzero(fresh_mask[members])
        if self.inner == "mds":
            return local.size >= self.k_inner
        if local.size < self.k_inner:
            return False
        return self._icode.peelable([self._inner_ids[j] for j in local])

    @property
    def nwait(self):
        """Two-level decodability predicate for ``asyncmap(nwait=...)``:
        arrive per group at the inner floor, complete at the outer
        floor."""
        return hierarchical_nwait(
            self.group_indices, self._group_arrived, self.outer
        )

    def arrived_groups(self, pool: AsyncPool, epoch: int | None = None) -> list[int]:
        """Groups whose inner floor is met by the pool's fresh results."""
        fresh = pool.fresh_indices(epoch)
        mask = np.zeros(self.n_workers, dtype=bool)
        mask[fresh] = True
        return [
            g for g in range(self.H) if self._group_arrived(g, mask)
        ]

    # -- decode -----------------------------------------------------------
    def _inner_decode(self, g: int, pool: AsyncPool, fresh_mask: np.ndarray) -> np.ndarray:
        """Group ``g``'s coded product block ``Ã_g @ B`` from its fresh
        shards — one small solve (MDS) or peel (LT), never fleet-sized."""
        members = self.group_indices[g]
        local = np.flatnonzero(fresh_mask[members])
        if self.inner == "mds":
            sel = local[: self.k_inner]
            shards = jnp.stack([
                jnp.asarray(pool.results[int(members[j])])
                for j in sel
            ]).astype(self._decode_dtype)
            G_S = jnp.asarray(
                self._inner_G[sel].astype(self._decode_dtype)
            )
            blocks = _decode(G_S, shards, self.precision)
            return np.asarray(blocks.reshape(-1, *blocks.shape[2:]))
        ids = [self._inner_ids[j] for j in local]
        shards = np.stack([
            np.asarray(pool.results[int(members[j])]) for j in local
        ]).astype(self._decode_dtype)
        blocks = self._icode.decode(shards, ids)
        return blocks.reshape(-1, *blocks.shape[2:])

    def result(self, pool: AsyncPool, epoch: int | None = None) -> np.ndarray:
        """Decode the full product from the arrived groups (host copy).

        Refuses — naming both floors — when the arrived set cannot
        decode; on a recovery (any source group missing) the outer code
        reconstructs it from the survivors and the event is counted /
        flight-recorded.
        """
        fresh = pool.fresh_indices(epoch)
        mask = np.zeros(self.n_workers, dtype=bool)
        mask[fresh] = True
        arrived = [
            g for g in range(self.H) if self._group_arrived(g, mask)
        ]
        if not self.outer.decodable(arrived):
            raise ValueError(
                f"only {len(arrived)} of {self.H} groups are "
                f"inner-decodable (floor {self.k_inner} fresh of "
                f"{self.n_inner}) at epoch "
                f"{pool.epoch if epoch is None else epoch}; the outer "
                f"floor needs {self.L} decodable groups"
            )
        used = self.outer.select(arrived)
        if self.inner == "mds":
            # ALL inner decodes in one vmapped program (see
            # _decode_groups), one host round-trip for the lot
            sels = [
                np.flatnonzero(mask[self.group_indices[g]])[: self.k_inner]
                for g in used
            ]
            # host-side gather, ONE transfer: stacking device shards
            # with nested jnp.stack costs one dispatch per shard
            # (measured 3.6 ms vs 0.45 ms for the numpy gather at the
            # bench shape — docs/PERF.md round-14)
            shards = jnp.asarray(np.stack([
                np.stack([
                    np.asarray(pool.results[int(self.group_indices[g][j])])
                    for j in sel
                ])
                for g, sel in zip(used, sels)
            ]).astype(self._decode_dtype))
            G_S = jnp.asarray(
                np.stack([self._inner_G[sel] for sel in sels])
                .astype(self._decode_dtype)
            )
            blocks = np.asarray(_decode_groups(G_S, shards))
            inner_blocks = [
                b.reshape(-1, *b.shape[2:]) for b in blocks
            ]
        else:
            inner_blocks = [
                self._inner_decode(g, pool, mask) for g in used
            ]
        lost = self.H - len(arrived)
        recovered = self.L - sum(1 for g in used if g < self.L)
        if self._m is not None:
            if lost:
                self._m["losses"].inc(lost)
            for g in used:
                self._m["inner"][g].inc()
            if recovered:
                self._m["outer_rec"].inc(recovered)
        if self._flight is not None and recovered:
            self._flight.event(
                "hier outer recovery",
                epoch=int(pool.epoch if epoch is None else epoch),
                missing_groups=[g for g in range(self.L) if g not in used],
                recovered_blocks=int(recovered),
                arrived=len(arrived),
            )
        sources = self.outer.decode(inner_blocks, used)
        return np.ascontiguousarray(
            sources.reshape(-1, *sources.shape[2:])
        )
