"""Outer codes across host groups: the cheap half of two-level coding.

The hierarchical construction (ROADMAP item 3; Array BP-XOR codes for
hierarchically distributed matmul, arxiv 1904.11563) composes two codes
with very different price tags: a dense MDS/LT *inner* code over each
host's chip mesh (``ops/coding.py`` / ``ops/lt.py`` — solve- or
peel-decoded, already built) and a cheap XOR-style *outer* code striped
ACROSS hosts, whose decode is O(n) additions per element. This module
holds the outer half plus the predicate glue, and deliberately imports
neither jax nor any accelerator module: ``sim/tune.py`` prices
``(outer_rate, inner_nwait)`` pairs on virtual-time fleets through
exactly these objects (lazily imported — ``sim/`` is a GC001 hermetic
root), and the heavy device class (:class:`~.hierarchical.
HierarchicalCodedGemm`) composes them with the MXU encode/decode paths.

Over the reals the XOR of the paper's binary construction becomes a
sum (the same translation :mod:`.lt` makes for LT peeling): the parity
group holds ``Σ A_j`` and a lost source group is recovered by
subtracting the surviving sources from the parity — numerically benign
(0/1 coefficients, one subtraction chain of length H-2).

Two outer families:

* :class:`ParityOuter` — the rate-(H-1)/H fast path: H-1 systematic
  source groups + ONE sum-parity group. Any H-1 of H groups decode;
  losing any single host costs one O(n) subtraction pass, never a
  solve. This is the deployment default (host failures are rare and
  overwhelmingly singular).
* :class:`LTOuter` — lower rates via the systematic LT generator
  machinery (:class:`~.lt.LTCode`, the same generator/peeling engine
  ``ops/rateless.py`` draws its shard streams from): L source groups,
  H-L coded groups with patch-distribution supports, peeling decode.
  Survives multi-host loss at rate L/H.

The ``asyncmap`` wiring is one predicate (:func:`hierarchical_nwait`):
a group has *arrived* when its inner decodability floor is met over the
live ``repochs`` freshness mask, and the epoch completes when the
arrived group set clears the outer floor — a straggling or dead host is
simply never waited on.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from .lt import LTCode

__all__ = [
    "ParityOuter",
    "LTOuter",
    "make_outer",
    "partition_groups",
    "hierarchical_nwait",
]


class ParityOuter:
    """Rate-(H-1)/H sum-parity outer code over ``H`` host groups.

    Group ``g < L`` holds source block ``g``; group ``H-1`` holds the
    parity ``Σ_j A_j``. ``decodable`` is simply ``len(groups) >= L``
    (any L distinct of H), and ``decode`` is the XOR-translated
    recovery: at most one source can be missing, and it equals the
    parity minus the surviving sources.
    """

    kind = "parity"

    def __init__(self, H: int):
        if int(H) < 2:
            raise ValueError(
                f"parity outer code needs >= 2 groups, got {H}"
            )
        self.H = int(H)
        self.L = self.H - 1

    @property
    def rate(self) -> float:
        return self.L / self.H

    def generator_rows(self) -> np.ndarray:
        """(H, L) 0/1 generator: identity rows + the all-ones parity."""
        G = np.zeros((self.H, self.L), dtype=np.float32)
        G[: self.L] = np.eye(self.L, dtype=np.float32)
        G[self.L] = 1.0
        return G

    def decodable(self, groups: Sequence[int]) -> bool:
        """True iff the arrived group ids reach the outer floor (any
        ``L`` distinct groups of the H determine all L sources)."""
        return len({int(g) for g in groups}) >= self.L

    def decode(self, shards: Sequence[np.ndarray], groups: Sequence[int]) -> np.ndarray:
        """(L, rows, cols) source blocks from any L+ arrived groups.

        O(n) per element: either the L sources all arrived (pure
        gather) or exactly one is missing and costs the subtraction
        chain ``parity - Σ survivors``.
        """
        ids = [int(g) for g in groups]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate group ids {ids}")
        if not self.decodable(ids):
            raise ValueError(
                f"{len(ids)} arrived groups {sorted(ids)} sit below the "
                f"outer decodability floor {self.L} of this "
                f"rate-{self.L}/{self.H} parity code"
            )
        by_id = {g: np.asarray(s) for g, s in zip(ids, shards)}
        missing = [j for j in range(self.L) if j not in by_id]
        if not missing:
            return np.stack([by_id[j] for j in range(self.L)])
        # exactly one source can be absent (floor says >= L of L+1 ids)
        j = missing[0]
        rec = by_id[self.L].copy()  # the parity group
        for g, s in by_id.items():
            if g != self.L:
                rec -= s
        return np.stack([
            rec if i == j else by_id[i] for i in range(self.L)
        ])

    def select(self, arrived: Sequence[int]) -> list[int]:
        """The cheapest decodable subset of the arrived groups: the L
        sources when they all arrived (decode is a pure gather), else
        the surviving sources plus the parity (one subtraction chain)."""
        ids = sorted({int(g) for g in arrived})
        src = [g for g in ids if g < self.L]
        if len(src) == self.L:
            return src
        if not self.decodable(ids):
            raise ValueError(
                f"arrived groups {ids} sit below the outer floor {self.L}"
            )
        return src + [self.L]


class LTOuter:
    """Rate-L/H outer code on the systematic LT generator machinery.

    Group ``g`` takes outer shard id ``g`` of a systematic
    :class:`~.lt.LTCode` over L source groups: ids ``0..L-1`` ARE the
    sources, ids ``L..H-1`` are patch-distribution coded sums.
    ``decodable`` is peelability of the arrived id set and ``decode``
    is the peeling pass — still 0/1 subtractions, never a solve, but
    unlike parity it survives multi-host loss when H - L > 1.
    """

    kind = "lt"

    def __init__(self, H: int, L: int, *, seed: int = 0):
        if not 1 <= int(L) <= int(H):
            raise ValueError(
                f"need 1 <= L <= H for an (H={H}, L={L}) outer code"
            )
        self.H, self.L = int(H), int(L)
        self.code = LTCode(self.L, seed=seed, systematic=True)
        # the deployed window is the H group ids themselves; the
        # systematic prefix guarantees the full set peels, so the
        # no-loss epoch is always decodable
        if not self.code.peelable(list(range(self.H))):  # pragma: no cover
            raise ValueError(
                f"outer window 0..{self.H - 1} does not peel for L={L}"
            )

    @property
    def rate(self) -> float:
        return self.L / self.H

    def generator_rows(self) -> np.ndarray:
        """(H, L) 0/1 generator rows for the H group shard ids."""
        return self.code.generator_rows(list(range(self.H)))

    def decodable(self, groups: Sequence[int]) -> bool:
        ids = sorted({int(g) for g in groups})
        if len(ids) < self.L:  # cheap reject before the peel walk
            return False
        return self.code.peelable(ids)

    def decode(self, shards: Sequence[np.ndarray], groups: Sequence[int]) -> np.ndarray:
        ids = [int(g) for g in groups]
        if not self.decodable(ids):
            raise ValueError(
                f"arrived groups {sorted(set(ids))} sit below the outer "
                f"decodability floor of this (H={self.H}, L={self.L}) "
                "LT outer code (peeling stalls)"
            )
        return self.code.decode(np.stack([np.asarray(s) for s in shards]), ids)

    def select(self, arrived: Sequence[int]) -> list[int]:
        """A decodable subset of the arrived groups, preferring the
        systematic prefix (pure gather) and otherwise the shortest
        peelable id prefix — every selected group pays one inner
        decode, so fewer is cheaper."""
        ids = sorted({int(g) for g in arrived})
        src = [g for g in ids if g < self.L]
        if len(src) == self.L:
            return src
        chosen: list[int] = []
        for g in ids:
            chosen.append(g)
            if len(chosen) >= self.L and self.code.peelable(chosen):
                return chosen
        raise ValueError(
            f"arrived groups {ids} sit below the outer decodability "
            f"floor of this (H={self.H}, L={self.L}) LT outer code"
        )


def make_outer(H: int, *, rate: float | None = None, kind: str = "auto",
               seed: int = 0):
    """Outer-code factory: ``kind="auto"`` picks the parity fast path
    at the rate-(H-1)/H point and the LT generator machinery anywhere
    else. ``rate=None`` defaults to (H-1)/H — single-host-loss
    tolerance, the deployment default."""
    H = int(H)
    if rate is None:
        L = H - 1 if H > 1 else 1
    else:
        L = int(round(H * float(rate)))
    if L < 1:
        raise ValueError(
            f"outer rate {rate} over {H} groups rounds to L={L} source "
            "groups — below the outer decodability floor (L >= 1)"
        )
    if L > H:
        raise ValueError(
            f"outer rate {rate} over {H} groups rounds to L={L} > H"
        )
    if kind == "auto":
        kind = "parity" if L == H - 1 else "lt"
    if kind == "parity":
        if L != H - 1:
            raise ValueError(
                f"parity outer codes are rate (H-1)/H; got L={L} of H={H}"
            )
        return ParityOuter(H)
    if kind == "lt":
        return LTOuter(H, L, seed=seed)
    raise ValueError(f"unknown outer code kind {kind!r}")


def partition_groups(
    n_workers: int, groups: int | Sequence[Sequence[int]]
) -> list[np.ndarray]:
    """Normalize a fleet partition: either ``groups`` host groups of
    contiguous worker indices (the single-host / sim layout) or an
    explicit partition (e.g. :func:`~..parallel.multihost.host_groups`
    — one group per hosting process). Groups must be equal-sized,
    disjoint, and cover ``0..n_workers-1`` exactly."""
    n = int(n_workers)
    if isinstance(groups, (int, np.integer)):
        H = int(groups)
        if H < 1 or n % H != 0:
            raise ValueError(
                f"{n} workers do not partition evenly into {H} groups"
            )
        size = n // H
        return [
            np.arange(g * size, (g + 1) * size, dtype=np.int64)
            for g in range(H)
        ]
    part = [np.asarray([int(w) for w in g], dtype=np.int64) for g in groups]
    if not part:
        raise ValueError("empty group partition")
    sizes = {len(g) for g in part}
    if sizes == {0} or len(sizes) != 1:
        raise ValueError(
            f"host groups must be equal-sized, got sizes "
            f"{sorted(len(g) for g in part)}"
        )
    flat = np.concatenate(part)
    if sorted(flat.tolist()) != list(range(n)):
        raise ValueError(
            f"groups must cover workers 0..{n - 1} exactly once, got "
            f"{sorted(flat.tolist())}"
        )
    return part


def hierarchical_nwait(
    group_indices: Sequence[np.ndarray],
    inner_arrived: Callable[[int, np.ndarray], bool],
    outer,
):
    """Predicate factory for ``asyncmap(nwait=...)`` — the two-level
    completion rule evaluated over the live ``repochs`` after every
    arrival (reference src/MPIAsyncPools.jl:152-158, the same
    mechanism :func:`~.coding.nwait_decodable` rides):

    * group ``g`` has ARRIVED when ``inner_arrived(g, fresh_mask)``
      says its inner decodability floor is met (>= k fresh shards for
      MDS, a peelable fresh id set for LT);
    * the epoch COMPLETES when the arrived group set clears
      ``outer.decodable`` — so a host that straggles or dies is never
      waited on, as long as the survivors clear the outer floor.
    """

    idx = [np.asarray(g, dtype=np.int64) for g in group_indices]

    def pred(epoch: int, repochs: np.ndarray) -> bool:
        fresh = np.asarray(repochs) == epoch
        arrived = [g for g in range(len(idx)) if inner_arrived(g, fresh)]
        return outer.decodable(arrived)

    return pred
