"""MatDot-coded GEMM: partition the contraction dimension, decode from
any 2p-1 of n.

The third coded-matmul family here (with MDS row coding, ops/coding.py,
and polynomial both-factor codes, ops/polynomial.py), after Dutta et al.,
"On the Optimal Recovery Threshold of Coded Matrix Multiplication"
(public technique). Where polynomial codes partition the *output* (each
worker computes 1/(pq) of C's entries over the full inner dimension),
MatDot partitions the *inner* dimension: A splits into p column blocks
A_j (m × kd/p), B into p row blocks B_j (kd/p × nc), and worker i
computes the full-size m × nc product

    C̃_i = Ã_i @ B̃_i,   Ã_i = Σ_j A_j x_i^j,   B̃_i = Σ_j B_j x_i^(p-1-j)

— 1/p of the total FLOPs each. The polynomial C̃(x) has degree 2p-2 and
its x^(p-1) coefficient is exactly Σ_j A_j @ B_j = A @ B, so any 2p-1
evaluations recover C. The trade against polynomial codes: lower
per-worker compute threshold arithmetic (recovery 2p-1 < p² for the same
split count) but each worker outputs the full m × nc block (more result
bytes); MatDot wins when the inner dimension dominates.

TPU-first choices (mirroring ops/polynomial.py):

* **Workers encode their own B̃_i** from the single broadcast ``B`` — a
  weighted sum over its p row blocks fused in front of the MXU matmul,
  preserving the pool's snapshot-broadcast semantics (reference
  src/MPIAsyncPools.jl:51-61; one ICI broadcast on a slice).
* **Decode is one weighted sum.** The x^(p-1) coefficient is a linear
  functional of any 2p-1 evaluations: with Vandermonde V_S over the
  arrived points, ``C = Σ_i w_i C̃_i`` where ``w = V_S^{-T} e_{p-1}``.
  On device that is a single einsum over the stacked shards — exactly
  the masked-combine shape the ``repochs`` arrival mask drives
  everywhere else in this framework (SURVEY §2.1).
* **Chebyshev evaluation points** for real-field Vandermonde
  conditioning (SURVEY §7 "Float64 / conditioning" hard part).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backends.base import DelayFn
from ._evalgemm import EvalPointCodedGemm, chebyshev_points

__all__ = ["MatDotCode", "MatDotGemm", "MatDotWeightCache"]


class MatDotWeightCache:
    """Bounded per-arrival-pattern cache of masked decode weights.

    ``get(sel)`` returns the length-n weight vector with the 2p-1
    interpolation weights on the arrived indices and 0 elsewhere — the
    form every MatDot decode path consumes (bulk-synchronous mesh epoch,
    pool-fused psum, host combine). One source of truth for the
    numerically sensitive Vandermonde solve, and one bound: there are
    C(n, 2p-1) possible arrival patterns, so the dict is cleared at
    ``max_entries`` rather than growing toward that.
    """

    def __init__(self, code: "MatDotCode", max_entries: int = 4096):
        self.code = code
        self.max_entries = int(max_entries)
        self._cache: dict[tuple, np.ndarray] = {}

    def get(self, sel) -> np.ndarray:
        sel = tuple(int(x) for x in sel)
        w = self._cache.get(sel)
        if w is None:
            w = np.zeros(self.code.n)
            w[list(sel)] = self.code.decode_weights(list(sel))
            if len(self._cache) >= self.max_entries:
                self._cache.clear()
            self._cache[sel] = w
        return w


@partial(jax.jit, static_argnames=("p", "precision"))
def _matdot_worker(A_i, w_i, B, p, precision):
    # B: (kd, nc) -> (p, kd/p, nc) row blocks; B̃_i = Σ_j w_i[j] B_j
    kd, nc = B.shape
    Bp = B.reshape(p, kd // p, nc)
    B_enc = jnp.einsum("j,jkw->kw", w_i, Bp, precision=precision)
    return jnp.matmul(A_i, B_enc, precision=precision)


@partial(jax.jit, static_argnames=("precision",))
def _matdot_combine(weights, shards, precision):
    # C = Σ_i w_i C̃_i : one einsum over the arrived evaluations
    return jnp.einsum("i,irw->rw", weights, shards, precision=precision)


class MatDotCode:
    """MatDot code with p inner-dimension blocks over n workers;
    recovery threshold ``k = 2p - 1``.

    >>> code = MatDotCode(p=2, n=5)
    >>> A_enc = code.encode_A(A_blocks)      # (p, m, kd/p) -> (5, m, kd/p)
    >>> # worker i: A_enc[i] @ (sum_j B_weights[i, j] * B_j)
    >>> C = code.combine(shards, indices)    # any 3 of 5 -> exact A @ B
    """

    def __init__(
        self,
        p: int,
        n: int,
        *,
        dtype=np.float32,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
    ):
        if p < 1:
            raise ValueError(f"need p >= 1, got p={p}")
        self.k = 2 * int(p) - 1  # recovery threshold
        if n < self.k:
            raise ValueError(
                f"need n >= 2p-1 workers for decodability, got n={n} < "
                f"{self.k}"
            )
        self.p, self.n = int(p), int(n)
        self.precision = precision
        self.points = chebyshev_points(self.n)
        # A-encode weights x_i^j, B-encode weights x_i^(p-1-j); decode
        # interpolates degree-(2p-2) evaluations
        self.VA = (self.points[:, None] ** np.arange(self.p)).astype(dtype)
        self.VB = (
            self.points[:, None] ** (self.p - 1 - np.arange(self.p))
        ).astype(dtype)
        self._VC = self.points[:, None] ** np.arange(self.k)  # float64

    def encode_A(self, blocks) -> jax.Array:
        """(p, m, kd/p) column blocks of A -> (n, m, kd/p) evaluations."""
        blocks = jnp.asarray(blocks)
        if blocks.shape[0] != self.p:
            raise ValueError(
                f"expected {self.p} A-blocks, got {blocks.shape[0]}"
            )
        return jnp.einsum(
            "nj,jrc->nrc", jnp.asarray(self.VA), blocks,
            precision=self.precision,
        )

    def decode_weights(self, indices) -> np.ndarray:
        """The linear-functional weights w with ``C = Σ w_i C̃_i`` for
        the given arrived evaluation points: ``w = V_S^{-T} e_{p-1}``
        (solved in float64 host-side — a k×k system, negligible next to
        the m×nc shards it combines)."""
        idx = np.asarray(indices)
        if idx.shape[0] != self.k or len(set(idx.tolist())) != self.k:
            raise ValueError(
                f"need exactly 2p-1={self.k} distinct shard indices, "
                f"got {idx}"
            )
        e = np.zeros(self.k)
        e[self.p - 1] = 1.0
        return np.linalg.solve(self._VC[idx].T, e)

    def combine(self, shards, indices) -> jax.Array:
        """Any 2p-1 worker products -> the exact ``A @ B`` (one einsum)."""
        shards = jnp.asarray(shards)
        if shards.shape[0] != self.k:
            raise ValueError(
                f"expected {self.k} shards, got {shards.shape[0]}"
            )
        w = jnp.asarray(self.decode_weights(indices), dtype=shards.dtype)
        return _matdot_combine(w, shards, self.precision)


class MatDotGemm(EvalPointCodedGemm):
    """``C = A @ B`` from any 2p-1 of n workers, inner dim partitioned.

    Worker i holds the static evaluation ``Ã_i`` (m × kd/p) and encodes
    its own ``B̃_i`` from the broadcast payload — per-worker FLOPs are
    1/p of the product.

    >>> mg = MatDotGemm(A, p=2, n=5)
    >>> pool = AsyncPool(5)
    >>> repochs = asyncmap(pool, B, mg.backend, nwait=mg.nwait)
    >>> C = mg.result_device(pool)          # exact A @ B from 3 of 5
    """

    def __init__(
        self,
        A: np.ndarray,
        p: int,
        n: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        dtype=None,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
    ):
        if dtype is not None:
            A = np.asarray(A, dtype=dtype)
        m, kd = A.shape
        if kd % p != 0:
            raise ValueError(
                f"inner dim {kd} must divide evenly into p={p} blocks"
            )
        if devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        self.code = MatDotCode(p, n, dtype=A.dtype, precision=precision)
        self.p, self.n = p, n
        self.precision = precision
        # A's column blocks: (m, kd) -> (p, m, kd/p)
        blocks = jnp.asarray(A).reshape(m, p, kd // p).transpose(1, 0, 2)
        self._setup_workers(
            self.code.encode_A(blocks), self.code.VB, n, devices, delay_fn
        )

    def _work(self, i: int, payload: jax.Array, epoch: int) -> jax.Array:
        if payload.shape[0] % self.p != 0:
            raise ValueError(
                f"B rows {payload.shape[0]} must divide evenly into "
                f"p={self.p} blocks"
            )
        return _matdot_worker(
            self.A_shards[i], self.B_weights[i], payload, self.p,
            self.precision,
        )

    def _decode_shards(self, shards, idx):
        # one weighted einsum; stale shards never read
        return self.code.combine(shards, idx)
