"""Device-resident GF(2^8) Reed-Solomon: byte-exact coding without
leaving HBM.

:class:`~..utils.rs_gf256.RSGF256` runs on the host (native C++ or
NumPy); this is the same code — identical Cauchy generator, bit-identical
shards — executed on device, so byte payloads that already live in HBM
(packed checkpoints, quantized weights, serialized buffers) encode and
decode without a host round-trip, the framework's standing rule that
host transfer is the slow edge (SURVEY §7).

GF(256) has no MXU path, so the matmul over the field is built from the
two primitives the VPU does have: a 64 KiB product-table **gather** and
an **XOR reduction**. ``C[i, l] = XOR_j MUL[G[i, j], D[j, l]]`` runs as a
``lax.scan`` over the k contraction steps, each step a (rows, L) gather
+ XOR — O(k) kernel launches fused into one compiled loop, (rows, L)
live memory instead of a (rows, k, L) intermediate.

Decode inverts the k×k generator submatrix on the host (tiny, exact
GF arithmetic) and applies it on device the same way; which k rows is
driven by the pool's ``repochs`` arrival mask like every other decoder
here (SURVEY §2.1).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.rs_gf256 import RSGF256, _MUL, _np_invert

__all__ = ["DeviceRSGF256", "gf256_matmul"]


@jax.jit
def _gf_matmul_gather(mul_table, M, D):
    # C[i, l] = XOR_j mul_table[M[i, j], D[j, l]]
    def step(acc, j):
        rows = jnp.take(mul_table, M[:, j].astype(jnp.int32), axis=0)
        prod = jnp.take_along_axis(
            rows, D[j].astype(jnp.int32)[None, :], axis=1
        )  # (rows, L): rows[i, l] = mul[M[i,j], D[j,l]]
        return acc ^ prod, None

    k = M.shape[1]
    acc0 = jnp.zeros((M.shape[0], D.shape[1]), dtype=jnp.uint8)
    acc, _ = jax.lax.scan(step, acc0, jnp.arange(k))
    return acc


def _gf_mul_bitslice(a, b):
    """Elementwise GF(256) product by carry-less multiply + reduction
    mod the primitive polynomial 0x11D — 8 shift/mask/XOR rounds then 7
    conditional reductions, all VPU-vectorizable int32 ops; no gathers
    (TPU gathers serialize; bitwise ops run at vector width)."""
    a = a.astype(jnp.int32)
    b = b.astype(jnp.int32)
    prod = jnp.zeros_like(a)
    for i in range(8):  # carry-less multiply: prod up to degree 14
        bit = (b >> i) & 1
        prod = prod ^ ((a << i) * bit)
    for deg in range(14, 7, -1):  # reduce high bits with x^8 = 0x1D
        bit = (prod >> deg) & 1
        prod = prod ^ ((_PRIM_I32 << (deg - 8)) * bit)
    return prod.astype(jnp.uint8)


_PRIM_I32 = 0x11D


@jax.jit
def _gf_matmul_bitslice(M, D):
    # XOR-contraction with the elementwise bit-sliced product
    def step(acc, j):
        prod = _gf_mul_bitslice(M[:, j][:, None], D[j][None, :])
        return acc ^ prod, None

    k = M.shape[1]
    acc0 = jnp.zeros((M.shape[0], D.shape[1]), dtype=jnp.uint8)
    acc, _ = jax.lax.scan(step, acc0, jnp.arange(k))
    return acc


_MUL_DEV = None


def _mul_table_dev():
    # one 64 KiB H2D upload per process, not per call
    global _MUL_DEV
    if _MUL_DEV is None:
        _MUL_DEV = jnp.asarray(_MUL)
    return _MUL_DEV


def gf256_matmul(M, D, *, method: str = "bitslice") -> jax.Array:
    """GF(256) matrix product of uint8 arrays ``(r, k) x (k, L)`` on
    device. ``method``:

    * ``"bitslice"`` (default) — carry-less multiply + polynomial
      reduction, pure elementwise XOR/shift ops (vector-unit friendly;
      TPU gathers serialize, bitwise ops run at full vector width);
    * ``"gather"`` — 64 KiB product-table lookups (wins on backends
      with fast gathers).
    """
    M = jnp.asarray(M, dtype=jnp.uint8)
    D = jnp.asarray(D, dtype=jnp.uint8)
    if method == "bitslice":
        return _gf_matmul_bitslice(M, D)
    if method == "gather":
        return _gf_matmul_gather(_mul_table_dev(), M, D)
    raise ValueError(f"unknown method {method!r}")


class DeviceRSGF256:
    """Systematic (n, k) Cauchy-RS over bytes, encode/decode on device.

    Bit-identical to :class:`~..utils.rs_gf256.RSGF256` (the generator is
    shared), so shards may be produced on device and decoded on the host
    or vice versa.

    >>> rs = DeviceRSGF256(n=8, k=6)
    >>> coded = rs.encode(data_dev)          # (6, L) uint8 -> (8, L)
    >>> back = rs.decode(coded[idx], idx)    # any 6 distinct rows
    """

    def __init__(self, n: int, k: int, *, method: str = "bitslice"):
        self.n, self.k = int(n), int(k)
        if method not in ("bitslice", "gather"):
            raise ValueError(f"unknown method {method!r}")
        self.method = method
        # host codec supplies the generator (native C++ when available)
        self._host = RSGF256(n, k)
        self.G = self._host.G  # (n, k) uint8, systematic
        self._G_dev = jnp.asarray(self.G)
        self._inv_cache: dict[tuple, jnp.ndarray] = {}

    def encode(self, data) -> jax.Array:
        """(k, L) uint8 source -> (n, L) coded shards (first k = source)."""
        data = jnp.asarray(data, dtype=jnp.uint8)
        if data.ndim != 2 or data.shape[0] != self.k:
            raise ValueError(
                f"expected ({self.k}, L) uint8 array, got {data.shape}"
            )
        return gf256_matmul(self._G_dev, data, method=self.method)

    def _inverse(self, indices) -> jnp.ndarray:
        idx = tuple(int(i) for i in indices)
        if len(idx) != self.k or len(set(idx)) != self.k:
            raise ValueError(
                f"need exactly k={self.k} distinct indices, got {idx}"
            )
        if min(idx) < 0 or max(idx) >= self.n:
            raise ValueError(f"indices out of range [0, {self.n}): {idx}")
        inv = self._inv_cache.get(idx)
        if inv is None:
            # tiny k x k GF inversion, exact, host-side. Bounded: churning
            # arrival patterns over many epochs would otherwise grow the
            # cache toward C(n, k) entries; recomputing is cheap.
            if len(self._inv_cache) >= 4096:
                self._inv_cache.clear()
            inv = jnp.asarray(_np_invert(self.G[list(idx)]))
            self._inv_cache[idx] = inv
        return inv

    def decode(self, shards, indices) -> jax.Array:
        """Any k distinct coded rows -> the (k, L) source bytes, exactly."""
        shards = jnp.asarray(shards, dtype=jnp.uint8)
        if shards.ndim != 2 or shards.shape[0] != self.k:
            raise ValueError(
                f"expected ({self.k}, L) uint8 array, got {shards.shape}"
            )
        return gf256_matmul(
            self._inverse(indices), shards, method=self.method
        )
