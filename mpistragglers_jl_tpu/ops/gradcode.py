"""Gradient coding: exact full-batch gradients despite s stragglers.

Cyclic-repetition gradient coding (Tandon et al., "Gradient Coding"):
the dataset is partitioned into n chunks; worker i computes a fixed
linear combination of the gradients of chunks ``{i, i+1, ..., i+s}``
(cyclic), so each chunk is replicated on s+1 workers. From the coded
sums of *any* n-s workers, the decoder finds combination weights ``a``
with ``aᵀ B_S = 1ᵀ`` and recovers the exact sum of all n chunk
gradients — stragglers cost nothing but the (s+1)× compute replication.

The pool's ``repochs`` mask (reference src/MPIAsyncPools.jl:109,:168)
selects the arrived rows ``S``; the coefficient matrix ``B`` uses random
support coefficients so every (n-s)-row subset is full-rank almost
surely, with feasibility checked at decode.
"""

from __future__ import annotations

import numpy as np

__all__ = ["GradientCode"]


class GradientCode:
    """(n, s) cyclic-repetition gradient code.

    ``B[i, j]`` is worker i's coefficient on chunk j, supported on the
    cyclic window ``{i, ..., i+s}``. ``decode_weights(arrived)`` returns
    per-worker weights whose combination reproduces ``sum_j grad_j``.
    """

    def __init__(self, n: int, s: int, *, seed: int = 0):
        if not 0 <= s < n:
            raise ValueError(f"need 0 <= s < n, got n={n}, s={s}")
        self.n, self.s = int(n), int(s)
        rng = np.random.default_rng(seed)
        # Tandon et al. cyclic construction: draw a random H (s×n) with
        # H @ 1 = 0; every row b_i of B lies in null(H) and is supported
        # on the cyclic window, with b_i[i] = 1. Then any n-s surviving
        # rows span null(H) (generic independence), which contains the
        # all-ones vector — so the decoder's aᵀ B_S = 1ᵀ is always
        # feasible. Arbitrary per-row random coefficients do NOT have
        # this property (1 is generically outside the row space).
        B = np.zeros((n, n))
        if s == 0:
            B = np.eye(n)
        else:
            H = rng.standard_normal((s, n))
            H -= H.mean(axis=1, keepdims=True)  # rows ⟂ all-ones
            for i in range(n):
                sup = [(i + d) % n for d in range(s + 1)]
                rest = sup[1:]
                # solve H[:, rest] c = -H[:, i]  (s×s, generically invertible)
                c = np.linalg.solve(H[:, rest], -H[:, sup[0]])
                B[i, sup[0]] = 1.0
                B[i, rest] = c
        self.B = B

    def support(self, i: int) -> list[int]:
        """Chunk ids worker i must compute (cyclic window of s+1)."""
        return [(i + d) % self.n for d in range(self.s + 1)]

    def decode_weights(self, arrived) -> np.ndarray:
        """Weights ``a`` with ``aᵀ B[arrived] = 1ᵀ`` (least-squares).

        Raises ``ValueError`` if the arrived set cannot reproduce the
        full gradient (fewer than n-s workers, or a degenerate subset).
        """
        idx = np.asarray(arrived)
        if idx.size < self.n - self.s:
            raise ValueError(
                f"need at least n-s={self.n - self.s} workers, "
                f"got {idx.size}"
            )
        B_S = self.B[idx]  # (m, n)
        a, *_ = np.linalg.lstsq(B_S.T, np.ones(self.n), rcond=None)
        if not np.allclose(B_S.T @ a, 1.0, atol=1e-6):
            raise ValueError(
                f"arrived set {idx.tolist()} cannot reproduce the full "
                "gradient (degenerate subset)"
            )
        return a
