"""Shared scaffolding for evaluation-point coded GEMM workloads.

:class:`PolyCodedGemm` (ops/polynomial.py) and :class:`MatDotGemm`
(ops/matdot.py) are the same machine around different codes: per-worker
static evaluations of A placed on devices, per-worker B-encode weights,
an :class:`~..backends.xla.XLADeviceBackend` running the fused
encode+matmul, a decodability-predicate ``nwait``, and a
fresh-shard harvest that decodes on the pool's first device. That
machinery lives here once; subclasses provide the code object (with
recovery threshold ``k``), the worker computation, and the decode.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backends.base import DelayFn
from ..backends.xla import XLADeviceBackend
from ..pool import AsyncPool
from .coding import nwait_decodable

__all__ = ["EvalPointCodedGemm", "chebyshev_points"]


def chebyshev_points(n: int) -> np.ndarray:
    """n distinct Chebyshev nodes in (-1, 1): real-field Vandermonde
    systems over these are far better conditioned than over equispaced
    points — what makes MXU-matmul decode viable in f32 (SURVEY §7
    "Float64 / conditioning")."""
    i = np.arange(n)
    return np.cos((2 * i + 1) * np.pi / (2 * n)).astype(np.float64)


class EvalPointCodedGemm:
    """Base for pool workloads computing ``A @ B`` from coded
    evaluations. Subclasses must, in ``__init__``, set ``self.code``
    (exposing ``k``), ``self.devices``, then call :meth:`_setup_workers`
    — and implement ``_work(i, payload, epoch)`` plus
    :meth:`_decode_shards`.
    """

    code = None  # set by subclass before _setup_workers
    devices: list

    def _setup_workers(
        self,
        coded_A,
        B_weights,
        n: int,
        devices: Sequence[jax.Device] | None,
        delay_fn: DelayFn | None,
    ) -> None:
        """Place per-worker A evaluations + B-encode weights round-robin
        over the devices and wire the XLA backend."""
        self.A_shards = [
            jax.device_put(coded_A[i], self.devices[i % len(self.devices)])
            for i in range(n)
        ]
        self.B_weights = [
            jax.device_put(
                jnp.asarray(B_weights[i]),
                self.devices[i % len(self.devices)],
            )
            for i in range(n)
        ]
        self.backend = XLADeviceBackend(
            self._work, n, devices=devices, delay_fn=delay_fn
        )

    @property
    def k(self) -> int:
        """Recovery threshold of the underlying code."""
        return self.code.k

    @property
    def nwait(self):
        """Decodability predicate: true at >= k fresh shards."""
        return nwait_decodable(self.k)

    def _decode_shards(self, shards: jax.Array, idx: np.ndarray) -> jax.Array:
        raise NotImplementedError

    def result_device(
        self, pool: AsyncPool, epoch: int | None = None
    ) -> jax.Array:
        """Decode the full product from the first k fresh shards,
        device-resident (host transfer is the slow edge, not HBM).
        Shards are gathered onto the pool's first device — the caller
        may have deliberately excluded other devices."""
        fresh = pool.fresh_indices(epoch)
        if fresh.size < self.k:
            raise ValueError(
                f"only {fresh.size} fresh shards at epoch "
                f"{pool.epoch if epoch is None else epoch}, need "
                f"k={self.k}"
            )
        idx = fresh[: self.k]
        shards = jnp.stack([
            jax.device_put(jnp.asarray(pool.results[i]), self.devices[0])
            for i in idx
        ])
        return self._decode_shards(shards, idx)

    def result(self, pool: AsyncPool, epoch: int | None = None) -> np.ndarray:
        """Host-copy variant of :meth:`result_device`."""
        return np.asarray(self.result_device(pool, epoch))
