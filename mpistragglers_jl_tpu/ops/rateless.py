"""Genuinely rateless LT-coded GEMM: re-tasks draw *fresh* coded shards.

:class:`~.coded_gemm.LTCodedGemm` fixes one window of shard ids at
construction — a re-tasked straggler recomputes the same shard, so a
slow epoch gains nothing from extra work. This module supplies the
actual point of a rateless code: **incremental redundancy**. Every
dispatch a worker receives within an epoch advances its private shard
*generation*; the shard id is the deterministic function

    shard_id(worker, generation) = worker + n_workers * generation

so ids never repeat across workers or rounds and the shard stream is
unbounded (the LT property: any prefix of distinct ids is a valid code).
Workers encode their own coded block lazily from the source blocks —
the on-worker-encoding pattern of :mod:`.matdot` (its workers build
``B̃_i`` from the broadcast payload) applied to the ``A`` side — so a
fresh shard costs one short weighted-sum + the usual MXU matmul, no
re-setup.

Arrivals are *accumulated*, not replaced: a worker whose round-1 shard
landed and whose round-2 re-dispatch lands later contributes **two**
shards to the epoch's decode set. The pool machinery carries this
without modification — the decodability ``nwait`` predicate is
re-evaluated after every arrival (reference src/MPIAsyncPools.jl:152-158)
and closes over the epoch's collected-shard set; multi-round draws reuse
the reference's caller-chosen-epoch contract (``asyncmap(...,
epoch=e)`` with the same ``e``: re-dispatching idle workers at an
unchanged epoch is exactly src/MPIAsyncPools.jl:87's "no monotonicity is
enforced", SURVEY §2.1).

Decode is peeling (ops/lt.py), identical to the fixed-window path; the
only new state is the per-epoch ``(shard_id, shard)`` collection and a
``stats`` record of shards consumed vs ``k`` (the rateless overhead the
benchmark reports).
"""

from __future__ import annotations

import threading
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backends.base import DelayFn
from ..backends.xla import XLADeviceBackend
from ..pool import AsyncPool, DeadWorkerError, asyncmap
from .gemm import _block_matmul
from .lt import LTCode


@jax.jit
def _encode_block(src, sup):
    """Ã_s = Σ source blocks in the shard's support — computed ON the
    worker's device from device-resident source blocks (one compile per
    support degree; degrees are <= k, so a handful of programs). The
    alternative — host-encoding then shipping the coded block — puts a
    block-sized H2D transfer on every fresh-shard draw."""
    return src[sup].sum(axis=0)

__all__ = ["RatelessLTGemm"]


class RatelessLTGemm:
    """Rateless LT-coded ``C = A @ B`` with incremental redundancy.

    >>> rg = RatelessLTGemm(A, n_workers=8, k=6)
    >>> pool = AsyncPool(8)
    >>> C = rg.multiply(B, pool)      # draws shards until the set peels
    >>> rg.stats["shards_used"]       # rateless overhead vs k

    ``multiply`` runs rounds: each round dispatches one fresh shard per
    idle worker and waits up to ``round_timeout`` for the collected set
    to become peelable; workers still busy with an earlier shard are
    left in flight (their eventual stale arrival is harvested and
    re-tasked with a *new* shard id by the pool's phase-1/phase-3
    machinery). A permanent straggler therefore costs one round of
    timeout, not decodability.
    """

    def __init__(
        self,
        A: np.ndarray,
        n_workers: int,
        k: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        seed: int = 0,
        dtype=None,
        precision: jax.lax.Precision | None = jax.lax.Precision.HIGHEST,
        block_cache_size: int = 64,
        systematic: bool = True,
    ):
        """``systematic=True`` (default): the generation-0 window's
        first k shards ARE the source blocks, so a straggler-free epoch
        peels from k arrivals and a straggler costs only the draws
        until its missing block is covered — measured overhead drops
        from ~1.6x to ~1.25x of k at (n=8, k=8) (docs/PERF.md round 3).
        Set False for the classic all-soliton stream."""
        if dtype is not None:
            A = np.asarray(A, dtype=dtype)
        else:
            A = np.asarray(A)
        m = A.shape[0]
        if m % k != 0:
            raise ValueError(f"rows {m} must divide evenly into k={k} blocks")
        if devices is None:
            devices = jax.devices()
        self.code = LTCode(k, seed=seed, systematic=systematic)
        self.k = int(k)
        self.n = int(n_workers)
        self.devices = list(devices)
        self.block_rows = m // k
        self.precision = precision
        # generation 0 is host-encoded at setup (below); the device
        # copy of the source blocks is uploaded LAZILY on the first
        # fresh-generation draw, so a straggler-free run pays zero
        # extra HBM and fresh shards thereafter encode device-side
        self._src = np.ascontiguousarray(A.reshape(k, m // k, *A.shape[1:]))
        self._src_dev: dict = {}
        self._block_cache: dict[int, jax.Array] = {}
        self._block_cache_size = int(block_cache_size)
        self._gen: dict[tuple[int, int], int] = {}  # (epoch, worker) -> gen
        # per-epoch collected shards: {shard_id: device array}; appended
        # by worker threads at completion, read by the nwait predicate
        # and the decoder on the coordinator thread
        self._collected: dict[int, dict[int, jax.Array]] = {}
        # epoch whose shards _work may retain; None until the first
        # multiply() (direct Backend-API users collect every epoch)
        self._live_epoch: int | None = None
        # epoch -> the shard-id set the nwait predicate fired on
        self._satisfied: dict[int, list[int]] = {}
        self._lock = threading.Lock()
        self.stats: dict = {}
        # generation 0 = the static window [0, n): pre-encode on device
        for i in range(self.n):
            self._coded_block(i, i)
        self.backend = XLADeviceBackend(
            self._work, self.n, devices=devices, delay_fn=delay_fn
        )

    # -- shard plumbing ---------------------------------------------------
    def shard_id(self, worker: int, generation: int) -> int:
        """Deterministic unbounded shard stream, distinct across workers
        and rounds."""
        return int(worker) + self.n * int(generation)

    def _coded_block(self, worker: int, sid: int) -> jax.Array:
        """The device-resident coded block Ã_sid = Σ (support blocks),
        encoded lazily and cached (bounded).

        Generation 0 (``sid < n``, the setup window) encodes host-side
        and uploads once — no device source copy for straggler-free
        runs. Fresh generations encode ON the worker's device from the
        lazily-uploaded source blocks (one H2D per device, ever; the
        numpy array goes to ``dev`` directly, no default-device bounce).
        The encode runs OUTSIDE the lock — an XLA compile for a new
        support degree must not stall every worker completion and the
        decodability predicate; a racing duplicate encode is benign.
        """
        with self._lock:
            blk = self._block_cache.get(sid)
            if blk is not None:
                return blk
        dev = self.devices[worker % len(self.devices)]
        sup = self.code.shard_indices(sid)
        if sid < self.n:
            enc = self._src[sup[0]].copy()
            for j in sup[1:]:
                enc += self._src[j]
            blk = jax.device_put(enc, dev)
        else:
            blk = _encode_block(self._device_src(dev), jnp.asarray(sup))
        with self._lock:
            if len(self._block_cache) >= self._block_cache_size:
                # keep generation 0 (the steady-state window) resident
                for key in [
                    s for s in self._block_cache if s >= self.n
                ]:
                    del self._block_cache[key]
            return self._block_cache.setdefault(sid, blk)

    def _device_src(self, dev) -> jax.Array:
        """Device-resident (k, rows, cols) source stack, created ONCE
        per device — single-flight.

        The previous lazy pattern let every dispatcher thread race the
        None check, so a round of fresh-generation draws paid n-1
        SERIALIZED copies of the full source upload; on the tunneled
        chip (H2D can crawl to ~1.5 MB/s) that outlived every round
        timeout and presented as `DeadWorkerError: workers [0..n-1]`
        (round-3 diagnosis). Now the first thread builds, the rest wait
        on an Event. Systematic codes never touch the host at all:
        the generation-0 identity blocks ARE the source blocks and are
        already HBM-resident, so the stack is one device-side concat.
        """
        with self._lock:
            entry = self._src_dev.get(dev)
            owner = entry is None
            if owner:
                entry = {"ready": threading.Event(), "src": None}
                self._src_dev[dev] = entry
        if not owner:
            entry["ready"].wait()
            src = entry["src"]
            if src is None:
                raise RuntimeError("device source construction failed")
            return src
        try:
            if self.code.systematic:
                with self._lock:
                    cached = [
                        self._block_cache.get(s) for s in range(self.k)
                    ]
                parts = []
                for s, c in enumerate(cached):
                    if c is None:  # block never encoded (n < k corner)
                        c = jax.device_put(self._src[s], dev)
                    elif c.device != dev:
                        # identity block resident on a sibling device:
                        # D2D copy, still no host round trip
                        c = jax.device_put(c, dev)
                    parts.append(c)
                entry["src"] = jnp.stack(parts)
            else:
                entry["src"] = jax.device_put(self._src, dev)
            return entry["src"]
        finally:
            if entry["src"] is None:
                # Build failed (e.g. transient HBM pressure during the
                # device_put). Drop the dead entry under the lock BEFORE
                # releasing waiters so a later call can retry instead of
                # hitting a permanently poisoned device for the object's
                # lifetime; current waiters still get the RuntimeError.
                with self._lock:
                    if self._src_dev.get(dev) is entry:
                        del self._src_dev[dev]
            entry["ready"].set()

    def prefetch_source(self) -> None:
        """Build the per-device source stacks up front.

        The first fresh-generation draw otherwise pays the source
        construction (a full H2D upload for classic streams) inside a
        round timeout; benches and latency-sensitive callers warm it
        here, off the clock. Systematic streams make this nearly free
        (device-side concat of the resident identity blocks)."""
        seen = []
        for dev in self.devices[: self.n]:
            if not any(dev is d for d in seen):
                seen.append(dev)
                self._device_src(dev)

    def _work(self, i: int, payload: jax.Array, epoch: int):
        """Worker compute: advance this worker's generation, encode the
        fresh shard's block, multiply. Runs in the backend's per-worker
        dispatcher thread (the XLA pool's worker side)."""
        with self._lock:
            gen = self._gen.get((epoch, i), 0)
            self._gen[(epoch, i)] = gen + 1
        sid = self.shard_id(i, gen)
        out = _block_matmul(
            self._coded_block(i, sid), payload, precision=self.precision
        )
        out = jax.block_until_ready(out)
        with self._lock:
            # only the live epoch accumulates: a straggler still in
            # flight from a pruned epoch must not re-create its dict
            # (that entry would never be pruned again and would pin the
            # shard in HBM for the object's life — ADVICE r2). The
            # shard itself is still returned so the pool's stale-
            # arrival bookkeeping stays intact; it is simply not
            # retained here.
            if self._live_epoch is None or epoch == self._live_epoch:
                self._collected.setdefault(epoch, {})[sid] = out
        return sid, out

    # -- decode-side ------------------------------------------------------
    def collected_ids(self, epoch: int) -> list[int]:
        with self._lock:
            return sorted(self._collected.get(epoch, {}))

    def decodable(self, epoch: int) -> bool:
        return self.code.peelable(self.collected_ids(epoch))

    def nwait(self, epoch: int):
        """Decodability predicate over the epoch's *collected* shard set
        (not just the latest per-worker result): re-evaluated after
        every arrival, reference src/MPIAsyncPools.jl:152-158.

        When the predicate fires it snapshots the satisfying shard set:
        workers still in flight keep landing between the pool's return
        and the decode, and counting (or peeling) those would inflate
        the rateless-overhead statistic past the draw-until-peel value
        the code actually achieved — the decode needs exactly the
        prefix that peeled."""

        def pred(ep: int, repochs: np.ndarray) -> bool:
            ids = self.collected_ids(epoch)
            if self.code.peelable(ids):
                with self._lock:
                    self._satisfied.setdefault(epoch, ids)
                return True
            return False

        return pred

    def multiply(
        self,
        B,
        pool: AsyncPool,
        *,
        round_timeout: float = 5.0,
        max_rounds: int = 8,
    ) -> np.ndarray:
        """Compute ``A @ B``, drawing coded shards until the set peels.

        Round r re-enters ``asyncmap`` at the *same* epoch: idle workers
        (everyone who already delivered) are re-dispatched and — because
        their generation advanced — compute shards never seen before.
        Workers still in flight are untouched. Raises
        :class:`~..pool.DeadWorkerError` only if ``max_rounds`` rounds
        all time out (every worker dead)."""
        epoch = pool.epoch + 1
        with self._lock:
            # prune: only the live epoch's shards are retained, and
            # _work drops late arrivals from any other epoch from here
            # on (see _work)
            self._live_epoch = epoch
            self._collected = {epoch: {}}
            self._satisfied = {}
            self._gen = {k_: v for k_, v in self._gen.items()
                         if k_[0] == epoch}
        pred = self.nwait(epoch)
        last_err: DeadWorkerError | None = None
        for _ in range(max_rounds):
            try:
                asyncmap(
                    pool, B, self.backend,
                    nwait=pred, epoch=epoch, timeout=round_timeout,
                )
                last_err = None
                break
            except DeadWorkerError as e:
                # round timed out short of decodability: the next round
                # re-dispatches every idle worker with a fresh shard id
                # (incremental redundancy); stragglers stay in flight
                last_err = e
                if self.decodable(epoch):  # arrived during unwinding
                    # snapshot like pred does: without it _decode falls
                    # back to everything collected and the overhead
                    # statistic re-inflates on exactly the straggler
                    # traces it measures
                    with self._lock:
                        self._satisfied.setdefault(
                            epoch, sorted(self._collected.get(epoch, {}))
                        )
                    last_err = None
                    break
        if last_err is not None:
            raise last_err
        return self._decode(epoch)

    def _decode(self, epoch: int) -> np.ndarray:
        with self._lock:
            shards_map = dict(self._collected.get(epoch, {}))
            satisfied = self._satisfied.get(epoch)
        # decode exactly the prefix the predicate fired on (see nwait);
        # direct Backend-API users without a predicate fall back to
        # everything collected
        ids = (
            [s for s in satisfied if s in shards_map]
            if satisfied is not None
            else sorted(shards_map)
        )
        shards = np.stack([np.asarray(shards_map[s]) for s in ids])
        blocks = self.code.decode(shards, ids)
        self.stats = {
            "epoch": int(epoch),
            "shards_used": len(ids),
            "k": self.k,
            "overhead": len(ids) / self.k,
            "max_generation": max(s // self.n for s in ids) if ids else 0,
        }
        return blocks.reshape(-1, *blocks.shape[2:])
