"""FleetPrefixCache: the hub that ties the three tiers together.

One hub per fleet. Every participating
:class:`~..models.serving.ServingScheduler` attaches
(:meth:`FleetPrefixCache.attach`) and from then on:

* its :class:`~..models.paging.PagePool` registrations are MIRRORED
  into the shared :class:`~.directory.FleetPageDirectory` (the pool's
  ``register_hook``/``unregister_hook`` — volatile registrations stay
  local: a wrapped page's bytes are only meaningful under the owner's
  ring phase, so advertising it fleet-wide would serve garbage);
* admission misses probe the directory (:meth:`probe`) and, when a
  plan commits, :meth:`fetch` pulls the page — host-DRAM store first
  (zero-copy view), then a peer replica's HBM over the r16
  migration-ring frame format (``page_to_frames``/``page_from_frames``)
  — instead of re-prefilling the tokens;
* cold pages the arena reclaims are offered to the T2 store
  (:meth:`spill`), priced through the
  :class:`~.planner.SpillFetchPlanner` byte model.

Failure behavior is fail-to-prefill everywhere: a partitioned peer
(:meth:`partition` — the router's partition hook notifies the hub), a
killed replica (:meth:`kill` — directory generations invalidate its
advertisements), an evicted store page, a mid-fetch surprise — every
one makes :meth:`fetch` return None and the scheduler falls back to
prefilling the chunk it was going to prefill anyway. The cache can
only ever SAVE work; it can never be needed for correctness.

Observability (opt-in, GC004): ``registry=`` publishes
``cache_fetch_bytes_total{src="dram"|"peer"}``, the store's spill
counters, the directory-size gauge, and ``cache_fetch_seconds`` (the
planner's priced cost per fetch — the sim plane charges the same
number to its virtual clock, so live and swept fetch latencies are
the same scale); ``flight=`` records fetch/fallback instants.
"""

from __future__ import annotations

from ..models.disagg import (MigrationRing, MigrationRingReader,
                             page_from_frames, page_to_frames)
from .directory import FleetPageDirectory
from .planner import SpillFetchPlanner
from .store import PageStore

__all__ = ["FleetPrefixCache"]


class FleetPrefixCache:
    """The fleet cache hub (module docstring).

    ``store_pages`` sizes the host-DRAM tier in pages; the byte size
    is fixed lazily at first :meth:`attach` from that scheduler's
    page-row geometry (every later attach must match — refused by
    name otherwise). ``store_pages=0`` disables T2: the hub then only
    brokers peer fetches.
    """

    def __init__(self, *, store_pages: int = 256, qos=None,
                 planner: "SpillFetchPlanner | None" = None,
                 slot_bytes: int = 1 << 20, ring_slots: int = 4,
                 registry=None, flight=None,
                 name: str = "fleet-cache"):
        if store_pages < 0:
            raise ValueError(
                f"store_pages must be >= 0 (0 disables the DRAM "
                f"tier), got {store_pages}"
            )
        self.name = name
        self.store_pages = int(store_pages)
        self.directory = FleetPageDirectory(registry=registry)
        self.planner = planner if planner is not None \
            else SpillFetchPlanner(batch_bytes=slot_bytes)
        self.store: PageStore | None = None  # lazy: needs page_bytes
        self.page_bytes: int | None = None
        self._qos = qos
        self._registry = registry
        self._flight = flight
        self._slot_bytes = int(slot_bytes)
        self._ring_slots = int(ring_slots)
        self._ring: MigrationRing | None = None
        self._reader: MigrationRingReader | None = None
        self._members: dict[str, object] = {}  # name -> scheduler
        self._unreachable: set[str] = set()
        self._n_auto = 0
        self.n_fetches = {"dram": 0, "peer": 0}
        self.n_fallbacks = 0
        self.n_spills = 0
        self.fetch_seconds_modeled = 0.0
        self.spill_seconds_modeled = 0.0
        self._m_fetch: dict[str, object] = {}
        self._m_fetch_s = (
            registry.histogram(
                "cache_fetch_seconds",
                help="modeled seconds per fetched page "
                "(planner byte model)",
            )
            if registry is not None else None
        )

    # -- membership ------------------------------------------------------

    def attach(self, sched, name: str | None = None) -> str:
        """A scheduler joins the fleet namespace; returns its replica
        name (auto ``"r<n>"`` when not given). Fixes the page-byte
        geometry on first attach, builds the T2 store and the peer
        migration ring, and installs the pool mirror hooks."""
        pb = int(sched._page_row_bytes())
        if self.page_bytes is None:
            self.page_bytes = pb
            if self.store_pages > 0:
                self.store = PageStore(
                    pb, self.store_pages, directory=self.directory,
                    registry=self._registry, flight=self._flight,
                    qos=self._qos, name=f"{self.name}-store",
                )
            self._ring = MigrationRing(
                slot_bytes=max(self._slot_bytes, pb),
                slots=self._ring_slots, name=f"{self.name}-ring",
            )
            self._reader = MigrationRingReader(self._ring)
        elif pb != self.page_bytes:
            raise ValueError(
                f"page geometry mismatch: fleet pages are "
                f"{self.page_bytes} bytes, attaching scheduler has "
                f"{pb} (page_tokens / quantize_kv / config drift?)"
            )
        if name is None:
            name = f"r{self._n_auto}"
            self._n_auto += 1
        if name in self._members:
            raise ValueError(
                f"replica name {name!r} already attached; a respawn "
                "calls kill() first (directory generations are the "
                "crash-consistency witness)"
            )
        self.directory.register_replica(name)
        self._members[name] = sched
        pool = sched.pool

        def _mirror_register(digest, pid, _pool=pool, _name=name):
            if not _pool.is_volatile(pid):
                self.directory.publish(digest, replica=_name,
                                       tier="hbm")

        def _mirror_unregister(digest, _name=name):
            self.directory.withdraw(digest, replica=_name, tier="hbm")

        pool.register_hook = _mirror_register
        pool.unregister_hook = _mirror_unregister
        # pages registered BEFORE attach (warm adoption) are mirrored
        # now, same volatility rule
        for d, pid in list(pool._digest_to_page.items()):
            _mirror_register(d, pid)
        return name

    def kill(self, name: str) -> None:
        """The replica's process is gone: drop its directory entries
        (generation bump — stale advertisements can never be served),
        unhook its pool, forget it. Its spilled DRAM pages SURVIVE:
        the store is host-side state, which is the whole point of the
        spill tier."""
        sched = self._members.pop(name, None)
        if sched is not None:
            sched.pool.register_hook = None
            sched.pool.unregister_hook = None
        self.directory.drop_replica(name)
        self._unreachable.discard(name)

    def partition(self, name: str) -> None:
        """``name`` is network-partitioned: peer fetches from or to it
        fail (fail-to-prefill) until :meth:`heal`. Its DRAM spills
        stay readable by everyone else — the store is host-local to
        the fleet, not to the replica."""
        if name in self._members:
            self._unreachable.add(name)

    def heal(self, name: str) -> None:
        self._unreachable.discard(name)

    def members(self) -> list[str]:
        return list(self._members)

    # -- lookup / fetch --------------------------------------------------

    def probe(self, digest: bytes, *,
              exclude: str | None = None) -> str | None:
        """Best reachable tier holding ``digest`` (``"dram"`` before
        ``"peer"``), or None — the admission planner's cheap question
        before it commits budget. Reachability honors partitions: a
        partitioned asker sees only nothing (it cannot reach the
        store host either); a partitioned owner's HBM is invisible."""
        if exclude is not None and exclude in self._unreachable:
            return None
        for rep, tier in self.directory.locate(digest, exclude=exclude):
            if tier == "dram":
                return "dram"
            if rep not in self._unreachable:
                return "peer"
        return None

    def fetch(self, digest: bytes, *,
              exclude: str | None = None) -> "tuple[str, object] | None":
        """Pull one page: ``("dram" | "peer", flat-uint8 payload)`` or
        None (fall back to prefill). DRAM is a zero-copy store view;
        peer rides the migration ring. The source location is leased
        for the duration — the store will not evict it mid-read — and
        every failure path degrades to the next location, then to
        None, never to an error: the bytes are always reproducible by
        prefill."""
        if exclude is not None and exclude in self._unreachable:
            return None
        for rep, tier in self.directory.locate(digest, exclude=exclude):
            if tier == "hbm" and rep in self._unreachable:
                continue
            with self.directory.lease(digest, rep, tier):
                got = (
                    self._fetch_dram(digest) if tier == "dram"
                    else self._fetch_peer(digest, rep)
                )
            if got is not None:
                src, payload = got
                self.n_fetches[src] += 1
                cost = self.planner.price(
                    self.page_bytes,
                    "fetch_dram" if src == "dram" else "fetch_peer",
                )
                self.fetch_seconds_modeled += cost
                if self._registry is not None:
                    m = self._m_fetch.get(src)
                    if m is None:
                        m = self._registry.counter(
                            "cache_fetch_bytes_total",
                            help="bytes of prefix pages served by "
                            "the fleet cache instead of re-prefill",
                            src=src,
                        )
                        self._m_fetch[src] = m
                    m.inc(self.page_bytes)
                if self._m_fetch_s is not None:
                    self._m_fetch_s.observe(cost)
                return got
        self.n_fallbacks += 1
        if self._flight is not None:
            self._flight.event(
                "cache fetch fallback", src="cache",
                digest=digest.hex()[:12],
            )
        return None

    def _fetch_dram(self, digest: bytes):
        if self.store is None:
            return None
        payload = self.store.get(digest)
        return None if payload is None else ("dram", payload)

    def _fetch_peer(self, digest: bytes, rep: str):
        sched = self._members.get(rep)
        if sched is None:
            return None
        pid = sched.pool.lookup(digest)
        if pid is None:  # withdrawn between locate and here
            return None
        payload = sched._page_payload(pid)
        frames = page_to_frames(self._ring, payload)
        flat = page_from_frames(self._reader, frames, ring=self._ring)
        return ("peer", flat)

    # -- spill -----------------------------------------------------------

    def wants(self, digest: bytes, *,
              exclude: str | None = None) -> bool:
        """Would a spill of ``digest`` be useful? False when T2 is
        disabled or the digest is already somewhere ELSE in the fleet
        namespace (``exclude`` is the would-be spiller, whose own
        about-to-die HBM entry must not count) — re-spilling a page a
        sibling still holds wastes the eviction bandwidth the planner
        is there to budget."""
        if self.store is None:
            return False
        return len(self.directory.locate(digest, exclude=exclude)) == 0

    def spill(self, digest: bytes, payload, *,
              tenant: str | None = None, src: str = "device") -> bool:
        """Offer one evicted page to the T2 store; True when it is
        resident after the call. The movement is priced through the
        planner (the modeled device→host cost the PERF byte model and
        the sim plane both charge)."""
        if self.store is None:
            return False
        ok = self.store.put(digest, payload, tenant=tenant)
        if ok:
            self.n_spills += 1
            self.spill_seconds_modeled += self.planner.price(
                self.page_bytes, "spill"
            )
        return ok

    # -- bookkeeping -----------------------------------------------------

    def check(self) -> None:
        self.directory.check()
        if self.store is not None:
            self.store.check()

    def stats(self) -> dict:
        return {
            "members": list(self._members),
            "unreachable": sorted(self._unreachable),
            "page_bytes": self.page_bytes,
            "fetches": dict(self.n_fetches),
            "fallbacks": self.n_fallbacks,
            "spills": self.n_spills,
            "fetch_seconds_modeled": self.fetch_seconds_modeled,
            "spill_seconds_modeled": self.spill_seconds_modeled,
            "directory": self.directory.stats(),
            "store": None if self.store is None else self.store.stats(),
            "planner": self.planner.stats(),
        }

    def close(self) -> None:
        for name in list(self._members):
            self.kill(name)
        if self.store is not None:
            self.store.close()
        if self._ring is not None:
            self._ring.close()
        if self._reader is not None:
            self._reader.close()

    def __repr__(self) -> str:
        return (
            f"FleetPrefixCache({len(self._members)} members, "
            f"dir={self.directory.size}, "
            f"store={None if self.store is None else self.store.pages})"
        )
