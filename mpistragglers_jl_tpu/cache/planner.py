"""Spill/fetch planner: page movements batched and priced in bytes.

The redistribution frame (arxiv 2112.01075): moving KV pages between
tiers is a layout problem, not an RPC problem — what matters is HOW
MANY BYTES cross each link, in how many batches, because every batch
pays a fixed per-message cost on top of the link's byte rate. The
planner turns a list of page movements into batches bounded by
``batch_bytes`` per (src, dst) link and prices each batch with the
affine model the PERF docs carry for every other transport in this
repo::

    seconds = alpha + nbytes / (gbs * 1e9)

``spill_gbs`` prices device→host traffic (a spill is a device gather
plus one host memcpy into the store region), ``fetch_gbs`` prices
host→device and peer→peer traffic (a dram fetch is a host memcpy plus
a device scatter; a peer fetch adds the migration-ring hop, which is
zero-copy under memfd and hence rides the same byte rate). The sim
plane charges these SAME prices to its virtual clock, which is what
makes spill-capacity sweeps comparable to live measurements.

Everything is pure arithmetic on the arguments — no clocks, no state
beyond lifetime counters — so planning is replay-pure by construction.
"""

from __future__ import annotations

__all__ = ["PageMove", "SpillFetchPlanner"]

#: Movement kinds and the rate each is priced with.
_KINDS = ("spill", "fetch_dram", "fetch_peer")


class PageMove:
    """One page movement: ``digest`` goes ``src`` -> ``dst`` (replica
    or store names) carrying ``nbytes``, of ``kind`` in :data:`_KINDS`."""

    __slots__ = ("digest", "src", "dst", "nbytes", "kind")

    def __init__(self, digest: bytes, *, src: str, dst: str,
                 nbytes: int, kind: str):
        if kind not in _KINDS:
            raise ValueError(
                f"unknown movement kind {kind!r}; choose one of {_KINDS}"
            )
        if nbytes < 1:
            raise ValueError(f"movement must carry bytes, got {nbytes}")
        self.digest = digest
        self.src = src
        self.dst = dst
        self.nbytes = int(nbytes)
        self.kind = kind

    def __repr__(self) -> str:
        return (
            f"PageMove({self.digest.hex()[:12]}, {self.src}->{self.dst},"
            f" {self.nbytes}B, {self.kind})"
        )


class SpillFetchPlanner:
    """Batches page movements per link and prices them (module
    docstring). ``batch_bytes`` bounds one batch — a bound makes the
    per-batch ``alpha`` honest (an unbounded batch would amortize the
    fixed cost to zero and the sweep would always choose infinite
    batches) and bounds the ring slot a live batch must fit in."""

    __slots__ = ("spill_gbs", "fetch_gbs", "alpha_s", "batch_bytes",
                 "planned_moves", "planned_bytes", "planned_batches")

    def __init__(self, *, spill_gbs: float = 8.0,
                 fetch_gbs: float = 8.0, alpha_s: float = 20e-6,
                 batch_bytes: int = 1 << 20):
        if not spill_gbs > 0 or not fetch_gbs > 0:
            raise ValueError(
                f"byte rates must be > 0 GB/s, got "
                f"({spill_gbs}, {fetch_gbs})"
            )
        if alpha_s < 0:
            raise ValueError(f"alpha_s must be >= 0, got {alpha_s}")
        if batch_bytes < 1:
            raise ValueError(
                f"batch_bytes must be >= 1, got {batch_bytes}"
            )
        self.spill_gbs = float(spill_gbs)
        self.fetch_gbs = float(fetch_gbs)
        self.alpha_s = float(alpha_s)
        self.batch_bytes = int(batch_bytes)
        self.planned_moves = 0
        self.planned_bytes = 0
        self.planned_batches = 0

    def rate_gbs(self, kind: str) -> float:
        if kind not in _KINDS:
            raise ValueError(
                f"unknown movement kind {kind!r}; choose one of {_KINDS}"
            )
        return self.spill_gbs if kind == "spill" else self.fetch_gbs

    def price(self, nbytes: int, kind: str) -> float:
        """Seconds one batch of ``nbytes`` takes on the ``kind`` link:
        ``alpha_s + nbytes / (rate * 1e9)``."""
        return self.alpha_s + int(nbytes) / (self.rate_gbs(kind) * 1e9)

    def plan(self, moves) -> list[dict]:
        """Group ``moves`` (:class:`PageMove` list) by (src, dst, kind)
        — preserving first-appearance link order and per-link move
        order, the determinism contract — split each link's run at
        ``batch_bytes``, and price every batch. Returns a list of
        ``{"src", "dst", "kind", "moves", "nbytes", "seconds"}``
        batches; ``sum(b["seconds"])`` is the serialized cost, the
        upper bound a sweep charges (links can overlap in reality —
        that is upside, never modeled as guaranteed)."""
        runs: dict[tuple[str, str, str], list[PageMove]] = {}
        for m in moves:
            runs.setdefault((m.src, m.dst, m.kind), []).append(m)
        out: list[dict] = []
        for (src, dst, kind), ms in runs.items():
            batch: list[PageMove] = []
            size = 0
            for m in ms:
                if batch and size + m.nbytes > self.batch_bytes:
                    out.append(self._batch(src, dst, kind, batch, size))
                    batch, size = [], 0
                batch.append(m)
                size += m.nbytes
            if batch:
                out.append(self._batch(src, dst, kind, batch, size))
        return out

    def _batch(self, src: str, dst: str, kind: str,
               moves: list, nbytes: int) -> dict:
        self.planned_moves += len(moves)
        self.planned_bytes += nbytes
        self.planned_batches += 1
        return {
            "src": src, "dst": dst, "kind": kind, "moves": list(moves),
            "nbytes": nbytes, "seconds": self.price(nbytes, kind),
        }

    def stats(self) -> dict:
        return {
            "moves": self.planned_moves,
            "bytes": self.planned_bytes,
            "batches": self.planned_batches,
            "spill_gbs": self.spill_gbs,
            "fetch_gbs": self.fetch_gbs,
            "alpha_s": self.alpha_s,
            "batch_bytes": self.batch_bytes,
        }

    def __repr__(self) -> str:
        return (
            f"SpillFetchPlanner(spill={self.spill_gbs}GB/s, "
            f"fetch={self.fetch_gbs}GB/s, batch={self.batch_bytes}B)"
        )
