"""Fleet-wide tiered prefix cache: HBM -> host-DRAM -> peer fetch.

The r19 prefix plane made each replica's KV pages shareable WITHIN the
replica (chained-sha256 digests, cold-page retention, COW). This
package promotes that namespace to the FLEET: a digest evicted from
one replica's device arena spills to a host-DRAM page store, and a
digest resident on replica B is fetched by replica A over the r16
migration-ring frame format instead of being re-prefilled — the
straggler-tolerance thesis applied to memory: never redo work a
sibling already finished.

The moving parts:

* :class:`~.directory.FleetPageDirectory` — digest -> locations with
  per-replica generations (crash consistency), residency leases, and
  eviction notifications;
* :class:`~.store.PageStore` — the T2 host-DRAM tier on
  ``native/rings.py`` regions, pin-count lifetimes, zero-copy reads,
  tenant ``spill_pages`` quotas;
* :class:`~.planner.SpillFetchPlanner` — page movements batched per
  link and priced ``alpha + bytes/rate`` (the PERF byte model the sim
  plane charges to its virtual clock);
* :class:`~.client.FleetPrefixCache` — the hub schedulers attach to:
  pool-mirror hooks, admission probe/fetch, spill, partition/kill
  handling, opt-in counters.

Correctness posture: the cache can only SAVE prefill work, never be
required for it. Every failure — partition, kill, eviction, geometry
mismatch mid-flight — degrades to re-prefilling the chunk, and
token streams served off spilled-then-fetched pages are bit-identical
to never-spilled ones (tests/test_fleet_cache.py holds the oracle).
"""

from .client import FleetPrefixCache
from .directory import FleetPageDirectory, Lease, TIERS
from .planner import PageMove, SpillFetchPlanner
from .store import PageStore

__all__ = [
    "FleetPrefixCache",
    "FleetPageDirectory",
    "Lease",
    "TIERS",
    "PageMove",
    "SpillFetchPlanner",
    "PageStore",
]
