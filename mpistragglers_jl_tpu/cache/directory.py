"""Fleet-level prefix-page namespace: digest -> where the bytes live.

Every :class:`~..models.paging.PagePool` keeps a per-replica table
mapping chained prefix digests to resident pages. This module promotes
that table to a FLEET namespace: one :class:`FleetPageDirectory` maps
each digest to its current locations across three tiers —

* ``hbm``  — resident in some replica's device page arena (T1);
* ``dram`` — spilled to the host-DRAM :class:`~.store.PageStore` (T2);
* ``peer`` is not a stored tier but a *lookup outcome*: an ``hbm``
  location on a replica other than the asker (T3 — the page crosses
  on the migration-ring frame format instead of being re-prefilled).

The directory is pure host bookkeeping (stdlib only), deterministic
(insertion-ordered books, no clocks, no randomness — sim days through
it replay bit-identically), and crash-consistent by generation: every
replica registers with :meth:`register_replica` and gets a generation
number; a kill/respawn bumps the generation and drops the dead
incarnation's locations eagerly, and :meth:`locate` re-validates the
generation on every read, so a location published by a dead
incarnation can never be served even if an eager drop was missed.

Residency leases pin a location against eviction for the duration of
a fetch (:meth:`lease` / :meth:`Lease.release`, idempotent); eviction
notifications (:meth:`subscribe`) let the scheduler-side clients react
to a withdrawal — e.g. stop advertising a spilled page a store evicted.
"""

from __future__ import annotations

from typing import Callable

__all__ = ["FleetPageDirectory", "Lease", "TIERS"]

#: Stored tiers, fetch-preference order: a digest resident in host
#: DRAM is served from there before a peer replica's HBM is disturbed
#: (a dram fetch is one memcpy off the local host; a peer fetch costs
#: the owner a device gather plus a ring hop).
TIERS = ("dram", "hbm")


class Lease:
    """One residency pin on a (digest, replica, tier) location: while
    held, the location must not be evicted (the store checks
    :meth:`FleetPageDirectory.leased` before choosing victims).
    ``release()`` is idempotent — fetch fallback paths may release on
    every exit without double-counting."""

    __slots__ = ("directory", "digest", "replica", "tier", "_live")

    def __init__(self, directory: "FleetPageDirectory", digest: bytes,
                 replica: str, tier: str):
        self.directory = directory
        self.digest = digest
        self.replica = replica
        self.tier = tier
        self._live = True

    def release(self) -> None:
        if not self._live:
            return
        self._live = False
        self.directory._drop_lease(self.digest)

    def __enter__(self) -> "Lease":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class FleetPageDirectory:
    """The fleet prefix-page namespace (module docstring). All
    mutators are cheap dict operations; the optional ``registry=``
    publishes the directory-size gauge (GC004: dark by default)."""

    def __init__(self, *, registry=None):
        # digest -> {(replica, tier): generation}, both dicts
        # insertion-ordered (determinism: locate() scans in publish
        # order within a tier)
        self._locs: dict[bytes, dict[tuple[str, str], int]] = {}
        self._gen: dict[str, int] = {}
        self._leases: dict[bytes, int] = {}
        self._subs: list[Callable] = []
        self.n_published = 0
        self.n_withdrawn = 0
        self.n_replica_drops = 0
        self._registry = registry
        self._m_size = (
            registry.gauge(
                "cache_directory_size",
                help="digests with at least one live location in the "
                "fleet page directory",
            )
            if registry is not None else None
        )

    # -- membership -----------------------------------------------------

    def register_replica(self, replica: str) -> int:
        """A replica (or the page store) joins the namespace; returns
        its generation. Re-registering an existing name is the
        RESPAWN case: the generation bumps and every location the dead
        incarnation published is dropped — publications made before a
        crash must not survive it."""
        if not replica or not isinstance(replica, str):
            raise ValueError(
                f"replica name must be a non-empty str, got {replica!r}"
            )
        if replica in self._gen:
            self._purge(replica)
            self.n_replica_drops += 1
        self._gen[replica] = self._gen.get(replica, 0) + 1
        return self._gen[replica]

    def generation(self, replica: str) -> int:
        """Current generation of ``replica`` (0 = never registered)."""
        return self._gen.get(replica, 0)

    def drop_replica(self, replica: str) -> None:
        """Crash handling: invalidate every location ``replica``
        published (any tier) — the dead incarnation's HBM pages are
        gone with its process; its next :meth:`register_replica` is a
        fresh generation. Unknown names are a no-op (a replica that
        never published has nothing to drop)."""
        if replica not in self._gen:
            return
        self._purge(replica)
        self._gen[replica] += 1  # leases/locations of the old gen die
        self.n_replica_drops += 1

    def _purge(self, replica: str) -> None:
        dead = []
        for d, locs in self._locs.items():
            for (rep, tier) in list(locs):
                if rep == replica:
                    locs.pop((rep, tier))
                    self._notify(d, rep, tier)
            if not locs:
                dead.append(d)
        for d in dead:
            self._locs.pop(d, None)
        self._set_size()

    # -- publication ----------------------------------------------------

    def publish(self, digest: bytes, *, replica: str,
                tier: str) -> None:
        """Record that ``replica`` holds ``digest`` in ``tier``. The
        replica must be registered (its generation stamps the entry —
        that stamp is what :meth:`locate` re-validates). Idempotent
        per (digest, replica, tier): re-publishing refreshes the
        generation stamp."""
        if tier not in ("hbm", "dram"):
            raise ValueError(
                f"unknown tier {tier!r}: stored tiers are hbm/dram "
                "(peer is a lookup outcome, not a stored tier)"
            )
        gen = self._gen.get(replica)
        if gen is None:
            raise ValueError(
                f"publish from unregistered replica {replica!r}: call "
                "register_replica first (the generation stamp is the "
                "crash-consistency witness)"
            )
        self._locs.setdefault(digest, {})[(replica, tier)] = gen
        self.n_published += 1
        self._set_size()

    def withdraw(self, digest: bytes, *, replica: str,
                 tier: str) -> bool:
        """The location is gone (page freed, store evicted, content
        overwritten). Returns True when an entry was removed;
        subscribers are notified either way only on actual removal."""
        locs = self._locs.get(digest)
        if locs is None or locs.pop((replica, tier), None) is None:
            return False
        if not locs:
            self._locs.pop(digest, None)
        self.n_withdrawn += 1
        self._notify(digest, replica, tier)
        self._set_size()
        return True

    # -- lookup ---------------------------------------------------------

    def locate(self, digest: bytes, *,
               exclude: str | None = None) -> list[tuple[str, str]]:
        """Live locations of ``digest`` as ``(replica, tier)`` pairs,
        dram first then hbm (:data:`TIERS`), ``exclude`` (the asking
        replica — its own HBM residency is a LOCAL hit, not a fleet
        one) filtered out. Generation-checked: entries whose stamp no
        longer matches the replica's current generation are stale
        (published before a crash the eager purge missed) and are
        pruned here, never served."""
        locs = self._locs.get(digest)
        if not locs:
            return []
        out = []
        stale = []
        for (rep, tier), gen in locs.items():
            if self._gen.get(rep) != gen:
                stale.append((rep, tier))
                continue
            if rep == exclude:
                continue
            out.append((rep, tier))
        for key in stale:
            locs.pop(key, None)
        if stale and not locs:
            self._locs.pop(digest, None)
            self._set_size()
        out.sort(key=lambda rt: TIERS.index(rt[1]))
        return out

    def __contains__(self, digest: bytes) -> bool:
        return digest in self._locs

    def has(self, digest: bytes, *, replica: str, tier: str) -> bool:
        locs = self._locs.get(digest)
        return bool(locs) and (replica, tier) in locs

    @property
    def size(self) -> int:
        """Digests with at least one location."""
        return len(self._locs)

    # -- leases ---------------------------------------------------------

    def lease(self, digest: bytes, replica: str, tier: str) -> Lease:
        """Pin a location for the duration of a fetch. The lease does
        not validate residency (the fetch path already did via
        :meth:`locate`); it only guarantees that a cooperating evictor
        (:meth:`leased`) will pass over the digest while it is held."""
        self._leases[digest] = self._leases.get(digest, 0) + 1
        return Lease(self, digest, replica, tier)

    def leased(self, digest: bytes) -> bool:
        return self._leases.get(digest, 0) > 0

    def _drop_lease(self, digest: bytes) -> None:
        n = self._leases.get(digest, 0) - 1
        if n > 0:
            self._leases[digest] = n
        else:
            self._leases.pop(digest, None)

    # -- eviction notifications ------------------------------------------

    def subscribe(self, callback: Callable) -> None:
        """``callback(digest, replica, tier)`` fires on every location
        removal (withdraw, replica drop, stale prune). Callbacks must
        not mutate the directory reentrantly for the same digest."""
        self._subs.append(callback)

    def _notify(self, digest: bytes, replica: str, tier: str) -> None:
        for cb in self._subs:
            cb(digest, replica, tier)

    def _set_size(self) -> None:
        if self._m_size is not None:
            self._m_size.set(len(self._locs))

    # -- invariants -----------------------------------------------------

    def check(self) -> None:
        """Structural invariants: no empty location maps, every entry
        names a registered replica, every generation stamp is at most
        the replica's current one, lease counts positive."""
        for d, locs in self._locs.items():
            if not locs:
                raise AssertionError(f"digest {d.hex()} has no locations")
            for (rep, tier), gen in locs.items():
                if rep not in self._gen:
                    raise AssertionError(
                        f"location names unregistered replica {rep!r}"
                    )
                if gen > self._gen[rep]:
                    raise AssertionError(
                        f"location generation {gen} is from the future "
                        f"(replica {rep!r} at {self._gen[rep]})"
                    )
                if tier not in ("hbm", "dram"):
                    raise AssertionError(f"unknown stored tier {tier!r}")
        for d, n in self._leases.items():
            if n < 1:
                raise AssertionError(f"non-positive lease count {n}")

    def stats(self) -> dict:
        by_tier = {"hbm": 0, "dram": 0}
        for locs in self._locs.values():
            for (_rep, tier) in locs:
                by_tier[tier] += 1
        return {
            "digests": len(self._locs),
            "locations": by_tier,
            "replicas": len(self._gen),
            "published": self.n_published,
            "withdrawn": self.n_withdrawn,
            "replica_drops": self.n_replica_drops,
        }

    def __repr__(self) -> str:
        return (
            f"FleetPageDirectory(digests={len(self._locs)}, "
            f"replicas={len(self._gen)})"
        )
