"""Host-DRAM page store: the T2 tier that absorbs cold evictions.

When a replica's :meth:`~..models.serving.ServingScheduler._free_slot`
retires the last reference to a registered prefix page, r19 keeps the
page COLD in HBM until arena pressure reclaims it. This store catches
the next step of that lifecycle: the reclaimed page's KV bytes land in
one host-DRAM region (``native/rings.py`` — a memfd region where the
platform has ``memfd_create``, the heap twin elsewhere), divided into
page-sized slots under the established :class:`~..native.rings.
RingAlloc` pin discipline:

* every resident page holds a ``"store"`` pin on its slot;
* :meth:`get` serves the page as a ZERO-COPY ``memoryview`` over the
  region, adding one ``("view", n)`` pin released by
  :func:`~..native.rings.track_release` when the last derived view
  dies — eviction of a viewed page frees the directory entry at once
  but the slot's bytes survive until every reader is gone (the same
  keep-window semantics result rings give transport consumers);
* eviction is oldest-first in insertion order, skipping digests the
  :class:`~.directory.FleetPageDirectory` holds a residency lease on
  (a fetch in progress must not watch its source evaporate).

QoS extends here (r19 page quotas → spill tier): a tenant's
``spill_pages`` contract bounds how many of ITS evicted pages the
store keeps; at the bound the tenant's own oldest spilled page is
evicted first — one tenant's eviction storm cannot flush another
tenant's warm prefixes out of DRAM. ``spill_pages=0`` means the store
refuses that tenant's pages outright.

All observability is opt-in (GC004): ``registry=`` publishes
``cache_spill_bytes_total``, ``cache_store_evictions_total`` and the
``cache_store_pages`` gauge; ``flight=`` records spill/evict instants.
"""

from __future__ import annotations

import numpy as np

from ..native.rings import RingAlloc, as_u8, region_create, track_release

__all__ = ["PageStore"]


class PageStore:
    """Fixed-capacity host-DRAM page store (module docstring).

    ``page_bytes`` must match the arena's
    :meth:`~..models.serving.ServingScheduler._page_row_bytes` — the
    store is a byte-level cache, so every participating replica must
    share one page geometry; :meth:`put` refuses mismatched payloads
    by name rather than serving torn pages later.
    """

    def __init__(self, page_bytes: int, capacity_pages: int, *,
                 name: str = "fleet-page-store", directory=None,
                 registry=None, flight=None, qos=None):
        if page_bytes < 1 or capacity_pages < 1:
            raise ValueError(
                f"need page_bytes >= 1 and capacity_pages >= 1, got "
                f"({page_bytes}, {capacity_pages})"
            )
        self.page_bytes = int(page_bytes)
        self.capacity_pages = int(capacity_pages)
        self.name = name
        self._region = region_create(
            self.page_bytes * self.capacity_pages, name
        )
        self._ring = RingAlloc(self.capacity_pages)
        # digest -> (slot, gen); insertion order IS eviction age
        self._slots: dict[bytes, tuple[int, int]] = {}
        self._tenant_of: dict[bytes, str | None] = {}
        self._tenant_count: dict[str, int] = {}
        self._vclock = 0  # unique ("view", n) pin tokens
        self._directory = directory
        self._qos = qos
        self._flight = flight
        self.n_puts = 0
        self.n_hits = 0
        self.n_evictions = 0
        self.n_refused = 0
        self.spilled_bytes = 0
        if directory is not None:
            directory.register_replica(name)
        self._m_spill = self._m_evict = self._m_pages = None
        if registry is not None:
            self._m_spill = registry.counter(
                "cache_spill_bytes_total",
                help="bytes of evicted prefix pages absorbed by the "
                "host-DRAM page store",
            )
            self._m_evict = registry.counter(
                "cache_store_evictions_total",
                help="pages evicted from the host-DRAM store "
                "(capacity or tenant spill quota)",
            )
            self._m_pages = registry.gauge(
                "cache_store_pages",
                help="prefix pages resident in the host-DRAM store",
            )

    # -- write side ------------------------------------------------------

    def put(self, digest: bytes, payload, *,
            tenant: str | None = None) -> bool:
        """Absorb one evicted page. True when the digest is resident
        after the call (already present counts); False when the store
        refused it — tenant spill quota exhausted with nothing of its
        own to evict, or every slot pinned by live views. A refusal is
        never an error: the page's bytes are reproducible by prefill,
        the store only saves the work."""
        if digest in self._slots:
            return True
        buf = as_u8(payload)
        if buf.size != self.page_bytes:
            raise ValueError(
                f"payload is {buf.size} bytes, store pages are "
                f"{self.page_bytes}: page geometry must match across "
                "the fleet (quantize_kv / page_tokens / config drift?)"
            )
        if not self._make_room_for(tenant):
            self.n_refused += 1
            return False
        got = self._ring.acquire(("store",))
        while got is None:
            # every slot pinned: evict an unleased resident (its slot
            # may itself stay view-pinned — keep going) or give up
            if not self._evict_one(protect=digest):
                self.n_refused += 1
                return False
            got = self._ring.acquire(("store",))
        slot, gen = got
        off = slot * self.page_bytes
        self._region.view[off:off + self.page_bytes] = buf
        self._slots[digest] = (slot, gen)
        self._tenant_of[digest] = tenant
        if tenant is not None:
            self._tenant_count[tenant] = \
                self._tenant_count.get(tenant, 0) + 1
        self.n_puts += 1
        self.spilled_bytes += self.page_bytes
        if self._directory is not None:
            self._directory.publish(
                digest, replica=self.name, tier="dram"
            )
        if self._m_spill is not None:
            self._m_spill.inc(self.page_bytes)
        if self._m_pages is not None:
            self._m_pages.set(len(self._slots))
        if self._flight is not None:
            self._flight.event(
                "page spilled", src="cache", tenant=tenant,
                digest=digest.hex()[:12],
            )
        return True

    def _make_room_for(self, tenant: str | None) -> bool:
        """Enforce the tenant's ``spill_pages`` quota BEFORE the slot
        acquire: over the bound, the tenant's own oldest page goes
        first (mirror of r19 cold-page reclaim). False = this tenant
        may not spill at all right now."""
        if self._qos is None or tenant is None or tenant not in self._qos:
            return True
        quota = self._qos.get(tenant).spill_pages
        if quota is None:
            return True
        if quota == 0:
            return False
        while self._tenant_count.get(tenant, 0) >= quota:
            if not self._evict_one(tenant=tenant):
                return False
        return True

    def _evict_one(self, *, tenant: str | None = None,
                   protect: bytes | None = None) -> bool:
        """Evict the oldest unleased resident page — ``tenant``'s own
        oldest when given (quota path), any tenant's otherwise
        (capacity path). False when nothing is evictable."""
        for d in self._slots:
            if d == protect:
                continue
            if tenant is not None and self._tenant_of.get(d) != tenant:
                continue
            if self._directory is not None and self._directory.leased(d):
                continue
            reason = (
                "tenant_spill_quota" if tenant is not None
                else "store_capacity"
            )
            self._drop(d, reason)
            return True
        return False

    def _drop(self, digest: bytes, reason: str) -> None:
        slot, gen = self._slots.pop(digest)
        self._ring.release(slot, gen, "store")
        t = self._tenant_of.pop(digest, None)
        if t is not None:
            n = self._tenant_count.get(t, 0) - 1
            if n > 0:
                self._tenant_count[t] = n
            else:
                self._tenant_count.pop(t, None)
        self.n_evictions += 1
        if self._directory is not None:
            self._directory.withdraw(
                digest, replica=self.name, tier="dram"
            )
        if self._m_evict is not None:
            self._m_evict.inc()
        if self._m_pages is not None:
            self._m_pages.set(len(self._slots))
        if self._flight is not None:
            self._flight.event(
                "page evicted", src="cache", reason=reason,
                digest=digest.hex()[:12],
            )

    # -- read side -------------------------------------------------------

    def get(self, digest: bytes) -> "memoryview | None":
        """The page's bytes as a zero-copy ``memoryview`` over the
        region, or None on miss. The view pins its slot
        (``track_release``): even if the page is evicted while the
        caller still reads, the bytes stay put until the last derived
        view dies — the caller never copies defensively and never
        reads a torn page."""
        entry = self._slots.get(digest)
        if entry is None:
            return None
        slot, gen = entry
        off = slot * self.page_bytes
        view = self._region.view[off:off + self.page_bytes]
        self._vclock += 1
        holder = ("view", self._vclock)
        self._ring.add_holder(slot, gen, holder)
        track_release(view, self._ring.release, slot, gen, holder)
        self.n_hits += 1
        return memoryview(view)

    def __contains__(self, digest: bytes) -> bool:
        return digest in self._slots

    # -- bookkeeping -----------------------------------------------------

    @property
    def pages(self) -> int:
        return len(self._slots)

    def tenant_pages(self, tenant: str) -> int:
        return self._tenant_count.get(tenant, 0)

    def drop_tenant(self, tenant: str) -> int:
        """Evict every page ``tenant`` spilled (contract teardown);
        returns the count."""
        mine = [d for d, t in self._tenant_of.items() if t == tenant]
        for d in mine:
            self._drop(d, "tenant_teardown")
        return len(mine)

    def check(self) -> None:
        """Structural invariants: resident count within capacity,
        tenant counts consistent with the per-digest book, every
        resident slot still store-pinned (generation live)."""
        if len(self._slots) > self.capacity_pages:
            raise AssertionError(
                f"{len(self._slots)} resident > {self.capacity_pages} "
                "capacity"
            )
        counts: dict[str, int] = {}
        for d, t in self._tenant_of.items():
            if d not in self._slots:
                raise AssertionError("tenant book names a missing digest")
            if t is not None:
                counts[t] = counts.get(t, 0) + 1
        if counts != self._tenant_count:
            raise AssertionError(
                f"tenant counts drifted: {counts} != {self._tenant_count}"
            )
        for d, (slot, gen) in self._slots.items():
            if not self._ring.add_holder(slot, gen, "store"):
                raise AssertionError(
                    f"resident digest {d.hex()[:12]} lost its slot "
                    f"(slot {slot} gen {gen} stale)"
                )

    def stats(self) -> dict:
        return {
            "pages": len(self._slots),
            "capacity": self.capacity_pages,
            "page_bytes": self.page_bytes,
            "puts": self.n_puts,
            "hits": self.n_hits,
            "evictions": self.n_evictions,
            "refused": self.n_refused,
            "spilled_bytes": self.spilled_bytes,
            "pinned_slots": self._ring.pinned,
        }

    def close(self) -> None:
        """Withdraw every advertisement and release the region. Live
        served views keep their slots' bytes alive (heap twin) or the
        mapping pinned (memfd) — the established close discipline."""
        for d in list(self._slots):
            self._drop(d, "store_close")
        self._region.close()

    def __repr__(self) -> str:
        return (
            f"PageStore({len(self._slots)}/{self.capacity_pages} pages"
            f" x {self.page_bytes}B)"
        )
