"""TPU-native straggler-resilient async worker pools.

A from-scratch re-design of the reference MPIAsyncPools.jl
(severinson/MPIStragglers.jl) for JAX/XLA device meshes: a coordinator
broadcasts work to a pool of n workers and returns as soon as the ``nwait``
fastest respond (or an arbitrary predicate over per-worker receive-epochs
holds), with stale results harvested and re-tasked across epochs — the
primitive under erasure-coded GEMM and gradient-coded SGD that decode from
any k-of-n shards.
"""

from .pool import AsyncPool, asyncmap, asyncmap_fused, waitall, DeadWorkerError
from .backends import Backend, LocalBackend, ProcessBackend, WorkerFailure

__all__ = [
    "AsyncPool",
    "asyncmap",
    "asyncmap_fused",
    "waitall",
    "DeadWorkerError",
    "Backend",
    "LocalBackend",
    "ProcessBackend",
    "NativeProcessBackend",
    "XLADeviceBackend",
    "WorkerFailure",
    "SimBackend",
    "VirtualClock",
]

def _version() -> str:
    # pyproject.toml is the single source of truth. Prefer reading it
    # directly when running from a source tree (an older installed
    # wheel's metadata must not shadow the tree); fall back to dist
    # metadata for installed packages, where pyproject isn't shipped.
    import os

    pyproject = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "pyproject.toml"
    )
    try:
        import tomllib

        with open(pyproject, "rb") as f:
            return tomllib.load(f)["project"]["version"]
    except Exception:
        # py3.10 has no tomllib: a plain parse of the version line keeps
        # the source tree authoritative there too (an installed wheel's
        # metadata must never shadow the tree)
        import re

        try:
            with open(pyproject, encoding="utf-8") as f:
                m = re.search(
                    r'^version\s*=\s*"([^"]+)"', f.read(), re.MULTILINE
                )
            if m:
                return m.group(1)
        except OSError:
            pass
    try:
        from importlib.metadata import version as dist_version

        return dist_version("mpistragglers_jl_tpu")
    except Exception:  # pragma: no cover - source tree, py<3.11
        return "0+unknown"


__version__ = _version()


def __getattr__(name):
    # lazy: keep `import mpistragglers_jl_tpu` jax-free for
    # LocalBackend-only (pure numpy) use
    if name == "XLADeviceBackend":
        from .backends.xla import XLADeviceBackend

        return XLADeviceBackend
    if name == "NativeProcessBackend":
        # lazy: first use compiles the C++ transport
        from .backends.native import NativeProcessBackend

        return NativeProcessBackend
    if name in ("SimBackend", "VirtualClock"):
        # lazy: the sim plane is stdlib+numpy but pulls the whole
        # replay/tune surface (and utils) with it — a LocalBackend-only
        # import should stay as light as before ISSUE 5. GC001 proves
        # sim/ accelerator-free via its own hermetic-root walk.
        from . import sim

        return getattr(sim, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
