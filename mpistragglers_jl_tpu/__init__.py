"""TPU-native straggler-resilient async worker pools.

A from-scratch re-design of the reference MPIAsyncPools.jl
(severinson/MPIStragglers.jl) for JAX/XLA device meshes: a coordinator
broadcasts work to a pool of n workers and returns as soon as the ``nwait``
fastest respond (or an arbitrary predicate over per-worker receive-epochs
holds), with stale results harvested and re-tasked across epochs — the
primitive under erasure-coded GEMM and gradient-coded SGD that decode from
any k-of-n shards.
"""

from .pool import AsyncPool, asyncmap, waitall, DeadWorkerError
from .backends import Backend, LocalBackend, ProcessBackend, WorkerFailure

__all__ = [
    "AsyncPool",
    "asyncmap",
    "waitall",
    "DeadWorkerError",
    "Backend",
    "LocalBackend",
    "ProcessBackend",
    "NativeProcessBackend",
    "XLADeviceBackend",
    "WorkerFailure",
]

__version__ = "0.1.0"


def __getattr__(name):
    # lazy: keep `import mpistragglers_jl_tpu` jax-free for
    # LocalBackend-only (pure numpy) use
    if name == "XLADeviceBackend":
        from .backends.xla import XLADeviceBackend

        return XLADeviceBackend
    if name == "NativeProcessBackend":
        # lazy: first use compiles the C++ transport
        from .backends.native import NativeProcessBackend

        return NativeProcessBackend
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
