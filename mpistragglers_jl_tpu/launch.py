"""``mpiexec``-equivalent one-shot SPMD launcher.

The reference's whole topology comes up with one command —
``mpiexec -n N julia script.jl`` (test/runtests.jl:17): N OS processes
run the *same* script, rank 0 is the coordinator by convention and ranks
1..N-1 are workers. This module reproduces that experience:

.. code-block:: console

    python -m mpistragglers_jl_tpu.launch -n 8 my_script.py [args...]

launches 8 copies of ``my_script.py``; inside the script,

.. code-block:: python

    from mpistragglers_jl_tpu import launch
    ctx = launch.init()
    if ctx.is_coordinator:
        backend = ctx.coordinator_backend()   # all workers connected
        ...asyncmap(pool, payload, backend)...
        backend.shutdown()
    else:
        ctx.serve(work_fn)                    # blocks until shutdown

mirrors the reference's ``if rank == root: coordinator_main() else:
worker_main()`` split (examples/iterative_example.jl), with the library
owning everything the reference left to convention: the rendezvous
address, the shared auth secret, the worker loop, the shutdown
broadcast, and non-zero-exit propagation (a failed rank fails the
launch, like mpiexec).

Implementation notes. The launcher picks a fresh Unix-socket address
(or ``--address tcp://host:port`` for multi-host-style runs) and a
random auth token, and hands both to every rank through the
environment (``MSGT_ADDRESS`` / ``MSGT_AUTH`` / ``MSGT_RANK`` /
``MSGT_NRANKS``). Rank 0 binds the socket; workers' connect loop
retries until it is up (worker.py), so start order does not matter.
"""

from __future__ import annotations

import argparse
import os
import secrets
import signal
import subprocess
import sys
import tempfile
import time
import uuid
from dataclasses import dataclass

__all__ = ["LaunchContext", "init", "main"]

_ENV_RANK = "MSGT_RANK"
_ENV_NRANKS = "MSGT_NRANKS"
_ENV_ADDRESS = "MSGT_ADDRESS"
_ENV_AUTH = "MSGT_AUTH"


@dataclass(frozen=True)
class LaunchContext:
    """This rank's view of a launched job (reference analog: the
    ``MPI.Comm_rank``/``Comm_size`` pair every script starts with)."""

    rank: int
    n_ranks: int
    address: str
    token: bytes

    @property
    def is_coordinator(self) -> bool:
        """Rank 0 is the coordinator, the reference's root convention
        (examples/iterative_example.jl:10)."""
        return self.rank == 0

    @property
    def n_workers(self) -> int:
        """Pool size: every rank except the coordinator."""
        return self.n_ranks - 1

    @property
    def worker_index(self) -> int:
        """This rank's pool index (valid on worker ranks only)."""
        if self.rank == 0:
            raise RuntimeError("rank 0 is the coordinator, not a worker")
        return self.rank - 1

    def coordinator_backend(self, *, connect_timeout: float = 60.0, **kw):
        """The connected :class:`~.backends.native.NativeProcessBackend`
        over this job's workers (coordinator rank only)."""
        if not self.is_coordinator:
            raise RuntimeError(
                "coordinator_backend() is for rank 0; workers call serve()"
            )
        from .backends.native import NativeProcessBackend

        return NativeProcessBackend(
            None,
            self.n_workers,
            spawn=False,
            address=self.address,
            auth=self.token,
            connect_timeout=connect_timeout,
            **kw,
        )

    def serve(self, work_fn, delay_fn=None, *,
              connect_timeout: float = 60.0) -> None:
        """Run this rank's worker loop until the coordinator's shutdown
        broadcast (worker ranks only). ``work_fn(i, payload, epoch)``."""
        from .worker import run_worker

        run_worker(
            self.address,
            self.worker_index,
            work_fn,
            delay_fn,
            token=self.token,
            connect_timeout=connect_timeout,
        )


def init() -> LaunchContext:
    """Read this process's launch environment (set by ``main``).

    Raises ``RuntimeError`` when not running under the launcher — a
    script can catch that to fall back to single-process mode.
    """
    rank = os.environ.get(_ENV_RANK)
    if rank is None:
        raise RuntimeError(
            "not launched via `python -m mpistragglers_jl_tpu.launch`; "
            f"{_ENV_RANK} is unset"
        )
    token = os.environ.get(_ENV_AUTH, "")
    return LaunchContext(
        rank=int(rank),
        n_ranks=int(os.environ[_ENV_NRANKS]),
        address=os.environ[_ENV_ADDRESS],
        token=token.encode(),
    )


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m mpistragglers_jl_tpu.launch",
        description="Run one script on N processes: rank 0 coordinates, "
        "ranks 1..N-1 serve as pool workers (the mpiexec experience, "
        "reference test/runtests.jl:17).",
    )
    ap.add_argument("-n", "--nranks", type=int, required=True,
                    help="total ranks incl. the coordinator (pool size n-1)")
    ap.add_argument(
        "--address", default=None,
        help="rendezvous address (default: fresh Unix socket; pass "
        "tcp://host:port to exercise the TCP transport)",
    )
    ap.add_argument(
        "--grace", type=float, default=10.0,
        help="seconds workers get to exit after the coordinator returns "
        "before being terminated",
    )
    ap.add_argument("script", help="Python script every rank executes")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed through to the script")
    args = ap.parse_args(argv)
    if args.nranks < 2:
        ap.error("-n must be >= 2 (one coordinator + at least one worker)")

    address = args.address or os.path.join(
        tempfile.gettempdir(), f"msgt-launch-{uuid.uuid4().hex[:12]}.sock"
    )
    token = secrets.token_hex(16)
    procs: list[subprocess.Popen] = []
    base_env = dict(os.environ)
    base_env[_ENV_NRANKS] = str(args.nranks)
    base_env[_ENV_ADDRESS] = address
    base_env[_ENV_AUTH] = token
    try:
        for r in range(args.nranks):
            env = dict(base_env)
            env[_ENV_RANK] = str(r)
            procs.append(
                subprocess.Popen(
                    [sys.executable, args.script, *args.script_args],
                    env=env,
                )
            )
        # the job is over when the coordinator is: it owns the epoch
        # loop and broadcasts shutdown on exit (backend.shutdown)
        rc = procs[0].wait()
        deadline = time.monotonic() + args.grace
        codes = [rc]
        for p in procs[1:]:
            try:
                codes.append(p.wait(
                    timeout=max(0.0, deadline - time.monotonic())
                ))
            except subprocess.TimeoutExpired:
                p.terminate()
                try:
                    codes.append(p.wait(timeout=5.0))
                except subprocess.TimeoutExpired:  # pragma: no cover
                    p.kill()
                    codes.append(p.wait())
    except KeyboardInterrupt:  # forward ^C to the whole job, mpiexec-style
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for p in procs:
            try:
                p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                p.kill()
        raise
    finally:
        if args.address is None and os.path.exists(address):
            try:
                os.unlink(address)
            except OSError:  # pragma: no cover
                pass
    # a failed rank fails the launch, like mpiexec
    sys.exit(max(codes, key=abs) if any(codes) else 0)


if __name__ == "__main__":
    main()
