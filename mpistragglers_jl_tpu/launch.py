"""``mpiexec``-equivalent one-shot SPMD launcher.

The reference's whole topology comes up with one command —
``mpiexec -n N julia script.jl`` (test/runtests.jl:17): N OS processes
run the *same* script, rank 0 is the coordinator by convention and ranks
1..N-1 are workers. This module reproduces that experience:

.. code-block:: console

    python -m mpistragglers_jl_tpu.launch -n 8 my_script.py [args...]

launches 8 copies of ``my_script.py``; inside the script,

.. code-block:: python

    from mpistragglers_jl_tpu import launch
    ctx = launch.init()
    if ctx.is_coordinator:
        backend = ctx.coordinator_backend()   # all workers connected
        ...asyncmap(pool, payload, backend)...
        backend.shutdown()
    else:
        ctx.serve(work_fn)                    # blocks until shutdown

mirrors the reference's ``if rank == root: coordinator_main() else:
worker_main()`` split (examples/iterative_example.jl), with the library
owning everything the reference left to convention: the rendezvous
address, the shared auth secret, the worker loop, the shutdown
broadcast, and non-zero-exit propagation (a failed rank fails the
launch, like mpiexec).

Implementation notes. The launcher picks a fresh Unix-socket address
(or ``--address tcp://host:port`` for multi-host-style runs) and a
random auth token, and hands both to every rank through the
environment (``MSGT_ADDRESS`` / ``MSGT_AUTH`` / ``MSGT_RANK`` /
``MSGT_NRANKS``). Rank 0 binds the socket; workers' connect loop
retries until it is up (worker.py), so start order does not matter.

**Multi-host** (``mpiexec --hostfile`` equivalent, reference
test/runtests.jl:17 via libmpi):

.. code-block:: console

    python -m mpistragglers_jl_tpu.launch -n 16 --hosts hostA,hostB my_script.py
    python -m mpistragglers_jl_tpu.launch -n 16 --hostfile hosts.txt my_script.py

Ranks are block-assigned to hosts in order (``hostA:slots`` caps a
host's share; a hostfile holds one ``host[:slots]`` per line, ``#``
comments allowed). The first host gets rank 0 and should be the
launching machine (or reachable at the ``--address`` host). Each
remote host gets ONE ssh session running this module in span mode
(``--_span A:B``), which forks its rank processes locally and exits
with the span's worst code — so a failed remote rank fails the launch
exactly like a local one. Assumptions are mpiexec's: passwordless ssh
and the same filesystem layout (script path + package importable) on
every host. ``--launcher`` substitutes the ssh command (the e2e test
fakes two hosts as two local process groups with separate tmpdirs
this way).
"""

from __future__ import annotations

import argparse
import os
import secrets
import signal
import subprocess
import sys
import tempfile
import time
import uuid
from dataclasses import dataclass

__all__ = ["LaunchContext", "init", "main"]

_ENV_RANK = "MSGT_RANK"
_ENV_NRANKS = "MSGT_NRANKS"
_ENV_ADDRESS = "MSGT_ADDRESS"
_ENV_AUTH = "MSGT_AUTH"


@dataclass(frozen=True)
class LaunchContext:
    """This rank's view of a launched job (reference analog: the
    ``MPI.Comm_rank``/``Comm_size`` pair every script starts with)."""

    rank: int
    n_ranks: int
    address: str
    token: bytes

    @property
    def is_coordinator(self) -> bool:
        """Rank 0 is the coordinator, the reference's root convention
        (examples/iterative_example.jl:10)."""
        return self.rank == 0

    @property
    def n_workers(self) -> int:
        """Pool size: every rank except the coordinator."""
        return self.n_ranks - 1

    @property
    def worker_index(self) -> int:
        """This rank's pool index (valid on worker ranks only)."""
        if self.rank == 0:
            raise RuntimeError("rank 0 is the coordinator, not a worker")
        return self.rank - 1

    def coordinator_backend(self, *, connect_timeout: float = 60.0, **kw):
        """The connected :class:`~.backends.native.NativeProcessBackend`
        over this job's workers (coordinator rank only)."""
        if not self.is_coordinator:
            raise RuntimeError(
                "coordinator_backend() is for rank 0; workers call serve()"
            )
        from .backends.native import NativeProcessBackend

        return NativeProcessBackend(
            None,
            self.n_workers,
            spawn=False,
            address=self.address,
            auth=self.token,
            connect_timeout=connect_timeout,
            **kw,
        )

    def serve(self, work_fn, delay_fn=None, *,
              connect_timeout: float = 60.0) -> None:
        """Run this rank's worker loop until the coordinator's shutdown
        broadcast (worker ranks only). ``work_fn(i, payload, epoch)``."""
        from .worker import run_worker

        run_worker(
            self.address,
            self.worker_index,
            work_fn,
            delay_fn,
            token=self.token,
            connect_timeout=connect_timeout,
        )


def init() -> LaunchContext:
    """Read this process's launch environment (set by ``main``).

    Raises ``RuntimeError`` when not running under the launcher — a
    script can catch that to fall back to single-process mode.
    """
    rank = os.environ.get(_ENV_RANK)
    if rank is None:
        raise RuntimeError(
            "not launched via `python -m mpistragglers_jl_tpu.launch`; "
            f"{_ENV_RANK} is unset"
        )
    token = os.environ.get(_ENV_AUTH, "")
    return LaunchContext(
        rank=int(rank),
        n_ranks=int(os.environ[_ENV_NRANKS]),
        address=os.environ[_ENV_ADDRESS],
        token=token.encode(),
    )


def parse_hosts(hosts_arg: str | None, hostfile: str | None
                ) -> list[tuple[str, int | None]]:
    """``--hosts a,b:4`` / hostfile lines ``host[:slots]`` (or mpiexec's
    ``host slots=K``) -> [(host, slots-or-None), ...]."""
    entries: list[str] = []
    if hosts_arg:
        entries.extend(h.strip() for h in hosts_arg.split(",") if h.strip())
    if hostfile:
        with open(hostfile) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if line:
                    entries.append(line.replace(" slots=", ":"))
    out: list[tuple[str, int | None]] = []
    for e in entries:
        if e.startswith("["):  # bracketed IPv6 literal: [addr][:slots]
            addr, _, rest = e[1:].partition("]")
            if rest.startswith(":"):
                out.append((addr, int(rest[1:])))
            elif not rest:
                out.append((addr, None))
            else:
                raise ValueError(f"malformed host entry {e!r}")
        elif e.count(":") > 1:
            # a bare IPv6 literal is ambiguous with host:slots —
            # rsplit would silently eat the last address group
            raise ValueError(
                f"ambiguous host entry {e!r}: bracket IPv6 literals "
                "([fe80::1] or [fe80::1]:4)"
            )
        elif ":" in e:
            host, slots = e.rsplit(":", 1)
            out.append((host, int(slots)))
        else:
            out.append((e, None))
    return out


def assign_ranks(n: int, hosts: list[tuple[str, int | None]]
                 ) -> list[tuple[str, range]]:
    """Block-assign ranks 0..n-1 to hosts in order (mpiexec fill
    semantics): capped hosts take their slot count, uncapped hosts split
    the remainder evenly (earlier hosts take the extra)."""
    caps = [s for _, s in hosts]
    free = [i for i, s in enumerate(caps) if s is None]
    fixed = sum(s for s in caps if s is not None)
    rest = n - fixed
    if free:
        if rest < 0:
            raise ValueError(f"host slots sum to {fixed} > -n {n}")
        share, extra = divmod(max(rest, 0), len(free))
        for j, i in enumerate(free):
            caps[i] = share + (1 if j < extra else 0)
    elif fixed != n:
        raise ValueError(
            f"host slots sum to {fixed} but -n is {n}; they must match "
            "(or leave a host uncapped to absorb the remainder)"
        )
    spans, start = [], 0
    for (host, _), c in zip(hosts, caps):
        if c:
            spans.append((host, range(start, start + c)))
            start += c
    if start != n:
        raise ValueError(f"assigned {start} ranks for -n {n}")
    return spans


def _is_local(host: str) -> bool:
    import socket

    return host in (
        "localhost", "127.0.0.1", socket.gethostname(),
        socket.getfqdn(),
    )


def _spawn_rank(r: int, base_env: dict, script: str,
                script_args: list[str]) -> subprocess.Popen:
    env = dict(base_env)
    env[_ENV_RANK] = str(r)
    return subprocess.Popen(
        [sys.executable, script, *script_args], env=env
    )


def _remote_cmd(launcher: str, host: str, span: range, base_env: dict,
                grace: float, script: str, script_args: list[str]
                ) -> list[str]:
    """One ssh(-like) invocation running this module in span mode on
    ``host``. The rendezvous env rides explicit ``env`` assignments
    (ssh does not forward the environment); cwd is re-entered so the
    same relative script path resolves (mpiexec's same-layout
    assumption)."""
    import shlex

    exports = " ".join(
        f"{k}={shlex.quote(base_env[k])}"
        for k in (_ENV_NRANKS, _ENV_ADDRESS)
    )
    # the auth secret is deliberately NOT in the exports: anything on
    # the ssh command line lands in `ps` output on BOTH hosts for the
    # job's lifetime. It rides the already-open stdin pipe instead
    # (first line; see the span-mode reader in main)
    remote = (
        f"cd {shlex.quote(os.getcwd())} && env {exports} "
        f"{shlex.quote(sys.executable)} -m mpistragglers_jl_tpu.launch "
        f"--_span {span.start}:{span.stop} --grace {grace} "
        f"-n {base_env[_ENV_NRANKS]} "
        + " ".join(shlex.quote(a) for a in [script, *script_args])
    )
    return [*shlex.split(launcher), host, remote]


def _span_stdin_watchdog(
    procs: list[subprocess.Popen], verdict: dict
) -> None:
    """Tie a span runner's life to its ssh channel: when the launcher
    dies or aborts the job, the ssh client goes away, this process's
    stdin hits EOF, and the watchdog kills the span's rank processes
    instead of orphaning them on the remote host (ssh without a pty
    delivers no signal on channel close — EOF on stdin is the only
    portable death notice). Before killing, it records the span's
    worst *already observed* rank code in ``verdict`` and the main
    thread exits with THAT — an early rank failure must survive the
    teardown of a hung sibling, and the main thread's own waits would
    otherwise race to report the watchdog's SIGTERM instead.

    Armed only when stdin is a pipe or socket (what sshd and the
    launcher's stdin=PIPE provide): a manual span-mode run with a tty
    or /dev/null stdin must not see instant EOF and kill its ranks at
    startup."""
    import stat
    import threading

    try:
        mode = os.fstat(0).st_mode
    except OSError:  # pragma: no cover - no stdin at all
        return
    if not (stat.S_ISFIFO(mode) or stat.S_ISSOCK(mode)):
        return

    def watch():
        try:
            # raw os.read, NOT sys.stdin.buffer: a daemon thread
            # blocked in a buffered read holds the buffer lock through
            # interpreter shutdown and CPython aborts with a fatal
            # _enter_buffered_busy error when the span exits normally
            while os.read(0, 4096):
                pass  # the launcher never writes; wait for EOF
        except OSError:  # pragma: no cover - stdin already closed
            pass
        codes = [
            rc for p in procs if (rc := p.poll()) is not None
        ]
        worst = max(codes, key=abs) if any(codes) else 0
        verdict["worst"] = abs(worst) if worst else 0
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5.0
        for p in procs:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:  # pragma: no cover
                p.kill()
        # last resort: if a child is unreapable even after SIGKILL
        # (D-state), the main thread's unbounded wait would hang the
        # span and the launcher would misreport the failure as cleanup;
        # exiting here carries the SAME code the main thread would use,
        # so whichever side wins the race reports identically
        os._exit(verdict["worst"])

    threading.Thread(target=watch, daemon=True, name="span-watchdog").start()


def _wait_span(procs: list[subprocess.Popen], ranks: list[int],
               grace: float) -> list[int]:
    """Wait a group of rank processes: if rank 0 is in the group it
    finishes first (it owns the shutdown broadcast), then the rest get
    ``grace`` seconds before termination; a group without rank 0 waits
    for the broadcast-driven exits unboundedly (mpiexec semantics)."""
    codes: list[int] = []
    rest = list(zip(ranks, procs))
    if 0 in ranks:
        i0 = ranks.index(0)
        p0 = procs[i0]
        rest = [rp for rp in rest if rp[0] != 0]
        # Poll ALL procs while the coordinator runs: a sibling (local
        # rank or whole remote span) that dies nonzero early must abort
        # the job promptly, mpiexec-style — otherwise a span that died
        # before serving (bad host, ssh crash after the token write)
        # leaves the coordinator waiting for workers that will never
        # connect and the launch hangs unboundedly (advisor r3).
        while True:
            rc0 = p0.poll()
            if rc0 is not None:
                codes.append(rc0)
                break
            failed = next(
                ((r, p, p.poll()) for r, p in rest
                 if p.poll() not in (None, 0)),
                None,
            )
            if failed is not None:
                r, _, rc = failed
                what = f"rank {r}" if r >= 0 else "remote span"
                print(
                    f"launch: {what} exited {rc} before the job "
                    "finished; aborting", file=sys.stderr,
                )
                _teardown(procs)
                return [rc]
            time.sleep(0.05)
        deadline = time.monotonic() + grace
        for _, p in rest:
            try:
                codes.append(
                    p.wait(timeout=max(0.0, deadline - time.monotonic()))
                )
            except subprocess.TimeoutExpired:
                if p.stdin is not None:
                    # remote span: closing the ssh channel EOFs the
                    # remote watchdog, which kills its ranks and exits
                    # with the span's worst already-observed code —
                    # collect THAT, so an early remote rank failure is
                    # not masked by a hung sibling
                    try:
                        p.stdin.close()
                    except OSError:  # pragma: no cover
                        pass
                    try:
                        codes.append(p.wait(timeout=15.0))
                        continue
                    except subprocess.TimeoutExpired:  # pragma: no cover
                        pass
                # the launcher is killing this rank itself (grace
                # expired after a clean coordinator exit) — that is
                # cleanup, not a rank failure, so it must not mask a
                # real failure code from another rank
                p.terminate()
                try:
                    p.wait(timeout=5.0)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    p.kill()
                    p.wait()
                codes.append(0)
    else:
        for _, p in rest:
            codes.append(p.wait())
    return codes


def _teardown(procs: list[subprocess.Popen]) -> None:
    """Tear the whole job down: EOF remote liveness channels (the span
    watchdog reaps its ranks — a signal to the ssh client never
    crosses), SIGINT local ranks, then wait/kill."""
    for p in procs:
        if p.stdin is not None:
            try:
                p.stdin.close()
            except OSError:
                pass
        if p.poll() is None:
            p.send_signal(signal.SIGINT)
    for p in procs:
        try:
            p.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            p.kill()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m mpistragglers_jl_tpu.launch",
        description="Run one script on N processes: rank 0 coordinates, "
        "ranks 1..N-1 serve as pool workers (the mpiexec experience, "
        "reference test/runtests.jl:17).",
    )
    ap.add_argument("-n", "--nranks", type=int, required=True,
                    help="total ranks incl. the coordinator (pool size n-1)")
    ap.add_argument(
        "--address", default=None,
        help="rendezvous address (default: fresh Unix socket, or "
        "tcp://<this-host>:<random port> under --hosts)",
    )
    ap.add_argument(
        "--hosts", default=None,
        help="comma-separated host[:slots] list; ranks are block-"
        "assigned in order, the first host takes rank 0 (mpiexec "
        "hostfile semantics over ssh)",
    )
    ap.add_argument(
        "--hostfile", default=None,
        help="file of host[:slots] lines (mpiexec 'host slots=K' "
        "accepted); combined after --hosts",
    )
    ap.add_argument(
        "--launcher", default="ssh -o BatchMode=yes",
        help="command prefix to reach a remote host (default "
        "'ssh -o BatchMode=yes'; the e2e test substitutes a local "
        "fake to model two hosts as two process groups)",
    )
    ap.add_argument(
        "--grace", type=float, default=10.0,
        help="seconds workers get to exit after the coordinator returns "
        "before being terminated",
    )
    ap.add_argument(
        "--_span", default=None, help=argparse.SUPPRESS,
    )  # internal: 'A:B' — run ranks A..B-1 locally (remote side of ssh)
    ap.add_argument("script", help="Python script every rank executes")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed through to the script")
    args = ap.parse_args(argv)
    if args.nranks < 2:
        ap.error("-n must be >= 2 (one coordinator + at least one worker)")

    if args._span is not None:
        # span mode: this process IS one host's share of the job; the
        # rendezvous env was injected by the launching side
        a, b = (int(x) for x in args._span.split(":"))
        base_env = dict(os.environ)
        if _ENV_AUTH not in base_env:
            # the secret arrives as the FIRST stdin line (never on the
            # ssh command line — argv is world-readable via ps); read
            # it before the watchdog takes over the pipe
            line = sys.stdin.buffer.readline()
            if not line.strip():
                ap.error(
                    f"span mode needs {_ENV_AUTH} in the environment or "
                    "the secret on the first stdin line"
                )
            base_env[_ENV_AUTH] = line.strip().decode()
        for key in (_ENV_NRANKS, _ENV_ADDRESS):
            if key not in base_env:
                ap.error(f"span mode requires {key} in the environment")
        procs = [
            _spawn_rank(r, base_env, args.script, args.script_args)
            for r in range(a, b)
        ]
        verdict: dict = {}
        _span_stdin_watchdog(procs, verdict)
        codes = _wait_span(procs, list(range(a, b)), args.grace)
        if "worst" in verdict:
            # channel EOF tore the span down: report the failure the
            # watchdog observed BEFORE killing, not the kill signals
            sys.exit(verdict["worst"])
        sys.exit(max(codes, key=abs) if any(codes) else 0)

    hosts = parse_hosts(args.hosts, args.hostfile)
    if hosts:
        spans = assign_ranks(args.nranks, hosts)
        if args.address is None:
            import socket

            port = 20000 + secrets.randbelow(40000)
            # rank 0 binds on the FIRST host, so the address host must
            # be that machine's name as the OTHER hosts resolve it: the
            # first --hosts entry verbatim when it is remote, this
            # machine's hostname when the first entry is a local alias
            # ("localhost" would make remote workers dial themselves)
            first = hosts[0][0]
            host0 = socket.gethostname() if _is_local(first) else first
            address = f"tcp://{host0}:{port}"
        else:
            address = args.address
        if not address.startswith("tcp://"):
            ap.error("--hosts requires a tcp:// --address")
    else:
        spans = [("localhost", range(args.nranks))]
        address = args.address or os.path.join(
            tempfile.gettempdir(), f"msgt-launch-{uuid.uuid4().hex[:12]}.sock"
        )
    token = secrets.token_hex(16)
    base_env = dict(os.environ)
    base_env[_ENV_NRANKS] = str(args.nranks)
    base_env[_ENV_ADDRESS] = address
    base_env[_ENV_AUTH] = token

    procs: list[subprocess.Popen] = []
    ranks_of: list[list[int]] = []  # local rank lists; [-1] = remote span
    try:
        for host, span in spans:
            if _is_local(host):
                for r in span:
                    procs.append(
                        _spawn_rank(r, base_env, args.script,
                                    args.script_args)
                    )
                    ranks_of.append([r])
            else:
                # stdin=PIPE, held open for the job's life: the remote
                # span runner's watchdog treats EOF on this channel as
                # the launch dying and tears its ranks down (no orphaned
                # remote processes on abort — see _span_stdin_watchdog)
                p = subprocess.Popen(
                    _remote_cmd(
                        args.launcher, host, span, base_env, args.grace,
                        args.script, args.script_args,
                    ),
                    stdin=subprocess.PIPE,
                )
                # first stdin line = the auth secret (see _remote_cmd);
                # the pipe then stays open as the job-liveness channel
                procs.append(p)
                ranks_of.append([-1] if 0 not in span else [0])
                try:
                    p.stdin.write((token + "\n").encode())
                    p.stdin.flush()
                except OSError as e:
                    # ssh died immediately (bad host, ssh not on PATH):
                    # the token write hits a broken pipe. Treat it as a
                    # failed span — reap this proc's code and tear the
                    # rest of the job down via the shared cleanup below
                    # instead of escaping with a raw traceback that
                    # orphans already-spawned ranks (advisor r3 finding).
                    code = p.wait()
                    print(
                        f"launch: span on {host!r} failed before start "
                        f"(exit {code}): {e}",
                        file=sys.stderr,
                    )
                    _teardown(procs)
                    sys.exit(code if code else 1)
        flat_ranks = [r for rs in ranks_of for r in rs]
        codes = _wait_span(procs, flat_ranks, args.grace)
    except KeyboardInterrupt:  # forward ^C to the whole job, mpiexec-style
        _teardown(procs)
        raise
    finally:
        if (
            args.address is None
            and not address.startswith("tcp://")
            and os.path.exists(address)
        ):
            try:
                os.unlink(address)
            except OSError:  # pragma: no cover
                pass
    # a failed rank (local or remote span) fails the launch, like mpiexec
    sys.exit(max(codes, key=abs) if any(codes) else 0)


if __name__ == "__main__":
    main()
