"""XLA device backend: pool workers are accelerator devices.

This is the TPU-native replacement for the reference's transport layer
(MPI.jl point-to-point over OS processes — SURVEY §2 component C8). The
mapping, per SURVEY §7 "the hard parts":

=====================  ==================================================
reference (MPI)         here (JAX/XLA)
=====================  ==================================================
worker process          an accelerator device (TPU chip / virtual CPU
                        device); several pool workers may time-slice one
                        device when the pool is larger than the slice
``MPI.Isend``           ``jax.device_put`` of the payload onto the
                        worker's device — an asynchronous H2D DMA whose
                        result is an *immutable* snapshot, so the
                        reference's ``isendbuf`` copy discipline
                        (src/MPIAsyncPools.jl:63-66,:130) is free
compute on worker       a jitted per-shard program dispatched on the
                        worker's device; XLA's async dispatch returns a
                        future-like ``jax.Array`` immediately
``MPI.Waitany!``        per-worker dispatcher threads block on
                        ``Array.block_until_ready`` and signal the shared
                        completion condition (backends/base.py), so the
                        coordinator's hot loop sleeps instead of spinning
=====================  ==================================================

Crucially there is **no collective in the straggle-exposed path**: each
worker's program is independent, so a slow or dead device delays nobody
else — a single ``pjit`` with a ``psum`` would re-introduce the very
bulk-synchronous straggler penalty this design exists to kill (SURVEY §7).
Collectives belong in the decode/combine step over the k winners (see
parallel/collectives.py).

Results are left device-resident; the decode/combine step can consume
them without a host round-trip (``pool.results[i]``), and only a caller-
provided ``recvbuf`` forces a D2H gather.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Sequence

import jax
import numpy as np

from .base import SlotBackend, WorkerError

# work_fn(worker_index, device_payload, epoch) -> jax.Array (device-resident)
XLAWorkFn = Callable[[int, jax.Array, int], jax.Array]
DelayFn = Callable[[int, int], float]

_SHUTDOWN = object()


class XLADeviceBackend(SlotBackend):
    """n pool workers executing jitted programs on accelerator devices.

    Parameters
    ----------
    work_fn:
        ``work_fn(worker_index, payload, epoch) -> jax.Array``. Called in
        the worker's dispatcher thread with the payload already resident
        on the worker's device. It should be (or call) a jitted function;
        it may close over per-worker device-resident operands (e.g. a
        matrix shard placed at setup time). ``epoch`` is a Python int;
        pass it into jitted code as an array to avoid retracing.
    n_workers:
        Pool size. May exceed the device count (workers then time-slice
        devices round-robin — the single-real-chip case).
    devices:
        Devices to map workers onto; defaults to ``jax.devices()``.
    delay_fn:
        Deterministic straggler injection, seconds of host-side stall
        before dispatch as a function of ``(worker, epoch)``. On a real
        TPU slice stragglers are rare (SURVEY §7), so injection is the
        test mechanism of record.
    """

    def __init__(
        self,
        work_fn: XLAWorkFn,
        n_workers: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
    ):
        super().__init__(n_workers)
        if devices is None:
            devices = jax.devices()
        self.devices = [devices[i % len(devices)] for i in range(n_workers)]
        self.work_fn = work_fn
        self.delay_fn = delay_fn
        self._closed = False
        # per-epoch snapshot cache: device -> device-resident payload.
        # asyncmap broadcasts ONE sendbuf to all idle workers per epoch
        # (reference src/MPIAsyncPools.jl:118-139), so workers sharing a
        # device can share one H2D transfer; cleared in begin_epoch.
        self._payload_cache: dict = {}
        self._mailboxes: list[queue.Queue] = [
            queue.Queue(maxsize=1) for _ in range(n_workers)
        ]
        self._threads = [
            threading.Thread(
                target=self._dispatcher_loop, args=(i,), daemon=True,
                name=f"xla-worker-{i}",
            )
            for i in range(n_workers)
        ]
        for t in self._threads:
            t.start()

    def _dispatcher_loop(self, i: int) -> None:
        """Worker-side loop (reference §3.2) as a device dispatcher.

        Blocking mailbox get is the worker's ``Waitany!([control, data])``
        select; the shutdown sentinel is the control channel.
        """
        mbox = self._mailboxes[i]
        while True:
            msg = mbox.get()
            if msg is _SHUTDOWN:
                return
            seq, payload, epoch = msg
            if self.delay_fn is not None:
                d = float(self.delay_fn(i, epoch))
                if d > 0:
                    time.sleep(d)
            try:
                result = self.work_fn(i, payload, epoch)
                # wait for the device computation to actually finish —
                # this thread *is* the arrival detector; block_until_ready
                # releases the GIL so n workers wait concurrently
                result = jax.block_until_ready(result)
            except BaseException as e:
                result = WorkerError(i, epoch, e)
            self._complete(i, seq, result)

    def _start(self, i: int, sendbuf, epoch: int, seq: int, tag: int) -> None:
        if self._closed:
            raise RuntimeError("backend has been shut down")
        # Asynchronous H2D (or D2D) transfer onto the worker's device.
        # jax arrays are immutable, so this IS the payload snapshot: the
        # caller may mutate a numpy sendbuf immediately after dispatch.
        # Within one epoch the coordinator broadcasts a single stable
        # sendbuf, so the transfer is shared across workers on a device.
        dev = self.devices[i]
        payload = self._payload_cache.get(dev)
        if payload is None:
            payload = jax.device_put(sendbuf, dev)
            self._payload_cache[dev] = payload
        self._mailboxes[i].put((seq, payload, epoch))

    def begin_epoch(self, epoch: int) -> None:
        self._payload_cache.clear()

    def shutdown(self) -> None:
        self._closed = True
        for mbox in self._mailboxes:
            try:
                mbox.put_nowait(_SHUTDOWN)
            except queue.Full:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
