"""XLA device backend: pool workers are accelerator devices.

This is the TPU-native replacement for the reference's transport layer
(MPI.jl point-to-point over OS processes — SURVEY §2 component C8). The
mapping, per SURVEY §7 "the hard parts":

=====================  ==================================================
reference (MPI)         here (JAX/XLA)
=====================  ==================================================
worker process          an accelerator device (TPU chip / virtual CPU
                        device); several pool workers may time-slice one
                        device when the pool is larger than the slice
``MPI.Isend``           ``jax.device_put`` of the payload onto the
                        worker's device — an asynchronous H2D DMA whose
                        result is an *immutable* snapshot, so the
                        reference's ``isendbuf`` copy discipline
                        (src/MPIAsyncPools.jl:63-66,:130) is free
compute on worker       a jitted per-shard program dispatched on the
                        worker's device; XLA's async dispatch returns a
                        future-like ``jax.Array`` immediately
``MPI.Waitany!``        per-worker dispatcher threads block on
                        ``Array.block_until_ready`` and signal the shared
                        completion condition (backends/base.py), so the
                        coordinator's hot loop sleeps instead of spinning
=====================  ==================================================

Crucially there is **no collective in the straggle-exposed path**: each
worker's program is independent, so a slow or dead device delays nobody
else — a single ``pjit`` with a ``psum`` would re-introduce the very
bulk-synchronous straggler penalty this design exists to kill (SURVEY §7).
Collectives belong in the decode/combine step over the k winners (see
parallel/collectives.py).

Results are left device-resident; the decode/combine step can consume
them without a host round-trip (``pool.results[i]``), and only a caller-
provided ``recvbuf`` forces a D2H gather.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax

from .base import MailboxBackend, DelayFn

# work_fn(worker_index, device_payload, epoch) -> jax.Array (device-resident)
XLAWorkFn = Callable[[int, jax.Array, int], jax.Array]


class XLADeviceBackend(MailboxBackend):
    """n pool workers executing jitted programs on accelerator devices.

    Parameters
    ----------
    work_fn:
        ``work_fn(worker_index, payload, epoch) -> jax.Array``. Called in
        the worker's dispatcher thread with the payload already resident
        on the worker's device. It should be (or call) a jitted function;
        it may close over per-worker device-resident operands (e.g. a
        matrix shard placed at setup time). ``epoch`` is a Python int;
        pass it into jitted code as an array to avoid retracing.
    n_workers:
        Pool size. May exceed the device count (workers then time-slice
        devices round-robin — the single-real-chip case).
    devices:
        Devices to map workers onto; defaults to ``jax.devices()``.
    delay_fn:
        Deterministic straggler injection, seconds of host-side stall
        before dispatch as a function of ``(worker, epoch)``. On a real
        TPU slice stragglers are rare (SURVEY §7), so injection is the
        test mechanism of record.
    """

    def __init__(
        self,
        work_fn: XLAWorkFn,
        n_workers: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
    ):
        if devices is None:
            devices = jax.devices()
        self.devices = [devices[i % len(devices)] for i in range(n_workers)]
        self.work_fn = work_fn
        # (device, epoch) -> device-resident payload. asyncmap broadcasts
        # ONE stable sendbuf to all idle workers per epoch (reference
        # src/MPIAsyncPools.jl:118-139), so workers sharing a device share
        # one H2D transfer; keyed by epoch so direct Backend-API users
        # dispatching fresh payloads at new epochs never see stale data.
        self._payload_cache: dict = {}
        self._cache_armed = False
        super().__init__(
            n_workers, delay_fn=delay_fn, join_timeout=5.0,
            thread_name="xla-worker",
        )

    def _snapshot(self, i: int, sendbuf, epoch: int) -> jax.Array:
        # Asynchronous H2D (or D2D) transfer onto the worker's device.
        # jax arrays are immutable, so this IS the payload snapshot: the
        # caller may mutate a numpy sendbuf immediately after dispatch.
        # The per-device cache is armed only between begin_epoch and
        # end_epoch (inside asyncmap, where the single-threaded
        # coordinator cannot mutate sendbuf mid-call); direct
        # Backend-API dispatches always re-snapshot, same contract as
        # the native backend.
        dev = self.devices[i]
        if not self._cache_armed:
            return jax.device_put(sendbuf, dev)
        key = (dev, epoch)
        payload = self._payload_cache.get(key)
        if payload is None:
            payload = jax.device_put(sendbuf, dev)
            self._payload_cache[key] = payload
        return payload

    def _compute(self, i: int, payload: jax.Array, epoch: int) -> jax.Array:
        result = self.work_fn(i, payload, epoch)
        # wait for the device computation to actually finish — this
        # thread *is* the arrival detector; block_until_ready releases
        # the GIL so n workers wait concurrently
        return jax.block_until_ready(result)

    def begin_epoch(self, epoch: int) -> None:
        # arm the shared-payload cache for this asyncmap call
        self._payload_cache = {}
        self._cache_armed = True

    def end_epoch(self) -> None:
        # disarm when asyncmap returns: any later direct dispatch of a
        # mutated host buffer must get a fresh device snapshot (same
        # contract as the native backend; base.py end_epoch). Clearing
        # also drops the device payload so it isn't pinned between calls.
        self._payload_cache = {}
        self._cache_armed = False
