"""XLA device backend: pool workers are accelerator devices.

This is the TPU-native replacement for the reference's transport layer
(MPI.jl point-to-point over OS processes — SURVEY §2 component C8). The
mapping, per SURVEY §7 "the hard parts":

=====================  ==================================================
reference (MPI)         here (JAX/XLA)
=====================  ==================================================
worker process          an accelerator device (TPU chip / virtual CPU
                        device); several pool workers may time-slice one
                        device when the pool is larger than the slice
``MPI.Isend``           ``jax.device_put`` of the payload onto the
                        worker's device — an asynchronous H2D DMA whose
                        result is an *immutable* snapshot, so the
                        reference's ``isendbuf`` copy discipline
                        (src/MPIAsyncPools.jl:63-66,:130) is free
compute on worker       a jitted per-shard program dispatched on the
                        worker's device; XLA's async dispatch returns a
                        future-like ``jax.Array`` immediately
``MPI.Waitany!``        per-worker dispatcher threads block on
                        ``Array.block_until_ready`` and signal the shared
                        completion condition (backends/base.py), so the
                        coordinator's hot loop sleeps instead of spinning
=====================  ==================================================

Crucially there is **no collective in the straggle-exposed path**: each
worker's program is independent, so a slow or dead device delays nobody
else — a single ``pjit`` with a ``psum`` would re-introduce the very
bulk-synchronous straggler penalty this design exists to kill (SURVEY §7).
Collectives belong in the decode/combine step over the k winners (see
parallel/collectives.py).

Results are left device-resident; the decode/combine step can consume
them without a host round-trip (``pool.results[i]``), and only a caller-
provided ``recvbuf`` forces a D2H gather.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax

from .base import MailboxBackend, DelayFn


class _BatchDone:
    """A fused-dispatch group handed to a device's dispatcher thread."""

    __slots__ = ("items", "stacked")

    def __init__(self, items, stacked):
        self.items = items      # [(worker, seq, payload, epoch, tag)]
        self.stacked = stacked  # enqueued fused result, leading = member


class StackedSlice:
    """A pool worker's lazy view into a fused-dispatch result.

    In batch mode one device program computes every member's result
    stacked on the leading axis; slicing each member out eagerly would
    cost one device op per worker — on a dispatch-latency-bound link
    (the tunneled chip) that dwarfs the compute. Decode paths that
    consume the whole stack (ops/coded_gemm.py) read ``stacked`` +
    ``index`` directly and never pay for slices; anything else
    (``recvbuf`` bitcopies, generic callers) materializes transparently
    via ``__array__``/``materialize``."""

    __slots__ = ("stacked", "index")

    def __init__(self, stacked, index: int):
        self.stacked = stacked
        self.index = int(index)

    @property
    def nbytes(self) -> int:  # pool pre-dispatch recvbuf validation
        import numpy as _np

        shape = self.stacked.shape[1:]
        return int(_np.prod(shape)) * self.stacked.dtype.itemsize

    @property
    def shape(self) -> tuple:
        """The member result's shape (one row of the stack) — lets
        shape-driven consumers (the fused adopter) treat slices like
        the arrays they stand for."""
        return tuple(self.stacked.shape[1:])

    @property
    def dtype(self):
        return self.stacked.dtype

    def materialize(self):
        return self.stacked[self.index]

    def __array__(self, dtype=None, copy=None):
        import numpy as _np

        out = _np.asarray(self.materialize())
        if dtype is not None:
            out = out.astype(dtype, copy=False)
        return out

class WindowHandle:
    """One asynchronously-dispatched fused multi-epoch program.

    ``outputs`` is the program's raw pytree of future-like
    ``jax.Array``s (XLA async dispatch); :meth:`harvest` is the single
    consumption fence for the whole K-epoch window — device-side
    failures surface there, not as per-worker completions."""

    __slots__ = ("outputs", "epoch0", "epochs")

    def __init__(self, outputs, epoch0: int, epochs: int):
        self.outputs = outputs
        self.epoch0 = int(epoch0)
        self.epochs = int(epochs)

    def harvest(self):
        return jax.block_until_ready(self.outputs)


# work_fn(worker_index, device_payload, epoch) -> jax.Array (device-resident)
XLAWorkFn = Callable[[int, jax.Array, int], jax.Array]


class XLADeviceBackend(MailboxBackend):
    """n pool workers executing jitted programs on accelerator devices.

    Parameters
    ----------
    work_fn:
        ``work_fn(worker_index, payload, epoch) -> jax.Array``. Called in
        the worker's dispatcher thread with the payload already resident
        on the worker's device. It should be (or call) a jitted function;
        it may close over per-worker device-resident operands (e.g. a
        matrix shard placed at setup time). ``epoch`` is a Python int;
        pass it into jitted code as an array to avoid retracing.
    n_workers:
        Pool size. May exceed the device count (workers then time-slice
        devices round-robin — the single-real-chip case).
    devices:
        Devices to map workers onto; defaults to ``jax.devices()``.
    delay_fn:
        Deterministic straggler injection, seconds of host-side stall
        before dispatch as a function of ``(worker, epoch)``. On a real
        TPU slice stragglers are rare (SURVEY §7), so injection is the
        test mechanism of record.
    """

    def __init__(
        self,
        work_fn: XLAWorkFn,
        n_workers: int,
        *,
        devices: Sequence[jax.Device] | None = None,
        delay_fn: DelayFn | None = None,
        batch_fn=None,
        batch_arrival: str = "ready",
    ):
        """``batch_fn(worker_ids, payload, epoch) -> stacked`` (optional):
        coalesced dispatch. When pool workers share a device (the
        single-chip case; on a real slice each worker owns a chip), the
        per-worker programs of one epoch are submitted as ONE fused
        device program: dispatches buffer until the pool's
        :meth:`flush`, which calls ``batch_fn`` once per device with
        that device's worker ids and slices the stacked result back
        into per-worker completions. This removes the per-worker
        dispatch round-trip — the dominant epoch cost when one chip
        hosts many workers. Incompatible with ``delay_fn`` (per-worker
        injected stalls are meaningless inside one fused program)."""
        if batch_fn is not None and delay_fn is not None:
            raise ValueError(
                "batch_fn coalesces a device's workers into one program; "
                "per-worker delay_fn injection cannot apply inside it"
            )
        if batch_arrival not in ("ready", "enqueue"):
            raise ValueError(
                f"batch_arrival must be 'ready'|'enqueue', got {batch_arrival!r}"
            )
        # "ready": a dispatcher thread block_until_ready()s the fused
        # result — arrival means the device finished (true straggler
        # detection; the default). "enqueue": completions post as soon
        # as the fused program is submitted — XLA's async dispatch IS
        # the execution model, successive epochs pipeline on the device,
        # and the caller's consumption fence is the materialization
        # point. Enqueue mode is the single-chip throughput mode: with
        # every pool worker time-slicing one device there is no
        # independent-arrival information to detect anyway, and a
        # per-epoch host sync costs a full host<->device round trip.
        # Device-side failures then surface at the consumption fence,
        # not as per-worker WorkerFailure.
        self.batch_arrival = batch_arrival
        self.batch_fn = batch_fn
        self._pending: list = []  # buffered dispatches awaiting flush()
        if devices is None:
            devices = jax.devices()
        self.devices = [devices[i % len(devices)] for i in range(n_workers)]
        self.work_fn = work_fn
        # (device, epoch) -> device-resident payload. asyncmap broadcasts
        # ONE stable sendbuf to all idle workers per epoch (reference
        # src/MPIAsyncPools.jl:118-139), so workers sharing a device share
        # one H2D transfer; keyed by epoch so direct Backend-API users
        # dispatching fresh payloads at new epochs never see stale data.
        self._payload_cache: dict = {}
        self._cache_armed = False
        super().__init__(
            n_workers, delay_fn=delay_fn, join_timeout=5.0,
            thread_name="xla-worker",
        )

    def _snapshot(self, i: int, sendbuf, epoch: int) -> jax.Array:
        # Asynchronous H2D (or D2D) transfer onto the worker's device.
        # jax arrays are immutable, so this IS the payload snapshot: the
        # caller may mutate a numpy sendbuf immediately after dispatch.
        # The per-device cache is armed only between begin_epoch and
        # end_epoch (inside asyncmap, where the single-threaded
        # coordinator cannot mutate sendbuf mid-call); direct
        # Backend-API dispatches always re-snapshot, same contract as
        # the native backend.
        dev = self.devices[i]
        if not self._cache_armed:
            return jax.device_put(sendbuf, dev)
        key = (dev, epoch)
        payload = self._payload_cache.get(key)
        if payload is None:
            payload = jax.device_put(sendbuf, dev)
            self._payload_cache[key] = payload
        return payload

    def _compute(self, i: int, payload: jax.Array, epoch: int) -> jax.Array:
        result = self.work_fn(i, payload, epoch)
        # wait for the device computation to actually finish — this
        # thread *is* the arrival detector; block_until_ready releases
        # the GIL so n workers wait concurrently
        return jax.block_until_ready(result)

    # -- coalesced dispatch (batch_fn mode) -------------------------------
    def _start(self, i: int, sendbuf, epoch: int, seq: int, tag: int) -> None:
        if self.batch_fn is None:
            super()._start(i, sendbuf, epoch, seq, tag)
            return
        if self._closed:
            raise RuntimeError("backend has been shut down")
        payload = self._snapshot(i, sendbuf, epoch)
        self._pending.append((i, seq, payload, epoch, tag))

    def test(self, i: int, *, tag: int = 0):
        self.flush()  # a phase-3 re-task may be sitting in the buffer
        return super().test(i, tag=tag)

    def wait_any(self, indices, timeout=None, *, tags=None):
        self.flush()
        return super().wait_any(indices, timeout, tags=tags)

    def wait(self, i: int, timeout: float | None = None, *, tag: int = 0):
        self.flush()
        return super().wait(i, timeout, tag=tag)

    def flush(self) -> None:
        if self.batch_fn is None or not self._pending:
            return
        pending, self._pending = self._pending, []
        # one fused program per (device, payload, epoch): members of a
        # group MUST share the payload snapshot and epoch — direct
        # Backend-API users may dispatch distinct payloads back-to-back
        # (asyncmap's broadcast shares one snapshot per device, so the
        # epoch path stays a single group per device)
        groups: dict = {}
        for item in pending:
            key = (self.devices[item[0]], id(item[2]), item[3])
            groups.setdefault(key, []).append(item)
        for dev_items in groups.values():
            ids = tuple(item[0] for item in dev_items)
            _, _, payload, epoch, _ = dev_items[0]
            try:
                # enqueue is asynchronous; the fused program computes
                # every member's result stacked on the leading axis
                stacked = self.batch_fn(ids, payload, epoch)
            except BaseException as e:
                # a failed submission must not strand the group's slots
                # outstanding (waitall would hang forever) — fail every
                # member the way the worker loop does
                from .base import WorkerError

                for w, seq, _, _ep, tag in dev_items:
                    self._complete(w, seq, WorkerError(w, epoch, e), tag)
                continue
            if self.batch_arrival == "enqueue":
                # async-dispatch mode: submitted = arrived; the fused
                # result is a future the consumption fence materializes
                for j, (w, seq, _, _ep, tag) in enumerate(dev_items):
                    self._complete(w, seq, StackedSlice(stacked, j), tag)
                continue
            # the device's dispatcher thread becomes the arrival
            # detector for the whole group: one block_until_ready, then
            # per-member completions with their slice of the stack
            mbox_i = dev_items[0][0]
            self._mailboxes[mbox_i].put(
                (_BatchDone(dev_items, stacked), None, None, None)
            )

    def _worker_loop(self, i: int) -> None:  # overrides MailboxBackend
        if self.batch_fn is None:
            super()._worker_loop(i)
            return
        from .base import _SHUTDOWN, WorkerError

        mbox = self._mailboxes[i]
        while True:
            msg = mbox.get()
            if msg is _SHUTDOWN:
                return
            batch = msg[0]
            try:
                stacked = jax.block_until_ready(batch.stacked)
                for j, (w, seq, _, epoch, tag) in enumerate(
                    batch.items
                ):
                    self._complete(w, seq, StackedSlice(stacked, j), tag)
            except BaseException as e:  # surfaced on harvest, not lost
                for w, seq, _, epoch, tag in batch.items:
                    self._complete(w, seq, WorkerError(w, epoch, e), tag)

    # -- multi-epoch dispatch (fused K-epoch windows) ---------------------
    def submit_window(self, window_fn, *args, epoch0: int, epochs: int):
        """Multi-epoch dispatch: ONE asynchronous submission covering
        ``epochs`` epochs — the compiled K-epoch coordination program
        (parallel/device_coord.py) — with no per-epoch ``_start`` /
        mailbox round-trips and no dispatcher-thread arrival
        detection: XLA's async dispatch IS the in-flight state, and
        the returned :class:`WindowHandle`'s ``harvest()`` is the one
        fence. The transport layer keeps what it owns — the shutdown
        guard, and the failure envelope: a submission failure raises
        through :class:`~.base.WorkerError` (worker ``-1``: a fused
        window has no single owning worker) so callers see the same
        :class:`~.base.WorkerFailure` surface as per-epoch dispatch.
        """
        if self._closed:
            raise RuntimeError("backend has been shut down")
        from .base import WorkerError

        try:
            out = window_fn(*args)  # asynchronous: returns futures
        except BaseException as e:
            WorkerError(-1, int(epoch0), e).raise_()
        return WindowHandle(out, int(epoch0), int(epochs))

    def begin_epoch(self, epoch: int) -> None:
        # arm the shared-payload cache for this asyncmap call
        self._payload_cache = {}
        self._cache_armed = True

    def end_epoch(self) -> None:
        # disarm when asyncmap returns: any later direct dispatch of a
        # mutated host buffer must get a fresh device snapshot (same
        # contract as the native backend; base.py end_epoch). Clearing
        # also drops the device payload so it isn't pinned between calls.
        self._payload_cache = {}
        self._cache_armed = False
