"""Process workers over the native C++ transport (native/transport.cpp).

Functionally the twin of :class:`~.process.ProcessBackend` — n spawned
OS worker processes, real serialization boundary, dead-worker detection —
but the coordinator side is the native runtime instead of Python pipes
and reader threads: framed messages over Unix-domain sockets, an epoll
progress thread doing all partial I/O (the libmpi progress-engine role,
SURVEY component C8), and ``wait_any`` blocking in native
``msgt_coord_waitany`` rather than a Python condition variable. The pool
above is unchanged; this backend exists so the hot host-side wait loop
(reference ``MPI.Waitany!``, src/MPIAsyncPools.jl:161) runs in native
code with zero Python threads on the coordinator.

Construction falls back with :class:`~..native.NativeBuildError` if no
compiler is available; callers wanting automatic degradation should
catch it and build a :class:`~.process.ProcessBackend` instead.
"""

from __future__ import annotations

import multiprocessing as mp
import secrets
import tempfile
import time as _time
import uuid
from collections import deque
from pathlib import Path
from typing import Sequence

from ..native import codec
from ..native import transport as T
from ..obs.aggregate import OBS_TAG as _OBS_TAG  # stdlib-only module
from .base import Backend, Deadline, DeadWorkerError, DelayFn, WorkerError
from .process import RemoteWorkerError, WorkerProcessDied, WorkFn

__all__ = ["NativeProcessBackend"]


def _straggle_exhausted(ranks, deadline: Deadline, timeout):
    """Every awaited rank is dead under ``on_dead="straggle"``: burn the
    caller's remaining timeout and report a timeout (an early None would
    be indistinguishable from one anyway), or — with no timeout — raise
    instead of hanging forever the way the reference's Waitall! does."""
    if timeout is None:
        raise DeadWorkerError(sorted({int(r) for r in ranks}), None)
    left = deadline.remaining()
    if left:
        _time.sleep(left)
    return None


def _native_worker_main(
    rank: int, path: str, work_fn: WorkFn, delay_fn: DelayFn | None,
    token: bytes, telemetry: bool = False, zero_copy: bool = True,
) -> None:
    """Spawned-process entry: the shared worker loop (worker.py — the
    reference's receive -> stall -> compute -> send convention, SURVEY
    §3.2) with errors swallowed (the coordinator sees the disconnect)."""
    from ..worker import run_worker

    try:
        run_worker(path, rank, work_fn, delay_fn, token=token,
                   telemetry=telemetry, zero_copy=zero_copy)
    except (KeyboardInterrupt, Exception):
        pass


class NativeProcessBackend(Backend):
    """n worker processes; all coordinator-side I/O in the C++ runtime.

    Same contract as :class:`~.process.ProcessBackend` (picklable
    ``work_fn(i, payload, epoch)`` / ``delay_fn``). Payloads travel via
    the zero-copy codec (native/codec.py): plain ndarrays go as raw
    bytes — ONE snapshot copy into the native send queue, shared across
    the epoch's whole broadcast — so in-flight sends survive caller
    mutation (the reference's ``isendbuf`` discipline,
    src/MPIAsyncPools.jl:130) at memcpy cost, not pickle cost.
    """

    def __init__(
        self,
        work_fn: WorkFn | None,
        n_workers: int,
        *,
        delay_fn: DelayFn | None = None,
        mp_context: str = "spawn",
        connect_timeout: float = 60.0,
        join_timeout: float = 5.0,
        address: str | None = None,
        spawn: bool = True,
        accept: bool = True,
        auth: bytes | str | None = None,
        on_dead: str = "error",
        zero_copy: bool = True,
        registry=None,
        flight=None,
        exporter=None,
    ):
        """``address``: Unix-socket path (default: a fresh temp path) or
        ``tcp://host:port`` for multi-host (port 0 = ephemeral; the
        resolved address is ``self.address``). ``spawn=False`` starts no
        local processes — external workers (e.g. remote hosts running
        ``python -m mpistragglers_jl_tpu.worker``) must connect within
        ``connect_timeout``; ``work_fn`` may then be None (it runs on
        the workers' side). ``accept=False`` defers the handshake: the
        constructor returns immediately after binding so ``address``
        (with its resolved ephemeral port) can be handed to workers
        first; call :meth:`accept` before the first dispatch.

        ``auth``: shared secret every connecting worker must prove (via
        HMAC challenge-response in the hello; the secret never crosses
        the wire). With ``spawn=True`` a random per-backend secret is
        generated automatically, so locally spawned pools are always
        authenticated. With ``spawn=False`` the default is open —
        SECURITY: an unauthenticated TCP listener admits *any* process
        that can reach the port, and payloads are unpickled (arbitrary
        code execution); either pass an ``auth`` secret (give workers
        the same one via ``MSGT_AUTH`` / ``--auth-file``) or bind only
        on a trusted network.

        ``zero_copy`` (default True) enables the round-12 persistent
        shared-memory paths on same-host transports: broadcast bodies
        >= 1 MiB stage in an arena every worker maps once, and worker
        result bodies >= 64 KiB come back through per-worker result
        rings served as ``np.frombuffer`` views — see docs/API.md
        "Zero-copy transport". ``False`` forces the copying socket
        transport for everything this backend controls — the
        coordinator's broadcast paths and any workers it SPAWNS
        (baselines/debugging). External ``spawn=False`` workers own
        their result-ring choice: launch them with ``--no-zero-copy``
        for a fully copying baseline. TCP transports are copying
        regardless.

        ``registry`` / ``flight`` / ``exporter`` follow the obs/
        contract (None = dark, zero cost): ``registry`` turns on
        cross-process telemetry — spawned workers run with
        ``telemetry=True`` (external ``spawn=False`` workers opt in
        with ``--telemetry``) and their frames, arriving on the
        reserved OBS tag, merge into the registry under
        ``worker="<rank>"`` labels — plus the transport's zero-copy
        counters (bytes moved without a userspace copy, ring-full
        stalls, pinned-slot gauge/high-water); ``flight`` mirrors
        merged worker spans into the ring; ``exporter`` registers the
        pool health check + trace sources on an
        :class:`~..obs.ObsServer`."""
        if on_dead not in ("error", "straggle"):
            raise ValueError(f"on_dead must be 'error'|'straggle', got {on_dead!r}")
        self.on_dead = on_dead
        self.n_workers = int(n_workers)
        self.work_fn = work_fn
        self.delay_fn = delay_fn
        self._join_timeout = join_timeout
        self._connect_timeout = connect_timeout
        self._closed = False
        self._spawn = bool(spawn)
        if self._spawn and work_fn is None:
            raise ValueError("work_fn is required when spawning workers")
        # seq numbers are allocated per RANK (unique across tags) so a
        # frame identifies its dispatch unambiguously; per-channel state
        # is keyed (rank, tag) — tags multiplex independent message
        # streams over one connection, like MPI tags on a communicator
        # (reference test/kmap2.jl:11-12)
        self._seq_counter = [0] * self.n_workers
        self._cur: dict[tuple[int, int], int] = {}     # (rank, tag) -> seq
        self._epochs: dict[tuple[int, int], int] = {}  # epoch in flight
        # frames that arrived for a channel other than the one being
        # awaited; at most one live frame per channel (slot discipline)
        self._stash: dict[tuple[int, int], deque] = {}
        # per-epoch payload encoding cache (see _encode): the codec
        # prefix plus a SHARED native snapshot of the body, taken once
        # per broadcast instead of once per worker
        self._pick_src = None
        self._pick_epoch = None
        self._pick_prefix = b""
        self._pick_shared: T.SharedPayload | None = None
        # dispatch that failed instantly (dead worker): surfaced at the
        # next test/wait instead of raising inside the pool's send phase
        self._synthetic: dict[tuple[int, int], WorkerError] = {}
        if address is None:
            address = str(
                Path(tempfile.gettempdir())
                / f"msgt-{uuid.uuid4().hex[:12]}.sock"
            )
        if auth is None:
            # spawned workers inherit the secret through the process args,
            # so authentication costs nothing — default it on. External
            # workers need the secret delivered out-of-band, so open is
            # the only workable spawn=False default (documented above).
            auth = secrets.token_bytes(16) if self._spawn else b""
        self._token = auth.encode() if isinstance(auth, str) else bytes(auth)
        self._mp_context = mp_context
        self._zero_copy = bool(zero_copy)
        self.aggregator = None
        if registry is not None or flight is not None:
            from ..obs.aggregate import TelemetryAggregator

            self.aggregator = TelemetryAggregator(
                registry, flight=flight
            )
        # opt-in transport telemetry (obs/ contract: None = dark, the
        # hot path pays one is-None check per dispatch)
        self._registry = registry
        self._tstats_last = {
            "arena_bytes": 0, "ring_bytes": 0,
            "arena_stalls": 0, "ring_stalls": 0,
        }
        if registry is not None:
            self._m_arena_bytes = registry.counter(
                "transport_zero_copy_bytes_total",
                help="payload bytes served without a userspace copy",
                path="arena",
            )
            self._m_ring_bytes = registry.counter(
                "transport_zero_copy_bytes_total",
                help="payload bytes served without a userspace copy",
                path="ring",
            )
            self._m_stalls_c = registry.counter(
                "transport_ring_full_stalls_total",
                help="allocations that fell back to the copying "
                "transport because every slot was pinned",
                side="coordinator",
            )
            self._m_stalls_w = registry.counter(
                "transport_ring_full_stalls_total",
                help="allocations that fell back to the copying "
                "transport because every slot was pinned",
                side="worker",
            )
            self._m_pinned = registry.gauge(
                "transport_pinned_slots",
                help="zero-copy slots currently pinned by live views",
            )
            self._m_pinned_peak = registry.gauge(
                "transport_pinned_slots_peak",
                help="high-water mark of pinned zero-copy slots",
            )
        self._coord = T.Coordinator(
            address, self.n_workers, token=self._token,
            zero_copy=self._zero_copy,
        )
        self._sock_path = self._coord.address  # ephemeral port resolved
        self._procs: list = [None] * self.n_workers
        self._accepted = False
        if self._spawn:
            for i in range(self.n_workers):
                self._spawn_worker(i)
        if accept:
            self.accept(timeout=connect_timeout)
        if exporter is not None:
            exporter.register_backend(self)

    def accept(self, timeout: float | None = None) -> None:
        """Complete the worker handshake (no-op if already done)."""
        if self._accepted:
            return
        try:
            self._coord.accept(
                timeout=self._connect_timeout if timeout is None else timeout
            )
        except T.TransportError:
            self.shutdown()
            raise
        self._accepted = True

    @property
    def address(self) -> str:
        """The address workers connect to (give this to remote workers
        in ``spawn=False`` mode)."""
        return self._sock_path

    def _spawn_worker(self, i: int) -> None:
        """Start (or restart) the worker process for rank i."""
        ctx = mp.get_context(self._mp_context)
        proc = ctx.Process(
            target=_native_worker_main,
            args=(i, self._sock_path, self.work_fn, self.delay_fn,
                  self._token, self.aggregator is not None,
                  self._zero_copy),
            daemon=True,
            name=f"pool-native-worker-{i}",
        )
        proc.start()
        self._procs[i] = proc

    # -- Backend interface -------------------------------------------------
    def begin_epoch(self, epoch: int) -> None:
        # arm the payload encoding cache for this epoch and drop the
        # previous epoch's entry. The cache is ONLY active for an epoch
        # announced via begin_epoch (i.e. inside asyncmap, where the
        # coordinator is single-threaded and the sendbuf cannot mutate
        # between the phase-2/phase-3 dispatches of one call); direct
        # Backend-API dispatches never hit it, so their payloads are
        # snapshotted at every dispatch as the class docstring promises.
        self._drop_cache()
        self._pick_epoch = int(epoch)

    def end_epoch(self) -> None:
        # disarm: a direct dispatch AFTER asyncmap returns (e.g. manual
        # re-task of a mutated buffer at the same epoch number) must
        # re-encode, preserving snapshot-at-dispatch semantics
        self._drop_cache()
        self._pick_epoch = None

    def _drop_cache(self) -> None:
        self._pick_src = None
        self._pick_prefix = b""
        if self._pick_shared is not None:
            self._pick_shared.release()  # queued frames keep their refs
            self._pick_shared = None

    def _send_payload(self, i: int, sendbuf, epoch: int, tag: int) -> bool:
        """Encode + enqueue one dispatch, zero-copy where possible.

        asyncmap broadcasts ONE stable sendbuf to every idle worker per
        epoch (reference src/MPIAsyncPools.jl:118-139), so inside an
        epoch the body is snapshotted once — preferentially into a slot
        of the PERSISTENT broadcast arena (round 12: one memcpy, fd-less
        control frames to workers that already map the arena), falling
        back to a one-shot shared payload when the arena does not apply
        or every slot is still pinned — and the n dispatches (and
        phase-3 re-tasks) enqueue references. No pickling for plain
        ndarrays (native/codec.py). Direct Backend-API dispatches
        always re-encode, so in-place payload mutation between
        dispatches is always observed."""
        cacheable = epoch == self._pick_epoch
        if not (cacheable and sendbuf is self._pick_src):
            prefix, body = codec.encode(sendbuf)
            if cacheable:
                self._drop_cache()
                self._pick_src = sendbuf
                self._pick_prefix = prefix
                self._pick_shared = (
                    self._coord.arena_payload(body)
                    or self._coord.payload(body)
                )
                self._pick_epoch = epoch  # _drop_cache left it intact
            else:
                return self._coord.isend2(
                    i, prefix, body,
                    seq=self._seq_counter[i], epoch=epoch, tag=tag,
                )
        return self._coord.isend_shared(
            i, self._pick_prefix, self._pick_shared,
            seq=self._seq_counter[i], epoch=epoch, tag=tag,
        )

    def _check_ready(self) -> None:
        if self._closed:
            raise RuntimeError("backend has been shut down")
        if not self._accepted:
            # dispatching before the handshake would queue frames on
            # fd-less peers and then hang the wait forever
            raise RuntimeError(
                "worker handshake incomplete: call backend.accept() "
                "before dispatching (accept=False mode)"
            )

    def dispatch(self, i: int, sendbuf, epoch: int, *, tag: int = 0) -> None:
        self._check_ready()
        key = (i, int(tag))
        self._seq_counter[i] += 1
        self._cur[key] = self._seq_counter[i]
        self._epochs[key] = int(epoch)
        if self.aggregator is not None:
            # half of a clock-offset sample; the worker's matching
            # stamps ride back on its telemetry frame (same seq)
            self.aggregator.note_dispatch(
                i, self._seq_counter[i], _time.perf_counter()
            )
        ok = self._send_payload(i, sendbuf, int(epoch), int(tag))
        if self._registry is not None:
            self._publish_transport()
        if not ok:
            # rank already dead. "error": fail the task at the next
            # harvest instead of hanging the pool. "straggle": the task
            # is silently lost — the rank is an infinite straggler and
            # simply never freshens (reference SURVEY §5 semantics).
            if self.on_dead == "error":
                self._synthetic[key] = WorkerError(
                    i, epoch, WorkerProcessDied(i)
                )

    def _publish_transport(self) -> None:
        """Mirror the transport's zero-copy stats into the opt-in
        registry (counter deltas; the coordinator's dict is the source
        of truth). Callers guard on ``self._registry is not None``."""
        s = self._coord.stats
        last = self._tstats_last
        d = s["arena_bytes"] - last["arena_bytes"]
        if d:
            self._m_arena_bytes.inc(d)
            last["arena_bytes"] = s["arena_bytes"]
        d = s["ring_bytes"] - last["ring_bytes"]
        if d:
            self._m_ring_bytes.inc(d)
            last["ring_bytes"] = s["ring_bytes"]
        d = s["arena_stalls"] - last["arena_stalls"]
        if d:
            self._m_stalls_c.inc(d)
            last["arena_stalls"] = s["arena_stalls"]
        d = s["ring_stalls"] - last["ring_stalls"]
        if d:
            self._m_stalls_w.inc(d)
            last["ring_stalls"] = s["ring_stalls"]
        self._m_pinned.set(self._coord.pinned_slots())
        self._m_pinned_peak.set(s["pinned_peak"])

    def _consume_obs(self, j: int, msg: T.Message) -> bool:
        """Absorb a telemetry frame (the reserved OBS tag): merge it
        into the aggregator when one is attached, drop it otherwise.
        Returns True iff the frame was telemetry — callers skip it and
        keep waiting for real completions either way. (The tag test is
        one int compare, so dark wait loops stay at is-None cost.)"""
        if int(msg.tag) != _OBS_TAG or msg.kind != T.KIND_DATA:
            return False
        if self.aggregator is not None:
            try:
                frame = codec.decode(msg.payload, msg.body)
            except Exception:
                return True  # malformed telemetry never kills a wait
            self.aggregator.merge(
                j, frame, t_recv_c=_time.perf_counter()
            )
        return True

    def _drain_obs(self, i: int, timeout: float = 2.0) -> None:
        """Pull queued telemetry frames for rank ``i`` (the
        shutdown-drain frame workers flush before exiting). The worker
        process has already been joined, but the frame still has to
        travel socket buffer -> epoll progress thread -> queue, so an
        empty poll retries briefly instead of declaring the queue
        drained (a single non-blocking pass raced the progress thread
        and lost end-of-run deltas). Non-telemetry DATA frames (an
        unharvested straggler's late result) are dropped and skipped —
        the backend is shutting down and no channel will be read again,
        but the telemetry frames queued BEHIND them must still merge.
        The loop ends at the sticky KIND_DEATH marker the dead rank's
        poll synthesizes once its real frames are out — the
        "everything drained" signal; ``timeout`` only bounds the
        pathological no-death case."""
        deadline = _time.perf_counter() + timeout
        while True:
            msg = self._coord.poll(i)
            if msg is None:
                if _time.perf_counter() >= deadline:
                    return
                _time.sleep(0.002)
                continue
            if msg.kind == T.KIND_DEATH:
                return  # queue fully drained (marker fires last)
            self._consume_obs(i, msg)  # telemetry merged; stale
            # results dropped — keep going for the frames behind them

    def _decode(self, i: int, msg: T.Message, tag: int):
        if msg.kind == T.KIND_DEATH:
            return WorkerError(
                i, self._epochs.get((i, tag), 0), WorkerProcessDied(i)
            )
        if msg.kind == T.KIND_ERROR:
            exc_type, text, tb = codec.decode(msg.payload)
            return WorkerError(
                i, msg.epoch, RemoteWorkerError(exc_type, text, tb)
            )
        # result-ring frames carry the codec prefix in-frame and the
        # body out-of-band (a zero-copy view into the worker's ring);
        # holding the decoded array pins the slot until released
        return codec.decode(msg.payload, msg.body)

    def _route(self, j: int, msg: T.Message, want_tag: int):
        """Classify an arriving frame against channel ``(j, want_tag)``:
        return the frame if it is this channel's current completion,
        stash it if it belongs to another live channel, drop it if its
        dispatch was superseded. DEATH frames always surface (they are
        rank-wide, and the native marker is sticky — every channel that
        waits on a dead rank sees one)."""
        if msg.kind == T.KIND_DEATH:
            return msg
        mtag = int(msg.tag)
        if msg.seq != self._cur.get((j, mtag), -1):
            return None  # superseded dispatch; drop
        if mtag != int(want_tag):
            self._stash.setdefault((j, mtag), deque()).append(msg)
            return None
        return msg

    def _stash_pop(self, key: tuple[int, int]) -> T.Message | None:
        st = self._stash.get(key)
        while st:
            msg = st.popleft()
            # re-verify: the channel may have re-dispatched (direct
            # Backend-API use) while the frame sat stashed
            if msg.seq == self._cur.get(key, -1):
                return msg
        return None

    def _next(
        self, i: int, *, block: bool, timeout: float | None = None,
        tag: int = 0,
    ):
        """Fetch the completion for channel ``(i, tag)``'s current
        dispatch, skipping frames from superseded dispatches (stale seq)
        and parking frames that belong to other tags."""
        self._check_ready()
        key = (i, int(tag))
        syn = self._synthetic.pop(key, None)
        if syn is not None:
            return syn
        stashed = self._stash_pop(key)
        if stashed is not None:
            return self._decode(i, stashed, key[1])
        deadline = Deadline(timeout)
        while True:
            if block:
                got = self._coord.waitany([i], timeout=deadline.remaining())
                if got is None:
                    return None  # timeout
                _, msg = got
            else:
                msg = self._coord.poll(i)
                if msg is None:
                    return None
            if msg.kind == T.KIND_DEATH and self.on_dead == "straggle":
                # infinite-straggler semantics: a dead rank never
                # completes; it does not error either. (Real frames a
                # worker delivered before dying were already drained —
                # the native poll only synthesizes the death marker on
                # an empty queue.)
                if not block:
                    return None
                return _straggle_exhausted([i], deadline, timeout)
            if self._consume_obs(i, msg):
                continue  # piggybacked telemetry, not a completion
            msg = self._route(i, msg, key[1])
            if msg is not None:
                return self._decode(i, msg, key[1])

    def test(self, i: int, *, tag: int = 0):
        return self._next(i, block=False, tag=tag)

    def wait_any(
        self,
        indices: Sequence[int],
        timeout: float | None = None,
        *,
        tags: Sequence[int] | None = None,
    ) -> tuple[int, object] | None:
        self._check_ready()
        idx = [int(j) for j in indices]
        if not idx:
            raise ValueError("wait_any over an empty index set would hang")
        tgs = [0] * len(idx) if tags is None else [int(t) for t in tags]
        if len(tgs) != len(idx):
            raise ValueError("tags must align one-to-one with indices")
        # the same worker may be awaited on several channels at once
        # (wait_any([0, 0], tags=[0, 1]) — SlotBackend honors this, so
        # must we): route against the full awaited-pair set per rank
        awaited: dict[int, list[int]] = {}
        for j, t in zip(idx, tgs):
            awaited.setdefault(j, []).append(t)
        for j, t in zip(idx, tgs):
            syn = self._synthetic.pop((j, t), None)
            if syn is not None:  # already complete
                return j, syn
            stashed = self._stash_pop((j, t))
            if stashed is not None:
                return j, self._decode(j, stashed, t)
        deadline = Deadline(timeout)
        live = list(idx)
        while True:
            got = self._coord.waitany(live, timeout=deadline.remaining())
            if got is None:
                return None  # timed out
            j, msg = got
            if msg.kind == T.KIND_DEATH:
                if self.on_dead == "straggle":
                    # infinite-straggler semantics. The marker only
                    # surfaces once the rank's real frames drained (the
                    # native poll synthesizes it on an empty queue), so
                    # dropping the rank HERE — not via an is_dead
                    # pre-filter — never loses a delivered result.
                    live = [r for r in live if r != j]
                    if not live:
                        return _straggle_exhausted(idx, deadline, timeout)
                    continue  # keep waiting on the survivors
                # rank-wide: surface on this rank's first awaited channel
                # (the sticky native marker re-fires for the others)
                return j, self._decode(j, msg, awaited[j][0])
            if self._consume_obs(j, msg):
                continue  # piggybacked telemetry, not a completion
            mtag = int(msg.tag)
            if msg.seq != self._cur.get((j, mtag), -1):
                continue  # superseded dispatch; drop
            if mtag in awaited[j]:
                return j, self._decode(j, msg, mtag)
            self._stash.setdefault((j, mtag), deque()).append(msg)

    def wait(self, i: int, timeout: float | None = None, *, tag: int = 0):
        return self._next(i, block=True, timeout=timeout, tag=tag)

    def dead_workers(self) -> list[int]:
        """Ranks the transport currently marks dead (not yet
        respawned/reaccepted) — the ``/healthz`` pool check reads
        this."""
        if self._closed:
            return list(range(self.n_workers))
        return [
            i for i in range(self.n_workers) if self._coord.is_dead(i)
        ]

    def respawn(self, i: int, *, connect_timeout: float = 60.0) -> None:
        """Elastic recovery: replace a dead worker process with a fresh
        one on the same rank (the reference has no such capability — a
        dead rank is permanent and hangs ``Waitall!``, SURVEY §5). The
        new process reconnects through the transport's reaccept path;
        pool state is untouched — the rank simply becomes dispatchable
        again, and any frames from the old incarnation are dropped by
        the seq guard."""
        if self._closed:
            raise RuntimeError("backend has been shut down")
        if not self._spawn:
            raise RuntimeError(
                "respawn() needs locally spawned workers; for external "
                "workers restart the remote process and call reaccept()"
            )
        if not self._coord.is_dead(i) and self._procs[i].is_alive():
            raise RuntimeError(f"worker {i} is alive; nothing to respawn")
        if self._procs[i].is_alive():  # pragma: no cover - zombie socket
            self._procs[i].terminate()
        self._procs[i].join(timeout=self._join_timeout)
        self._spawn_worker(i)
        # reaccept tolerates a not-yet-drained HUP within its timeout
        self._coord.reaccept(i, timeout=connect_timeout)
        # synthetic failures for rank i, if set, stay: they record
        # dispatches the old incarnation never received — the pool must
        # still see them fail

    def reaccept(self, i: int, *, timeout: float = 60.0) -> None:
        """External-worker recovery (``spawn=False``): after the remote
        worker process for rank ``i`` is restarted out-of-band, accept
        its reconnect so the rank becomes dispatchable again."""
        if self._closed:
            raise RuntimeError("backend has been shut down")
        self._coord.reaccept(i, timeout=timeout)

    def reap(self, i: int) -> None:
        """Elastic shrink: deliberately retire worker process ``i`` —
        the pair of :meth:`respawn`, and the verb the fleet
        controller's pool scaler uses (``fleet/failover.py``). The
        process is terminated; the transport's native progress thread
        sees the HUP and sets the sticky dead marker, so the rank
        reads as dead (:meth:`dead_workers`) until :meth:`respawn`
        reconnects a fresh incarnation. Reap at an epoch boundary
        (after ``waitall``) to retire a rank with nothing outstanding.
        Idempotent while already dead."""
        if self._closed:
            raise RuntimeError("backend has been shut down")
        if not self._spawn:
            raise RuntimeError(
                "reap() needs locally spawned workers; stop external "
                "workers out-of-band (the transport marks the rank "
                "dead on its HUP)"
            )
        if self._coord.is_dead(i):
            return
        proc = self._procs[i]
        if proc.is_alive():
            proc.terminate()
        proc.join(timeout=self._join_timeout)
        # the native epoll thread stamps the sticky marker on the HUP;
        # wait for it so dead_workers() is truthful on return
        deadline = _time.monotonic() + self._join_timeout
        while not self._coord.is_dead(i):
            if _time.monotonic() >= deadline:  # pragma: no cover
                raise RuntimeError(
                    f"worker {i} terminated but the transport never "
                    "marked the rank dead"
                )
            _time.sleep(0.005)

    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        # don't pin the last payload + its native snapshot for the
        # backend object's remaining lifetime
        self._drop_cache()
        self._pick_epoch = None
        if not self._accepted:
            # handshake never completed: there is no connection to send a
            # control frame on and nothing graceful to wait for — a
            # join-first drain would burn join_timeout per blocked worker
            for p in self._procs:
                if p is not None and p.is_alive():
                    p.terminate()
            for p in self._procs:
                if p is not None:
                    p.join(timeout=self._join_timeout)
                    if not p.is_alive():
                        p.close()
            self._coord.close()
            return
        for i in range(self.n_workers):
            # control-channel broadcast (reference test/kmap2.jl:14-18)
            self._coord.isend(i, b"", kind=T.KIND_CONTROL)
        for p in self._procs:
            if p is not None:
                p.join(timeout=self._join_timeout)
        if self.aggregator is not None:
            # the workers flushed a final telemetry frame before
            # exiting; nothing polls the queues after this point, so
            # drain them here or the end-of-run deltas are lost. A
            # rank whose process is still alive (wedged in work_fn —
            # about to be terminated below) never sent a drain frame
            # and never will: poll it non-blockingly for whatever is
            # already queued instead of burning the retry window per
            # stuck rank
            for i in range(self.n_workers):
                p = self._procs[i]
                alive = p is not None and p.is_alive()
                self._drain_obs(i, timeout=0.0 if alive else 2.0)
        for p in self._procs:
            if p is not None and p.is_alive():  # pragma: no cover
                p.terminate()
                p.join(timeout=self._join_timeout)  # reap before close
        for p in self._procs:
            if p is not None and not p.is_alive():
                p.close()  # release the spawn sentinel fds deterministically
        self._coord.close()
