"""Process workers over the native C++ transport (native/transport.cpp).

Functionally the twin of :class:`~.process.ProcessBackend` — n spawned
OS worker processes, real serialization boundary, dead-worker detection —
but the coordinator side is the native runtime instead of Python pipes
and reader threads: framed messages over Unix-domain sockets, an epoll
progress thread doing all partial I/O (the libmpi progress-engine role,
SURVEY component C8), and ``wait_any`` blocking in native
``msgt_coord_waitany`` rather than a Python condition variable. The pool
above is unchanged; this backend exists so the hot host-side wait loop
(reference ``MPI.Waitany!``, src/MPIAsyncPools.jl:161) runs in native
code with zero Python threads on the coordinator.

Construction falls back with :class:`~..native.NativeBuildError` if no
compiler is available; callers wanting automatic degradation should
catch it and build a :class:`~.process.ProcessBackend` instead.
"""

from __future__ import annotations

import multiprocessing as mp
import pickle
import tempfile
import time
import traceback
import uuid
from pathlib import Path
from typing import Sequence

import numpy as np

from ..native import transport as T
from .base import Backend, DelayFn, WorkerError
from .process import RemoteWorkerError, WorkerProcessDied, WorkFn

__all__ = ["NativeProcessBackend"]


def _native_worker_main(
    rank: int, path: str, work_fn: WorkFn, delay_fn: DelayFn | None
) -> None:
    """Worker process entry: the reference worker loop (SURVEY §3.2 —
    receive -> stall -> compute -> send, control channel for shutdown,
    examples/iterative_example.jl:55-82) over the native transport."""
    try:
        w = T.Worker(path, rank)
    except Exception:
        return
    try:
        while True:
            msg = w.recv()
            if msg is None or msg.kind == T.KIND_CONTROL:
                break  # coordinator gone, or shutdown broadcast
            payload = pickle.loads(msg.payload)
            if delay_fn is not None:
                d = float(delay_fn(rank, msg.epoch))
                if d > 0:
                    time.sleep(d)
            try:
                out = pickle.dumps(
                    work_fn(rank, payload, msg.epoch), protocol=5
                )
                kind = T.KIND_DATA
            except BaseException as e:
                out = pickle.dumps(
                    (type(e).__name__, str(e), traceback.format_exc()),
                    protocol=5,
                )
                kind = T.KIND_ERROR
            if not w.send(out, seq=msg.seq, epoch=msg.epoch, kind=kind):
                break
    except (KeyboardInterrupt, Exception):
        pass
    finally:
        w.close()


class NativeProcessBackend(Backend):
    """n worker processes; all coordinator-side I/O in the C++ runtime.

    Same contract as :class:`~.process.ProcessBackend` (picklable
    ``work_fn(i, payload, epoch)`` / ``delay_fn``); the payload snapshot
    happens twice over — pickled at dispatch, then copied into the native
    send queue — so in-flight sends survive caller mutation (the
    reference's ``isendbuf`` discipline, src/MPIAsyncPools.jl:130).
    """

    def __init__(
        self,
        work_fn: WorkFn,
        n_workers: int,
        *,
        delay_fn: DelayFn | None = None,
        mp_context: str = "spawn",
        connect_timeout: float = 60.0,
        join_timeout: float = 5.0,
    ):
        self.n_workers = int(n_workers)
        self.work_fn = work_fn
        self.delay_fn = delay_fn
        self._join_timeout = join_timeout
        self._closed = False
        self._seqs = [0] * self.n_workers
        self._epochs = [0] * self.n_workers  # epoch of in-flight dispatch
        # dispatch that failed instantly (dead worker): surfaced at the
        # next test/wait instead of raising inside the pool's send phase
        self._synthetic: list[WorkerError | None] = [None] * self.n_workers
        sock = Path(tempfile.gettempdir()) / f"msgt-{uuid.uuid4().hex[:12]}.sock"
        self._sock_path = str(sock)
        self._mp_context = mp_context
        self._coord = T.Coordinator(self._sock_path, self.n_workers)
        self._procs: list = [None] * self.n_workers
        for i in range(self.n_workers):
            self._spawn_worker(i)
        try:
            self._coord.accept(timeout=connect_timeout)
        except T.TransportError:
            self.shutdown()
            raise

    def _spawn_worker(self, i: int) -> None:
        """Start (or restart) the worker process for rank i."""
        ctx = mp.get_context(self._mp_context)
        proc = ctx.Process(
            target=_native_worker_main,
            args=(i, self._sock_path, self.work_fn, self.delay_fn),
            daemon=True,
            name=f"pool-native-worker-{i}",
        )
        proc.start()
        self._procs[i] = proc

    # -- Backend interface -------------------------------------------------
    def dispatch(self, i: int, sendbuf, epoch: int, *, tag: int = 0) -> None:
        if self._closed:
            raise RuntimeError("backend has been shut down")
        payload = sendbuf
        if hasattr(payload, "__array__") and not isinstance(payload, np.ndarray):
            payload = np.asarray(payload)  # device arrays are not picklable
        self._seqs[i] += 1
        self._epochs[i] = int(epoch)
        ok = self._coord.isend(
            i, pickle.dumps(payload, protocol=5),
            seq=self._seqs[i], epoch=int(epoch), tag=int(tag),
        )
        if not ok:  # rank already dead: fail the task, don't hang the pool
            self._synthetic[i] = WorkerError(i, epoch, WorkerProcessDied(i))

    def _decode(self, i: int, msg: T.Message):
        if msg.kind == T.KIND_DEATH:
            return WorkerError(
                i, self._epochs[i], WorkerProcessDied(i)
            )
        if msg.kind == T.KIND_ERROR:
            exc_type, text, tb = pickle.loads(msg.payload)
            return WorkerError(
                i, msg.epoch, RemoteWorkerError(exc_type, text, tb)
            )
        return pickle.loads(msg.payload)

    def _next(self, i: int, *, block: bool, timeout: float | None = None):
        """Fetch the completion for worker ``i``'s current dispatch,
        skipping frames from superseded dispatches (stale seq)."""
        if self._closed:
            raise RuntimeError("backend has been shut down")
        if self._synthetic[i] is not None:
            out = self._synthetic[i]
            self._synthetic[i] = None
            return out
        deadline = None if timeout is None else time.perf_counter() + timeout
        while True:
            if block:
                left = (
                    None if deadline is None
                    else max(deadline - time.perf_counter(), 0.0)
                )
                got = self._coord.waitany([i], timeout=left)
                if got is None:
                    return None  # timeout
                _, msg = got
            else:
                msg = self._coord.poll(i)
                if msg is None:
                    return None
            if msg.kind == T.KIND_DATA or msg.kind == T.KIND_ERROR:
                if msg.seq != self._seqs[i]:
                    continue  # superseded dispatch; drop and keep looking
            return self._decode(i, msg)

    def test(self, i: int):
        return self._next(i, block=False)

    def wait_any(self, indices: Sequence[int]) -> tuple[int, object]:
        if self._closed:
            raise RuntimeError("backend has been shut down")
        idx = [int(j) for j in indices]
        if not idx:
            raise ValueError("wait_any over an empty index set would hang")
        for j in idx:  # synthetic failures first — they're already complete
            if self._synthetic[j] is not None:
                out = self._synthetic[j]
                self._synthetic[j] = None
                return j, out
        while True:
            got = self._coord.waitany(idx, timeout=None)
            assert got is not None  # no timeout passed
            j, msg = got
            if msg.kind in (T.KIND_DATA, T.KIND_ERROR) and msg.seq != self._seqs[j]:
                continue
            return j, self._decode(j, msg)

    def wait(self, i: int, timeout: float | None = None):
        return self._next(i, block=True, timeout=timeout)

    def respawn(self, i: int, *, connect_timeout: float = 60.0) -> None:
        """Elastic recovery: replace a dead worker process with a fresh
        one on the same rank (the reference has no such capability — a
        dead rank is permanent and hangs ``Waitall!``, SURVEY §5). The
        new process reconnects through the transport's reaccept path;
        pool state is untouched — the rank simply becomes dispatchable
        again, and any frames from the old incarnation are dropped by
        the seq guard."""
        if self._closed:
            raise RuntimeError("backend has been shut down")
        if not self._coord.is_dead(i) and self._procs[i].is_alive():
            raise RuntimeError(f"worker {i} is alive; nothing to respawn")
        if self._procs[i].is_alive():  # pragma: no cover - zombie socket
            self._procs[i].terminate()
        self._procs[i].join(timeout=self._join_timeout)
        self._spawn_worker(i)
        # reaccept tolerates a not-yet-drained HUP within its timeout
        self._coord.reaccept(i, timeout=connect_timeout)
        # _synthetic[i], if set, stays: it records a dispatch the old
        # incarnation never received — the pool must still see it fail

    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for i in range(self.n_workers):
            # control-channel broadcast (reference test/kmap2.jl:14-18)
            self._coord.isend(i, b"", kind=T.KIND_CONTROL)
        for p in self._procs:
            p.join(timeout=self._join_timeout)
        for p in self._procs:
            if p.is_alive():  # pragma: no cover - stuck worker
                p.terminate()
        self._coord.close()
