"""Backend protocol: the transport layer under :mod:`..pool`.

A backend plays the role the ``comm: MPI.Comm`` argument plays in the
reference (src/MPIAsyncPools.jl:68): it owns the in-flight request state
(the reference's ``sreqs``/``rreqs`` vectors, src/MPIAsyncPools.jl:26-27)
and provides the completion primitives the pool's three phases need:

==============  =========================================================
pool phase       backend primitive        reference analog
==============  =========================================================
phase 1 drain    ``test(i)``              ``MPI.Test!`` (:99)
phase 2 send     ``dispatch(i, ...)``     ``MPI.Isend``/``Irecv!`` (:137-138)
phase 3 wait     ``wait_any(indices)``    ``MPI.Waitany!`` (:161)
waitall          ``wait(i, timeout)``     ``MPI.Waitall!`` (:212)
shutdown         ``shutdown()``           control-channel broadcast
                                          (test/kmap2.jl:14-18)
==============  =========================================================

:class:`SlotBackend` is a shared implementation skeleton: one *slot* per
(worker, tag) holding at most one outstanding task (the pool's ``active``
flag discipline guarantees single occupancy per channel), a completion
event per slot, and a condition variable notified on every completion so
``wait_any`` can sleep instead of spinning. Subclasses only implement how
a task actually runs (thread compute, XLA device dispatch, ...).

Tags multiplex independent message channels over one backend, exactly as
MPI tags multiplex one communicator (the reference separates data and
control streams by tag — test/kmap2.jl:11-12 — and two pools can share a
comm on distinct tags). Each tag is an isolated channel: its own slots,
its own completions; a dispatch on tag 1 can be in flight to the same
worker as a dispatch on tag 0, and results never cross channels.
"""

from __future__ import annotations

import queue
import threading
import time
from abc import ABC, abstractmethod
from typing import Callable, Sequence

DelayFn = Callable[[int, int], float]

_SHUTDOWN = object()


class Deadline:
    """Shared remaining-time arithmetic for timeout-capable waits.

    ``Deadline(None)`` never expires and ``remaining()`` stays None
    (block forever); otherwise ``remaining()`` is clamped to >= 0 so it
    can be handed to any wait primitive directly.
    """

    __slots__ = ("_at",)

    def __init__(self, timeout: float | None):
        self._at = (
            None if timeout is None else time.perf_counter() + timeout
        )

    def remaining(self) -> float | None:
        if self._at is None:
            return None
        return max(self._at - time.perf_counter(), 0.0)



class DeadWorkerError(TimeoutError):
    """Raised when workers fail to respond in time: by ``asyncmap``
    (with ``timeout=``) and ``waitall`` at the pool layer, and by
    ``on_dead="straggle"`` backends when an unbounded wait would
    otherwise block forever on only-dead ranks.

    The reference has no failure detection: a dead worker is
    indistinguishable from an infinite straggler and ``waitall!`` hangs
    on it (SURVEY §5). Defined here, beside the Backend contract, so
    backends never import the orchestration layer above them.
    """

    def __init__(self, dead, timeout):
        self.dead = [int(d) for d in dead]  # backend ranks still active
        self.timeout = timeout
        tail = (
            f"within {timeout} s" if timeout is not None
            else "(unbounded wait, all awaited ranks dead)"
        )
        super().__init__(f"workers {self.dead} did not respond {tail}")


class WorkerFailure(RuntimeError):
    """A worker raised during compute; re-raised coordinator-side at
    harvest (the reference loses worker errors entirely — assertions die
    inside mpiexec subprocesses, SURVEY §4)."""

    def __init__(self, worker: int, epoch: int, error: BaseException):
        self.worker = worker
        self.epoch = epoch
        self.error = error
        super().__init__(f"worker {worker} failed at epoch {epoch}: {error!r}")


class WorkerError:
    """Marker carrying a captured worker exception to the coordinator."""

    __slots__ = ("worker", "epoch", "error")

    def __init__(self, worker: int, epoch: int, error: BaseException):
        self.worker = worker
        self.epoch = epoch
        self.error = error

    def raise_(self) -> None:
        raise WorkerFailure(self.worker, self.epoch, self.error)


class Backend(ABC):
    """Minimal transport interface consumed by ``asyncmap``/``waitall``."""

    n_workers: int

    @abstractmethod
    def dispatch(self, i: int, sendbuf, epoch: int, *, tag: int = 0) -> None:
        """Start asynchronous work on worker ``i`` with a *snapshot* of
        ``sendbuf`` (the reference's ``isendbufs[i] .= sendbuf`` discipline,
        src/MPIAsyncPools.jl:130 — here the backend owns the snapshot)."""

    @abstractmethod
    def test(self, i: int, *, tag: int = 0):
        """Non-blocking completion probe on channel ``tag``. Returns the
        result exactly once if worker ``i`` has completed, else None
        (``MPI.Test!``)."""

    @abstractmethod
    def wait_any(
        self,
        indices: Sequence[int],
        timeout: float | None = None,
        *,
        tags: Sequence[int] | None = None,
    ) -> tuple[int, object] | None:
        """Block until any worker in ``indices`` completes on its paired
        channel; return ``(i, result)`` (``MPI.Waitany!``), or None if
        ``timeout`` seconds elapse first. ``tags`` aligns with
        ``indices`` (None = all on tag 0) — the in-flight request for
        worker ``indices[j]`` is the one dispatched with ``tags[j]``,
        mirroring MPI requests remembering the tag they were posted
        with."""

    @abstractmethod
    def wait(self, i: int, timeout: float | None = None, *, tag: int = 0):
        """Block until worker ``i`` completes on channel ``tag``; return
        its result, or None on timeout (building block for
        ``MPI.Waitall!``-style drains)."""

    def flush(self) -> None:  # pragma: no cover - default no-op
        """Called by ``asyncmap`` after its dispatch phase (and by
        ``waitall`` before draining): backends that coalesce dispatches
        (e.g. one fused device program for all pool workers sharing a
        chip — XLADeviceBackend's batch mode) submit the coalesced work
        here. The reference analog is a no-op: its Isends are already
        posted individually."""

    def shutdown(self) -> None:  # pragma: no cover - default no-op
        """Release worker resources (the reference's control-channel
        shutdown broadcast, examples/iterative_example.jl:50-52)."""

    def begin_epoch(self, epoch: int) -> None:  # pragma: no cover - no-op
        """Called by ``asyncmap`` once per call, before any dispatch.
        Backends may use it to reset per-epoch state (e.g. the XLA
        backend's shared-payload snapshot cache)."""

    def end_epoch(self) -> None:  # pragma: no cover - default no-op
        """Called by ``asyncmap`` when the call finishes (including on
        error). Backends disarm any per-epoch fast paths here so direct
        Backend-API dispatches between calls see full snapshot
        semantics."""


class _Slot:
    """One in-flight task slot. At most one outstanding task per worker."""

    __slots__ = ("seq", "done", "result", "outstanding")

    def __init__(self):
        self.seq = 0  # dispatch sequence number, guards late completions
        self.done = False
        self.result = None
        self.outstanding = False


class SlotBackend(Backend):
    """Completion-event machinery shared by concrete backends.

    Slots are per (worker, tag): ``_channels[tag]`` is a full worker-width
    slot vector, created lazily the first time a tag is used. Channel 0
    always exists (the default-tag fast path)."""

    def __init__(self, n_workers: int):
        self.n_workers = int(n_workers)
        self._channels: dict[int, list[_Slot]] = {
            0: [_Slot() for _ in range(self.n_workers)]
        }
        self._cond = threading.Condition()

    def _chan(self, tag: int) -> list[_Slot]:
        """Slot vector for ``tag``; caller must hold ``self._cond``."""
        slots = self._channels.get(tag)
        if slots is None:
            slots = [_Slot() for _ in range(self.n_workers)]
            self._channels[tag] = slots
        return slots

    # -- subclass surface -------------------------------------------------
    @abstractmethod
    def _start(self, i: int, sendbuf, epoch: int, seq: int, tag: int) -> None:
        """Begin asynchronous execution; must eventually call
        ``self._complete(i, seq, result, tag)`` from any thread."""

    # -- completion plumbing ---------------------------------------------
    def _complete(self, i: int, seq: int, result, tag: int = 0) -> None:
        with self._cond:
            slot = self._chan(tag)[i]
            if slot.seq != seq or not slot.outstanding:
                return  # stale completion from a superseded dispatch
            slot.result = result
            slot.done = True
            self._cond.notify_all()

    def _take(self, slot: _Slot):
        result = slot.result
        slot.result = None
        slot.done = False
        slot.outstanding = False
        return result

    # -- Backend interface ------------------------------------------------
    def dispatch(self, i: int, sendbuf, epoch: int, *, tag: int = 0) -> None:
        tag = int(tag)
        with self._cond:
            slot = self._chan(tag)[i]
            if slot.outstanding:
                raise RuntimeError(
                    f"worker {i} already has an outstanding task on tag "
                    f"{tag}; the pool must only dispatch to inactive workers"
                )
            slot.seq += 1
            slot.done = False
            slot.result = None
            slot.outstanding = True
            seq = slot.seq
        try:
            self._start(i, sendbuf, epoch, seq, tag)
        except BaseException:
            # roll the slot back: a task that never started must not leave
            # an outstanding slot that wait/wait_any would block on forever
            with self._cond:
                if slot.seq == seq:
                    slot.outstanding = False
            raise

    def test(self, i: int, *, tag: int = 0):
        with self._cond:
            slot = self._chan(int(tag))[i]
            if slot.outstanding and slot.done:
                return self._take(slot)
            return None

    def wait_any(
        self,
        indices: Sequence[int],
        timeout: float | None = None,
        *,
        tags: Sequence[int] | None = None,
    ) -> tuple[int, object] | None:
        idx = [int(i) for i in indices]
        if not idx:
            raise ValueError("wait_any over an empty index set would hang")
        tgs = [0] * len(idx) if tags is None else [int(t) for t in tags]
        if len(tgs) != len(idx):
            raise ValueError("tags must align one-to-one with indices")
        ready: list[tuple[int, _Slot]] = []

        def scan() -> bool:
            for i, t in zip(idx, tgs):
                slot = self._chan(t)[i]
                if slot.outstanding and slot.done:
                    ready.append((i, slot))
                    return True
            return False

        with self._cond:
            if not self._cond.wait_for(scan, timeout=timeout):
                return None
            i, slot = ready[-1]
            return i, self._take(slot)

    def wait(self, i: int, timeout: float | None = None, *, tag: int = 0):
        with self._cond:
            slot = self._chan(int(tag))[i]
            if not slot.outstanding:
                raise RuntimeError(
                    f"worker {i} has no outstanding task on tag {int(tag)}"
                )
            ok = self._cond.wait_for(lambda: slot.done, timeout=timeout)
            if not ok:
                return None
            return self._take(slot)


class MailboxBackend(SlotBackend):
    """Worker-loop skeleton: one dispatcher thread + depth-1 mailbox each.

    This is the reference's worker-side convention (receive -> optional
    injected stall -> compute -> deliver, with a control channel for
    shutdown; examples/iterative_example.jl:55-82, SURVEY §3.2) made a
    first-class, reusable library component. The depth-1 mailbox models
    an ``MPI.Isend`` whose matching ``Irecv!`` the worker only posts
    after finishing its previous compute; the shutdown sentinel is the
    control-tag broadcast (test/kmap2.jl:14-18).

    Subclasses implement:

    * ``_snapshot(i, sendbuf, epoch)`` — produce the private payload
      snapshot enqueued to the worker (the reference's ``isendbuf``
      discipline, src/MPIAsyncPools.jl:63-66,:130);
    * ``_compute(i, payload, epoch)`` — the worker computation.
    """

    def __init__(
        self,
        n_workers: int,
        *,
        delay_fn: DelayFn | None = None,
        join_timeout: float = 2.0,
        thread_name: str = "pool-worker",
    ):
        super().__init__(n_workers)
        self.delay_fn = delay_fn
        self._closed = False
        self._join_timeout = join_timeout
        # unbounded: occupancy is bounded by the slot discipline at one
        # outstanding task per (worker, tag) channel, so the queue holds
        # at most n_tags-in-use messages — a fixed depth-1 box would
        # deadlock the coordinator when a second channel dispatches while
        # the worker is busy with the first
        self._mailboxes: list[queue.Queue] = [
            queue.Queue() for _ in range(n_workers)
        ]
        self._threads = [
            threading.Thread(
                target=self._worker_loop, args=(i,), daemon=True,
                name=f"{thread_name}-{i}",
            )
            for i in range(n_workers)
        ]
        for t in self._threads:
            t.start()

    @abstractmethod
    def _snapshot(self, i: int, sendbuf, epoch: int):
        ...

    @abstractmethod
    def _compute(self, i: int, payload, epoch: int):
        ...

    def _worker_loop(self, i: int) -> None:
        mbox = self._mailboxes[i]
        while True:
            msg = mbox.get()
            if msg is _SHUTDOWN:
                return
            seq, payload, epoch, tag = msg
            if self.delay_fn is not None:
                d = float(self.delay_fn(i, epoch))
                if d > 0:
                    time.sleep(d)
            try:
                result = self._compute(i, payload, epoch)
            except BaseException as e:  # surfaced on harvest, not lost
                result = WorkerError(i, epoch, e)
            self._complete(i, seq, result, tag)

    def _start(self, i: int, sendbuf, epoch: int, seq: int, tag: int) -> None:
        if self._closed:
            raise RuntimeError("backend has been shut down")
        payload = self._snapshot(i, sendbuf, epoch)
        self._mailboxes[i].put((seq, payload, epoch, tag))

    def shutdown(self) -> None:
        self._closed = True
        for mbox in self._mailboxes:
            mbox.put_nowait(_SHUTDOWN)
        for t in self._threads:
            t.join(timeout=self._join_timeout)
